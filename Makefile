# Developer entry points (the reference's Makefile:80-122 analog:
# test / test-race / lint battery).

PY ?= python

.PHONY: test test-race verify verify-ha verify-churn verify-faults \
        verify-adaptive verify-static verify-telemetry verify-soak soak \
        verify-cluster-obs verify-dispatch verify-ingress verify-ops \
        verify-inference lint bench \
        bench-suite bench-sweep bench-scale bench-latency bench-frames \
        bench-ingress bench-churn bench-adaptive bench-history \
        bench-rounds bench-infer images native native-sanitize

test:
	$(PY) -m pytest tests/ -q

# The HA-store verification subset under the tier-1 command's flags:
# kvstore (incl. the ensemble + 3-OS-process leader-SIGKILL tests),
# chaos (leader kill mid-traffic), and the deployment composition that
# renders the 3-replica spec.  `not slow` mirrors tier-1; RUN_SLOW=1
# adds the slow cross-process soaks.
verify-ha:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_kvstore.py tests/test_kvstore_remote.py \
	    tests/test_kvstore_ha.py tests/test_chaos.py tests/test_deploy.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly

# Incremental-table-compile verification: the randomized churn property
# suite (delta-built tables ≡ from-scratch rebuilds after every step,
# swap-under-traffic atomicity) + a fast CPU bench_churn smoke that
# checks delta beats full rebuilds AND ships O(changed) rows.  The
# full-scale (64k rules / 4k pods, ≥10x) run is `make bench-churn`.
verify-churn:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_table_delta.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/bench_churn.py --smoke --check \
	    --min-speedup 1.5

bench-churn:
	$(PY) scripts/bench_churn.py --check

# Adaptive-coalesce verification: the governor unit/property suite
# (K monotonicity, SLO bound across an offered-load sweep, pow2-bucket
# pre-warm, mock-engine verdict parity at every chosen K, native k_cap,
# deeper in-flight window) + a reduced-scale frontier smoke asserting
# >= 1.5x over fixed K=64 at saturation on a (simulated) floor-bound
# link while the added-latency budget holds at the reference load.
# The full frontier (tunnel floor, production scale) is
# `make bench-adaptive`.
verify-adaptive:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_governor.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/bench_adaptive.py --smoke --check \
	    --min-speedup 1.5 --out /tmp/benchadapt_verify.jsonl

bench-adaptive:
	$(PY) scripts/bench_adaptive.py --check

# Dispatch round-chain verification (ISSUE 11): the flat-punt /
# packed-harvest test subset (device semantics, verdict parity at
# every governor K on both engines, packed round-trip properties),
# then the two round-fusion gates at reduced scale — bench_rounds.py
# asserts the packed harvest blocks on <= 2 materialisations per batch
# with a lower materialize p50 at equal load (simulated-floor row is
# the judged one on CPU, always labelled), and mesh_overhead.py
# asserts the STRUCTURAL round cut on the 8-device virtual mesh:
# flat-punt's partitioned-session sharded program compiles to strictly
# fewer collectives than flat-safe's, at wall-time parity (emulated
# collectives carry no interconnect latency, so the removed round
# cannot show as wall time here — see the script docstring).
# Full-scale recordings are `make bench-rounds` /
# `python scripts/mesh_overhead.py --check`.
verify-dispatch:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_pipeline.py tests/test_governor.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/bench_rounds.py --smoke --check
	JAX_PLATFORMS=cpu $(PY) scripts/mesh_overhead.py --smoke --check

bench-rounds:
	$(PY) scripts/bench_rounds.py --check

# Many-core host ingress verification (ISSUE 12): the fanout-handoff /
# drain-call native units, the steering-rotation regression across an
# eject→rejoin cycle at N=8, the global-budget ledger property suite
# (sum of per-shard chosen-K added latency holds the ONE
# coalesce_slo_us under skewed backlogs, on both engines, with the
# overload case honestly accounted), the placement/ledger
# observability surfaces — then a reduced-scale scaling smoke through
# the official harness gating wall-clock efficiency ≥ 0.8 at N=4
# (honest notes where the box caps real parallelism).  The full
# recorded tier (N ∈ {1,2,4,8} at bench scale → FRAMEBENCH_r06.jsonl)
# is `make bench-ingress`.
verify-ingress:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_shards.py tests/test_governor.py \
	    tests/test_native_sanitize.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/frame_bench.py --shards-tier 1,4 \
	    --frames 2048 --rounds 3 --check --min-eff 0.8 --gate-shards 4

bench-ingress:
	$(PY) scripts/frame_bench.py --shards-tier 1,2,4,8 --check \
	    --out FRAMEBENCH_r06.jsonl

# In-network inference verification (ISSUE 14): the scorer/table/
# renderer/CRD suites (device↔host band parity, delta-builder churn
# property, mock-engine oracle parity at every governor K on both
# engines incl. the quarantine action path, the CRD→delta-swap→
# quarantine e2e demo with pcap + flight evidence, packed-word
# round-trip property, REST/netctl/metrics/dashboard surfaces), the
# scoring A/B gate at smoke scale (scores exactly the enrolled rows;
# ~free under the simulated dispatch floor), and the static gate —
# hot-path-sync must stay clean with the scorer in the dispatch path,
# obs-parity with the inference pins.
verify-inference:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_inference.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/bench_infer.py --smoke --check
	$(PY) scripts/check_static.py vpp_tpu/ --rule hot-path-sync \
	    --rule obs-parity

bench-infer:
	$(PY) scripts/bench_infer.py --check --out BENCHINFER_r14.jsonl

# Telemetry verification (ISSUE 8): the histogram/span/flight suites
# (single-writer vs reader-merge property, bucket boundaries, the full
# controller-driven span lifecycle with mock engines, ejection flight
# dumps, REST/netctl/metrics surfaces) + the static gate — in
# particular hot-path-sync must stay clean with the recorder on the
# dispatch path.  These tests also run in plain `make test`/tier-1
# (tests/test_telemetry.py); `make lint` byte-compiles + checks
# vpp_tpu/telemetry/ with the rest of the tree.
verify-telemetry:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_telemetry.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	$(PY) scripts/check_static.py vpp_tpu/ --rule hot-path-sync \
	    --rule obs-parity

# Datapath fault-domain verification: the fault-injection harness units
# (injector semantics, swap rollback, poisoned-batch quarantine, REST/
# netctl health) + the chaos suite (shard ejection mid-traffic with
# oracle verdict parity, hang deadlines, atomic multi-shard swap
# rollback, all-shards-down policies, agent/store/leader kills).
# `not slow` mirrors tier-1; RUN_SLOW=1 adds the cross-process soaks.
verify-faults:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_faults.py tests/test_chaos.py tests/test_shards.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly

# Race-amplified run: CPython has no Go-style race detector, so instead
# the whole suite runs under dev mode (threading/resource warnings are
# errors-adjacent) with a pathologically small thread switch interval,
# maximising interleavings across the event loop, dbwatcher, scheduler
# retry timers and the gRPC watch threads.  Hardened (ISSUE 7):
# ResourceWarnings (unclosed sockets, pcap handles, ring fds) are hard
# errors, and conftest's sessionfinish hook fails the run if any
# non-daemon thread (supervisor executor, governor timer, watch
# stream) survives suite teardown — threads must JOIN on stop.
test-race:
	VPP_TPU_RACE_STRESS=1 $(PY) -X dev -m pytest tests/ -q \
	    -W error::ResourceWarning \
	    -W error::pytest.PytestUnraisableExceptionWarning

# Static battery (ISSUE 7): byte-compile + the invariant checker gate
# (hot-path-sync, jit-discipline, lock-discipline, obs-parity — see
# vpp_tpu/analysis/) + test-tree collection (import errors, syntax,
# circular imports).
lint:
	$(PY) -m compileall -q vpp_tpu tests scripts bench.py benchsuite.py
	$(PY) scripts/check_static.py vpp_tpu/
	$(PY) -m pytest tests/ -q --collect-only > /dev/null
	@echo lint OK

# Invariant-battery verification: the checker self-tests (fixture
# snippets that MUST flag and MUST pass, waiver syntax, call-graph
# reachability) + the repo-is-clean gate over the live tree.
verify-static:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_static_analysis.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	$(PY) scripts/check_static.py vpp_tpu/

# Cluster-soak verification (ISSUE 9): the fake-kubelet harness units
# (real conflist parsed, real shim binary exec'd over gRPC AND the
# stdlib-HTTP fallback, manifest/chart cross-validation), controller
# resilience observability, churn-script determinism, and the tier-1
# soak-smoke — ~8 procnode agents over a 3-replica HA store of OS
# processes, every fault class (leader SIGKILL, store-outage window,
# shard eject/hang/swap-fail, agent SIGKILL-restart) fired at least
# once with mock-engine verdict parity as the oracle.  RUN_SLOW=1 adds
# the mid-size scripted run.
verify-soak:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_soak.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly

# Cluster-observability verification (ISSUE 10): span stitching and
# histogram cross-node merge properties, the fleet aggregator's
# partial-failure contract (unreachable/SIGSTOPped agents are reported
# gaps with last-seen ages, never hangs), a procnode multi-agent run
# asserting one store write stitches into a cluster span covering all
# nodes with monotone adoption lags, `netctl cluster` with a dead agent
# (gap shown, exit 0), and the dispatch round-chain attribution — plus
# the static gate with the cluster-surface obs-parity pins.
verify-cluster-obs:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_cluster_obs.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	$(PY) scripts/check_static.py vpp_tpu/ --rule obs-parity

# Operational-resilience verification (ISSUE 13): the version-skew
# matrix (old↔new client/store/replica in both directions, below-floor
# refused cleanly, unknown fields round-tripped byte-identically
# through the codec/mirror), live HA membership change (learner
# snapshot catch-up BEFORE voting rights, one-change-at-a-time,
# leader-removal orderly handoff with revision identity across
# survivors, runtime member refresh keeping long-lived watchers alive
# across replica replacement), graceful drain/rejoin (FSM, retriable
# code-11 CNI rejection, drained-vs-gap scraper contract, netctl
# drain|undrain) — plus the planned-operations soak smoke firing the
# rolling-upgrade / membership-grow+shrink / drain drills over real OS
# processes with churn and parity probes running throughout.
verify-ops:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_compat.py tests/test_ops.py \
	    tests/test_kvstore_ha.py tests/test_kvstore_remote.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly

# The full mega-cluster chaos soak (the ISSUE 9 acceptance run): ≥50
# agents, ≥1000 pod ADD/DEL through the real exec'd CNI shim, ≥2 leader
# kills, ≥2 store-outage windows, ≥4 shard faults, ≥2 agent restarts —
# self-checking (nonzero exit on any parity mismatch / unconverged
# node), recorded to SOAK_r08.jsonl.
soak:
	JAX_PLATFORMS=cpu $(PY) scripts/soak_cluster.py --check

# The aggregate verification gate: static battery + every subsystem's
# verify target, soak-smoke included.
verify: lint verify-static verify-ha verify-churn verify-adaptive \
        verify-dispatch verify-ingress verify-telemetry verify-faults \
        verify-inference verify-cluster-obs verify-soak verify-ops
	@echo verify OK

bench:
	$(PY) bench.py

bench-suite:
	$(PY) benchsuite.py

bench-sweep:
	$(PY) benchsuite.py --sweep

bench-scale:
	$(PY) benchsuite.py --scale

bench-latency:
	$(PY) benchsuite.py --latency

bench-frames:
	$(PY) scripts/frame_bench.py

# Perf trajectory across every recorded BENCH*_r* artifact: one
# series-per-metric view with round-over-round deltas and regression
# flags (ISSUE 10 satellite) — a reader over the recorded evidence,
# never a re-run.  BENCH_HISTORY_CHECK=1 exits nonzero on regressions.
bench-history:
	$(PY) scripts/bench_history.py $(if $(BENCH_HISTORY_CHECK),--check)

native:
	$(MAKE) -C native/hostshim

# Sanitizer-hardened native builds (ISSUE 7): ASan+UBSan flavors of the
# hostshim .so and loopbench, a TSan loopbench for the threaded admit
# path, then the native-engine test subset under them.
#
# - loopbench.asan runs with LEAK DETECTION ON (pure C++ process, every
#   allocation attributable) over the mixed, threaded and sharded shapes;
# - loopbench.tsan runs the `threaded` shape (N pushers vs one
#   admit/harvest consumer — the legacy contention pattern) AND the
#   `sharded` shape (ISSUE 12: one fanout feeder distributing across N
#   independent rings while N consumer threads drive their own
#   admit→route→harvest loops — the real many-core front-end handoff);
# - the pytest subset loads libhostshim.asan.so into a libasan-preloaded
#   interpreter.  detect_leaks=0 there (CPython keeps arenas/interned
#   objects to exit — see native/hostshim/asan.supp), and the subset
#   excludes XLA lowering: jaxlib's MLIR throws through a statically
#   linked __cxa_throw the preloaded GCC ASan cannot intercept (environment
#   incompatibility, aborts on any jit compile — not a hostshim defect).
#   C++ coverage is unchanged: the deselected test re-runs shim.apply,
#   which TestParseApplyVxlan already drives.
# Suppression files ride along even while empty so a future entry lands
# reviewed (they must stay justified in-file; see their headers).
CXX ?= g++
ASAN_LIB = $(shell $(CXX) -print-file-name=libasan.so)
native-sanitize:
	$(MAKE) -C native/hostshim SANITIZE=asan
	$(MAKE) -C native/hostshim SANITIZE=asan loopbench
	$(MAKE) -C native/hostshim SANITIZE=tsan loopbench
	LSAN_OPTIONS=suppressions=native/hostshim/asan.supp \
	    UBSAN_OPTIONS=halt_on_error=1 \
	    native/build/loopbench.asan 16384 3 mixed
	LSAN_OPTIONS=suppressions=native/hostshim/asan.supp \
	    UBSAN_OPTIONS=halt_on_error=1 \
	    native/build/loopbench.asan 16384 3 threaded 4
	LSAN_OPTIONS=suppressions=native/hostshim/asan.supp \
	    UBSAN_OPTIONS=halt_on_error=1 \
	    native/build/loopbench.asan 16384 3 sharded 4
	TSAN_OPTIONS="suppressions=native/hostshim/tsan.supp halt_on_error=1" \
	    native/build/loopbench.tsan 8192 3 threaded 8
	TSAN_OPTIONS="suppressions=native/hostshim/tsan.supp halt_on_error=1" \
	    native/build/loopbench.tsan 8192 3 sharded 8
	LD_PRELOAD=$(ASAN_LIB) \
	    VPP_TPU_HOSTSHIM_LIB=$(CURDIR)/native/build/libhostshim.asan.so \
	    ASAN_OPTIONS=detect_leaks=0 UBSAN_OPTIONS=halt_on_error=1 \
	    JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_native_sanitize.py tests/test_hostshim.py \
	    -k 'not pipeline' -q -p no:cacheprovider -p no:xdist -p no:randomly
	@echo native-sanitize OK

# Container images (the reference's docker/build-all.sh analog).  One
# multi-stage build, one target per component; see deploy/docker/.
DOCKER ?= docker
IMAGE_TAG ?= latest
images:
	$(DOCKER) build -f deploy/docker/Dockerfile --target store  -t vpp-tpu-store:$(IMAGE_TAG) .
	$(DOCKER) build -f deploy/docker/Dockerfile --target ksr    -t vpp-tpu-ksr:$(IMAGE_TAG) .
	$(DOCKER) build -f deploy/docker/Dockerfile --target agent  -t vpp-tpu-agent:$(IMAGE_TAG) .
	$(DOCKER) build -f deploy/docker/Dockerfile --target netctl -t vpp-tpu-netctl:$(IMAGE_TAG) .
