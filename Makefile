# Developer entry points (the reference's Makefile:80-122 analog:
# test / test-race / lint battery).

PY ?= python

.PHONY: test test-race verify-ha verify-churn verify-faults \
        verify-adaptive lint bench bench-suite bench-sweep bench-scale \
        bench-latency bench-frames bench-churn bench-adaptive images native

test:
	$(PY) -m pytest tests/ -q

# The HA-store verification subset under the tier-1 command's flags:
# kvstore (incl. the ensemble + 3-OS-process leader-SIGKILL tests),
# chaos (leader kill mid-traffic), and the deployment composition that
# renders the 3-replica spec.  `not slow` mirrors tier-1; RUN_SLOW=1
# adds the slow cross-process soaks.
verify-ha:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_kvstore.py tests/test_kvstore_remote.py \
	    tests/test_kvstore_ha.py tests/test_chaos.py tests/test_deploy.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly

# Incremental-table-compile verification: the randomized churn property
# suite (delta-built tables ≡ from-scratch rebuilds after every step,
# swap-under-traffic atomicity) + a fast CPU bench_churn smoke that
# checks delta beats full rebuilds AND ships O(changed) rows.  The
# full-scale (64k rules / 4k pods, ≥10x) run is `make bench-churn`.
verify-churn:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_table_delta.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/bench_churn.py --smoke --check \
	    --min-speedup 1.5

bench-churn:
	$(PY) scripts/bench_churn.py --check

# Adaptive-coalesce verification: the governor unit/property suite
# (K monotonicity, SLO bound across an offered-load sweep, pow2-bucket
# pre-warm, mock-engine verdict parity at every chosen K, native k_cap,
# deeper in-flight window) + a reduced-scale frontier smoke asserting
# >= 1.5x over fixed K=64 at saturation on a (simulated) floor-bound
# link while the added-latency budget holds at the reference load.
# The full frontier (tunnel floor, production scale) is
# `make bench-adaptive`.
verify-adaptive:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_governor.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly
	JAX_PLATFORMS=cpu $(PY) scripts/bench_adaptive.py --smoke --check \
	    --min-speedup 1.5 --out /tmp/benchadapt_verify.jsonl

bench-adaptive:
	$(PY) scripts/bench_adaptive.py --check

# Datapath fault-domain verification: the fault-injection harness units
# (injector semantics, swap rollback, poisoned-batch quarantine, REST/
# netctl health) + the chaos suite (shard ejection mid-traffic with
# oracle verdict parity, hang deadlines, atomic multi-shard swap
# rollback, all-shards-down policies, agent/store/leader kills).
# `not slow` mirrors tier-1; RUN_SLOW=1 adds the cross-process soaks.
verify-faults:
	JAX_PLATFORMS=cpu $(PY) -m pytest \
	    tests/test_faults.py tests/test_chaos.py tests/test_shards.py \
	    -q $(if $(RUN_SLOW),,-m 'not slow') --continue-on-collection-errors \
	    -p no:cacheprovider -p no:xdist -p no:randomly

# Race-amplified run: CPython has no Go-style race detector, so instead
# the whole suite runs under dev mode (threading/resource warnings are
# errors-adjacent) with a pathologically small thread switch interval,
# maximising interleavings across the event loop, dbwatcher, scheduler
# retry timers and the gRPC watch threads.
test-race:
	VPP_TPU_RACE_STRESS=1 $(PY) -X dev -m pytest tests/ -q

# Static battery: byte-compile everything and verify the test tree
# collects (import errors, syntax, circular imports).
lint:
	$(PY) -m compileall -q vpp_tpu tests scripts bench.py benchsuite.py
	$(PY) -m pytest tests/ -q --collect-only > /dev/null
	@echo lint OK

bench:
	$(PY) bench.py

bench-suite:
	$(PY) benchsuite.py

bench-sweep:
	$(PY) benchsuite.py --sweep

bench-scale:
	$(PY) benchsuite.py --scale

bench-latency:
	$(PY) benchsuite.py --latency

bench-frames:
	$(PY) scripts/frame_bench.py

native:
	$(MAKE) -C native/hostshim

# Container images (the reference's docker/build-all.sh analog).  One
# multi-stage build, one target per component; see deploy/docker/.
DOCKER ?= docker
IMAGE_TAG ?= latest
images:
	$(DOCKER) build -f deploy/docker/Dockerfile --target store  -t vpp-tpu-store:$(IMAGE_TAG) .
	$(DOCKER) build -f deploy/docker/Dockerfile --target ksr    -t vpp-tpu-ksr:$(IMAGE_TAG) .
	$(DOCKER) build -f deploy/docker/Dockerfile --target agent  -t vpp-tpu-agent:$(IMAGE_TAG) .
	$(DOCKER) build -f deploy/docker/Dockerfile --target netctl -t vpp-tpu-netctl:$(IMAGE_TAG) .
