"""Benchmark suite — all five BASELINE.md configurations.

``bench.py`` is the driver-run headline (config 5, the 10k-rule +
1k-service stress).  This suite reproduces the remaining reference
harnesses on the TPU data plane:

1. pod-to-pod, single node, no policies   (scripts/contiv-pod-perf.sh)
2. ~20-rule NetworkPolicy suite, ACL path (tests/policy suite)
3. ClusterIP with 8 backends, NAT44 LB    (scripts/lb-perf-test.sh)
4. 2-node VXLAN overlay + SNAT egress     (two_node robot suites)
5. 10k rules + 1k services stress         (tests/policy/perf/gen-policy.py)

Usage: ``python benchsuite.py [--config N] [--batch B] [--iters I]``.
Prints one JSON line per configuration:
    {"config": k, "metric": ..., "value": N, "unit": "Mpps",
     "gbps_64b": ..., "gbps_1500b": ..., "vs_baseline": N}

vs_baseline is Mpps/40 against BASELINE.json's >=40 Mpps ACL+NAT44
target (VPP/DPDK parity on a 16-core Xeon).
"""

import argparse
import json
import random
import time

import jax.numpy as jnp

from vpp_tpu.conf import IPAMConfig
from vpp_tpu.ipam import IPAM
from vpp_tpu.models import ProtocolType
from vpp_tpu.ops.classify import NO_TABLE, build_rule_tables
from vpp_tpu.ops.nat import NatMapping, build_nat_tables, empty_sessions
from vpp_tpu.ops.packets import ip_to_u32, make_batch
from vpp_tpu.ops.pipeline import ROUTE_REMOTE, make_route_config, pipeline_step_jit
from vpp_tpu.policy.renderer.api import Action, ContivRule

import bench  # the config-5 stress builders live in bench.py


def _net(cidr):
    import ipaddress

    return ipaddress.ip_network(cidr, strict=False)


def _measure(acl, nat, route, batch, iters, rounds=3):
    """Steady-state pipelined Mpps for one jitted pipeline config.

    Best-of-``rounds``: the shared-TPU tunnel shows high run-to-run
    variance, and the max is the honest estimate of what the pipeline
    sustains when the link is not the bottleneck."""
    sessions = empty_sessions(1 << 16)
    result = pipeline_step_jit(acl, nat, route, sessions, batch, jnp.int32(0))
    result.allowed.block_until_ready()
    sessions = result.sessions
    best = 0.0
    ts = 0
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            ts += 1
            result = pipeline_step_jit(
                acl, nat, route, sessions, batch, jnp.int32(ts)
            )
            sessions = result.sessions
        result.allowed.block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        best = max(best, batch.src_ip.shape[0] / dt / 1e6)
    return best, result


def _report(config, metric, mpps):
    print(
        json.dumps(
            {
                "config": config,
                "metric": metric,
                "value": round(mpps, 1),
                "unit": "Mpps",
                "gbps_64b": round(mpps * 64 * 8 / 1e3, 1),
                "gbps_1500b": round(mpps * 1500 * 8 / 1e3, 1),
                "vs_baseline": round(mpps / 40.0, 2),
            }
        ),
        flush=True,
    )


def _base_state(n_pods=8, mappings=(), rules=None, assignments=None):
    ipam = IPAM(IPAMConfig(), node_id=1)
    pod_ips = [f"10.1.1.{i + 2}" for i in range(n_pods)]
    tables = [rules] if rules else []
    assign = assignments if assignments is not None else {
        ip_to_u32(ip): (0, 0) if rules else (NO_TABLE, NO_TABLE)
        for ip in pod_ips
    }
    acl = build_rule_tables(tables, assign)
    nat = build_nat_tables(
        list(mappings),
        nat_loopback=str(ipam.nat_loopback_ip()),
        snat_ip="192.168.16.1",
        snat_enabled=True,
        pod_subnet=str(ipam.pod_subnet_all_nodes),
    )
    return ipam, pod_ips, acl, nat, make_route_config(ipam)


def config1(batch_size, iters):
    """Pod-to-pod forwarding, no policies (contiv-pod-perf analog)."""
    rng = random.Random(1)
    ipam, pod_ips, acl, nat, route = _base_state()
    flows = [
        (rng.choice(pod_ips), rng.choice(pod_ips), 6,
         rng.randrange(1024, 65535), 5201)  # iperf3 port
        for _ in range(batch_size)
    ]
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    _report(1, "pod-to-pod single node, no policies", mpps)


def config2(batch_size, iters):
    """~20-rule policy suite on the ACL path (tests/policy analog)."""
    rng = random.Random(2)
    rules = []
    for i in range(10):
        rules.append(
            ContivRule(
                action=Action.PERMIT,
                src_network=_net(f"10.1.{i}.0/24"),
                protocol=ProtocolType.TCP,
                dst_port=rng.choice([80, 443, 8080, 22]),
            )
        )
    for i in range(9):
        rules.append(
            ContivRule(
                action=Action.DENY,
                src_network=_net(f"192.168.{i}.0/24"),
                protocol=ProtocolType.UDP,
            )
        )
    rules.append(ContivRule(action=Action.DENY))
    ipam, pod_ips, acl, nat, route = _base_state(
        rules=rules,
        assignments={ip_to_u32(f"10.1.1.{i + 2}"): (0, 0) for i in range(8)},
    )
    flows = [
        (rng.choice(pod_ips), rng.choice(pod_ips), 6,
         rng.randrange(1024, 65535), rng.choice([80, 443, 22]))
        for _ in range(batch_size)
    ]
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    _report(2, "policy suite (~20 ACL rules)", mpps)


def config3(batch_size, iters):
    """ClusterIP with 8 backends through the NAT44 LB (lb-perf analog)."""
    rng = random.Random(3)
    backends = [(f"10.1.1.{i + 2}", 8080, 1) for i in range(8)]
    mapping = NatMapping("10.96.0.10", 80, 6, backends)
    ipam, pod_ips, acl, nat, route = _base_state(mappings=[mapping])
    flows = [
        (rng.choice(pod_ips), "10.96.0.10", 6, rng.randrange(1024, 65535), 80)
        for _ in range(batch_size)
    ]
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    assert bool(res.dnat_hit.all()), "all service flows must DNAT"
    _report(3, "ClusterIP, 8 backends, NAT44 LB", mpps)


def config4(batch_size, iters):
    """2-node overlay: remote pod traffic (VXLAN encap tags) + SNAT
    egress (two_node robot suites analog)."""
    rng = random.Random(4)
    ipam, pod_ips, acl, nat, route = _base_state()
    flows = []
    for i in range(batch_size):
        src = rng.choice(pod_ips)
        if i % 2 == 0:  # inter-node pod traffic -> node 2 subnet
            flows.append((src, f"10.1.2.{rng.randrange(2, 250)}", 6,
                          rng.randrange(1024, 65535), 5201))
        else:  # egress -> SNAT
            flows.append((src, f"{rng.randrange(20, 200)}.2.3.4", 6,
                          rng.randrange(1024, 65535), 443))
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    import numpy as np

    tags = np.asarray(res.route)
    assert (tags == ROUTE_REMOTE).sum() > 0, "expected VXLAN-bound flows"
    assert bool(res.snat_hit.any()), "expected SNAT egress flows"
    _report(4, "2-node VXLAN overlay + SNAT egress", mpps)


def config5(batch_size, iters):
    """The bench.py headline: 10k rules + 1k services stress."""
    acl, nat, route, sessions, pod_ips, mappings = bench.build_stress_state()
    batch = bench.build_traffic(pod_ips, mappings, batch_size)
    mpps, _ = _measure(acl, nat, route, batch, iters)
    _report(5, "10k ACL rules + 1k services stress", mpps)


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=int, choices=sorted(CONFIGS))
    parser.add_argument("--batch", type=int, default=16384)
    parser.add_argument("--iters", type=int, default=50)
    args = parser.parse_args()
    if args.config:
        CONFIGS[args.config](args.batch, args.iters)
        return
    # One subprocess per configuration.  The experimental remote-TPU
    # runtime degrades process-wide (~30x, permanently) after sustained
    # full-batch DNAT scatter workloads — measured: any config run after
    # config 3 in the same process drops from ~100 to ~1.5 Mpps, while
    # every config is fast standalone.  Process isolation keeps each
    # measurement honest.
    import subprocess
    import sys

    for key in sorted(CONFIGS):
        subprocess.run(
            [
                sys.executable, __file__,
                "--config", str(key),
                "--batch", str(args.batch),
                "--iters", str(args.iters),
            ],
            check=False,
        )


if __name__ == "__main__":
    main()
