"""Benchmark suite — all five BASELINE.md configurations.

``bench.py`` is the driver-run headline (config 5, the 10k-rule +
1k-service stress).  This suite reproduces the remaining reference
harnesses on the TPU data plane:

1. pod-to-pod, single node, no policies   (scripts/contiv-pod-perf.sh)
2. ~20-rule NetworkPolicy suite, ACL path (tests/policy suite)
3. ClusterIP with 8 backends, NAT44 LB    (scripts/lb-perf-test.sh)
4. 2-node VXLAN overlay + SNAT egress     (two_node robot suites)
5. 10k rules + 1k services stress         (tests/policy/perf/gen-policy.py)

Usage: ``python benchsuite.py [--config N] [--batch B] [--iters I]``.
Prints one JSON line per configuration:
    {"config": k, "metric": ..., "value": N, "unit": "Mpps",
     "gbps_64b": ..., "gbps_1500b": ..., "vs_baseline": N}

vs_baseline is Mpps/40 against BASELINE.json's >=40 Mpps ACL+NAT44
target (VPP/DPDK parity on a 16-core Xeon).
"""

import argparse
import json
import random
import time

import jax.numpy as jnp

from vpp_tpu.conf import IPAMConfig
from vpp_tpu.ipam import IPAM
from vpp_tpu.models import ProtocolType
from vpp_tpu.ops.classify import NO_TABLE, build_rule_tables
from vpp_tpu.ops.nat import NatMapping, build_nat_tables, empty_sessions
from vpp_tpu.ops.packets import ip_to_u32, make_batch
from vpp_tpu.ops.pipeline import (
    ROUTE_REMOTE,
    make_route_config,
    pipeline_step_jit,
    unpack_verdicts,
)
from vpp_tpu.policy.renderer.api import Action, ContivRule

import bench  # the config-5 stress builders live in bench.py


def _net(cidr):
    import ipaddress

    return ipaddress.ip_network(cidr, strict=False)


def _measure(acl, nat, route, batch, iters, rounds=3, step=None):
    """Steady-state pipelined Mpps for one pipeline config, using the
    production dispatch discipline (datapath/runner.py): the flat batch
    is split into 256-packet vectors and dispatched with the flat-safe
    discipline (batch-parallel with post-commit same-dispatch-reply
    reconciliation; pass ``step=pipeline_scan_ts0_jit`` for the sequential
    scan).  Returns (best_mpps, packed_result) — unpack verdict reads
    with ``_unpack`` AFTER every measurement is done (see main()'s
    deferred-verification note).

    Best-of-``rounds``: the shared-TPU tunnel shows high run-to-run
    variance, and the max is the honest estimate of what the pipeline
    sustains when the link is not the bottleneck."""
    import jax

    from vpp_tpu.ops.pipeline import (
        VECTOR_SIZE,
        pipeline_flat_safe_ts0_jit,
    )

    if step is None:
        step = pipeline_flat_safe_ts0_jit
    n = batch.src_ip.shape[0]
    assert n % VECTOR_SIZE == 0, "bench batches must be vector multiples"
    k = n // VECTOR_SIZE
    batches = jax.tree_util.tree_map(lambda a: a.reshape(k, VECTOR_SIZE), batch)
    sessions = empty_sessions(1 << 16)
    # Scalar base-ts entry points: the ts vector is built on device (a
    # host-side arange per dispatch is an extra tunnel round trip,
    # measured at a 40-100% tax in r4), and leaves come back flat.
    result = step(acl, nat, route, sessions, batches, jnp.int32(0))
    result.packed.block_until_ready()
    sessions = result.sessions
    best = 0.0
    ts = k
    for _ in range(rounds):
        t0 = time.perf_counter()
        for _ in range(iters):
            result = step(acl, nat, route, sessions, batches, jnp.int32(ts))
            ts += k
            sessions = result.sessions
        result.packed.block_until_ready()
        dt = (time.perf_counter() - t0) / iters
        best = max(best, n / dt / 1e6)
    return best, result


def _unpack(packed_result):
    """Verify-time host unpack of one packed dispatch result (pays the
    D2H transfer — call only after every measurement is done)."""
    import numpy as np

    return unpack_verdicts(np.asarray(packed_result.packed))


def _report(config, metric, mpps):
    print(
        json.dumps(
            {
                "config": config,
                "metric": metric,
                "value": round(mpps, 1),
                "unit": "Mpps",
                "gbps_64b": round(mpps * 64 * 8 / 1e3, 1),
                "gbps_1500b": round(mpps * 1500 * 8 / 1e3, 1),
                "vs_baseline": round(mpps / 40.0, 2),
            }
        ),
        flush=True,
    )


def _base_state(n_pods=8, mappings=(), rules=None, assignments=None):
    ipam = IPAM(IPAMConfig(), node_id=1)
    pod_ips = [f"10.1.1.{i + 2}" for i in range(n_pods)]
    tables = [rules] if rules else []
    assign = assignments if assignments is not None else {
        ip_to_u32(ip): (0, 0) if rules else (NO_TABLE, NO_TABLE)
        for ip in pod_ips
    }
    acl = build_rule_tables(tables, assign)
    nat = build_nat_tables(
        list(mappings),
        nat_loopback=str(ipam.nat_loopback_ip()),
        snat_ip="192.168.16.1",
        snat_enabled=True,
        pod_subnet=str(ipam.pod_subnet_all_nodes),
    )
    return ipam, pod_ips, acl, nat, make_route_config(ipam)


def config1(batch_size, iters):
    """Pod-to-pod forwarding, no policies (contiv-pod-perf analog)."""
    rng = random.Random(1)
    ipam, pod_ips, acl, nat, route = _base_state()
    flows = [
        (rng.choice(pod_ips), rng.choice(pod_ips), 6,
         rng.randrange(1024, 65535), 5201)  # iperf3 port
        for _ in range(batch_size)
    ]
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    _report(1, "pod-to-pod single node, no policies", mpps)

    def verify():
        assert bool(_unpack(res).allowed.all()), \
            "pod-to-pod with no policies must pass"
    return verify


def config2(batch_size, iters):
    """~20-rule policy suite on the ACL path (tests/policy analog)."""
    rng = random.Random(2)
    rules = []
    for i in range(10):
        rules.append(
            ContivRule(
                action=Action.PERMIT,
                src_network=_net(f"10.1.{i}.0/24"),
                protocol=ProtocolType.TCP,
                dst_port=rng.choice([80, 443, 8080, 22]),
            )
        )
    for i in range(9):
        rules.append(
            ContivRule(
                action=Action.DENY,
                src_network=_net(f"192.168.{i}.0/24"),
                protocol=ProtocolType.UDP,
            )
        )
    rules.append(ContivRule(action=Action.DENY))
    ipam, pod_ips, acl, nat, route = _base_state(
        rules=rules,
        assignments={ip_to_u32(f"10.1.1.{i + 2}"): (0, 0) for i in range(8)},
    )
    flows = [
        (rng.choice(pod_ips), rng.choice(pod_ips), 6,
         rng.randrange(1024, 65535), rng.choice([80, 443, 22]))
        for _ in range(batch_size)
    ]
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    _report(2, "policy suite (~20 ACL rules)", mpps)

    def verify():
        assert bool(_unpack(res).allowed.any()), "some flows match PERMIT rules"
    return verify


def config3(batch_size, iters):
    """ClusterIP with 8 backends through the NAT44 LB (lb-perf analog)."""
    rng = random.Random(3)
    backends = [(f"10.1.1.{i + 2}", 8080, 1) for i in range(8)]
    mapping = NatMapping("10.96.0.10", 80, 6, backends)
    ipam, pod_ips, acl, nat, route = _base_state(mappings=[mapping])
    flows = [
        (rng.choice(pod_ips), "10.96.0.10", 6, rng.randrange(1024, 65535), 80)
        for _ in range(batch_size)
    ]
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    _report(3, "ClusterIP, 8 backends, NAT44 LB", mpps)

    def verify():
        assert bool(_unpack(res).dnat_hit.all()), "all service flows must DNAT"
    return verify


def config4(batch_size, iters):
    """2-node overlay: remote pod traffic (VXLAN encap tags) + SNAT
    egress (two_node robot suites analog)."""
    rng = random.Random(4)
    ipam, pod_ips, acl, nat, route = _base_state()
    flows = []
    for i in range(batch_size):
        src = rng.choice(pod_ips)
        if i % 2 == 0:  # inter-node pod traffic -> node 2 subnet
            flows.append((src, f"10.1.2.{rng.randrange(2, 250)}", 6,
                          rng.randrange(1024, 65535), 5201))
        else:  # egress -> SNAT
            flows.append((src, f"{rng.randrange(20, 200)}.2.3.4", 6,
                          rng.randrange(1024, 65535), 443))
    mpps, res = _measure(acl, nat, route, make_batch(flows), iters)
    _report(4, "2-node VXLAN overlay + SNAT egress", mpps)

    def verify():
        v = _unpack(res)
        assert bool((v.route == ROUTE_REMOTE).any()), "expected VXLAN-bound flows"
        assert bool(v.snat_hit.any()), "expected SNAT egress flows"
    return verify


def config5(batch_size, iters):
    """The bench.py headline: 10k rules + 1k services stress."""
    acl, nat, route, sessions, pod_ips, mappings = bench.build_stress_state()
    batch = bench.build_traffic(pod_ips, mappings, batch_size)
    mpps, res = _measure(acl, nat, route, batch, iters)
    _report(5, "10k ACL rules + 1k services stress", mpps)

    def verify():
        v = _unpack(res)
        assert bool(v.dnat_hit.any()) and bool(v.snat_hit.any())
    return verify


CONFIGS = {1: config1, 2: config2, 3: config3, 4: config4, 5: config5}


def sweep(iters):
    """Mpps vs dispatch size on the config-5 stress state, comparing the
    flat single-batch dispatch against the production vector-scan
    dispatch (K 256-pkt vectors per device program).  Answers the
    round-1 question "what does the 256-packet regime cost?":
    the scan dispatch recovers small-vector semantics at large-batch
    throughput because sessions thread on device instead of bouncing
    through per-dispatch host round-trips."""
    import jax

    from vpp_tpu.ops.pipeline import (
        VECTOR_SIZE, pipeline_scan_ts0_jit,
    )

    acl, nat, route, _, pod_ips, mappings = bench.build_stress_state()
    for n in (256, 1024, 4096, 16384, 65536):
        batch = bench.build_traffic(pod_ips, mappings, n)
        # Flat dispatch: one n-wide batch per device call.
        sessions = empty_sessions(1 << 16)
        r = pipeline_step_jit(acl, nat, route, sessions, batch, jnp.int32(0))
        r.packed.block_until_ready()
        sessions = r.sessions
        it = max(20, min(400, 16384 * iters // n))
        flat_best, ts = 0.0, 0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(it):
                ts += 1
                r = pipeline_step_jit(acl, nat, route, sessions, batch, jnp.int32(ts))
                sessions = r.sessions
            r.packed.block_until_ready()
            flat_best = max(flat_best, n / ((time.perf_counter() - t0) / it) / 1e6)
        # Vector-scan dispatch: n/256 vectors per device call.
        k = n // VECTOR_SIZE
        batches = jax.tree_util.tree_map(lambda a: a.reshape(k, VECTOR_SIZE), batch)
        sessions = empty_sessions(1 << 16)
        r = pipeline_scan_ts0_jit(
            acl, nat, route, sessions, batches, jnp.int32(0)
        )
        r.packed.block_until_ready()
        sessions = r.sessions
        scan_best, ts = 0.0, k
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(it):
                r = pipeline_scan_ts0_jit(acl, nat, route, sessions, batches,
                                          jnp.int32(ts))
                ts += k
                sessions = r.sessions
            r.packed.block_until_ready()
            scan_best = max(scan_best, n / ((time.perf_counter() - t0) / it) / 1e6)
        # Flat-safe dispatch (production): batch-parallel + reconcile.
        safe_best, _ = _measure(acl, nat, route, batch, it)
        # Flat-punt (round-cut): straggler restores punted to the host.
        from vpp_tpu.ops.pipeline import pipeline_flat_punt_ts0_jit

        punt_best, _ = _measure(acl, nat, route, batch, it,
                                step=pipeline_flat_punt_ts0_jit)
        print(
            json.dumps(
                {
                    "sweep": "config5",
                    "dispatch_pkts": n,
                    "vectors": k,
                    "flat_mpps": round(flat_best, 2),
                    "scan_mpps": round(scan_best, 2),
                    "safe_mpps": round(safe_best, 2),
                    "punt_mpps": round(punt_best, 2),
                }
            ),
            flush=True,
        )


def latency(iters):
    """Latency-budgeted view of the dispatch-size tradeoff (VERDICT r2
    item 2).  For each dispatch size, measures the per-dispatch latency
    distribution (p50/p99 µs of dispatch + completion, no D2H) for both
    disciplines, alongside the pipelined throughput the sweep measures,
    and derives the batching (coalesce-fill) delay the dispatch size
    implies at 1/10/40 Mpps offered load: a K-vector dispatch cannot
    leave before K*256 packets have arrived, so its worst-case added
    latency at offered load L is fill(=pkts/L) + dispatch p50.

    The spec bar (SURVEY §7.3, <<6 us per 256-pkt batch) is a
    same-host-memory figure; across a host<->TPU link the honest
    budget is the measured dispatch latency itself — reported here so
    the headline can be stated as "X Mpps within Y us" and the
    coalesce governor's SLO default (and ceiling) is chosen from
    data (the static max_vectors pick this sweep used to anchor is
    now the governor's per-admit decision)."""
    import jax

    from vpp_tpu.ops.pipeline import (
        VECTOR_SIZE, pipeline_flat_punt_ts0_jit, pipeline_flat_safe_ts0_jit,
        pipeline_scan_ts0_jit,
    )

    acl, nat, route, _, pod_ips, mappings = bench.build_stress_state()
    n_lat_samples = max(100, min(300, iters * 2))  # p99 needs >=100
    for n in (256, 1024, 4096, 16384, 65536):
        batch = bench.build_traffic(pod_ips, mappings, n)
        k = n // VECTOR_SIZE
        batches = jax.tree_util.tree_map(lambda a: a.reshape(k, VECTOR_SIZE), batch)
        for disc in ("flat", "scan", "flat-safe", "flat-punt"):
            sessions = empty_sessions(1 << 16)
            ts = 0

            def dispatch():
                nonlocal sessions, ts
                if disc == "flat":
                    r = pipeline_step_jit(acl, nat, route, sessions, batch,
                                          jnp.int32(ts))
                    ts += 1
                else:
                    step = (pipeline_flat_safe_ts0_jit if disc == "flat-safe"
                            else pipeline_flat_punt_ts0_jit
                            if disc == "flat-punt"
                            else pipeline_scan_ts0_jit)
                    r = step(acl, nat, route, sessions, batches, jnp.int32(ts))
                    ts += k
                sessions = r.sessions
                return r.packed

            p50_s, p99_s, p999_s = bench.sample_dispatch_latency(
                dispatch, samples=n_lat_samples
            )
            p50, p99, p999 = p50_s * 1e6, p99_s * 1e6, p999_s * 1e6
            print(
                json.dumps(
                    {
                        "lat": "config5",
                        "dispatch_pkts": n,
                        "vectors": k,
                        "discipline": disc,
                        "p50_us": round(p50, 1),
                        "p99_us": round(p99, 1),
                        "p999_us": round(p999, 1),
                        "single_dispatch_mpps": round(n / p50, 2),
                        # Coalesce-fill delay: the time the FIRST packet
                        # of a dispatch waits for the batch to fill.
                        "fill_us_at_1mpps": round(n / 1.0, 1),
                        "fill_us_at_10mpps": round(n / 10.0, 1),
                        "fill_us_at_40mpps": round(n / 40.0, 1),
                        "worst_added_latency_us_at_40mpps": round(n / 40.0 + p50, 1),
                    }
                ),
                flush=True,
            )


def scale(iters):
    """Classify scale (VERDICT r1 #6): 64k ACL rules + 4k pods + 1k
    services through the FULL pipeline, Pallas-tiled first-match vs the
    dense [B, N] path (VPP_TPU_FORCE_DENSE A/B), production vector-scan
    dispatch."""
    import ipaddress
    import os

    import jax

    from vpp_tpu.ops.pipeline import make_route_config

    rng = random.Random(6)
    ipam = IPAM(IPAMConfig(), node_id=1)
    rules = []
    for _ in range(65535):
        net = ipaddress.ip_network(
            f"10.{rng.randrange(256)}.{rng.randrange(256)}.0/{rng.choice([16, 20, 24, 28])}",
            strict=False,
        )
        rules.append(
            ContivRule(
                action=Action.PERMIT if rng.random() < 0.9 else Action.DENY,
                src_network=net,
                protocol=ProtocolType.TCP if rng.random() < 0.7 else ProtocolType.UDP,
                dst_port=rng.choice([0, 80, 443, 8080, 53]),
            )
        )
    rules.append(ContivRule(action=Action.DENY))
    pod_ips = set()
    while len(pod_ips) < 4096:
        pod_ips.add(f"10.1.{rng.randrange(1, 64)}.{rng.randrange(2, 250)}")
    pod_ips = sorted(pod_ips)
    acl = build_rule_tables([rules], {ip_to_u32(ip): (0, 0) for ip in pod_ips})
    _, _, _, nat, _ = _base_state()
    route = make_route_config(ipam)
    flows = [
        (rng.choice(pod_ips), rng.choice(pod_ips), 6,
         rng.randrange(1024, 65535), rng.choice([80, 443]))
        for _ in range(16384)
    ]
    batch = make_batch(flows)

    def report(variant, mpps):
        print(
            json.dumps(
                {
                    "scale": "64k rules, 4k pods, full pipeline",
                    "variant": variant,
                    "value": round(mpps, 1),
                    "unit": "Mpps",
                    "vs_baseline": round(mpps / 40.0, 2),
                }
            ),
            flush=True,
        )

    # Production dispatch (flat-safe: batch-parallel + reconcile) and
    # the sequential vector-scan for comparison.
    mpps, _ = _measure(acl, nat, route, batch, iters)
    report("flat-safe", mpps)
    from vpp_tpu.ops.pipeline import pipeline_scan_ts0_jit

    mpps, _ = _measure(acl, nat, route, batch, iters, step=pipeline_scan_ts0_jit)
    report("vector-scan", mpps)

    # Wide flat dispatch: pallas vs dense A/B at [16384, 64k].
    for label, force in (("flat-pallas", ""), ("flat-dense", "1")):
        os.environ["VPP_TPU_FORCE_DENSE"] = force
        jax.clear_caches()
        sessions = empty_sessions(1 << 16)
        r = pipeline_step_jit(acl, nat, route, sessions, batch, jnp.int32(0))
        r.packed.block_until_ready()
        sessions = r.sessions
        best, ts = 0.0, 0
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(iters):
                ts += 1
                r = pipeline_step_jit(acl, nat, route, sessions, batch, jnp.int32(ts))
                sessions = r.sessions
            r.packed.block_until_ready()
            best = max(best, len(flows) / ((time.perf_counter() - t0) / iters) / 1e6)
        report(label, best)
    os.environ.pop("VPP_TPU_FORCE_DENSE", None)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", type=int, choices=sorted(CONFIGS))
    parser.add_argument("--batch", type=int, default=16384)
    parser.add_argument("--iters", type=int, default=50)
    parser.add_argument("--sweep", action="store_true",
                        help="Mpps vs dispatch size: flat / scan / flat-safe")
    parser.add_argument("--latency", action="store_true",
                        help="p50/p99 us per dispatch + coalesce-fill "
                             "delay at 1/10/40 Mpps offered load")
    parser.add_argument("--scale", action="store_true",
                        help="64k-rule / 4k-pod scale, pallas vs dense")
    parser.add_argument("--isolate", action="store_true",
                        help="one subprocess per config")
    args = parser.parse_args()
    if args.sweep:
        sweep(args.iters)
        return
    if args.latency:
        latency(args.iters)
        return
    if args.scale:
        scale(args.iters)
        return
    if args.config:
        verify = CONFIGS[args.config](args.batch, args.iters)
        verify()
        return
    if args.isolate:
        # --isolate remains for diagnosing runtime regressions like the
        # one below; in-process is the default.
        import subprocess
        import sys

        for key in sorted(CONFIGS):
            subprocess.run(
                [
                    sys.executable, __file__,
                    "--config", str(key),
                    "--batch", str(args.batch),
                    "--iters", str(args.iters),
                ],
                check=False,
            )
        return
    # Measure every config FIRST, verify afterwards.  Root cause of round
    # 1's "process-wide ~30x collapse after sustained DNAT workloads"
    # (diagnosed round 2, see scripts/tunnel_d2h_probe.py): on the
    # experimental axon-tunnel runtime, the FIRST device-to-host value
    # transfer of ANY kind — a 0-d bool(x.any()) scalar included —
    # permanently switches the process into a degraded dispatch mode
    # (~60 Mpps -> ~1 Mpps).  Only block_until_ready() and H2D transfers
    # are safe.  It was never a leak in this framework: the trigger was
    # the configs' result-verification fetches, which are therefore
    # deferred until every measurement is done.
    verifies = [(key, CONFIGS[key](args.batch, args.iters)) for key in sorted(CONFIGS)]
    for key, verify in verifies:
        verify()
    print(json.dumps({"verified_configs": [k for k, _ in verifies]}), flush=True)


if __name__ == "__main__":
    main()
