from .config import IPAMConfig, InterfaceConfig, RoutingConfig, NetworkConfig

__all__ = ["IPAMConfig", "InterfaceConfig", "RoutingConfig", "NetworkConfig"]
