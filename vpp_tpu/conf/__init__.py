from .config import (
    IPAMConfig,
    InterfaceConfig,
    NetworkConfig,
    OtherInterface,
    RoutingConfig,
)

__all__ = [
    "IPAMConfig", "InterfaceConfig", "OtherInterface",
    "RoutingConfig", "NetworkConfig",
]
