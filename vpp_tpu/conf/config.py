"""Framework configuration.

Analog of the reference's ContivConf plugin (plugins/contivconf/
contivconf_api.go: IPAMConfig :100, InterfaceConfig, RoutingConfig) with
the same defaults (contivconf.go:74-79 and k8s/contiv-vpp.yaml:42-45).
The reference merges four config sources by priority (file < NodeConfig
CRD < STN-reported < runtime); here the file/dict source is implemented
and the merge hook is ``NetworkConfig.overlay`` for CRD-style per-node
overrides.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass, field, replace
from typing import Optional, Tuple


def _net(cidr: str) -> ipaddress.IPv4Network:
    return ipaddress.ip_network(cidr)


@dataclass(frozen=True)
class IPAMConfig:
    """Address-space layout of the cluster (contivconf_api.go IPAMConfig)."""

    # Subnet used by all pods across all nodes; each node gets a
    # /pod_subnet_one_node_prefix_len chunk of it, indexed by node ID.
    pod_subnet_cidr: str = "10.1.0.0/16"
    pod_subnet_one_node_prefix_len: int = 24

    # Subnet for data-plane<->host interconnects of all nodes.
    host_subnet_cidr: str = "172.30.0.0/16"
    host_subnet_one_node_prefix_len: int = 24

    # Subnet from which node IPs are computed when not supplied externally.
    node_interconnect_cidr: str = "192.168.16.0/24"
    # True when node IPs come from the underlying infrastructure (DHCP)
    # rather than from node_interconnect_cidr arithmetic.
    node_interconnect_dhcp: bool = False

    # Subnet for VXLAN-tunnel source/destination endpoints (BVI IPs).
    vxlan_cidr: str = "192.168.30.0/24"

    # K8s service virtual IPs.
    service_cidr: str = "10.96.0.0/12"

    # IPs inside node_interconnect_cidr that must never be allocated
    # (e.g. the default gateway).
    excluded_node_ips: Tuple[str, ...] = ()

    def pod_subnet(self) -> ipaddress.IPv4Network:
        return _net(self.pod_subnet_cidr)

    def host_subnet(self) -> ipaddress.IPv4Network:
        return _net(self.host_subnet_cidr)

    def node_interconnect(self) -> ipaddress.IPv4Network:
        return _net(self.node_interconnect_cidr)

    def vxlan(self) -> ipaddress.IPv4Network:
        return _net(self.vxlan_cidr)

    def service(self) -> ipaddress.IPv4Network:
        return _net(self.service_cidr)


@dataclass(frozen=True)
class OtherInterface:
    """A non-main physical data-plane interface (contivconf_api.go
    GetOtherVPPInterfaces :574, sourced from NodeConfig
    OtherVPPInterfaces)."""

    name: str
    ip: str = ""          # CIDR; empty with use_dhcp=False = unnumbered
    use_dhcp: bool = False


@dataclass(frozen=True)
class InterfaceConfig:
    """Main data-plane interface settings (contivconf_api.go InterfaceConfig)."""

    main_interface: str = ""
    mtu: int = 1450
    # Steal-the-NIC mode: the single host NIC is taken over by the
    # data plane.
    stn_mode: bool = False
    # Acquire the main-interface IP via DHCP instead of IPAM arithmetic
    # (contivconf_api.go UseDHCP :32-36 / NodeInterconnectDHCP :118-120).
    use_dhcp: bool = False
    # Non-main physical interfaces to configure (NodeConfig
    # OtherVPPInterfaces via the priority merge).
    other_interfaces: Tuple["OtherInterface", ...] = ()


@dataclass(frozen=True)
class RoutingConfig:
    """Routing behavior knobs (contivconf_api.go RoutingConfig)."""

    # Use a VXLAN overlay between nodes (vs direct L3 when the fabric
    # routes pod subnets natively).
    use_vxlan: bool = True
    # VRF IDs for the two-VRF layout (main + pod).
    main_vrf_id: int = 0
    pod_vrf_id: int = 1
    # Route service CIDR traffic from the host into the data plane.
    route_service_cidr_to_dataplane: bool = False


@dataclass(frozen=True)
class NetworkConfig:
    """Top-level configuration (the contiv.conf analog)."""

    ipam: IPAMConfig = field(default_factory=IPAMConfig)
    interface: InterfaceConfig = field(default_factory=InterfaceConfig)
    routing: RoutingConfig = field(default_factory=RoutingConfig)
    # NAT-pipeline vector size: packets per classify->rewrite vector
    # (VPP's vector size).
    batch_size: int = 256
    # Coalesce CEILING: the most vectors the runner may fuse into one
    # device program (pow2-floored; sessions thread vector-to-vector
    # on device).  The per-admit pick under it comes from the coalesce
    # governor, so the ceiling sits in the capability band (256) —
    # VPP's adaptive vector size, not a fixed operating point.
    max_vectors: int = 256
    # Multi-vector dispatch discipline: "auto" picks from the measured
    # per-backend orderings (as of r4: flat-safe on every backend —
    # the commit-first restructure reversed r3's CPU ordering, see
    # FRAMEBENCH_r04); explicit "scan" / "flat-safe" / "flat-punt"
    # override per node, the same trace-time pattern as the NAT
    # lookup-discipline gate (use_hmap).  "flat-punt" cuts the
    # straggler-restore round off flat-safe's session-sync chain and
    # punts detected same-dispatch replies to the host slow path —
    # the right pick on GSPMD meshes and round-trip-bound tunnels
    # (docs/ARCHITECTURE.md "Dispatch round chain").
    dispatch: str = "auto"
    # Coalesce governor: "adaptive" picks the per-admit pow2 K from
    # the measured ingress backlog under the added-latency SLO below;
    # "fixed" restores the static cap (always admit up to the ceiling).
    coalesce: str = "adaptive"
    # Added-latency budget (µs) the governor holds when the link is
    # not saturated: the r5 latency record's production budget (K=64
    # worst added latency ~559 µs at the 40 Mpps reference load).
    coalesce_slo_us: float = 600.0
    # Compile every pow2 K bucket up to the ceiling at start and on
    # every table swap, so a load spike never stalls on the jit.
    coalesce_prewarm: bool = True
    # In-flight dispatch window: outstanding device dispatches the host
    # may run ahead of the oldest unharvested batch.
    max_inflight: int = 2
    # Many-core host ingress (ISSUE 12): number of host-side datapath
    # shards.  1 = the solo runner; N > 1 builds a ShardedDataplane
    # with N per-shard ring arenas fed by N PACKET_FANOUT sockets on
    # the uplink (kernel flow-hash multi-queue), N admit worker
    # threads, and ONE shared device session state.  The N per-shard
    # coalesce governors share coalesce_slo_us through a global-budget
    # ledger — the added-latency SLO stays a NODE budget, not N
    # budgets.
    datapath_shards: int = 1
    # Opt-in CPU affinity map, shard i → core set (VPP's
    # corelist-workers analog): semicolon-separated per-shard core
    # lists ("0-3;4-7;8,9"), or "auto" to spread the process's usable
    # cores evenly across shards, or "" for no pinning (default).
    shard_cores: str = ""
    # In-network inference plane (ISSUE 14): register the InferPolicy
    # event handler + applicator so CRD writes can enable per-vector
    # DNN scoring per namespace.  The subsystem is dormant (the scoring
    # stage compiles away) until a policy enrolls a namespace; this
    # knob removes even the control-plane surface.
    inference: bool = True

    @classmethod
    def from_dict(cls, data: Optional[dict]) -> "NetworkConfig":
        data = data or {}
        iface_data = dict(data.get("interface", {}))
        others = tuple(
            OtherInterface(**d) for d in iface_data.pop("other_interfaces", [])
        )
        return cls(
            ipam=IPAMConfig(**data.get("ipam", {})),
            interface=InterfaceConfig(other_interfaces=others, **iface_data),
            routing=RoutingConfig(**data.get("routing", {})),
            batch_size=data.get("batch_size", 256),
            max_vectors=data.get("max_vectors", 256),
            dispatch=data.get("dispatch", "auto"),
            coalesce=data.get("coalesce", "adaptive"),
            coalesce_slo_us=data.get("coalesce_slo_us", 600.0),
            coalesce_prewarm=data.get("coalesce_prewarm", True),
            max_inflight=data.get("max_inflight", 2),
            datapath_shards=data.get("datapath_shards", 1),
            shard_cores=data.get("shard_cores", ""),
            inference=data.get("inference", True),
        )

    def overlay(self, **kw) -> "NetworkConfig":
        """Per-node override merge (NodeConfig-CRD analog)."""
        return replace(self, **kw)
