"""PodManager — the CNI entry point into the event loop.

Analog of ``plugins/podmanager``: CNI Add/Del requests are wrapped into
*blocking* AddPod/DeletePod events (podmanager.go Add :240 / Delete
:275); the handler records LocalPods (container ID + network
namespace).  AddPod uses RevertOnFailure + Forward direction, DeletePod
is BestEffort + Reverse (podmanager_api.go:70,178) so connectivity is
torn down in the opposite order it was built.

Downstream handlers (ipv4net) fill ``event.interfaces`` / ``event.routes``
during processing — those become the CNI reply (cniReplyForAddPod :289).
"""

from __future__ import annotations

import logging
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..controller.api import EventHandler, UpdateDirection, UpdateEvent, UpdateTxnType
from ..models import PodID

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class LocalPod:
    """A pod deployed on this node (podmanager_api.go LocalPod :37)."""

    id: PodID
    container_id: str = ""
    network_namespace: str = ""


@dataclass
class PodCNIReply:
    """What the CNI caller gets back: allocated interfaces and routes."""

    interfaces: List[dict] = field(default_factory=list)
    routes: List[dict] = field(default_factory=list)
    ip_address: str = ""


class AddPod(UpdateEvent):
    """Blocking CNI-Add event (podmanager_api.go AddPod :70)."""

    name = "Add Pod"

    def __init__(self, pod: LocalPod):
        super().__init__(blocking=True)
        self.pod = pod
        self.reply = PodCNIReply()

    @property
    def direction(self) -> UpdateDirection:
        return UpdateDirection.FORWARD

    @property
    def transaction_type(self) -> UpdateTxnType:
        return UpdateTxnType.REVERT_ON_FAILURE

    def __str__(self) -> str:
        return f"{self.name} [{self.pod.id}]"


class DeletePod(UpdateEvent):
    """Blocking CNI-Del event (podmanager_api.go DeletePod :178)."""

    name = "Delete Pod"

    def __init__(self, pod_id: PodID):
        super().__init__(blocking=True)
        self.pod_id = pod_id

    @property
    def direction(self) -> UpdateDirection:
        return UpdateDirection.REVERSE

    @property
    def transaction_type(self) -> UpdateTxnType:
        return UpdateTxnType.BEST_EFFORT

    def __str__(self) -> str:
        return f"{self.name} [{self.pod_id}]"


@dataclass
class Sandbox:
    """One pod sandbox container as reported by the container runtime
    (the docker.APIContainers + InspectContainer fields the reference
    consumes, podmanager.go Resync :137-200)."""

    container_id: str
    pod_name: str = ""
    pod_namespace: str = ""
    network_namespace: str = ""
    state: str = "running"
    pid: int = 1  # 0 = bare sandbox without a process


class ContainerRuntime:
    """Runtime client interface (the Docker-client analog)."""

    def list_sandboxes(self) -> List[Sandbox]:
        raise NotImplementedError


class PodManager(EventHandler):
    """Tracks local pods; front end for CNI requests."""

    name = "podmanager"

    def __init__(self, event_loop=None, runtime: Optional[ContainerRuntime] = None):
        self.event_loop = event_loop
        # Container-runtime client used to re-learn local pods on resync;
        # None = CNI-registration only (pods re-register via repeated Adds).
        self.runtime = runtime
        self._local_pods: Dict[PodID, LocalPod] = {}
        # Drain gate (ISSUE 13): flipped by the DrainCoordinator (REST
        # thread), read by the CNI service threads before any event is
        # pushed.
        self._draining = False  # lock-free: GIL-atomic bool flip; an ADD racing the flip lands on one side of it exactly like an ADD racing the operator's drain command
        self._drain_gate = None  # lock-free: set/cleared together with _draining (same single-writer flip); the coordinator's rejection counter rides it

    # ------------------------------------------------------------ CNI facade

    def add_pod(
        self,
        name: str,
        namespace: str = "default",
        container_id: str = "",
        network_namespace: str = "",
        timeout: float = 30.0,
    ) -> PodCNIReply:
        """The CNI-Add RPC: push a blocking AddPod event and wait.

        Raises the processing error on failure (the CNI binary then
        reports the error back to kubelet).
        """
        if self._draining:
            gate = self._drain_gate
            if gate is not None:
                gate()  # raises NodeDraining AND counts the rejection
            from ..controller.drain import NodeDraining

            raise NodeDraining()
        pod = LocalPod(
            id=PodID(name=name, namespace=namespace),
            container_id=container_id,
            network_namespace=network_namespace,
        )
        event = AddPod(pod)
        self.event_loop.push_event(event)
        err = event.wait(timeout)
        if err is not None:
            raise err
        return event.reply

    def set_draining(self, draining: bool, gate=None) -> None:
        """Gate/ungate new CNI ADDs (the DrainCoordinator's hook).
        ``gate`` is the coordinator's rejecting callable (raises
        NodeDraining and counts it).  DELs are never gated — drain
        exists so pods can leave."""
        self._drain_gate = gate if draining else None
        self._draining = bool(draining)

    def delete_pod(self, name: str, namespace: str = "default", timeout: float = 30.0) -> None:
        """The CNI-Del RPC. Idempotent per CNI spec — and deliberately
        NOT drain-gated (teardown must work on a draining node)."""
        event = DeletePod(PodID(name=name, namespace=namespace))
        self.event_loop.push_event(event)
        err = event.wait(timeout)
        if err is not None:
            raise err

    # --------------------------------------------------------------- queries

    @property
    def local_pods(self) -> Dict[PodID, LocalPod]:
        return dict(self._local_pods)

    def get_local_pod(self, pod_id: PodID) -> Optional[LocalPod]:
        return self._local_pods.get(pod_id)

    # ------------------------------------------------------- event handling

    def handles_event(self, event) -> bool:
        return isinstance(event, (AddPod, DeletePod)) or event.method.is_resync

    def resync(self, event, kube_state, resync_count, txn) -> None:
        """Re-learn local pods from the container runtime (podmanager.go
        Resync :137-200): list sandbox containers, skip non-running /
        unlabeled / bare ones, rebuild the LocalPods map.  Like the
        reference, only the first resync and healing resyncs re-read the
        runtime (pods cannot appear without the agent knowing otherwise);
        a runtime listing failure is fatal (agent restart + retry)."""
        from ..controller.api import FatalError, HealingResync

        if self.runtime is None:
            return
        if resync_count > 1 and not isinstance(event, HealingResync):
            return
        try:
            sandboxes = self.runtime.list_sandboxes()
        except Exception as e:  # noqa: BLE001 - runtime down is fatal
            raise FatalError(f"failed to list sandbox containers: {e}")
        pods: Dict[PodID, LocalPod] = {}
        for sb in sandboxes:
            if sb.state != "running":
                continue
            if not sb.pod_name or not sb.pod_namespace:
                log.warning("sandbox %s missing pod identification", sb.container_id)
                continue
            if not sb.pid:
                continue  # bare sandbox without a process
            pod_id = PodID(name=sb.pod_name, namespace=sb.pod_namespace)
            pods[pod_id] = LocalPod(
                id=pod_id,
                container_id=sb.container_id,
                network_namespace=sb.network_namespace or f"/proc/{sb.pid}/ns/net",
            )
        self._local_pods = pods

    def update(self, event, txn) -> str:
        if isinstance(event, AddPod):
            # Remember what we overwrote so revert() can restore it (a
            # repeated CNI Add for the same pod replaces the sandbox).
            event._replaced = self._local_pods.get(event.pod.id)
            self._local_pods[event.pod.id] = event.pod
            return f"added local pod {event.pod.id}"
        if isinstance(event, DeletePod):
            removed = self._local_pods.pop(event.pod_id, None)
            return f"removed local pod {event.pod_id}" if removed else ""
        return ""

    def revert(self, event) -> None:
        if isinstance(event, AddPod):
            replaced = getattr(event, "_replaced", None)
            if replaced is not None:
                self._local_pods[event.pod.id] = replaced
            else:
                self._local_pods.pop(event.pod.id, None)
