from .podmanager import PodManager, AddPod, DeletePod, LocalPod

__all__ = ["PodManager", "AddPod", "DeletePod", "LocalPod"]
