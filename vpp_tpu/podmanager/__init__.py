from .podmanager import (
    AddPod,
    ContainerRuntime,
    DeletePod,
    LocalPod,
    PodManager,
    Sandbox,
)

__all__ = [
    "PodManager", "AddPod", "DeletePod", "LocalPod",
    "ContainerRuntime", "Sandbox",
]
