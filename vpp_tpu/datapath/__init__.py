"""Packet datapath — the runner that turns the jit pipeline into a
dataplane (frames in → classify/NAT on TPU → frames out).

The analog of the reference's DPDK→VPP fast path (vpp.env:1-3,
docker/vpp-vswitch/dev/Dockerfile:1-16): continuous frame ingest,
double-buffered batches through the TPU program, native verdict
application + VXLAN overlay encap, and a host slow path for NAT punts.
With NativeRing endpoints the admit/harvest loop runs in C++
(native/hostshim/runnerloop.cpp) — frames never cross Python
per-packet.
"""

from .governor import CoalesceGovernor, GovernorLedger, pow2_vectors
from .io import (
    AfPacketIO,
    FanoutHandoff,
    FaultInjectingSource,
    FrameSink,
    FrameSource,
    InMemoryRing,
    NativeRing,
    PcapReader,
    PcapWriter,
)
from .runner import (
    DataplaneRunner,
    DeviceSessionState,
    RunnerCounters,
    TableSwapError,
    VxlanOverlay,
)
from .shards import ShardedDataplane, ShardHealth

__all__ = [
    "AfPacketIO",
    "CoalesceGovernor",
    "DataplaneRunner",
    "DeviceSessionState",
    "FanoutHandoff",
    "FaultInjectingSource",
    "GovernorLedger",
    "FrameSink",
    "FrameSource",
    "InMemoryRing",
    "NativeRing",
    "PcapReader",
    "PcapWriter",
    "RunnerCounters",
    "ShardHealth",
    "ShardedDataplane",
    "TableSwapError",
    "VxlanOverlay",
    "pow2_vectors",
]
