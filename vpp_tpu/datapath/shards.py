"""Multi-shard dataplane — per-core host workers over one device state.

The reference's data plane scales across cores with DPDK multi-queue
RX + per-worker VPP graph instances, handing NAT flows between workers
so session state stays consistent (docs/ARCHITECTURE.md:20, the
dpdk-input → worker model).  The TPU-native translation splits the
same roles differently:

- **Host side (per core)**: N shards, each with its own rx/tx rings and
  its own native C++ admit/harvest loop (runnerloop.cpp).  Shard calls
  release the GIL, so a thread pool drives all shards' frame work
  concurrently on multi-core hosts — parse, rewrite, checksums, VXLAN
  encap all scale with cores, the way VPP workers do.
- **Device side (shared)**: ONE session table and ONE jit pipeline.
  Dispatches from all shards serialise on the DeviceSessionState lock
  and thread the session state in a single total order.  This deletes
  the reference's worker-handoff problem outright: a flow's forward
  packet admitted by shard 0 and its reply arriving on shard 3 hit the
  same device table, so no cross-worker handoff or flow-pinning is
  needed for correctness.  (PACKET_FANOUT_HASH still keeps flows
  shard-sticky for cache locality — see AfPacketIO's fanout support.)
- **Host slow path (shared)**: punts are rare; one lock-guarded
  HostSlowPath serves all shards, again because a punted flow's reply
  may land on any shard.

Ingest fanout options: PACKET_FANOUT on AF_PACKET sockets (kernel
multi-queue; vpp_tpu/datapath/io.py), or any per-shard frame source.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, List, Optional, Sequence, Tuple

from ..ops.classify import RuleTables
from ..ops.nat import NatTables
from .runner import DataplaneRunner, DeviceSessionState, VxlanOverlay
from .trace import PacketTracer

# A shard's IO endpoints: (source, tx_remote, tx_local, tx_host).
ShardIO = Tuple[object, object, object, object]


class ShardedDataplane:
    """N DataplaneRunner shards sharing one device session state, one
    host slow path, and one tracer; driven concurrently by a thread
    pool.  API mirrors the single runner (poll/drain/update_tables/
    metrics) so call sites swap in transparently."""

    def __init__(
        self,
        acl: RuleTables,
        nat: NatTables,
        route,
        overlay: VxlanOverlay,
        shard_ios: Sequence[ShardIO],
        batch_size: int = 256,
        max_vectors: int = 64,
        session_capacity: int = 1 << 16,
        workers: Optional[int] = None,
        **runner_kw,
    ):
        if not shard_ios:
            raise ValueError("need at least one shard")
        from ..ops.slowpath import HostSlowPath

        self.state = DeviceSessionState(session_capacity)
        self.slow = HostSlowPath()
        self.tracer = PacketTracer()
        self._host_lock = threading.Lock()
        self.overlay = overlay
        self.shards: List[DataplaneRunner] = [
            DataplaneRunner(
                acl=acl, nat=nat, route=route, overlay=overlay,
                source=src, tx=tx, local=local, host=host,
                batch_size=batch_size, max_vectors=max_vectors,
                state=self.state, slow=self.slow, tracer=self.tracer,
                host_lock=self._host_lock,
                **runner_kw,
            )
            for (src, tx, local, host) in shard_ios
        ]
        self._pool = ThreadPoolExecutor(
            max_workers=workers or len(self.shards),
            thread_name_prefix="dp-shard",
        )

    @property
    def engine(self) -> str:
        return self.shards[0].engine

    # Control-plane compile stats rider: inspect() is served from shard
    # 0's full view, so the provider lives there.
    @property
    def compile_stats_fn(self):
        return self.shards[0].compile_stats_fn

    @compile_stats_fn.setter
    def compile_stats_fn(self, fn) -> None:
        self.shards[0].compile_stats_fn = fn

    # --------------------------------------------------------------- loop

    def poll(self) -> int:
        """One scheduling turn on every shard, concurrently.  Each shard
        runs in exactly one pool task at a time (shards are not
        re-entrant); returns total frames transmitted this turn."""
        return sum(self._pool.map(lambda r: r.poll(), self.shards))

    def drain(self) -> int:
        """Drain every shard concurrently until all are idle."""
        return sum(self._pool.map(lambda r: r.drain(), self.shards))

    # ------------------------------------------------------------- tables

    def update_tables(self, acl=None, nat=None, route=None) -> None:
        """One swap for all shards: the backend retarget and the
        bypass-eligibility device reads (session/affinity occupancy on
        the SHARED state) are computed ONCE and handed to every shard,
        instead of once per shard — at 8+ shards the per-shard device
        round trips used to dominate the swap latency."""
        if not (acl is not None or nat is not None or route is not None):
            return
        from ..ops.nat import retarget_tables

        r0 = self.shards[0]
        if nat is not None:
            nat = retarget_tables(nat, r0._target_backend())
        # Disarm every shard's host bypass BEFORE any shard adopts: the
        # adopt + shared occupancy reads below take multiple batches'
        # worth of wall time, and a concurrent poll must not keep
        # forwarding via the bypass once deny rules are being installed.
        for r in self.shards:
            r._bypass_tables = False
        for r in self.shards:
            r._adopt_tables(acl, nat, route)
        # Shared-state occupancy reads only when the static half can
        # pass at all (the checks short-circuit before any device read
        # when the tables are non-trivial).
        state_clear = r0._bypass_state_clear() if r0._bypass_static_ok() else False
        for r in self.shards:
            r._refresh_bypass(state_clear=state_clear)

    # ------------------------------------------------------------ metrics

    def _aggregate_counters(self, sessions_active: int,
                            affinity_active: int,
                            slowpath_sessions: int) -> Dict[str, int]:
        """ONE aggregation body for metrics() and inspect(): per-shard
        totals summed, shared slow-path counters taken once, the
        (caller-supplied, already-transferred) device gauges injected —
        so the two views can never drift apart."""
        agg: Dict[str, int] = {}
        for r in self.shards:
            for key, value in r.counters.as_dict().items():
                agg[key] = agg.get(key, 0) + value
        # Table-swap ticks are per SWAP, not per shard: every shard
        # adopts the same tables in one update_tables call, so summing
        # would report N_shards x the true count — take shard 0's.
        for key, value in self.shards[0].counters.as_dict().items():
            if key.endswith("_swaps_total"):
                agg[key] = value
        for key, value in self.slow.counters.as_dict().items():
            agg[key] = value
        agg["datapath_sessions_active"] = sessions_active
        agg["datapath_affinity_active"] = affinity_active
        agg["datapath_slowpath_sessions_active"] = slowpath_sessions
        agg["datapath_inflight"] = sum(len(r._inflight) for r in self.shards)
        agg["datapath_shards"] = len(self.shards)
        return agg

    def metrics(self) -> Dict[str, int]:
        """Aggregated counters over all shards (shared gauges taken
        once, per-shard totals summed)."""
        one = self.shards[0].metrics()  # pays the device gauge reads
        return self._aggregate_counters(
            one.get("datapath_sessions_active", 0),
            one.get("datapath_affinity_active", 0),
            one.get("datapath_slowpath_sessions_active", 0),
        )

    def inspect(self) -> Dict[str, object]:
        """Live introspection (netctl inspect): shard 0's FULL view
        carries the shared state (device tables, sessions, slow path —
        the occupancy device reads are paid exactly once; the
        aggregated counters reuse those very values instead of calling
        metrics(), which would re-read them); every shard contributes
        its host-side dispatch/ring/counter slices, and the top-level
        rings/inflight aggregate across shards so the summary view
        reflects the whole node."""
        base = self.shards[0].inspect()
        base["shards"] = [
            {"dispatch": r.inspect_dispatch(), "rings": r.inspect_rings(),
             "counters": r.counters.as_dict()}
            for r in self.shards
        ]
        # Aggregate rings: sum frames/dropped per ring name.
        rings: Dict[str, Dict[str, int]] = {}
        for view in base["shards"]:
            for name, info in view["rings"].items():
                agg = rings.setdefault(name, {})
                for key, value in info.items():
                    agg[key] = agg.get(key, 0) + value
        base["rings"] = rings
        base["dispatch"]["inflight"] = sum(
            len(r._inflight) for r in self.shards)
        # Aggregated counters WITHOUT re-reading device occupancy:
        # shard 0's inspect() above already transferred the gauges.
        sessions = base["sessions"]
        base["counters"] = self._aggregate_counters(
            sessions["active"], sessions["affinity_pins"],
            base["slowpath"]["sessions"],
        )
        return base

    def close(self) -> None:
        self._pool.shutdown(wait=True)
