"""Multi-shard dataplane — per-core host workers over one device state.

The reference's data plane scales across cores with DPDK multi-queue
RX + per-worker VPP graph instances, handing NAT flows between workers
so session state stays consistent (docs/ARCHITECTURE.md:20, the
dpdk-input → worker model).  The TPU-native translation splits the
same roles differently:

- **Host side (per core)**: N shards, each with its own rx/tx rings and
  its own native C++ admit/harvest loop (runnerloop.cpp).  Shard calls
  release the GIL, so per-shard worker threads drive all shards' frame
  work concurrently on multi-core hosts — parse, rewrite, checksums,
  VXLAN encap all scale with cores, the way VPP workers do.
- **Device side (shared)**: ONE session table and ONE jit pipeline.
  Dispatches from all shards serialise on the DeviceSessionState lock
  and thread the session state in a single total order.  This deletes
  the reference's worker-handoff problem outright: a flow's forward
  packet admitted by shard 0 and its reply arriving on shard 3 hit the
  same device table, so no cross-worker handoff or flow-pinning is
  needed for correctness.  (PACKET_FANOUT_HASH still keeps flows
  shard-sticky for cache locality — see AfPacketIO's fanout support.)
- **Host slow path (shared)**: punts are rare; one lock-guarded
  HostSlowPath serves all shards, again because a punted flow's reply
  may land on any shard.

**Fault domains (shard supervision).**  Each shard is a supervised
fault domain: a per-shard health state machine

    healthy → degraded → ejected → probation → rejoined (→ healthy)

driven by dispatch deadlines (a poll that exceeds
``dispatch_deadline`` marks the shard hung and its worker thread is
abandoned) and consecutive-error thresholds (``eject_errors`` failed
polls eject).  Ejected shards stop receiving traffic — their queued
source frames are STEERED onto the surviving shards — and re-enter
via exponential-backoff probation: the runner is sanitised (in-flight
batches discarded, native loop rebuilt to release arena pins) and
must complete ``probation_polls`` clean polls to rejoin.  When EVERY
shard is down the ``on_all_down`` policy decides: ``"fail-closed"``
drops (and counts) ingress, ``"bypass"`` forwards it unfiltered over
the static host path — the HyperNAT-style host fallback, explicit
opt-in because it skips policy enforcement.

**Atomic multi-shard table swap.**  ``update_tables`` keeps the
last-good tables; if ANY shard's adopt fails, every shard is rolled
back to them and a retriable :class:`TableSwapError` surfaces — all
shards always serve the same table generation, never a mix.

Ingest fanout options: PACKET_FANOUT on AF_PACKET sockets (kernel
multi-queue; vpp_tpu/datapath/io.py), or any per-shard frame source.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from concurrent.futures import Future, ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeout
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..ops.classify import RuleTables
from ..ops.nat import NatTables
from ..testing.faults import FaultInjector
from .governor import GovernorLedger
from .runner import (
    DataplaneRunner,
    DeviceSessionState,
    TableSwapError,
    VxlanOverlay,
)
from .trace import PacketTracer

log = logging.getLogger(__name__)

# A shard's IO endpoints: (source, tx_remote, tx_local, tx_host).
ShardIO = Tuple[object, object, object, object]

STATE_HEALTHY = "healthy"
STATE_DEGRADED = "degraded"
STATE_EJECTED = "ejected"
STATE_PROBATION = "probation"
STATE_REJOINED = "rejoined"

# States that still receive traffic (everything but ejected).
_SERVING_STATES = (STATE_HEALTHY, STATE_DEGRADED, STATE_PROBATION,
                   STATE_REJOINED)


def parse_core_map(spec: str, n_shards: int) -> Optional[List[List[int]]]:
    """Parse the ``shard_cores`` config knob into a shard→core-set map
    (VPP's ``corelist-workers`` analog).

    - ``""``     → None (no pinning)
    - ``"auto"`` → the process's usable cores spread round-robin across
      the shards (shard i gets cores i, i+N, i+2N, ...)
    - ``"0-3;4-7;8,9"`` → one semicolon-separated core list per shard
      ("a-b" ranges and comma lists compose); must name exactly
      ``n_shards`` sets.
    """
    spec = (spec or "").strip()
    if not spec:
        return None
    if spec == "auto":
        try:
            usable = sorted(os.sched_getaffinity(0))
        except AttributeError:  # non-Linux: no affinity API, no pinning
            return None
        return [usable[i::n_shards] for i in range(n_shards)]
    sets: List[List[int]] = []
    for part in spec.split(";"):
        cores: List[int] = []
        for piece in part.split(","):
            piece = piece.strip()
            if not piece:
                continue
            if "-" in piece:
                lo, hi = piece.split("-", 1)
                cores.extend(range(int(lo), int(hi) + 1))
            else:
                cores.append(int(piece))
        sets.append(sorted(set(cores)))
    if len(sets) != n_shards:
        raise ValueError(
            f"shard_cores names {len(sets)} core sets for "
            f"{n_shards} shards: {spec!r}")
    return sets


@dataclasses.dataclass
class ShardHealth:  # owner: supervisor — every health transition runs on the poll() caller thread; workers never touch it
    """One shard's supervision record."""

    state: str = STATE_HEALTHY
    consecutive_errors: int = 0
    consecutive_ok: int = 0
    ejections: int = 0
    rejoins: int = 0
    eject_streak: int = 0     # ejections since the last full rejoin
    last_error: str = ""
    ejected_at: float = 0.0
    backoff: float = 0.0      # current probation backoff (seconds)
    dirty: bool = False       # runner needs sanitising before reuse

    def as_dict(self) -> Dict[str, object]:
        return {
            "state": self.state,
            "consecutive_errors": self.consecutive_errors,
            "ejections": self.ejections,
            "rejoins": self.rejoins,
            "backoff_s": round(self.backoff, 3),
            "last_error": self.last_error,
        }


class ShardedDataplane:
    """N DataplaneRunner shards sharing one device session state, one
    host slow path, one tracer, and one fault injector; each driven by
    its own supervised worker thread.  API mirrors the single runner
    (poll/drain/update_tables/metrics/inspect/health) so call sites
    swap in transparently."""

    def __init__(
        self,
        acl: RuleTables,
        nat: NatTables,
        route,
        overlay: VxlanOverlay,
        shard_ios: Sequence[ShardIO],
        batch_size: int = 256,
        # Coalesce ceiling (the governor picks the per-admit K under
        # it, per shard — each shard has its own rings, so each gets
        # its own backlog-driven governor; see runner.py).
        max_vectors: int = 256,
        session_capacity: int = 1 << 16,
        workers: Optional[int] = None,  # kept for API compat; per-shard now
        faults: Optional[FaultInjector] = None,
        # Supervision knobs.  The deadline default is generous: a first
        # dispatch legitimately pays jit compile time, and a false
        # ejection costs a probation round trip.
        dispatch_deadline: float = 30.0,
        eject_errors: int = 3,
        probation_polls: int = 3,
        reinit_backoff: float = 0.25,
        reinit_backoff_max: float = 8.0,
        on_all_down: str = "fail-closed",
        # Global added-latency budget (ISSUE 12): the N per-shard
        # governors share ONE coalesce_slo_us through a GovernorLedger
        # instead of each assuming the whole budget — aggregate added
        # latency stays inside the r5 production budget as shards
        # multiply.  Made explicit here (not **runner_kw) so the ledger
        # and the per-shard governors agree on the number.
        coalesce_slo_us: float = 600.0,
        # CPU placement (ISSUE 12): opt-in affinity map shard i → core
        # set.  Each shard's worker thread pins itself to its set at
        # spawn (and re-pins on the fresh executor a rejoin attaches),
        # so admit/parse/harvest cache state stays core-local — VPP's
        # corelist-workers analog.  NUMA locality follows first-touch
        # on the pinned core.  None/empty = no pinning (default).
        shard_cores: Optional[Sequence[Sequence[int]]] = None,
        **runner_kw,
    ):
        if not shard_ios:
            raise ValueError("need at least one shard")
        if on_all_down not in ("fail-closed", "bypass"):
            raise ValueError(
                f"on_all_down must be 'fail-closed' or 'bypass', "
                f"not {on_all_down!r}")
        if shard_cores is not None and len(shard_cores) not in (
                0, len(shard_ios)):
            raise ValueError(
                f"shard_cores maps {len(shard_cores)} shards but "
                f"{len(shard_ios)} shard_ios were given")
        from ..ops.slowpath import HostSlowPath

        self.state = DeviceSessionState(session_capacity)
        self.slow = HostSlowPath()
        self.tracer = PacketTracer()
        self.faults = faults if faults is not None else FaultInjector()
        self._host_lock = threading.Lock()
        self.overlay = overlay
        self.dispatch_deadline = dispatch_deadline
        self.eject_errors = eject_errors
        self.probation_polls = probation_polls
        self.reinit_backoff = reinit_backoff
        self.reinit_backoff_max = reinit_backoff_max
        self.on_all_down = on_all_down
        self.shards: List[DataplaneRunner] = [
            DataplaneRunner(
                acl=acl, nat=nat, route=route, overlay=overlay,
                source=src, tx=tx, local=local, host=host,
                batch_size=batch_size, max_vectors=max_vectors,
                coalesce_slo_us=coalesce_slo_us,
                state=self.state, slow=self.slow, tracer=self.tracer,
                host_lock=self._host_lock,
                faults=self.faults, shard_index=i,
                **runner_kw,
            )
            for i, (src, tx, local, host) in enumerate(shard_ios)
        ]
        # ONE global added-latency budget for the whole node: every
        # shard's governor caps against what the ledger has left after
        # the others' claims (bound before any worker thread exists).
        self.ledger = GovernorLedger(coalesce_slo_us, len(self.shards))
        for i, r in enumerate(self.shards):
            r.governor.bind_ledger(self.ledger, i)
        self.health_of: List[ShardHealth] = [
            ShardHealth() for _ in self.shards
        ]
        # CPU placement map (opt-in): normalised to one core tuple per
        # shard; () = unpinned.  _applied_cores[i] is written by shard
        # i's worker thread at executor spawn and read by inspect().
        self.shard_cores: List[Tuple[int, ...]] = [
            tuple(cores) for cores in (shard_cores or ())
        ] or [() for _ in self.shards]
        # lock-free: per-shard single-writer slots (shard i's first worker run writes index i; inspect readers tolerate staleness)
        self._applied_cores: List[Optional[str]] = [None] * len(self.shards)
        # One single-thread executor per shard (shards are not
        # re-entrant): a hung shard's executor can be ABANDONED without
        # stalling the others, and a fresh one attached at rejoin.
        self._execs: List[Optional[ThreadPoolExecutor]] = [  # owner: supervisor — executors swap on the poll() caller thread only
            self._new_exec(i) for i in range(len(self.shards))
        ]
        self._stuck: Dict[int, Future] = {}  # abandoned hung futures
        # Steering rotation cursor: where the NEXT steered frame lands
        # in the serving-target rotation.  Normalised modulo the live
        # target count on every use, so a cursor carried across an
        # eject→rejoin membership change can never index a stale
        # position or permanently bias the first survivor (ISSUE 12
        # satellite; the old frames[j::n] split always overfed
        # targets[0]).
        self._steer_cursor = 0  # owner: supervisor — steering runs on the poll() caller thread only
        # Supervisor counters (whole-engine, not per shard).
        self._ejections = 0
        self._rejoins = 0
        self._steered_frames = 0
        self._failclosed_drops = 0
        self._bypass_forwards = 0
        self._swap_rollbacks = 0

    def _new_exec(self, i: int) -> ThreadPoolExecutor:
        return ThreadPoolExecutor(
            max_workers=1, thread_name_prefix=f"dp-shard-{i}",
            initializer=self._pin_worker, initargs=(i,))

    def _pin_worker(self, i: int) -> None:
        """Executor initializer, running ON shard i's worker thread:
        apply the shard's opt-in core affinity.  Failures degrade to
        unpinned (recorded for inspect; placement is an optimisation,
        never a correctness gate)."""
        cores = self.shard_cores[i] if i < len(self.shard_cores) else ()
        if not cores:
            self._applied_cores[i] = ""
            return
        try:
            os.sched_setaffinity(0, cores)
            self._applied_cores[i] = ",".join(str(c) for c in cores)
        except (AttributeError, OSError, ValueError) as err:
            self._applied_cores[i] = f"error: {err}"
            log.warning("shard %d: core pinning to %s failed: %s",
                        i, cores, err)

    @property
    def engine(self) -> str:
        return self.shards[0].engine

    # Control-plane compile stats rider: inspect() is served from shard
    # 0's full view, so the provider lives there.
    @property
    def compile_stats_fn(self):
        return self.shards[0].compile_stats_fn

    @compile_stats_fn.setter
    def compile_stats_fn(self, fn) -> None:
        self.shards[0].compile_stats_fn = fn

    # --------------------------------------------------------------- loop

    def _serving(self) -> List[int]:
        return [i for i, h in enumerate(self.health_of)
                if h.state in _SERVING_STATES]

    def poll(self) -> int:
        """One supervised scheduling turn: advance the health state
        machine, steer ejected shards' queued frames onto survivors,
        then run one poll per serving shard concurrently — each under
        the dispatch deadline.  Returns total frames transmitted."""
        self._supervise_tick()
        serving = self._serving()
        self._steer(serving)
        futures: Dict[int, Future] = {
            i: self._execs[i].submit(self.shards[i].poll) for i in serving
        }
        total = 0
        deadline = time.monotonic() + self.dispatch_deadline
        for i, fut in futures.items():
            try:
                total += fut.result(
                    timeout=max(0.0, deadline - time.monotonic()))
            except FutureTimeout:
                self._on_hang(i, fut)
            except Exception as err:  # noqa: BLE001 - shard faults are data
                self._on_error(i, err)
            else:
                self._on_ok(i)
        return total

    def drain(self) -> int:
        """Poll until every serving shard is idle and nothing more can
        be steered; returns total frames transmitted.  Frames parked in
        an EJECTED shard's rings (unsteerable, e.g. pinned by a wedged
        batch) do not block drain — they are either steered on a later
        poll or discarded by the rejoin sanitise."""
        total = 0
        while True:
            sent = self.poll()
            total += sent
            if sent == 0 and self._idle():
                return total

    def _idle(self) -> bool:
        for i in self._serving():
            r = self.shards[i]
            if r._inflight:
                return False
            try:
                if len(r.source) > 0:  # type: ignore[arg-type]
                    return False
            except TypeError:
                pass
        return True

    # -------------------------------------------------------- supervision

    def _supervise_tick(self) -> None:
        """Move ejected shards whose backoff elapsed into probation:
        sanitise the runner (discard in-flight batches, rebuild the
        native loop to release arena pins) and attach a fresh worker if
        the old one was abandoned.  A shard whose hung thread is STILL
        wedged inside the runner cannot be touched safely — its
        ejection extends instead."""
        now = time.monotonic()
        for i, h in enumerate(self.health_of):
            if h.state != STATE_EJECTED:
                continue
            if now - h.ejected_at < h.backoff:
                continue
            stuck = self._stuck.get(i)
            if stuck is not None and not stuck.done():
                h.ejected_at = now  # still wedged; extend the ejection
                continue
            self._stuck.pop(i, None)
            if h.dirty:
                try:
                    self.shards[i].sanitize_after_fault()
                except Exception as err:  # noqa: BLE001
                    h.last_error = f"sanitize: {err}"
                    h.ejected_at = now
                    continue
                h.dirty = False
            if self._execs[i] is None:
                self._execs[i] = self._new_exec(i)
            # A hung worker that finally returned may have published a
            # claim AFTER the ejection zeroed it; re-zero now that the
            # shard is provably quiesced, before probation re-claims.
            self.ledger.release(i)
            h.state = STATE_PROBATION
            h.consecutive_ok = 0
            h.consecutive_errors = 0
            log.info("shard %d entering probation (ejection #%d)",
                     i, h.ejections)

    def _on_ok(self, i: int) -> None:
        h = self.health_of[i]
        h.consecutive_errors = 0
        if h.state == STATE_PROBATION:
            h.consecutive_ok += 1
            if h.consecutive_ok >= self.probation_polls:
                h.state = STATE_REJOINED
                h.rejoins += 1
                h.eject_streak = 0
                self._rejoins += 1
                log.info("shard %d rejoined after probation", i)
        elif h.state in (STATE_DEGRADED, STATE_REJOINED):
            h.state = STATE_HEALTHY

    def _on_error(self, i: int, err: Exception) -> None:
        h = self.health_of[i]
        h.last_error = str(err) or repr(err)
        h.consecutive_ok = 0
        h.consecutive_errors += 1
        # Always sanitise after a failed poll: a dispatch exception can
        # leave an admitted slot pinned in the native arena.
        try:
            self.shards[i].sanitize_after_fault()
        except Exception as serr:  # noqa: BLE001
            h.last_error = f"{h.last_error}; sanitize: {serr}"
        if h.state == STATE_PROBATION or \
                h.consecutive_errors >= self.eject_errors:
            self._eject(i, dirty=False)
        elif h.state in (STATE_HEALTHY, STATE_REJOINED):
            h.state = STATE_DEGRADED
        log.warning("shard %d poll failed (%d consecutive): %s",
                    i, h.consecutive_errors, h.last_error)

    def _on_hang(self, i: int, fut: Future) -> None:
        """The shard's poll blew the dispatch deadline: abandon its
        worker thread (it may be wedged in a device call forever) and
        eject.  The runner is marked dirty — it cannot be sanitised
        until the abandoned thread actually returns."""
        h = self.health_of[i]
        h.last_error = (
            f"dispatch deadline exceeded ({self.dispatch_deadline:.1f}s)")
        h.consecutive_ok = 0
        self._stuck[i] = fut
        ex = self._execs[i]
        self._execs[i] = None
        if ex is not None:
            ex.shutdown(wait=False)
        self._eject(i, dirty=True)
        log.error("shard %d hung; worker abandoned and shard ejected", i)

    def recover(self, shard: Optional[int] = None) -> int:
        """Operator-initiated recovery (netctl health --recover): zero
        the ejection backoff so the next poll takes the shard(s)
        straight into probation — the supervisor's safety checks
        (wedged-thread detection, sanitise, probation polls) still
        apply.  Returns how many ejected shards were expedited."""
        expedited = 0
        for i, h in enumerate(self.health_of):
            if shard is not None and i != shard:
                continue
            if h.state == STATE_EJECTED:
                h.backoff = 0.0
                h.ejected_at = 0.0
                expedited += 1
        return expedited

    def _eject(self, i: int, dirty: bool) -> None:
        h = self.health_of[i]
        h.state = STATE_EJECTED
        h.dirty = h.dirty or dirty
        h.ejections += 1
        h.eject_streak += 1
        self._ejections += 1
        # An ejected shard dispatches nothing: zero its budget claim so
        # a dead shard's stale reservation cannot throttle the very
        # survivors its traffic is being steered onto.
        self.ledger.release(i)
        h.backoff = min(self.reinit_backoff_max,
                        self.reinit_backoff * (2 ** (h.eject_streak - 1)))
        h.ejected_at = time.monotonic()
        # Post-mortem forensics (ISSUE 8): snapshot the shard's flight
        # recorder — its last N dispatches' K/backlog/generation/
        # verdict context — next to the quarantine pcap BEFORE the
        # runner is sanitised or its worker abandoned.  Reading the
        # ring is safe even for a hung shard: the recorder is a host
        # deque and the wedged thread is parked in a device call.
        try:
            self.shards[i].snapshot_flight(f"ejection: {h.last_error}")
        except OSError as err:  # forensics must never block supervision
            log.warning("shard %d flight snapshot failed: %s", i, err)

    # ------------------------------------------------------------ steering

    def _steer(self, serving: List[int]) -> None:
        """Drain ejected shards' queued source frames and redistribute
        them round-robin onto the survivors (their device results are
        identical — sessions are shared — so any shard can serve any
        flow).  The rotation continues from ``_steer_cursor`` and is
        re-normalised against the LIVE target list on every pass: the
        serving set changes across eject→rejoin cycles, and a cursor
        position minted under the old membership must neither index out
        of range nor keep skewing frames onto whichever survivor
        happened to sort first (at N=8 with one long-ejected shard the
        old header-of-list split persistently overfed shard 0 by up to
        a full burst slice per poll).  With NO survivors the
        ``on_all_down`` policy applies: fail-closed drop, or unfiltered
        static host bypass."""
        down = [i for i, h in enumerate(self.health_of)
                if h.state == STATE_EJECTED]
        if not down:
            return
        # Steer ONLY into sources whose send() enqueues for ingest
        # (ring-likes declare can_enqueue).  AfPacketIO.send would
        # TRANSMIT the raw frames back onto the wire unprocessed —
        # with fanout sockets the kernel redistributes on its own once
        # the ejected socket stops draining.
        targets = [self.shards[i] for i in serving
                   if getattr(self.shards[i].source, "can_enqueue", False)]
        burst = 1 << 12
        for i in down:
            r = self.shards[i]
            if serving and not targets:
                return  # survivors exist but their sources can't ingest
            try:
                frames = r.source.recv_batch(burst)
            except Exception:  # noqa: BLE001 - ring pinned by a wedged batch
                continue
            if not frames:
                continue
            if targets:
                nt = len(targets)
                # Normalise against the CURRENT epoch: after a rejoin
                # grows (or a second ejection shrinks) the target list,
                # the carried cursor is just a rotation offset again.
                start = self._steer_cursor % nt
                for j in range(min(nt, len(frames))):
                    # Frame f goes to targets[(start + f) % nt]: the
                    # slice below is that assignment, chunked so each
                    # target gets ONE send per pass.
                    chunk = frames[j::nt]
                    targets[(start + j) % nt].source.send(chunk)
                self._steer_cursor = (start + len(frames)) % nt
                self._steered_frames += len(frames)
            elif self.on_all_down == "bypass":
                self._bypass_forwards += self._bypass_forward(r, frames)
            else:
                self._failclosed_drops += len(frames)

    def _bypass_forward(self, r: DataplaneRunner, frames: List[bytes]) -> int:
        """All-shards-down static host bypass: route frames with pure
        host arithmetic — NO classify, NO NAT, no device — the explicit
        degraded mode trading policy enforcement for reachability.
        Mirrors the tail of the python harvest path."""
        from ..ops.packets import PacketBatch
        from ..ops.pipeline import ROUTE_HOST, ROUTE_LOCAL, ROUTE_REMOTE

        fb = r.shim.parse(frames)
        n = fb.n
        if n == 0:
            return 0
        dst = np.asarray(fb.batch.dst_ip)[:n]
        base = int(np.asarray(r.route.pod_subnet_base))
        mask = int(np.asarray(r.route.pod_subnet_mask))
        tbase = int(np.asarray(r.route.this_node_base))
        tmask = int(np.asarray(r.route.this_node_mask))
        hbits = int(np.asarray(r.route.host_bits))
        local = (dst & tmask) == tbase
        in_pod = (dst & mask) == base
        tag = np.where(local, ROUTE_LOCAL,
                       np.where(in_pod, ROUTE_REMOTE, ROUTE_HOST)).astype(np.int32)
        node_id = np.where(in_pod & ~local,
                           (dst - base) >> hbits, 0).astype(np.int32)
        allowed = np.ones(n, dtype=bool)
        orig = PacketBatch(
            src_ip=np.asarray(fb.batch.src_ip)[:n],
            dst_ip=dst,
            protocol=np.asarray(fb.batch.protocol)[:n],
            src_port=np.asarray(fb.batch.src_port)[:n],
            dst_port=np.asarray(fb.batch.dst_port)[:n],
        )
        fwd = r.shim.apply_masked(fb, allowed, orig)  # no rewrite
        sent = 0
        is_remote = (tag == ROUTE_REMOTE).astype(np.uint8)
        out_buf, out_off, out_len, out_rows, _ = r.shim.vxlan_encap(
            fb, fwd, is_remote, node_id, r.overlay.remote_ips,
            r.overlay.local_ip, r.overlay.local_node_id, r.overlay.vni,
        )
        if len(out_rows):
            r.tx.send([
                out_buf[int(out_off[j]):int(out_off[j]) + int(out_len[j])]
                .tobytes()
                for j in range(len(out_rows))
            ])
            sent += len(out_rows)
        for rows, sink in (
            (np.nonzero(fwd.astype(bool) & (tag == ROUTE_LOCAL))[0], r.local),
            (np.nonzero(fwd.astype(bool) & (tag == ROUTE_HOST))[0], r.host),
        ):
            if len(rows):
                sink.send([fb.frame(int(j)) for j in rows])
                sent += len(rows)
        return sent

    # ------------------------------------------------------------- tables

    def update_tables(self, acl=None, nat=None, route=None,
                      infer=None) -> None:
        """One ATOMIC swap for all shards: the backend retarget and the
        bypass-eligibility device reads (session/affinity occupancy on
        the SHARED state) are computed ONCE and handed to every shard.
        If ANY shard's adopt fails, every shard is rolled back to the
        last-good tables — the shards always agree on one table
        generation — and a retriable :class:`TableSwapError` surfaces
        to the caller (the scheduler applicator absorbs it into its
        FAILED/retry/healing machinery).  The inference table (ISSUE
        14) rides the same contract: a model update either lands on
        every shard or on none."""
        if not (acl is not None or nat is not None or route is not None
                or infer is not None):
            return
        from ..ops.nat import retarget_tables

        r0 = self.shards[0]
        last_good = (r0.acl, r0.nat, r0.route, r0.infer)
        # Disarm every shard's host bypass BEFORE any shard adopts: the
        # adopt + shared occupancy reads below take multiple batches'
        # worth of wall time, and a concurrent poll must not keep
        # forwarding via the bypass once deny rules are being installed.
        for r in self.shards:
            r._bypass_tables = False
        idx = -1
        try:
            if nat is not None:
                nat = retarget_tables(nat, r0._target_backend())
            for idx, r in enumerate(self.shards):
                r._adopt_tables(acl, nat, route, infer)
        except Exception as err:
            # Roll EVERY shard back to last-good (adopted or not — the
            # restore is reference assignment, idempotent), so no two
            # shards ever serve different table generations.  Each
            # shard's route-scalar cache drops too: a worker may have
            # refilled it from the half-adopted generation.
            for r in self.shards:
                r.acl, r.nat, r.route, r.infer = last_good
                r._route_cache = None
            # Re-align table generations: shards that adopted before
            # the failure bumped theirs, the failing one did not — left
            # alone they would diverge forever and the generation would
            # stop being a cross-shard correlation key for flight/trace
            # rows.  One PAST the highest: batches already harvested
            # under the transient new tables stamped max, so the
            # restored last-good state needs its OWN generation — a
            # post-mortem joining rows on table_gen must never mix
            # rolled-back-table verdicts with last-good ones.
            gen = max(r._table_gen for r in self.shards) + 1
            for r in self.shards:
                r._table_gen = gen
            self._swap_rollbacks += 1
            state_clear = (
                r0._bypass_state_clear() if r0._bypass_static_ok() else False)
            for r in self.shards:
                r._refresh_bypass(state_clear=state_clear)
            raise TableSwapError(
                f"multi-shard table swap failed on shard {idx}; all "
                f"{len(self.shards)} shards rolled back to last-good "
                f"tables: {err}"
            ) from err
        # Shared-state occupancy reads only when the static half can
        # pass at all (the checks short-circuit before any device read
        # when the tables are non-trivial).
        state_clear = r0._bypass_state_clear() if r0._bypass_static_ok() else False
        for r in self.shards:
            r._refresh_bypass(state_clear=state_clear)
        if r0.prewarm:
            # ONE prewarm per swap: every shard dispatches through the
            # same process-wide jit cache, and the bucket ledger makes
            # the other shards' (and same-shape future swaps') calls
            # free anyway.
            r0.prewarm_buckets()

    # ------------------------------------------------------------ metrics

    def _aggregate_counters(self, sessions_active: int,
                            affinity_active: int,
                            slowpath_sessions: int) -> Dict[str, int]:
        """ONE aggregation body for metrics() and inspect(): per-shard
        totals summed, shared slow-path counters taken once, the
        (caller-supplied, already-transferred) device gauges injected —
        so the two views can never drift apart."""
        agg: Dict[str, int] = {}
        for r in self.shards:
            for key, value in r.counters.as_dict().items():
                agg[key] = agg.get(key, 0) + value
        # Table-swap ticks are per SWAP, not per shard: every shard
        # adopts the same tables in one update_tables call, so summing
        # would report N_shards x the true count — take shard 0's.
        for key, value in self.shards[0].counters.as_dict().items():
            if key.endswith("_swaps_total"):
                agg[key] = value
        for key, value in self.slow.counters.as_dict().items():
            agg[key] = value
        agg["datapath_sessions_active"] = sessions_active
        agg["datapath_affinity_active"] = affinity_active
        agg["datapath_slowpath_sessions_active"] = slowpath_sessions
        agg["datapath_inflight"] = sum(len(r._inflight) for r in self.shards)
        agg["datapath_shards"] = len(self.shards)
        # Governor gauges: K/backlog are per-shard states — report the
        # deepest (the shard the node's latency story hinges on);
        # breach counts sum.
        agg["datapath_governor_k"] = max(
            r.governor.current_k for r in self.shards)
        agg["datapath_governor_backlog"] = max(
            r.governor.backlog for r in self.shards)
        agg["datapath_governor_slo_breaches_total"] = sum(
            r.governor.slo_breaches for r in self.shards)
        # Global-budget ledger gauges (sharded engine only — a solo
        # runner has no ledger; solo ⊆ sharded parity is one-way).
        agg["datapath_governor_ledger_committed_us"] = int(
            self.ledger.committed_us())
        agg["datapath_governor_ledger_constrained_total"] = sum(
            r.governor.ledger_constrained for r in self.shards)
        # Supervisor counters: engine-level, not per shard (rollbacks
        # happen once per failed swap, so the per-runner counter — only
        # ticked by solo-runner update_tables — is overridden here).
        agg["datapath_swap_rollbacks_total"] = self._swap_rollbacks
        agg["datapath_shards_serving"] = len(self._serving())
        agg["datapath_shard_ejections_total"] = self._ejections
        agg["datapath_shard_rejoins_total"] = self._rejoins
        agg["datapath_steered_frames_total"] = self._steered_frames
        agg["datapath_failclosed_drops_total"] = self._failclosed_drops
        agg["datapath_bypass_forwards_total"] = self._bypass_forwards
        return agg

    def metrics(self) -> Dict[str, int]:
        """Aggregated counters over all shards (shared gauges taken
        once, per-shard totals summed)."""
        one = self.shards[0].metrics()  # pays the device gauge reads
        return self._aggregate_counters(
            one.get("datapath_sessions_active", 0),
            one.get("datapath_affinity_active", 0),
            one.get("datapath_slowpath_sessions_active", 0),
        )

    # ---------------------------------------------------------- telemetry

    def latency_histograms(self):
        """Whole-node latency histograms: every shard's single-writer
        recorders merged on read (same names as the solo runner, so the
        metrics exporter and dashboard see one schema)."""
        from ..telemetry import LatencyRecorder

        return LatencyRecorder.merged(r.telemetry for r in self.shards)

    def inspect_latency(self) -> Dict[str, object]:
        return {name: hist.snapshot()
                for name, hist in self.latency_histograms().items()}

    def inference_bands(self):
        """Whole-node score log2-histogram: per-band counts summed
        across every shard's single-writer counters."""
        bands = [0] * len(self.shards[0].inference_bands())
        for r in self.shards:
            for i, count in enumerate(r.inference_bands()):
                bands[i] += count
        return bands

    def inspect_inference(self) -> Dict[str, object]:
        """The whole-node inference pillar: table state from shard 0
        (every shard adopts the same table atomically), action/score
        counters summed across shards, swaps taken once (one tick per
        engine-wide swap, same rule as the _swaps_total aggregation)."""
        base = self.shards[0].inspect_inference()
        for key in ("scored", "logged", "deprioritized", "quarantined"):
            base[key] = sum(
                getattr(r.counters, f"inference_{key}") for r in self.shards)
        base["score_bands"] = self.inference_bands()
        return base

    def dump_flight(self, limit: int = 0) -> Dict[str, object]:
        """All shards' flight rings, each labelled with its shard index
        (post-mortems usually chase ONE shard's history)."""
        return {
            "shards": [{
                "shard": i,
                **r.flight.status(),
                "records": r.flight.dump(limit),
            } for i, r in enumerate(self.shards)],
        }

    def health(self) -> Dict[str, object]:
        """The fault-domain report (REST /contiv/v1/health → `netctl
        health`): per-shard state machine positions + engine-level
        ejection/steer/quarantine/rollback counters."""
        serving = self._serving()
        shard_views = []
        for i, (h, r) in enumerate(zip(self.health_of, self.shards)):
            view = h.as_dict()
            view["shard"] = i
            view["quarantined_batches"] = r.counters.quarantined_batches
            view["poisoned_frames"] = r.counters.dropped_poisoned
            view["dispatch_errors"] = r.counters.dispatch_errors
            view["source_errors"] = r.counters.source_errors
            shard_views.append(view)
        return {
            "policy_all_down": self.on_all_down,
            "shards_total": len(self.shards),
            "shards_serving": len(serving),
            "all_down": not serving,
            "ejections": self._ejections,
            "rejoins": self._rejoins,
            "steered_frames": self._steered_frames,
            "failclosed_drops": self._failclosed_drops,
            "bypass_forwards": self._bypass_forwards,
            "swap_rollbacks": self._swap_rollbacks,
            "quarantined_batches": sum(
                r.counters.quarantined_batches for r in self.shards),
            "poisoned_frames": sum(
                r.counters.dropped_poisoned for r in self.shards),
            "shards": shard_views,
        }

    def inspect(self) -> Dict[str, object]:
        """Live introspection (netctl inspect): shard 0's FULL view
        carries the shared state (device tables, sessions, slow path —
        the occupancy device reads are paid exactly once; the
        aggregated counters reuse those very values instead of calling
        metrics(), which would re-read them); every shard contributes
        its host-side dispatch/ring/counter slices, and the top-level
        rings/inflight aggregate across shards so the summary view
        reflects the whole node."""
        base = self.shards[0].inspect()
        base["health"] = self.health()
        base["shards"] = [
            {"dispatch": r.inspect_dispatch(), "rings": r.inspect_rings(),
             "counters": r.counters.as_dict(),
             "health": h.as_dict()}
            for r, h in zip(self.shards, self.health_of)
        ]
        # Aggregate rings: sum frames/dropped per ring name.
        rings: Dict[str, Dict[str, int]] = {}
        for view in base["shards"]:
            for name, info in view["rings"].items():
                agg = rings.setdefault(name, {})
                for key, value in info.items():
                    agg[key] = agg.get(key, 0) + value
        base["rings"] = rings
        base["dispatch"]["inflight"] = sum(
            len(r._inflight) for r in self.shards)
        # Whole-node governor view: per-shard K histograms merged,
        # breach/decision counts summed, current K and backlog reported
        # per shard (each shard's rings have their own depth).
        gov = base["dispatch"]["governor"]
        hist: Dict[str, int] = {}
        for r in self.shards:
            for key, value in r.governor.k_hist.items():
                hist[str(key)] = hist.get(str(key), 0) + value
        gov["k_histogram"] = {k: hist[k] for k in sorted(hist, key=int)}
        gov["decisions"] = sum(r.governor.decisions for r in self.shards)
        gov["slo_breaches"] = sum(
            r.governor.slo_breaches for r in self.shards)
        gov["ledger_constrained"] = sum(
            r.governor.ledger_constrained for r in self.shards)
        gov["samples"] = sum(r.governor.samples for r in self.shards)
        gov["per_shard_k"] = [r.governor.current_k for r in self.shards]
        gov["per_shard_backlog"] = [r.governor.backlog for r in self.shards]
        # Global-budget ledger: the shared SLO pool the per-shard caps
        # are computed against (ISSUE 12) — committed claims, per-shard
        # reservations, and how often the OTHER shards' load (not a
        # shard's own SLO math) was what shrank a cap.
        gov["ledger"] = self.ledger.snapshot()
        # CPU/NUMA placement: the configured affinity map next to what
        # each worker thread actually applied ("" = unpinned by
        # config, "error: ..." = pinning failed and the shard runs
        # unpinned, None = worker not spawned yet).
        base["dispatch"]["placement"] = {
            "shard_cores": [list(c) for c in self.shard_cores],
            "applied": list(self._applied_cores),
            "host_cores": os.cpu_count() or 0,
        }
        # Whole-node round-chain attribution: every shard's per-round
        # histograms merged on read (same discipline as the latency
        # pillars below; shard 0's solo view would miss the others).
        from ..telemetry import Log2Histogram

        base["dispatch"]["rounds"] = {
            name: Log2Histogram().merged(
                r.rounds[name] for r in self.shards).snapshot()
            for name in self.shards[0].rounds
        }
        # Whole-node latency view: merged across every shard's
        # single-writer recorders (shard 0's solo view would miss the
        # other shards' samples); flight status aggregates similarly.
        base["latency"] = self.inspect_latency()
        # Whole-node inference view: counters + score histogram summed
        # across shards (the table itself is shard-identical).
        base["inference"] = self.inspect_inference()
        base["flight"] = {
            "recorded": sum(len(r.flight) for r in self.shards),
            "capacity": sum(r.flight.capacity for r in self.shards),
            "dispatches_total": sum(
                r.flight.status()["dispatches_total"] for r in self.shards),
        }
        # Aggregated counters WITHOUT re-reading device occupancy:
        # shard 0's inspect() above already transferred the gauges.
        sessions = base["sessions"]
        base["counters"] = self._aggregate_counters(
            sessions["active"], sessions["affinity_pins"],
            base["slowpath"]["sessions"],
        )
        return base

    def close(self) -> None:
        # Release any injected hangs first so abandoned threads can
        # finish instead of leaking for their full timeout.
        self.faults.disarm()
        for ex in self._execs:
            if ex is not None:
                ex.shutdown(wait=True)
        # Release per-shard host resources (pcap handles, native
        # arenas) — but never under a thread that may still be wedged
        # INSIDE the runner: freeing the native arena under it would be
        # a use-after-free in C++.  Those shards' resources fall to the
        # GC safety nets instead.
        for i, r in enumerate(self.shards):
            stuck = self._stuck.get(i)
            if stuck is not None and not stuck.done():
                continue
            r.close()
