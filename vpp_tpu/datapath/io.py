"""Frame sources and sinks for the datapath runner.

The reference ingests packets through DPDK NIC queues bound via
pkg/pci (pci.go:40) into VPP's dpdk-input node.  The TPU-native runner
abstracts ingest/egress behind two tiny interfaces so the same loop
drives: an in-memory ring (tests, benchmarks), pcap replay (offline),
or an AF_PACKET raw socket on a real interface (veth/NIC).
"""

from __future__ import annotations

import collections
import socket
import struct
import threading
from typing import Iterable, List, Optional, Protocol, Sequence

# The C++-backed frame ring (runnerloop.cpp) — the buffer-view
# source/sink the native runner loop consumes; re-exported here so IO
# call sites pick between InMemoryRing (pure Python) and NativeRing.
from ..shim.hostshim import (  # noqa: F401
    FanoutHandoff,
    NativeRing,
    afp_rx_ring,
    afp_tx_ring,
)


class FrameSource(Protocol):
    def recv_batch(self, max_frames: int) -> List[bytes]:
        """Up to ``max_frames`` raw Ethernet frames; empty list = idle."""
        ...


class FrameSink(Protocol):
    def send(self, frames: Sequence[bytes]) -> None:
        ...


class FaultInjectingSource:
    """Wrap any :class:`FrameSource` with a ``frame-source-error``
    injection site (vpp_tpu/testing/faults.py): an armed plan makes
    ``recv_batch`` raise exactly where a flapping NIC / dead socket
    would, driving the runner's degrade-don't-die source handling
    through the production code path.  Python-engine sources only —
    the native engine's rings are consumed in C++, so its site lives
    in the runner's admit."""

    def __init__(self, source: FrameSource, faults, shard: int = 0):
        self.source = source
        self.faults = faults
        self.shard = shard

    @property
    def can_enqueue(self) -> bool:
        return getattr(self.source, "can_enqueue", False)

    def __len__(self) -> int:
        return len(self.source)  # type: ignore[arg-type]

    def backlog_hint(self) -> int:
        hint = getattr(self.source, "backlog_hint", None)
        if hint is not None:
            return int(hint())
        try:
            return len(self.source)  # type: ignore[arg-type]
        except TypeError:
            return -1

    def recv_batch(self, max_frames: int) -> List[bytes]:
        from ..testing.faults import SITE_FRAME_SOURCE_ERROR

        self.faults.fire(SITE_FRAME_SOURCE_ERROR, shard=self.shard)
        return self.source.recv_batch(max_frames)

    def send(self, frames: Sequence[bytes]) -> None:
        self.source.send(frames)  # type: ignore[attr-defined]


class InMemoryRing:
    """Thread-safe frame ring — both a source and a sink.

    The unit-test / benchmark transport, and the rx queue the virtual
    wire of the cluster harness delivers into.
    """

    # send() ENQUEUES for ingest (unlike AfPacketIO.send, which
    # transmits): the shard supervisor may steer an ejected shard's
    # frames into this source.
    can_enqueue = True

    def __init__(self, capacity: int = 1 << 16):
        self._dq: "collections.deque[bytes]" = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self.dropped = 0

    def __len__(self) -> int:
        return len(self._dq)

    def backlog_hint(self) -> int:
        """Queued frame count (the coalesce governor's depth probe)."""
        return len(self._dq)

    def send(self, frames: Sequence[bytes]) -> None:
        with self._lock:
            maxlen = self._dq.maxlen or 0
            for f in frames:
                if len(self._dq) >= maxlen:
                    self.dropped += 1
                else:
                    self._dq.append(bytes(f))

    def recv_batch(self, max_frames: int) -> List[bytes]:
        out: List[bytes] = []
        with self._lock:
            while self._dq and len(out) < max_frames:
                out.append(self._dq.popleft())
        return out


# ---------------------------------------------------------------------------
# pcap replay / capture (classic pcap, linktype EN10MB)
# ---------------------------------------------------------------------------

_PCAP_MAGIC_LE = 0xA1B2C3D4
_PCAP_MAGIC_BE = 0xD4C3B2A1


class PcapReader:
    """Replay frames from a classic pcap file (a deterministic traffic
    source, the TRex/pcap-replay analog of tests/policy/perf)."""

    def __init__(self, path: str, loop: bool = False):
        self.path = path
        self.loop = loop
        self._frames = self._load(path)
        self._pos = 0

    @staticmethod
    def _load(path: str) -> List[bytes]:
        frames: List[bytes] = []
        with open(path, "rb") as fh:
            hdr = fh.read(24)
            if len(hdr) < 24:
                return frames
            magic = struct.unpack("<I", hdr[:4])[0]
            if magic == _PCAP_MAGIC_LE:
                endian = "<"
            elif magic == _PCAP_MAGIC_BE:
                endian = ">"
            else:
                raise ValueError(f"{path}: not a classic pcap file")
            while True:
                rec = fh.read(16)
                if len(rec) < 16:
                    break
                _, _, incl, _ = struct.unpack(f"{endian}IIII", rec)
                data = fh.read(incl)
                if len(data) < incl:
                    break
                frames.append(data)
        return frames

    def recv_batch(self, max_frames: int) -> List[bytes]:
        if self._pos >= len(self._frames):
            if not self.loop or not self._frames:
                return []
            self._pos = 0
        out = self._frames[self._pos:self._pos + max_frames]
        self._pos += len(out)
        return out

    def backlog_hint(self) -> int:
        """Frames left in the replay (a looping reader always reports
        full depth — replay IS a saturating source)."""
        if self.loop:
            return len(self._frames)
        return max(0, len(self._frames) - self._pos)


class PcapWriter:
    """Capture sink writing a classic pcap file."""

    def __init__(self, path: str, snaplen: int = 65535):
        self._fh = open(path, "wb")
        self._snaplen = snaplen
        self._fh.write(struct.pack("<IHHiIII", _PCAP_MAGIC_LE, 2, 4, 0, 0, snaplen, 1))
        self._ts = 0

    def send(self, frames: Sequence[bytes]) -> None:
        for f in frames:
            self._ts += 1
            incl = min(len(f), self._snaplen)
            self._fh.write(struct.pack("<IIII", self._ts // 1000000, self._ts % 1000000, incl, len(f)))
            self._fh.write(f[:incl])

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        self._fh.close()

    def __del__(self):  # pragma: no cover - GC safety net
        # The capture must never leak an open file handle: quarantine
        # writers live on runners whose owners may drop them without a
        # close (the test-race ResourceWarning gate enforces this).
        try:
            self._fh.close()
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass


# ---------------------------------------------------------------------------
# AF_PACKET raw socket (real interfaces / veth pairs)
# ---------------------------------------------------------------------------


class AfPacketIO:
    """Raw-socket source+sink bound to one interface.

    The kernel-path stand-in for the reference's DPDK NIC binding
    (pkg/pci/pci.go DriverBind :40) — zero-dependency, works on veth
    pairs for e2e tests and on a real NIC for small deployments.
    Requires CAP_NET_RAW; construction raises PermissionError without.

    Multi-queue ingest (the DPDK RSS analog): open N sockets on the
    same interface with the same ``fanout_group`` and the kernel
    spreads frames across them (PACKET_FANOUT).  The default ``hash``
    mode keeps a flow on one socket — one shard's rings stay
    flow-sticky, the property VPP's per-worker RX queues rely on.
    Each shard of a ShardedDataplane gets its own fanout socket.
    """

    ETH_P_ALL = 0x0003
    SOL_PACKET = 263
    PACKET_FANOUT = 18
    FANOUT_MODES = {
        "hash": 0,      # symmetric-ish flow hash (flow-sticky)
        "lb": 1,        # round-robin load balance
        "cpu": 2,       # incoming CPU
        "rollover": 3,  # fill one socket, overflow to next
        "rnd": 4,       # random
        "qm": 5,        # NIC RX queue mapping (true multi-queue)
    }

    def __init__(self, ifname: str, blocking_ms: int = 0,
                 fanout_group: Optional[int] = None,
                 fanout_mode: str = "hash"):
        self.ifname = ifname
        self._sock = socket.socket(
            socket.AF_PACKET, socket.SOCK_RAW, socket.htons(self.ETH_P_ALL)
        )
        try:
            self._sock.bind((ifname, 0))
            if fanout_group is not None:
                mode = self.FANOUT_MODES[fanout_mode]
                self._sock.setsockopt(
                    self.SOL_PACKET, self.PACKET_FANOUT,
                    (fanout_group & 0xFFFF) | (mode << 16),
                )
            if blocking_ms:
                self._sock.settimeout(blocking_ms / 1000.0)
            else:
                self._sock.setblocking(False)
        except BaseException:
            # A half-constructed IO must not leak its raw socket: bind
            # or PACKET_FANOUT can fail AFTER the fd exists (fanout is
            # EOPNOTSUPP on some interfaces/kernels) and the caller
            # never gets an object to close (found by the test-race
            # ResourceWarning gate).
            self._sock.close()
            raise

    def recv_batch(self, max_frames: int) -> List[bytes]:
        out: List[bytes] = []
        while len(out) < max_frames:
            try:
                frame = self._sock.recv(65535)
            except (BlockingIOError, socket.timeout):
                break
            if frame:
                out.append(frame)
        return out

    def backlog_hint(self) -> int:
        """AF_PACKET cannot report queue DEPTH — SIOCINQ on a packet
        socket returns only the next frame's size.  Report 0 (idle) vs
        -1 (frames pending, depth unknown): the governor's saturation
        ramp takes over for depth-blind sources."""
        import fcntl

        try:
            buf = struct.pack("i", 0)
            pending = struct.unpack(
                "i", fcntl.ioctl(self._sock.fileno(), 0x541B, buf))[0]
        except OSError:
            return -1
        return 0 if pending == 0 else -1

    def send(self, frames: Sequence[bytes]) -> None:
        for f in frames:
            try:
                self._sock.send(f)
            except BlockingIOError:
                pass  # TX queue full — kernel drop semantics

    # ------------------------------------------------- native burst IO

    def fileno(self) -> int:
        return self._sock.fileno()

    def rx_into(self, ring: NativeRing, max_frames: int = 1 << 12) -> int:
        """Burst-receive straight into a native ring (recvmmsg in C++;
        no per-frame Python)."""
        return afp_rx_ring(self.fileno(), ring, max_frames)

    def tx_from(self, ring: NativeRing, max_frames: int = 1 << 12) -> int:
        """Burst-transmit a native ring's frames (sendmmsg in C++)."""
        return afp_tx_ring(self.fileno(), ring, max_frames)

    def close(self) -> None:
        self._sock.close()
