"""Load-adaptive vector coalescing governor.

VPP's core scheduling insight is that the vector size *adapts to
load*: frames accumulate while the previous vector is in flight, so
per-dispatch fixed costs amortise exactly when throughput matters and
vectors stay small (low latency) when the link is quiet (SURVEY §6).
The runner's admit has always been backlog-shaped — it dispatches the
power-of-two bucket of whatever the ring holds — but the CAP was a
static ``max_vectors=64``, the largest coalesce whose *fixed-K* fill
latency held the budget.  That cap leaves the 400+ Mpps capability
band (K=256, NATPROFILE_r05: the production dispatch is
dispatch-floor-bound; device compute is essentially free) on the
table at exactly the loads where latency is already queue-dominated.

The governor replaces the static pick with a per-admit decision:

- **Backlog term.**  ``K_fill`` = the pow2 vector count covering the
  measured ingress backlog.  Frames already queued pay no extra fill
  wait for a deeper coalesce — they are *there* — so deep backlog ⇒
  large K (amortising the dispatch floor *reduces* their latency),
  idle link ⇒ K=1.
- **SLO term.**  An online exponentially-weighted least-squares fit
  of the dispatch time model ``t(K) = floor + K·vec`` (the dispatch
  floor and per-vector service time, learned from harvest timings).
  ``K_slo`` = the largest pow2 whose predicted *added latency* —
  service time times the in-flight window depth a frame may wait
  behind — stays under the configured budget.  The governor
  speculates above the backlog only never; it CAPS at ``K_slo`` when
  the queue does not already demand more.
- **Breach accounting.**  When backlog demands more than ``K_slo``
  allows, clamping would only grow the queue (and with it latency):
  the governor follows the backlog up to the ceiling and counts an
  ``slo_breach`` — saturation is reported, not hidden.

The same pow2 bucketing as the fixed cap bounds jit recompiles, and
:meth:`DataplaneRunner.prewarm_buckets` compiles every bucket up to
the ceiling at start/table-swap time so a load spike never stalls on
compilation (see ``_PREWARMED``).

HyperNAT (arXiv:2111.08193) makes the same amortise-the-fixed-
offload-cost argument for SmartNIC NAT; RVH (arXiv:1909.07159) shows
classification batching frontiers are load-dependent — the right K is
a function of offered load, not a constant.
"""

from __future__ import annotations

from typing import Dict, List, Optional


def pow2_vectors(n_frames: int, batch_size: int, cap: int) -> int:
    """The power-of-two vector count whose ``k * batch_size`` covers
    ``n_frames``, capped at ``cap`` (bounded jit recompiles).  The ONE
    sizing rule shared by the runner's admits, the quarantine's
    sub-batch packer, and the governor."""
    k = 1
    while k * batch_size < n_frames and k < cap:
        k *= 2
    return k


class GovernorLedger:
    """Shared added-latency budget across N per-shard governors.

    With one governor per shard (each shard owns its rings, so each
    needs its own backlog view) every shard used to assume it had the
    WHOLE ``coalesce_slo_us`` budget: at N shards the aggregate added
    latency a saturated node could sign off on grew N-fold, silently
    leaving the r5 production budget behind exactly when the many-core
    front end is earning its keep.  The ledger makes the budget global:
    each shard PUBLISHES the predicted added latency of its latest
    chosen K (``predict_us(K) × window`` — the same quantity slo_cap
    bounds) into its own slot, and every shard's cap is computed
    against what the budget has left after the OTHER shards' claims.

    Concurrency contract (this is hot-path state — no lock):

    - every slot is SINGLE-WRITER: only shard i's worker thread writes
      ``_claims[i]``/``_constrained[i]`` (list-item assignment of a
      float is atomic under the GIL);
    - readers sum the other slots and tolerate ONE-DECISION staleness:
      two shards deciding in the same instant may briefly over-commit
      by at most one dispatch's claim, and the very next decision on
      either shard re-reads and corrects.  Sequentially-ordered
      decisions never overshoot (the property the governor test pins).

    The supervisor zeroes an ejected shard's claim so a dead shard's
    stale reservation cannot starve the survivors.
    """

    def __init__(self, slo_us: float, n_shards: int):
        self.slo_us = slo_us
        self.n_shards = n_shards
        self._claims: List[float] = [0.0] * n_shards  # lock-free: per-shard slots — shard i's worker writes index i (list-item float store, atomic under the GIL); release() zeroes a slot only after the supervisor has quiesced that shard; readers sum and tolerate one-decision staleness
        self._constrained: List[int] = [0] * n_shards  # lock-free: same single-writer-slot discipline as _claims (per-shard decision counters)

    def claim(self, shard: int, added_us: float) -> None:
        """Publish shard ``shard``'s latest predicted added latency."""
        self._claims[shard] = added_us  # holds nothing: single-writer slot

    def release(self, shard: int) -> None:
        """Zero a shard's claim (ejection / shutdown): its reservation
        must not throttle the survivors."""
        self._claims[shard] = 0.0

    def note_constrained(self, shard: int) -> None:
        self._constrained[shard] += 1

    def available_us(self, shard: int) -> float:
        """Budget left for ``shard``: the global SLO minus every OTHER
        shard's published claim (never negative)."""
        others = 0.0
        for i, c in enumerate(self._claims):
            if i != shard:
                others += c
        return max(0.0, self.slo_us - others)

    def committed_us(self) -> float:
        return sum(self._claims)

    def snapshot(self) -> Dict[str, object]:
        claims = list(self._claims)
        return {
            "slo_us": self.slo_us,
            "shards": self.n_shards,
            "committed_us": round(sum(claims), 1),
            "per_shard_claim_us": [round(c, 1) for c in claims],
            "constrained": list(self._constrained),
            "constrained_total": sum(self._constrained),
        }


# Process-global pre-warm ledger: jit caches are per process, so once
# ONE runner (or shard) has compiled a (discipline, table-shape, K)
# bucket every other runner hits it — re-executing the warm dispatch
# per shard would just burn device time.  Keyed by the abstract shapes
# only; values never enter.
_PREWARMED: set = set()


class CoalesceGovernor:
    """Per-runner (per-shard) admit-time K picker.

    Not thread-safe by itself: each :class:`DataplaneRunner` owns one
    instance and calls it only from its own poll thread (the sharded
    engine gives every shard its own governor, like its own rings).
    """

    def __init__(
        self,
        batch_size: int,
        max_vectors: int,
        slo_us: float = 600.0,
        window: int = 2,
        alpha: float = 0.05,
        enabled: bool = True,
    ):
        self.batch_size = batch_size
        self.max_vectors = max_vectors    # the pow2 ceiling
        self.slo_us = slo_us
        self.window = max(1, window)      # in-flight depth a frame may wait behind
        self.alpha = alpha
        self.enabled = enabled
        # Global-budget coordination (sharded engine): when bound, this
        # governor's SLO headroom is what the GovernorLedger has left
        # after the other shards' published claims — N shards share ONE
        # coalesce_slo_us, they do not each assume it (ISSUE 12).
        self.ledger: Optional[GovernorLedger] = None  # owner: bound once at construction by the sharded engine, before workers start
        self.shard_index = 0  # owner: set once at bind time, before workers start
        # Exponentially-weighted least squares for t(K) = floor + K*vec
        # (seconds).  Accumulators decay by (1-alpha) per observation.
        self._s1 = 0.0
        self._sk = 0.0
        self._skk = 0.0
        self._st = 0.0
        self._skt = 0.0
        self.floor_us: Optional[float] = None
        self.vec_us: Optional[float] = None
        # Ramp state for depth-blind sources (AF_PACKET reports only
        # next-frame presence): grow K while admits saturate their cap,
        # decay when they come back less than half full.
        self._ramp_k = 1
        # Observability.
        self.current_k = 1
        self.backlog = 0
        self.decisions = 0
        self.slo_breaches = 0
        self.ledger_constrained = 0
        self.k_hist: Dict[int, int] = {}
        self.samples = 0

    def bind_ledger(self, ledger: GovernorLedger, shard: int) -> None:
        """Join a shared global-budget ledger (sharded engine only).
        Must happen before the shard's worker thread runs — the binding
        itself is single-assignment, never re-bound live."""
        self.ledger = ledger
        self.shard_index = shard

    # ------------------------------------------------------------ model

    def observe(self, k: int, seconds: float) -> None:
        """Feed one measured (K, per-dispatch wall seconds) sample into
        the EW least-squares fit."""
        if seconds <= 0.0 or k <= 0:
            return
        d = 1.0 - self.alpha
        self._s1 = self._s1 * d + 1.0
        self._sk = self._sk * d + k
        self._skk = self._skk * d + k * k
        self._st = self._st * d + seconds
        self._skt = self._skt * d + k * seconds
        self.samples += 1
        det = self._s1 * self._skk - self._sk * self._sk
        mean_t = self._st / self._s1
        mean_k = self._sk / self._s1
        if det > 1e-12 and self._skk / self._s1 > mean_k * mean_k * (1 + 1e-9):
            slope = (self._s1 * self._skt - self._sk * self._st) / det
            intercept = mean_t - slope * mean_k
            # A dispatch has a physical floor >= 0 and vectors cannot
            # take negative time; clamp the fit to the feasible cone
            # (tiny-sample noise can put it outside).
            slope = max(0.0, slope)
            intercept = max(0.0, min(intercept, mean_t))
            self.vec_us = slope * 1e6
            self.floor_us = intercept * 1e6
        else:
            # Degenerate: every sample at the same K — attribute the
            # mean to the floor at that K, leave the slope unknown.
            if self.vec_us is None:
                self.floor_us = mean_t * 1e6
            else:
                self.floor_us = max(0.0, mean_t * 1e6 - mean_k * self.vec_us)

    def predict_us(self, k: int) -> Optional[float]:
        """Predicted wall time of one K-vector dispatch (µs), or None
        before any timing has been observed."""
        if self.floor_us is None:
            return None
        return self.floor_us + k * (self.vec_us or 0.0)

    def _budget_us(self) -> float:
        """This decision's added-latency headroom: the whole SLO for a
        solo governor; what the shared ledger has left after the OTHER
        shards' claims when bound (never more than the SLO itself)."""
        if self.ledger is None:
            return self.slo_us
        return min(self.slo_us, self.ledger.available_us(self.shard_index))

    def slo_cap(self, budget_us: Optional[float] = None) -> int:
        """Largest pow2 K (≤ ceiling) whose predicted ADDED latency
        fits the budget: one dispatch's service time times the
        in-flight window depth, because a frame admitted into a full
        window harvests behind window-1 predecessors' dispatches.
        Deepening ``max_inflight`` therefore SHRINKS the cap — the
        governor compensates for deeper pipelining instead of silently
        multiplying the budget.  (Queue wait before admission is the
        backlog term's business, not this cap's.)  With a bound
        GovernorLedger the budget is the GLOBAL SLO headroom left by
        the other shards — N shards share one budget instead of each
        assuming it.  Optimistic (= ceiling) until the model has
        data."""
        if budget_us is None:
            budget_us = self._budget_us()
        if self.floor_us is None or self.slo_us <= 0:
            return self.max_vectors
        k = 1
        while k * 2 <= self.max_vectors and \
                (self.predict_us(k * 2) or 0.0) * self.window <= budget_us:
            k *= 2
        return k

    # --------------------------------------------------------- decision

    def choose_k(self, backlog: int) -> int:
        """Pick the pow2 vector cap for the next admit from the
        measured ingress backlog depth (``backlog < 0`` = source cannot
        report depth; the saturation ramp stands in)."""
        if not self.enabled:
            self.current_k = self.max_vectors
            return self.max_vectors
        self.decisions += 1
        if backlog is None or backlog < 0:
            k_fill = self._ramp_k
            self.backlog = -1
        else:
            self.backlog = int(backlog)
            k_fill = pow2_vectors(max(1, self.backlog), self.batch_size,
                                  self.max_vectors)
        budget = self._budget_us()
        cap = self.slo_cap(budget)
        if self.ledger is not None and k_fill > cap and \
                cap < self.slo_cap(self.slo_us):
            # The shared ledger (other shards' load), not this shard's
            # own SLO math, shrank the cap AND the shrunken cap binds
            # this decision (the backlog wanted more) — counted so a
            # sub-linear-scaling investigation can see budget contention
            # (DEVGUIDE "Diagnosing sub-linear shard scaling").  A cap
            # shrunk below a level the backlog never asked for is not
            # contention: an idle shard beside a saturated one must not
            # count millions of phantom constraints.  Guard order keeps
            # the second slo_cap evaluation (a pow2 predict loop) off
            # the solo hot path, where no ledger can ever shrink a cap.
            self.ledger_constrained += 1
            self.ledger.note_constrained(self.shard_index)
        if k_fill <= cap:
            k = k_fill
        else:
            # Queueing already dominates: clamping K below the backlog
            # would grow the queue and with it every frame's latency —
            # follow the backlog to the ceiling and account the breach
            # (against the GLOBAL budget when a ledger is bound:
            # saturation of the shared budget is reported, not hidden).
            k = min(k_fill, self.max_vectors)
            pred = self.predict_us(k)
            if pred is not None and pred * self.window > budget:
                self.slo_breaches += 1
        self.current_k = k
        # Publish this decision's claim so the OTHER shards' next caps
        # see it.  The claim is the same quantity slo_cap bounds —
        # predicted service time × window depth; 0 while the model is
        # still warming (an unknown claim must not starve the fleet).
        if self.ledger is not None:
            pred = self.predict_us(k)
            self.ledger.claim(
                self.shard_index,
                (pred or 0.0) * self.window,
            )
        return k

    def admitted(self, n_frames: int, k_cap: int) -> None:
        """Post-admit feedback: records the chosen bucket and drives
        the depth-blind ramp (saturated cap ⇒ double, under-half ⇒
        halve)."""
        k_used = pow2_vectors(max(1, n_frames), self.batch_size, k_cap)
        if n_frames > 0:
            self.k_hist[k_used] = self.k_hist.get(k_used, 0) + 1
        if n_frames >= k_cap * self.batch_size:
            self._ramp_k = min(self.max_vectors, max(self._ramp_k, k_cap) * 2)
        elif n_frames * 2 < k_cap * self.batch_size:
            self._ramp_k = max(1, k_used)

    # ---------------------------------------------------- observability

    def snapshot(self) -> Dict[str, object]:
        return {
            "enabled": self.enabled,
            "slo_us": self.slo_us,
            "ceiling": self.max_vectors,
            "window": self.window,
            "current_k": self.current_k,
            "backlog": self.backlog,
            "floor_us": round(self.floor_us, 1)
            if self.floor_us is not None else None,
            "vec_us": round(self.vec_us, 3)
            if self.vec_us is not None else None,
            "slo_cap": self.slo_cap(),
            "decisions": self.decisions,
            "slo_breaches": self.slo_breaches,
            "ledger_constrained": self.ledger_constrained,
            "samples": self.samples,
            "k_histogram": {str(k): v for k, v in sorted(self.k_hist.items())},
        }
