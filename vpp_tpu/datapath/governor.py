"""Load-adaptive vector coalescing governor.

VPP's core scheduling insight is that the vector size *adapts to
load*: frames accumulate while the previous vector is in flight, so
per-dispatch fixed costs amortise exactly when throughput matters and
vectors stay small (low latency) when the link is quiet (SURVEY §6).
The runner's admit has always been backlog-shaped — it dispatches the
power-of-two bucket of whatever the ring holds — but the CAP was a
static ``max_vectors=64``, the largest coalesce whose *fixed-K* fill
latency held the budget.  That cap leaves the 400+ Mpps capability
band (K=256, NATPROFILE_r05: the production dispatch is
dispatch-floor-bound; device compute is essentially free) on the
table at exactly the loads where latency is already queue-dominated.

The governor replaces the static pick with a per-admit decision:

- **Backlog term.**  ``K_fill`` = the pow2 vector count covering the
  measured ingress backlog.  Frames already queued pay no extra fill
  wait for a deeper coalesce — they are *there* — so deep backlog ⇒
  large K (amortising the dispatch floor *reduces* their latency),
  idle link ⇒ K=1.
- **SLO term.**  An online exponentially-weighted least-squares fit
  of the dispatch time model ``t(K) = floor + K·vec`` (the dispatch
  floor and per-vector service time, learned from harvest timings).
  ``K_slo`` = the largest pow2 whose predicted *added latency* —
  service time times the in-flight window depth a frame may wait
  behind — stays under the configured budget.  The governor
  speculates above the backlog only never; it CAPS at ``K_slo`` when
  the queue does not already demand more.
- **Breach accounting.**  When backlog demands more than ``K_slo``
  allows, clamping would only grow the queue (and with it latency):
  the governor follows the backlog up to the ceiling and counts an
  ``slo_breach`` — saturation is reported, not hidden.

The same pow2 bucketing as the fixed cap bounds jit recompiles, and
:meth:`DataplaneRunner.prewarm_buckets` compiles every bucket up to
the ceiling at start/table-swap time so a load spike never stalls on
compilation (see ``_PREWARMED``).

HyperNAT (arXiv:2111.08193) makes the same amortise-the-fixed-
offload-cost argument for SmartNIC NAT; RVH (arXiv:1909.07159) shows
classification batching frontiers are load-dependent — the right K is
a function of offered load, not a constant.
"""

from __future__ import annotations

from typing import Dict, Optional


def pow2_vectors(n_frames: int, batch_size: int, cap: int) -> int:
    """The power-of-two vector count whose ``k * batch_size`` covers
    ``n_frames``, capped at ``cap`` (bounded jit recompiles).  The ONE
    sizing rule shared by the runner's admits, the quarantine's
    sub-batch packer, and the governor."""
    k = 1
    while k * batch_size < n_frames and k < cap:
        k *= 2
    return k


# Process-global pre-warm ledger: jit caches are per process, so once
# ONE runner (or shard) has compiled a (discipline, table-shape, K)
# bucket every other runner hits it — re-executing the warm dispatch
# per shard would just burn device time.  Keyed by the abstract shapes
# only; values never enter.
_PREWARMED: set = set()


class CoalesceGovernor:
    """Per-runner (per-shard) admit-time K picker.

    Not thread-safe by itself: each :class:`DataplaneRunner` owns one
    instance and calls it only from its own poll thread (the sharded
    engine gives every shard its own governor, like its own rings).
    """

    def __init__(
        self,
        batch_size: int,
        max_vectors: int,
        slo_us: float = 600.0,
        window: int = 2,
        alpha: float = 0.05,
        enabled: bool = True,
    ):
        self.batch_size = batch_size
        self.max_vectors = max_vectors    # the pow2 ceiling
        self.slo_us = slo_us
        self.window = max(1, window)      # in-flight depth a frame may wait behind
        self.alpha = alpha
        self.enabled = enabled
        # Exponentially-weighted least squares for t(K) = floor + K*vec
        # (seconds).  Accumulators decay by (1-alpha) per observation.
        self._s1 = 0.0
        self._sk = 0.0
        self._skk = 0.0
        self._st = 0.0
        self._skt = 0.0
        self.floor_us: Optional[float] = None
        self.vec_us: Optional[float] = None
        # Ramp state for depth-blind sources (AF_PACKET reports only
        # next-frame presence): grow K while admits saturate their cap,
        # decay when they come back less than half full.
        self._ramp_k = 1
        # Observability.
        self.current_k = 1
        self.backlog = 0
        self.decisions = 0
        self.slo_breaches = 0
        self.k_hist: Dict[int, int] = {}
        self.samples = 0

    # ------------------------------------------------------------ model

    def observe(self, k: int, seconds: float) -> None:
        """Feed one measured (K, per-dispatch wall seconds) sample into
        the EW least-squares fit."""
        if seconds <= 0.0 or k <= 0:
            return
        d = 1.0 - self.alpha
        self._s1 = self._s1 * d + 1.0
        self._sk = self._sk * d + k
        self._skk = self._skk * d + k * k
        self._st = self._st * d + seconds
        self._skt = self._skt * d + k * seconds
        self.samples += 1
        det = self._s1 * self._skk - self._sk * self._sk
        mean_t = self._st / self._s1
        mean_k = self._sk / self._s1
        if det > 1e-12 and self._skk / self._s1 > mean_k * mean_k * (1 + 1e-9):
            slope = (self._s1 * self._skt - self._sk * self._st) / det
            intercept = mean_t - slope * mean_k
            # A dispatch has a physical floor >= 0 and vectors cannot
            # take negative time; clamp the fit to the feasible cone
            # (tiny-sample noise can put it outside).
            slope = max(0.0, slope)
            intercept = max(0.0, min(intercept, mean_t))
            self.vec_us = slope * 1e6
            self.floor_us = intercept * 1e6
        else:
            # Degenerate: every sample at the same K — attribute the
            # mean to the floor at that K, leave the slope unknown.
            if self.vec_us is None:
                self.floor_us = mean_t * 1e6
            else:
                self.floor_us = max(0.0, mean_t * 1e6 - mean_k * self.vec_us)

    def predict_us(self, k: int) -> Optional[float]:
        """Predicted wall time of one K-vector dispatch (µs), or None
        before any timing has been observed."""
        if self.floor_us is None:
            return None
        return self.floor_us + k * (self.vec_us or 0.0)

    def slo_cap(self) -> int:
        """Largest pow2 K (≤ ceiling) whose predicted ADDED latency
        fits the budget: one dispatch's service time times the
        in-flight window depth, because a frame admitted into a full
        window harvests behind window-1 predecessors' dispatches.
        Deepening ``max_inflight`` therefore SHRINKS the cap — the
        governor compensates for deeper pipelining instead of silently
        multiplying the budget.  (Queue wait before admission is the
        backlog term's business, not this cap's.)  Optimistic
        (= ceiling) until the model has data."""
        if self.floor_us is None or self.slo_us <= 0:
            return self.max_vectors
        k = 1
        while k * 2 <= self.max_vectors and \
                (self.predict_us(k * 2) or 0.0) * self.window <= self.slo_us:
            k *= 2
        return k

    # --------------------------------------------------------- decision

    def choose_k(self, backlog: int) -> int:
        """Pick the pow2 vector cap for the next admit from the
        measured ingress backlog depth (``backlog < 0`` = source cannot
        report depth; the saturation ramp stands in)."""
        if not self.enabled:
            self.current_k = self.max_vectors
            return self.max_vectors
        self.decisions += 1
        if backlog is None or backlog < 0:
            k_fill = self._ramp_k
            self.backlog = -1
        else:
            self.backlog = int(backlog)
            k_fill = pow2_vectors(max(1, self.backlog), self.batch_size,
                                  self.max_vectors)
        cap = self.slo_cap()
        if k_fill <= cap:
            k = k_fill
        else:
            # Queueing already dominates: clamping K below the backlog
            # would grow the queue and with it every frame's latency —
            # follow the backlog to the ceiling and account the breach.
            k = min(k_fill, self.max_vectors)
            pred = self.predict_us(k)
            if pred is not None and pred * self.window > self.slo_us:
                self.slo_breaches += 1
        self.current_k = k
        return k

    def admitted(self, n_frames: int, k_cap: int) -> None:
        """Post-admit feedback: records the chosen bucket and drives
        the depth-blind ramp (saturated cap ⇒ double, under-half ⇒
        halve)."""
        k_used = pow2_vectors(max(1, n_frames), self.batch_size, k_cap)
        if n_frames > 0:
            self.k_hist[k_used] = self.k_hist.get(k_used, 0) + 1
        if n_frames >= k_cap * self.batch_size:
            self._ramp_k = min(self.max_vectors, max(self._ramp_k, k_cap) * 2)
        elif n_frames * 2 < k_cap * self.batch_size:
            self._ramp_k = max(1, k_used)

    # ---------------------------------------------------- observability

    def snapshot(self) -> Dict[str, object]:
        return {
            "enabled": self.enabled,
            "slo_us": self.slo_us,
            "ceiling": self.max_vectors,
            "window": self.window,
            "current_k": self.current_k,
            "backlog": self.backlog,
            "floor_us": round(self.floor_us, 1)
            if self.floor_us is not None else None,
            "vec_us": round(self.vec_us, 3)
            if self.vec_us is not None else None,
            "slo_cap": self.slo_cap(),
            "decisions": self.decisions,
            "slo_breaches": self.slo_breaches,
            "samples": self.samples,
            "k_histogram": {str(k): v for k, v in sorted(self.k_hist.items())},
        }
