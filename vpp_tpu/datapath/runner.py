"""The dataplane runner — frames in, TPU pipeline, frames out.

This is the component the round-1 verdict called "the difference
between a kernel benchmark and a dataplane": a loop that continuously
ingests raw Ethernet frames, keeps multiple batches in flight through
the jit-compiled classify→NAT→route pipeline, applies verdicts and
rewrites natively (hostshim, RFC 1624 incremental checksums), VXLAN-
encapsulates traffic bound for other nodes, and punts session
anomalies to the exact host slow path.

Double buffering rides JAX's async dispatch: ``pipeline_step_jit``
returns device futures immediately and the next batch's dispatch
chains on the previous result's session array *without* materialising
it — the host only blocks when it harvests the oldest in-flight batch,
by which time ≥1 newer batch is already queued behind it on device.
This is the memif/DPDK in-flight vector discipline of the reference's
data plane (SURVEY §7.3 double-buffered transfers).
"""

from __future__ import annotations

import collections
import dataclasses
from typing import Deque, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.nat import NatSessions, NatTables, empty_sessions, session_occupancy, sweep_sessions
from ..ops.classify import RuleTables
from ..ops.packets import PacketBatch
from ..ops.pipeline import (
    ROUTE_HOST,
    ROUTE_LOCAL,
    ROUTE_REMOTE,
    VECTOR_SIZE,
    RouteConfig,
    flatten_scan_result,
    pipeline_scan_jit,
    pipeline_step_jit,
)
from ..ops.slowpath import HostSlowPath
from ..shim.hostshim import FrameBatch, HostShim
from .io import FrameSink, FrameSource
from .trace import PacketTracer


@dataclasses.dataclass
class VxlanOverlay:
    """Full-mesh overlay config: node-ID-indexed remote VTEP IPs.

    The analog of the reference's per-node VXLAN tunnel set inside one
    bridge domain (plugins/ipv4net/node.go vxlanIfToOtherNode :524,
    VNI 10/port 4789 full mesh per docs/NETWORKING.md:127-144).
    """

    local_ip: int
    local_node_id: int
    vni: int = 10
    max_nodes: int = 256

    def __post_init__(self):
        self.remote_ips = np.zeros(self.max_nodes, dtype=np.uint32)

    def set_remote(self, node_id: int, ip: int) -> None:
        if node_id >= len(self.remote_ips):
            grown = np.zeros(node_id + 1, dtype=np.uint32)
            grown[: len(self.remote_ips)] = self.remote_ips
            self.remote_ips = grown
        self.remote_ips[node_id] = ip

    def del_remote(self, node_id: int) -> None:
        if 0 <= node_id < len(self.remote_ips):
            self.remote_ips[node_id] = 0


@dataclasses.dataclass
class RunnerCounters:
    rx_frames: int = 0
    rx_decapped: int = 0
    tx_local: int = 0
    tx_remote: int = 0
    tx_host: int = 0
    dropped_denied: int = 0
    dropped_slowpath: int = 0
    dropped_unroutable: int = 0
    dropped_unparseable: int = 0
    dropped_foreign_vni: int = 0
    punts: int = 0
    host_restores: int = 0
    batches: int = 0

    def as_dict(self) -> Dict[str, int]:
        return {f"datapath_{k}_total": v for k, v in dataclasses.asdict(self).items()}


class DataplaneRunner:
    """Per-node datapath: source → decap → TPU pipeline → apply →
    {local sink, VXLAN-encapped remote sink, host sink}."""

    def __init__(
        self,
        acl: RuleTables,
        nat: NatTables,
        route: RouteConfig,
        overlay: VxlanOverlay,
        source: FrameSource,
        tx: FrameSink,
        local: Optional[FrameSink] = None,
        host: Optional[FrameSink] = None,
        batch_size: int = 256,
        max_vectors: int = 1,
        max_inflight: int = 2,
        session_capacity: int = 1 << 16,
        sweep_interval: int = 4096,
        sweep_max_age: int = 1 << 20,
        shim: Optional[HostShim] = None,
    ):
        self.acl = acl
        self.nat = nat
        self.route = route
        self.overlay = overlay
        self.source = source
        self.tx = tx
        self.local = local if local is not None else tx
        self.host = host if host is not None else tx
        self.batch_size = batch_size
        # When >1, coalesce up to max_vectors queued batch_size-packet
        # vectors into ONE device dispatch via pipeline_scan: sessions
        # thread between vectors on device, dispatch cost amortises
        # K-fold.  K is bucketed to powers of two to bound recompiles,
        # so the effective cap is the power-of-two floor of max_vectors.
        self.max_vectors = 1
        while self.max_vectors * 2 <= max(1, max_vectors):
            self.max_vectors *= 2
        self.max_inflight = max(1, max_inflight)
        self.sweep_interval = sweep_interval
        self.sweep_max_age = sweep_max_age
        self.shim = shim or HostShim()
        self.sessions: NatSessions = empty_sessions(session_capacity)
        self.slow = HostSlowPath()
        self.counters = RunnerCounters()
        # Sampled per-packet verdict traces (vpptrace analog), enabled on
        # demand via REST/netctl.
        self.tracer = PacketTracer()
        self._ts = 0
        # In-flight queue of (FrameBatch, PipelineResult, ts).
        self._inflight: Deque[Tuple[FrameBatch, object, int]] = collections.deque()

    # ------------------------------------------------------------- tables

    def update_tables(
        self,
        acl: Optional[RuleTables] = None,
        nat: Optional[NatTables] = None,
        route: Optional[RouteConfig] = None,
    ) -> None:
        """Atomic table swap: takes effect for the NEXT dispatched batch
        (in-flight batches complete against the tables they saw — the
        same semantics as VPP's ACL/NAT table swap under traffic)."""
        if acl is not None:
            self.acl = acl
        if nat is not None:
            self.nat = nat
        if route is not None:
            self.route = route

    # --------------------------------------------------------------- loop

    def poll(self) -> int:
        """One scheduling turn: admit new batches up to the in-flight
        window, then harvest the oldest completed batch.  Returns the
        number of frames transmitted this turn."""
        admitted = True
        while len(self._inflight) < self.max_inflight and admitted:
            admitted = self._admit()
        if not self._inflight:
            return 0
        return self._harvest()

    def drain(self) -> int:
        """Run until the source is idle and all in-flight work is
        harvested; returns total frames transmitted."""
        total = 0
        while True:
            total += self.poll()
            if not self._inflight and not self._admit():
                return total

    def _admit(self) -> bool:
        frames = self.source.recv_batch(self.batch_size * self.max_vectors)
        if not frames:
            return False
        self.counters.rx_frames += len(frames)
        # Pack once; every later stage works on views into this buffer.
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(len(frames), dtype=np.uint64)
        np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(b"".join(frames), dtype=np.uint8).copy()
        # Overlay ingress: de-encapsulate VXLAN frames (offset math in
        # native code, zero copies).  Only our VNI belongs to this
        # overlay segment — foreign VNIs are dropped, preserving the
        # reference's one-bridge-domain-per-VNI isolation
        # (plugins/ipv4net/node.go vxlanBridgeDomain :482).
        in_off, in_len, vnis = self.shim.vxlan_decap_view(buf, offsets, lens)
        is_vxlan = vnis >= 0
        keep = ~is_vxlan | (vnis == self.overlay.vni)
        self.counters.rx_decapped += int((is_vxlan & keep).sum())
        self.counters.dropped_foreign_vni += int((~keep).sum())
        if not keep.all():
            in_off, in_len = in_off[keep], in_len[keep]
            if not len(in_off):
                return True  # batch consumed entirely by foreign-VNI drops
        # Vector count for this dispatch: enough 256-pkt vectors to hold
        # the kept frames, bucketed to a power of two (bounded compiles).
        n_kept = len(in_off)
        k = 1
        while k * self.batch_size < n_kept and k < self.max_vectors:
            k *= 2
        fb = self.shim.parse_view(buf, in_off, in_len, pad_to=k * self.batch_size)
        batch = PacketBatch(
            src_ip=jnp.asarray(fb.batch.src_ip),
            dst_ip=jnp.asarray(fb.batch.dst_ip),
            protocol=jnp.asarray(fb.batch.protocol),
            src_port=jnp.asarray(fb.batch.src_port),
            dst_port=jnp.asarray(fb.batch.dst_port),
        )
        prev_ts = self._ts
        self._ts += k
        if k == 1:
            result = pipeline_step_jit(
                self.acl, self.nat, self.route, self.sessions, batch,
                jnp.int32(self._ts),
            )
        else:
            vectors = jax.tree_util.tree_map(
                lambda a: a.reshape((k, self.batch_size) + a.shape[1:]), batch
            )
            tss = jnp.arange(prev_ts + 1, prev_ts + 1 + k, dtype=jnp.int32)
            result = flatten_scan_result(
                pipeline_scan_jit(
                    self.acl, self.nat, self.route, self.sessions, vectors, tss
                )
            )
        # Chain the session state into the next dispatch WITHOUT
        # materialising — keeps the device busy back-to-back.
        self.sessions = result.sessions
        self._inflight.append((fb, result, self._ts))
        self.counters.batches += 1
        if self.sweep_interval and (
            self._ts // self.sweep_interval != prev_ts // self.sweep_interval
        ):
            self.sessions = sweep_sessions(self.sessions, self._ts, self.sweep_max_age)
            self.slow.sweep(self._ts, self.sweep_max_age)
        return True

    def _harvest(self) -> int:
        fb, result, ts = self._inflight.popleft()
        n = fb.n
        # Materialise (blocks on THIS batch only; newer ones stay queued).
        allowed = np.asarray(result.allowed)[:n].copy()
        route_tag = np.asarray(result.route)[:n].copy()
        node_id = np.asarray(result.node_id)[:n].copy()
        punt = np.asarray(result.punt)[:n]
        reply_hit = np.asarray(result.reply_hit)[:n]
        dnat_hit = np.asarray(result.dnat_hit)[:n]
        snat_hit = np.asarray(result.snat_hit)[:n]
        rew = {
            "src_ip": np.asarray(result.batch.src_ip)[:n].copy(),
            "dst_ip": np.asarray(result.batch.dst_ip)[:n].copy(),
            "protocol": np.asarray(result.batch.protocol)[:n],
            "src_port": np.asarray(result.batch.src_port)[:n].copy(),
            "dst_port": np.asarray(result.batch.dst_port)[:n].copy(),
        }
        orig = {
            "src_ip": np.asarray(fb.batch.src_ip)[:n],
            "dst_ip": np.asarray(fb.batch.dst_ip)[:n],
            "protocol": np.asarray(fb.batch.protocol)[:n],
            "src_port": np.asarray(fb.batch.src_port)[:n],
            "dst_port": np.asarray(fb.batch.dst_port)[:n],
        }

        # ------------------------------------------------ host slow path
        slow_drops = 0
        if punt.any():
            self.counters.punts += int(punt.sum())
            outcome = self.slow.record_punts(orig, rew, punt, snat_hit, ts)
            for row, port in outcome.fixups:
                rew["src_port"][row] = port
            for row in outcome.drops:
                allowed[row] = False
            slow_drops = len(outcome.drops)
            self.counters.dropped_slowpath += slow_drops
        if len(self.slow):
            # Forward packets of flows with host port overrides.
            for row, port in self.slow.fixup_forward(orig, snat_hit & ~punt):
                rew["src_port"][row] = port
            # Replies that missed the device table.
            cand = ~reply_hit & ~dnat_hit & ~snat_hit
            restored = self.slow.restore_replies(orig, cand, ts)
            if restored:
                self.counters.host_restores += len(restored)
                for row, (s_ip, s_port, d_ip, d_port) in restored:
                    rew["src_ip"][row] = s_ip
                    rew["src_port"][row] = s_port
                    rew["dst_ip"][row] = d_ip
                    rew["dst_port"][row] = d_port
                    allowed[row] = True
                    route_tag[row], node_id[row] = self._route_of(d_ip)

        # ------------------------------------------------- packet trace
        self.tracer.record_batch(
            ts, orig, rew, allowed, route_tag, node_id,
            dnat_hit, snat_hit, reply_hit, punt,
        )

        # -------------------------------------------- native apply + TX
        rew_batch = PacketBatch(
            src_ip=rew["src_ip"], dst_ip=rew["dst_ip"], protocol=rew["protocol"],
            src_port=rew["src_port"], dst_port=rew["dst_port"],
        )
        fwd = self.shim.apply_masked(fb, allowed, rew_batch)
        allowed_bool = allowed.astype(bool)
        # Pipeline/policy denies exclude rows the slow path already
        # counted; rows permitted but unforwardable are parse failures
        # (non-IPv4 frames), not denials.
        self.counters.dropped_denied += int((~allowed_bool).sum()) - slow_drops
        self.counters.dropped_unparseable += int((allowed_bool & (fwd == 0)).sum())

        is_remote = (route_tag == ROUTE_REMOTE).astype(np.uint8)
        out_buf, out_off, out_len, out_rows, unroutable = self.shim.vxlan_encap(
            fb, fwd, is_remote, node_id, self.overlay.remote_ips,
            self.overlay.local_ip, self.overlay.local_node_id, self.overlay.vni,
        )
        self.counters.dropped_unroutable += unroutable
        sent = 0
        if len(out_rows):
            remote_frames = [
                out_buf[int(out_off[j]):int(out_off[j]) + int(out_len[j])].tobytes()
                for j in range(len(out_rows))
            ]
            self.tx.send(remote_frames)
            self.counters.tx_remote += len(remote_frames)
            sent += len(remote_frames)

        local_rows = np.nonzero(fwd.astype(bool) & (route_tag == ROUTE_LOCAL))[0]
        if len(local_rows):
            frames = [fb.frame(int(i)) for i in local_rows]
            self.local.send(frames)
            self.counters.tx_local += len(frames)
            sent += len(frames)

        host_rows = np.nonzero(fwd.astype(bool) & (route_tag == ROUTE_HOST))[0]
        if len(host_rows):
            frames = [fb.frame(int(i)) for i in host_rows]
            self.host.send(frames)
            self.counters.tx_host += len(frames)
            sent += len(frames)
        return sent

    def _route_of(self, dst_ip: int) -> Tuple[int, int]:
        """Host-side mirror of the pipeline's node-ID route arithmetic
        (for slow-path-restored packets only)."""
        base = int(np.asarray(self.route.pod_subnet_base))
        mask = int(np.asarray(self.route.pod_subnet_mask))
        tbase = int(np.asarray(self.route.this_node_base))
        tmask = int(np.asarray(self.route.this_node_mask))
        hbits = int(np.asarray(self.route.host_bits))
        if (dst_ip & tmask) == tbase:
            return ROUTE_LOCAL, 0
        if (dst_ip & mask) == base:
            return ROUTE_REMOTE, (dst_ip - base) >> hbits
        return ROUTE_HOST, 0

    # ------------------------------------------------------------ metrics

    def metrics(self) -> Dict[str, int]:
        out = self.counters.as_dict()
        out.update(self.slow.counters.as_dict())
        out["datapath_sessions_active"] = session_occupancy(self.sessions)
        out["datapath_slowpath_sessions_active"] = len(self.slow)
        out["datapath_inflight"] = len(self._inflight)
        return out
