"""The dataplane runner — frames in, TPU pipeline, frames out.

This is the component the round-1 verdict called "the difference
between a kernel benchmark and a dataplane": a loop that continuously
ingests raw Ethernet frames, keeps multiple batches in flight through
the jit-compiled classify→NAT→route pipeline, applies verdicts and
rewrites natively (hostshim, RFC 1624 incremental checksums), VXLAN-
encapsulates traffic bound for other nodes, and punts session
anomalies to the exact host slow path.

Double buffering rides JAX's async dispatch: ``pipeline_step_jit``
returns device futures immediately and the next batch's dispatch
chains on the previous result's session array *without* materialising
it — the host only blocks when it harvests the oldest in-flight batch,
by which time ≥1 newer batch is already queued behind it on device.
This is the memif/DPDK in-flight vector discipline of the reference's
data plane (SURVEY §7.3 double-buffered transfers).
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time
from typing import Callable, Deque, Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..ops.nat import (
    NatSessions, NatTables, affinity_occupancy, empty_sessions,
    retarget_tables, session_occupancy, sweep_affinity, sweep_sessions,
)
from ..ops.classify import RuleTables
from ..ops.infer import (
    INFER_ACT_DEPRIORITIZE,
    INFER_ACT_LOG,
    INFER_ACT_QUARANTINE,
    INFER_BANDS,
    InferTable,
)
from ..ops.packets import PacketBatch
from ..ops.pipeline import (
    PACKED_WORD,
    ROUTE_HOST,
    ROUTE_LOCAL,
    ROUTE_REMOTE,
    VECTOR_SIZE,
    VERDICT_ALLOWED,
    VERDICT_PUNT,
    RouteConfig,
    pack_verdicts_host,
    pipeline_flat_punt_ts0_jit,
    pipeline_flat_safe_ts0_jit,
    pipeline_scan_ts0_jit,
    pipeline_step_jit,
    unpack_verdicts,
)
from ..ops.slowpath import HostSlowPath, resolve_stragglers
from ..shim.hostshim import FrameBatch, HostShim, NativeLoop, NativeRing
from ..telemetry import (
    FlightRecorder,
    LatencyRecorder,
    Log2Histogram,
    record_stage,
)
from ..testing.faults import (
    SITE_DISPATCH_HANG,
    SITE_DISPATCH_RAISE,
    SITE_FRAME_SOURCE_ERROR,
    SITE_SWAP_FAIL,
    FaultInjected,
    FaultInjector,
)
from .governor import _PREWARMED, CoalesceGovernor, pow2_vectors
from .io import FrameSink, FrameSource
from .trace import PacketTracer


class TableSwapError(RuntimeError):
    """A table swap failed and was ROLLED BACK — every shard still
    serves the previous (last-good) tables.  Retriable: when the swap
    came from a scheduler applicator's ``on_compiled`` hook, the
    scheduler absorbs this into FAILED state + backoff retries, and an
    exhausted retry budget escalates to the controller's healing
    resync — the data plane never crashes and never splits brain."""


_BATCH_FIELDS = ("src_ip", "dst_ip", "protocol", "src_port", "dst_port")

# The per-dispatch host rounds the attribution histograms split the
# admit→harvest wall into (see DataplaneRunner.rounds).  Order is the
# execution order within one harvested dispatch.
DISPATCH_ROUNDS = ("wait", "materialize", "restore", "stitch")


@dataclasses.dataclass
class _HostResult:
    """A packed-result lookalike assembled on the HOST by the
    poisoned-batch quarantine: the packed verdict+rewrite rows are
    stitched together from the surviving sub-dispatches (numpy, same
    uint32 [4, B] layout as the device packing tail), with poisoned
    rows forced to deny.  The harvest paths only ever materialise and
    unpack ``.packed``, so it substitutes transparently."""

    packed: np.ndarray
    poisoned_rows: np.ndarray


@dataclasses.dataclass
class VxlanOverlay:
    """Full-mesh overlay config: node-ID-indexed remote VTEP IPs.

    The analog of the reference's per-node VXLAN tunnel set inside one
    bridge domain (plugins/ipv4net/node.go vxlanIfToOtherNode :524,
    VNI 10/port 4789 full mesh per docs/NETWORKING.md:127-144).
    """

    local_ip: int
    local_node_id: int
    vni: int = 10
    max_nodes: int = 256

    def __post_init__(self):
        self.remote_ips = np.zeros(self.max_nodes, dtype=np.uint32)

    def set_remote(self, node_id: int, ip: int) -> None:
        if node_id >= len(self.remote_ips):
            grown = np.zeros(node_id + 1, dtype=np.uint32)
            grown[: len(self.remote_ips)] = self.remote_ips
            self.remote_ips = grown
        self.remote_ips[node_id] = ip

    def del_remote(self, node_id: int) -> None:
        if 0 <= node_id < len(self.remote_ips):
            self.remote_ips[node_id] = 0


class DeviceSessionState:
    """Device-resident NAT session table + batch timestamp, shareable
    across shard runners (vpp_tpu/datapath/shards.py): the table is ONE
    device array regardless of how many host-side shards feed it, so a
    forward flow admitted on shard 0 restores its reply on shard 3 —
    no cross-worker handoff needed (the reference's NAT worker-handoff
    problem disappears because session state lives on the device, not
    per-core).  ``lock`` serialises jit dispatches so the session state
    threads dispatch-to-dispatch in a single total order."""

    def __init__(self, capacity: int = 1 << 16):
        self.sessions: NatSessions = empty_sessions(capacity)  # guarded-by: lock
        self.ts = 0             # guarded-by: lock
        self.lock = threading.RLock()
        # (ts, wall-time) of the last sweep — the affinity expiry
        # converts per-mapping SECONDS to timestamp units at the rate
        # measured between sweeps.
        self.sweep_mark = None
        # True once a has_affinity table has dispatched: pins may exist
        # in the shared session table.  Keeps the affinity sweep alive
        # after the LAST ClientIP service is deleted (tables rebuild
        # with has_affinity=False) so orphaned pins drain instead of
        # occupying slots forever — sweep_sessions deliberately skips
        # affinity rows, so nothing else would ever free them.  Cleared
        # when a sweep of a no-affinity table finds zero pins left.
        self.aff_pinned = False  # guarded-by: lock


@dataclasses.dataclass
class RunnerCounters:  # owner: shard worker — admit/dispatch/harvest/bypass all run inside this runner's poll(); swap ticks touch a quiesced or solo runner
    rx_frames: int = 0
    rx_decapped: int = 0
    tx_local: int = 0
    tx_remote: int = 0
    tx_host: int = 0
    dropped_denied: int = 0
    dropped_slowpath: int = 0
    dropped_unroutable: int = 0
    dropped_unparseable: int = 0
    dropped_foreign_vni: int = 0
    punts: int = 0
    host_restores: int = 0
    batches: int = 0
    bypass_batches: int = 0
    # Control→data plane swap observability: one tick per update_tables
    # table adoption (delta swaps included — the swap itself is always
    # atomic whole-object; what shrinks is the bytes shipped, counted by
    # the builders' DeltaStats surfaced via inspect()["compile"]).
    acl_swaps: int = 0
    nat_swaps: int = 0
    route_swaps: int = 0
    # Fault-domain observability: dispatch exceptions seen (including
    # those the quarantine recovered from), frame-source errors
    # absorbed, batches that went through bisection, frames dropped as
    # poisoned, and table swaps rolled back to last-good.
    dispatch_errors: int = 0
    source_errors: int = 0
    quarantined_batches: int = 0
    dropped_poisoned: int = 0
    swap_rollbacks: int = 0
    # Bytes the python admit did NOT copy a second time since the
    # packed buffer became single-pass writable (bytearray join): the
    # old np.frombuffer(join).copy() duplicated every batch.
    admit_copy_saved_bytes: int = 0
    # Bytes the harvest did NOT copy out of the materialised packed
    # result because nothing could mutate the verdicts (no punts, no
    # live host sessions, solo slow path): the all-fast-path case stays
    # zero-copy on BOTH engines (the python engine unconditionally
    # copied every leaf before ISSUE 11).
    harvest_copy_saved_bytes: int = 0
    # flat-punt round-cut discipline: same-dispatch replies the device
    # probe detected and punted, and how many of them the host resolved
    # against the same batch's committed forwards (the rest fall to the
    # ordinary punt path — crafted aliasing corners only).
    straggler_punts: int = 0
    straggler_restores: int = 0
    # In-network inference (ISSUE 14): rows the device scorer evaluated
    # (enrolled pod traffic), per-action firings, and inference-table
    # swap adoptions.  Quarantined rows are dropped + pcap-captured +
    # flight-recorded through the PR 3 forensics path; they are counted
    # HERE, not in dropped_denied.
    inference_scored: int = 0
    inference_logged: int = 0
    inference_deprioritized: int = 0
    inference_quarantined: int = 0
    inference_swaps: int = 0

    def as_dict(self) -> Dict[str, int]:
        return {f"datapath_{k}_total": v for k, v in dataclasses.asdict(self).items()}


class DataplaneRunner:
    """Per-node datapath: source → decap → TPU pipeline → apply →
    {local sink, VXLAN-encapped remote sink, host sink}."""

    def __init__(
        self,
        acl: RuleTables,
        nat: NatTables,
        route: RouteConfig,
        overlay: VxlanOverlay,
        source: FrameSource,
        tx: FrameSink,
        local: Optional[FrameSink] = None,
        host: Optional[FrameSink] = None,
        batch_size: int = 256,
        # max_vectors is the coalesce CEILING, not the pick: the
        # governor (datapath/governor.py) chooses the per-admit pow2 K
        # from the measured backlog depth under the added-latency SLO,
        # so the ceiling can sit in the capability band (K=256 sustains
        # 425-480 Mpps on the tunnel, NATPROFILE_r05/BENCHLAT_r05)
        # without the fixed-K latency pathology that forced the old
        # static 64 (K=256's 1.6 ms fill at 40 Mpps offered — 65 ms at
        # 1 Mpps! — blew every budget at low load).  An idle link still
        # dispatches K=1; only a deep queue earns a deep coalesce.
        max_vectors: int = 256,
        # In-flight dispatch window: how many outstanding device
        # dispatches host admit/parse may run ahead of the oldest
        # unharvested batch (VPP's in-flight vector discipline,
        # generalised from the historical fixed 2).  Deeper windows
        # overlap more host work with device time on floor-bound links;
        # the governor folds the depth into its SLO math (a frame may
        # wait behind window-1 predecessors' service).
        max_inflight: int = 2,
        # Coalesce governor: "adaptive" (default) picks K per admit
        # from backlog + EWMA dispatch-time estimates under
        # coalesce_slo_us of added latency; "fixed" restores the
        # static-cap behavior (always admit up to the ceiling).
        coalesce: str = "adaptive",
        coalesce_slo_us: float = 600.0,
        # Pre-warm: compile EVERY pow2 K bucket up to the ceiling at
        # construction/table-swap time so a load spike never stalls on
        # jit compilation.  Off by default (a swap-time compile burst
        # is wrong for short-lived test runners); production agents
        # enable it via NetworkConfig.coalesce_prewarm.
        prewarm: bool = False,
        session_capacity: int = 1 << 16,
        # Sweeps (idle-session GC + ClientIP-affinity expiry) run every
        # sweep_interval dispatched vectors.  Affinity timeouts are
        # therefore enforced at HOST-SWEEP granularity, best-effort by
        # design: a pin can overstay session_affinity_timeout by up to
        # one sweep interval (plus ts-rate estimation error — the
        # seconds→ts conversion uses the rate measured between the last
        # two sweeps, so idle gaps skew it), and keeps overriding the
        # hash pick until the sweep lands.  The in-dispatch lookup
        # deliberately does no age check: the reference's nat44 affinity
        # likewise expires on its cleanup scan, and an on-device bound
        # would buy sub-sweep precision nobody observes at the cost of a
        # per-packet gather of the timeout column.
        sweep_interval: int = 4096,
        sweep_max_age: int = 1 << 20,
        shim: Optional[HostShim] = None,
        engine: Optional[str] = None,
        mesh=None,
        partition_sessions: bool = False,
        # Multi-vector dispatch discipline: "scan" threads sessions
        # vector-to-vector with lax.scan (VPP's sequential-vector
        # semantics on device); "flat-safe" runs every vector batch-
        # parallel and recovers same-dispatch replies with post-commit
        # re-probes (pipeline_flat_safe) — faster at the production
        # coalesce on TPU, restores same-VECTOR replies the scan
        # cannot, and punts crafted-aliasing corners to the host slow
        # path instead of restoring them.  "flat-punt" (ISSUE 11) is
        # flat-safe with the straggler RESTORE cut: detected
        # same-dispatch replies punt to the host slow path (resolved
        # there against the same batch's forwards — never silently
        # mistranslated like plain flat), trimming the one read that
        # depends on the finalize scatter — the dependent session-sync
        # round MESHOVERHEAD_r05 showed each cost a collective on a
        # sharded mesh.  "auto" (default) picks per the backend this
        # runner dispatches to.  As of r4 the pick is flat-safe
        # EVERYWHERE: the commit-first restructure deleted the
        # pre-table restore probe, and the r3 CPU ordering (scan ~45%
        # ahead) REVERSED — flat-safe now measures ~70% ahead of scan
        # on CPU too (FRAMEBENCH_r04: 1.9-2.0 vs 1.1-1.2 Mpps e2e).
        # The knob stays: scan/flat-punt remain selectable per node
        # (pick flat-punt on meshes / round-trip-bound tunnels, see
        # docs/ARCHITECTURE.md "Dispatch round chain") and "auto"
        # keeps the seam for backends where the ordering may differ.
        dispatch: str = "auto",
        # Sharing hooks for the multi-shard engine (shards.py): a common
        # DeviceSessionState (one device session table for all shards),
        # a common host slow path + tracer, and the lock guarding them.
        state: Optional[DeviceSessionState] = None,
        slow=None,
        tracer=None,
        host_lock: Optional[threading.Lock] = None,
        # Fault domain: the (possibly shared) fault injector + this
        # runner's shard index within it; poisoned-batch quarantine
        # (bisect a repeatedly-crashing batch, drop + count + pcap the
        # offending frames, keep the loop running) and the forensics
        # capture path.
        faults: Optional[FaultInjector] = None,
        shard_index: int = 0,
        quarantine: bool = True,
        quarantine_pcap: Optional[str] = None,
        # In-network inference (ISSUE 14): the model-weights +
        # enrollment table compiled into every dispatch program.  None
        # (or a disabled table) compiles the scoring stage away — the
        # score-off program is the pre-inference pipeline bit-for-bit.
        infer: Optional[InferTable] = None,
    ):
        # Table references are LOCK-FREE atomic swaps by design: a swap
        # publishes whole new objects, in-flight batches keep the
        # references they captured, and readers never see a mix.
        self.acl = acl          # lock-free: atomic ref swap; in-flight batches keep their tables
        self.mesh = mesh
        # The lookup-discipline gate (use_hmap) is derived from the
        # backend the dispatch TARGETS, not the builder's process —
        # tables built CPU-side and shipped to TPU workers (or vice
        # versa) would otherwise keep the wrong crossover pick.
        self.nat = retarget_tables(nat, self._target_backend())  # lock-free: atomic ref swap (see acl)
        self.route = route      # lock-free: atomic ref swap (see acl)
        self.infer = infer      # lock-free: atomic ref swap (see acl)
        # Score log2-histogram: one counter per 3-bit band the packed
        # verdicts carry (band k <=> score >= 1 - 2^-k) — THE score
        # distribution surfaced via inspect()["inference"].
        self._infer_bands = [0] * INFER_BANDS  # owner: shard worker — harvest-side single writer; readers copy
        # Host-side mirror of the route scalars (filled lazily by
        # _route_of, invalidated per swap) — keeps the slow-path
        # restore from paying device reads per packet.
        self._route_cache: Optional[Tuple] = None  # lock-free: derived cache; worst case one re-read
        self.overlay = overlay
        self.source = source
        self.tx = tx
        self.local = local if local is not None else tx
        self.host = host if host is not None else tx
        self._native = None  # set after endpoint inspection below
        self.batch_size = batch_size
        # When >1, coalesce up to max_vectors queued batch_size-packet
        # vectors into ONE device dispatch: sessions thread between
        # vectors on device, dispatch cost amortises K-fold.  K is
        # bucketed to powers of two to bound recompiles, so the
        # effective ceiling is the power-of-two floor of max_vectors
        # (enforced by the property setter); the governor picks the
        # per-admit K under it.
        self.max_vectors = max_vectors
        if dispatch not in ("auto", "scan", "flat-safe", "flat-punt"):
            raise ValueError(f"unknown dispatch discipline: {dispatch!r}")
        if dispatch == "auto":
            # r4 measurement: flat-safe wins on BOTH backends since the
            # commit-first restructure (it used to lose on CPU).
            dispatch = "flat-safe"
        self.dispatch = dispatch
        self.max_inflight = max_inflight
        if coalesce not in ("adaptive", "fixed"):
            raise ValueError(f"unknown coalesce mode: {coalesce!r}")
        self.governor = CoalesceGovernor(
            batch_size=self._batch_size,
            max_vectors=self._max_vectors,
            slo_us=coalesce_slo_us,
            window=self._max_inflight,
            enabled=(coalesce == "adaptive"),
        )
        self.prewarm = prewarm
        # Governor timing taps: wall-clock of the previous harvest
        # completion (inter-completion intervals approximate per-
        # dispatch service time in the pipelined steady state), and
        # the pow2 buckets already timed once — a bucket's FIRST
        # dispatch may include a multi-second jit compile, which would
        # poison the EWLS fit (floor_us off by ~6 orders) and spray
        # false slo_breaches until the decay washes it out.
        self._last_harvest_t: Optional[float] = None  # owner: shard worker — sanitize touches a quiesced runner only
        self._timed_k: set = set()
        self.sweep_interval = sweep_interval
        self.sweep_max_age = sweep_max_age
        self.shim = shim or HostShim()
        # Multi-chip: when a jax.sharding.Mesh is supplied, tables and
        # sessions are placed on it (rules over the ``rules`` axis,
        # batch over ``data``; sessions replicated or hash-partitioned)
        # and every dispatch runs GSPMD-sharded — SURVEY §5.8's ICI
        # scaling axis, driven by the SAME runner loop as single-chip.
        self.partition_sessions = partition_sessions
        self._state = state or DeviceSessionState(session_capacity)
        if self.nat is not None and self.nat.has_affinity:
            self._state.aff_pinned = True
        if mesh is not None:
            self._shard_state()
        self.slow = slow if slow is not None else HostSlowPath()
        self._host_lock = host_lock or threading.Lock()
        # With a SHARED slow path (sharded engine), "will the slow path
        # mutate this batch's verdicts?" cannot be answered outside the
        # host lock — another shard may insert a session between the
        # check and the use — so harvest must always take the copying
        # path there.  Solo runners keep the zero-copy fast path.
        self._shared_host = host_lock is not None
        self.faults = faults if faults is not None else FaultInjector()
        self.shard_index = shard_index
        self.quarantine = quarantine
        self.quarantine_pcap = quarantine_pcap
        self._quarantine_writer = None  # owner: shard worker — close() touches a quiesced runner only
        self._last_fault_error = ""  # lock-free: diagnostic string; last-writer-wins is acceptable
        self.counters = RunnerCounters()
        # Optional zero-arg provider of control-plane compile stats (the
        # agent attaches the applicators' stats() here) — surfaced by
        # inspect() so `netctl inspect` shows full-vs-delta compile
        # counts and rows shipped next to the tables they produced.
        self.compile_stats_fn: Optional[Callable[[], Dict]] = None
        # Sampled per-packet verdict traces (vpptrace analog), enabled on
        # demand via REST/netctl.
        self.tracer = tracer if tracer is not None else PacketTracer()
        # Telemetry (ISSUE 8): latency histograms fed from the SAME
        # perf_counter timestamps the governor's timing fit takes — the
        # dispatch path gains zero new clock calls or device syncs —
        # plus the per-shard flight recorder of recent dispatches
        # (snapshotted next to the forensic pcap on ejection/
        # quarantine).  Both are single-writer (this runner's worker);
        # readers merge/copy on read.
        self.telemetry = LatencyRecorder()
        self.flight = FlightRecorder()
        # Round-chain attribution (ISSUE 10 satellite): where each
        # dispatch's host wall actually goes, per round of the
        # admit→harvest chain — `wait` (in-flight window: dispatch
        # enqueue → harvest begin), `materialize` (the host block on the
        # device program's outputs — the flat-safe commit→re-probe→
        # finalize chain surfaces HERE as transfer wait), `restore` (the
        # host slow path: punt servicing + reply restores), `stitch`
        # (quarantine screen + rewrite apply + TX).  Single-writer log2
        # histograms fed from perf_counter stamps the harvest already
        # brackets — zero device syncs added; this is the per-round
        # evidence ROADMAP #1's fusion work is judged against.
        self.rounds = {name: Log2Histogram() for name in DISPATCH_ROUNDS}
        # Monotonic table generation: bumped once per adopted swap so
        # flight-recorder rows and packet traces pin the exact tables a
        # batch dispatched under (correlates with propagation spans).
        self._table_gen = 0  # owner: control plane — only _adopt_tables bumps it (swaps serialise on the scheduler lock); workers read a plain int
        # In-flight queue: python engine (FrameBatch, result, ts, k,
        # t_admit, depth); native engine (slot, n, orig-SoA dict,
        # result, ts, k, t_admit, depth) — the (k, t_admit, depth)
        # tail feeds the governor's timing fit at harvest.
        self._inflight: Deque[Tuple] = collections.deque()
        # Engine selection (VERDICT r2 item 1): when every endpoint is a
        # NativeRing, admit/harvest run in C++ (runnerloop.cpp) and
        # frames never cross Python per-packet; the Python engine
        # remains for arbitrary sources/sinks and counter-parity tests.
        native_ok = all(
            isinstance(ep, NativeRing)
            for ep in (self.source, self.tx, self.local, self.host)
        )
        if engine not in (None, "native", "python"):
            raise ValueError(f"unknown engine {engine!r}")
        if engine == "native" and not native_ok:
            raise ValueError("native engine requires NativeRing endpoints")
        self.engine = engine or ("native" if native_ok else "python")
        self._native: Optional[NativeLoop] = None  # owner: shard worker — rebuild/close touch a quiesced runner only
        self._slot_next = 0  # owner: shard worker — resize/sanitize rebuilds touch a runner with nothing in flight
        if self.engine == "native":
            self._native = NativeLoop(
                self.source, self.tx, self.local, self.host,
                batch_size=self.batch_size, max_vectors=self.max_vectors,
                vni=self.overlay.vni, n_slots=self._n_slots,
            )
        self._bypass_tables = False  # lock-free: single-word disarm flag; swaps clear it BEFORE adopting, pollers re-derive
        self._bypass_route = None    # lock-free: written before _bypass_tables arms; read only when armed
        self._refresh_bypass()
        if self.prewarm:
            self.prewarm_buckets()

    # ------------------------------------------------------ host bypass

    def _bypass_static_ok(self) -> bool:
        """The device-read-free half of bypass eligibility: trivially
        permissive tables on a native, mesh-less runner.  An ENABLED
        inference table disqualifies the bypass even when the ACL/NAT
        tables are trivial — the scorer (and its quarantine action)
        only runs on the device dispatch path, and a bypassed frame
        would silently skip scoring exactly like it would skip a deny
        rule."""
        return (
            self._native is not None
            and self.mesh is None
            and self.acl is not None and self.nat is not None
            and self.route is not None
            and getattr(self.acl, "num_rules", 1) == 0
            and getattr(self.acl, "num_tables", 1) == 0
            and self.nat.num_mappings == 0
            and not bool(np.asarray(self.nat.snat_enabled))
            and not self.nat.has_affinity
            and (self.infer is None or not self.infer.enabled)
        )

    def _bypass_state_clear(self) -> bool:
        """The residual-state half (PAYS device occupancy reads): no
        slow-path flows, no live sessions, no ClientIP affinity pins.
        Orphaned pins drain via the affinity sweep, which only runs on
        the DISPATCH path — bypassing while pins remain would park them
        in the table forever (and stale pins would resurrect dead
        backend picks if the service reappears).  The sharded engine
        computes this ONCE per table swap (the session state is shared)
        and hands it to every shard's _refresh_bypass."""
        with self._state.lock:
            # The dispatch jits DONATE the session buffers; reading
            # occupancy outside the state lock races the donation on a
            # live engine ("Array has been deleted" — the ISSUE 9 soak
            # hit this on swap-under-traffic).  The lock serialises
            # against the dispatch that would invalidate the handle.
            return (
                len(self.slow) == 0
                and session_occupancy(self.sessions) == 0
                and affinity_occupancy(self.sessions) == 0
            )

    def _refresh_bypass(self, state_clear: Optional[bool] = None) -> None:
        """Precompute host-bypass eligibility — VPP's feature-less
        interface path: with NO ACL rules or tables, NO NAT mappings,
        SNAT off, and no residual session/slow-path state, EVERY frame
        is pass-through (allowed, unrewritten, never punted) and
        routing is pure subnet arithmetic.  Eligible polls skip the
        device dispatch entirely and run the fused native
        admit→route→harvest call (hs_loop_hostpath) — the loop's full
        measured capacity instead of the XLA round trip.  Re-derived on
        every table swap; the tracer is re-checked per poll (REST can
        enable it any time), and residual sessions only ever decay, so
        the one-shot occupancy check here stays valid.  ``state_clear``
        lets a caller that already paid the device occupancy reads
        (ShardedDataplane.update_tables) pass the result in."""
        eligible = self._bypass_static_ok() and (
            self._bypass_state_clear() if state_clear is None else state_clear
        )
        if eligible:
            self._bypass_route = (
                int(np.asarray(self.route.pod_subnet_base)),
                int(np.asarray(self.route.pod_subnet_mask)),
                int(np.asarray(self.route.this_node_base)),
                int(np.asarray(self.route.this_node_mask)),
                int(np.asarray(self.route.host_bits)),
            )
        self._bypass_tables = eligible
        self._bypass_recheck = False  # lock-free: bool hint; a lost write costs one extra re-derive

    def _bypass_ready(self) -> bool:
        # In-flight dispatched batches must harvest first (arena pins
        # release FIFO); an enabled tracer needs the dispatch path's
        # verdict recording.
        if self._bypass_tables and getattr(self, "_bypass_recheck", False) \
                and not self._inflight:
            # A harvest merged dispatch results (sessions/punts may now
            # exist) after eligibility was computed — an in-flight batch
            # dispatched under the OLD tables can create state the
            # table-swap-time check could not see.  Re-derive once.
            self._refresh_bypass()
        return (self._bypass_tables and not self._inflight
                and not self.tracer.enabled)

    def _bypass_once(self) -> Tuple[bool, int]:
        """One fused bypass batch; returns (consumed_anything, sent)."""
        ac = np.zeros(NativeLoop.ADMIT_COUNTERS, dtype=np.uint64)
        hc = np.zeros(NativeLoop.HARVEST_COUNTERS, dtype=np.uint64)
        n, sent = self._native.hostpath(
            self._slot_next, *self._bypass_route,
            self.overlay.remote_ips, self.overlay.local_ip,
            self.overlay.local_node_id, ac, hc,
        )
        self.counters.rx_frames += int(ac[0])
        self.counters.rx_decapped += int(ac[1])
        self.counters.dropped_foreign_vni += int(ac[2])
        if n > 0:
            self.counters.bypass_batches += 1
            self.counters.tx_remote += int(hc[0])
            self.counters.tx_local += int(hc[1])
            self.counters.tx_host += int(hc[2])
            self.counters.dropped_denied += int(hc[3])
            self.counters.dropped_unparseable += int(hc[4])
            self.counters.dropped_unroutable += int(hc[5])
        return (n > 0 or int(ac[0]) > 0), sent

    # ------------------------------------------------------ shared state

    # Session table + timestamp live in the (possibly shared)
    # DeviceSessionState; these properties keep the runner's historical
    # field API while routing through it.

    @property
    def sessions(self) -> NatSessions:
        return self._state.sessions

    @sessions.setter
    def sessions(self, value: NatSessions) -> None:  # holds: lock
        self._state.sessions = value

    @property
    def _ts(self) -> int:
        return self._state.ts

    @_ts.setter
    def _ts(self, value: int) -> None:  # holds: lock
        self._state.ts = value

    # ----------------------------------------------------- sizing knobs

    # batch_size / max_vectors / max_inflight are settable post-
    # construction (tests shrink them; operators deepen the window);
    # the native loop bakes the sizes into its slot layout, so the
    # setters rebuild it.  Only legal with no batches in flight.  The
    # governor tracks every change (its ceiling/vector math must match
    # the loop's).

    @property
    def batch_size(self) -> int:
        return self._batch_size

    @batch_size.setter
    def batch_size(self, value: int) -> None:
        self._check_resizable()
        self._batch_size = value
        if getattr(self, "governor", None) is not None:
            self.governor.batch_size = value
        self._rebuild_native()

    @property
    def max_vectors(self) -> int:
        return self._max_vectors

    @max_vectors.setter
    def max_vectors(self, value: int) -> None:
        self._check_resizable()
        k = 1
        while k * 2 <= max(1, value):
            k *= 2
        self._max_vectors = k
        if getattr(self, "governor", None) is not None:
            self.governor.max_vectors = k
        self._rebuild_native()

    @property
    def max_inflight(self) -> int:
        return self._max_inflight

    @max_inflight.setter
    def max_inflight(self, value: int) -> None:
        self._check_resizable()
        self._max_inflight = max(1, value)
        # One spare slot beyond the window: a harvest's SoA views must
        # stay stable while the next admit fills a fresh slot.
        self._n_slots = self._max_inflight + 1
        if getattr(self, "governor", None) is not None:
            self.governor.window = self._max_inflight
        self._rebuild_native()

    def _check_resizable(self) -> None:
        # Validate BEFORE mutating: a raise must not leave the Python
        # sizing divergent from the native slot layout.
        if getattr(self, "_native", None) is not None and self._inflight:
            raise RuntimeError("cannot resize the loop with batches in flight")

    def _rebuild_native(self) -> None:
        if self._native is None:
            return
        old = self._native
        self._native = NativeLoop(
            self.source, self.tx, self.local, self.host,
            batch_size=self._batch_size, max_vectors=self._max_vectors,
            vni=self.overlay.vni, n_slots=self._n_slots,
        )
        self._slot_next = 0
        old.close()

    # ------------------------------------------------------------- tables

    def _target_backend(self) -> str:
        """The JAX platform this runner's dispatches execute on."""
        if self.mesh is not None:
            return next(iter(self.mesh.devices.flat)).platform
        return jax.default_backend()

    def _shard_state(self) -> None:
        """(Re-)place tables + sessions onto the mesh."""
        from ..parallel.mesh import replicate_on_mesh, shard_dataplane

        # static: allow(lock-discipline) — mesh runners are driven single-threaded; placement runs at init/swap with no worker live
        self.acl, self.nat, self.route, self.sessions = shard_dataplane(
            self.mesh, self.acl, self.nat, self.route, self.sessions,
            partition_sessions=self.partition_sessions,
        )
        if self.infer is not None:
            # The inference table rides every dispatch too: replicate
            # it (a few KB of weights) so its leaves carry the mesh
            # placement — a single-device table mixed into a sharded
            # dispatch is an incompatible-devices error.
            self.infer = replicate_on_mesh(self.mesh, self.infer)

    def update_tables(
        self,
        acl: Optional[RuleTables] = None,
        nat: Optional[NatTables] = None,
        route: Optional[RouteConfig] = None,
        infer: Optional[InferTable] = None,
    ) -> None:
        """Atomic table swap: takes effect for the NEXT dispatched batch
        (in-flight batches complete against the tables they saw — the
        same semantics as VPP's ACL/NAT table swap under traffic).  This
        contract is what makes DELTA-BUILT tables safe: the builders'
        scatter produces new arrays without touching the old buffers, so
        a swap here can never mutate tables an in-flight dispatch still
        references.

        FAULT DOMAIN: the previous tables are kept as LAST-GOOD — any
        failure mid-swap (retarget, adopt, mesh re-shard, or an armed
        ``swap-fail`` injection) restores them and raises
        :class:`TableSwapError`, so the data plane keeps serving a
        consistent generation and the caller (scheduler applicator)
        retries instead of crashing the agent."""
        if acl is None and nat is None and route is None and infer is None:
            return
        last_good = (self.acl, self.nat, self.route, self.infer)
        # Disarm the host bypass BEFORE the new tables land: a
        # concurrent poll must never forward under a stale
        # bypass=eligible flag once deny rules exist.  The refresh
        # below re-arms it when the new tables are still trivial.
        self._bypass_tables = False
        try:
            self._adopt_tables(
                acl,
                retarget_tables(nat, self._target_backend())
                if nat is not None else None,
                route,
                infer,
            )
        except Exception as err:
            self.acl, self.nat, self.route, self.infer = last_good
            # A worker thread may have refilled the route-scalar cache
            # from the half-adopted generation between _adopt_tables'
            # clear and this rollback — drop it so _route_of re-reads
            # the restored route.
            self._route_cache = None
            self.counters.swap_rollbacks += 1
            self._last_fault_error = f"table swap failed: {err}"
            self._refresh_bypass()
            raise TableSwapError(
                f"table swap failed on shard {self.shard_index}; "
                f"rolled back to last-good tables: {err}"
            ) from err
        self._refresh_bypass()
        if self.prewarm:
            # New table shapes mean new jit cache keys: re-warm every
            # pow2 bucket NOW so the next load spike never stalls on a
            # compile (the process-global ledger makes same-shape swaps
            # free).
            self.prewarm_buckets()

    def _adopt_tables(
        self,
        acl: Optional[RuleTables],
        nat: Optional[NatTables],
        route: Optional[RouteConfig],
        infer: Optional[InferTable] = None,
    ) -> None:
        """The swap body minus retarget/bypass derivation — the sharded
        engine retargets ONCE and adopts on every shard (shards.py).
        The ``swap-fail`` site fires BEFORE any reference mutates, so
        an injected failure never leaves THIS shard partially adopted
        (multi-shard atomicity is the sharded engine's rollback)."""
        if acl is None and nat is None and route is None and infer is None:
            return
        t0 = time.perf_counter()
        self.faults.fire(SITE_SWAP_FAIL, shard=self.shard_index)
        # New tables may mean new jit cache keys: every bucket's
        # next dispatch may compile again, so its timing sample
        # must be re-screened (see _observe_harvest).
        self._timed_k.clear()
        if acl is not None:
            self.acl = acl
            self.counters.acl_swaps += 1
        if nat is not None:
            self.nat = nat
            self.counters.nat_swaps += 1
            if self.nat.has_affinity:
                # Pins may be created from now on; the sweep keeps
                # running (and draining orphans) even after a later
                # swap to a no-affinity table — see DeviceSessionState.
                # Under the state lock: the dispatch-path sweep CLEARS
                # this flag when the last orphan pin drains, and an
                # unguarded True here could lose against that clear
                # (lock-discipline checker finding; the flag is
                # guarded-by the state lock like the rest of the
                # shared session state).
                with self._state.lock:
                    self._state.aff_pinned = True
        if route is not None:
            self.route = route
            self.counters.route_swaps += 1
            # Host-side route-scalar cache follows the table generation.
            self._route_cache = None
        if infer is not None:
            # A model update is just another table swap: atomic ref
            # publish, in-flight batches keep the weights they saw, and
            # the last-good rollback above covers a failed adopt.
            self.infer = infer
            self.counters.inference_swaps += 1
        if self.mesh is not None and (
            acl is not None or nat is not None or route is not None
        ):
            from ..parallel.mesh import shard_dataplane

            self.acl, self.nat, self.route, _ = shard_dataplane(
                self.mesh, self.acl, self.nat, self.route, self.sessions,
                partition_sessions=self.partition_sessions,
            )
        if self.mesh is not None and infer is not None:
            # An infer-only swap must re-place the new table on the
            # mesh too — the acl/nat/route block above does not cover
            # it, and an unplaced table would mix devices (see
            # _shard_state).
            from ..parallel.mesh import replicate_on_mesh

            self.infer = replicate_on_mesh(self.mesh, self.infer)
        # One generation per adopted swap (whatever mix of tables it
        # carried): flight-recorder rows and packet traces stamp it.
        self._table_gen += 1
        # Propagation span: this shard's adoption duration (no-op when
        # no controller span is active, e.g. standalone benches).
        record_stage(f"adopt:shard{self.shard_index}",
                     time.perf_counter() - t0)

    # ----------------------------------------------------- bucket pre-warm

    def _bucket_signature(self, k: int) -> Tuple:
        """Process-global jit-cache identity of one dispatch bucket:
        the discipline plus the abstract (shape, dtype) of every table/
        session leaf.  Values never enter — cache keys are avals."""
        leaves = jax.tree_util.tree_leaves(
            (self.acl, self.nat, self.route, self.sessions, self.infer))
        return (
            self.dispatch, k, self._batch_size,
            # The inference static gate is part of the compiled program
            # (enabled=False traces the scoring stage away), so it must
            # key the warm ledger too — else an enable flip would look
            # pre-warmed while every bucket actually recompiles.
            None if self.infer is None else bool(self.infer.enabled),
            tuple(
                (tuple(getattr(leaf, "shape", ())),
                 str(getattr(leaf, "dtype", type(leaf).__name__)))
                for leaf in leaves
            ),
        )

    def _prewarm_one(self, k: int) -> None:
        """Compile (and run once, against a throwaway session table)
        the jit program the dispatch path would select at vector count
        ``k`` — the runner's own state is untouched."""
        size = k * self._batch_size
        z32 = jnp.zeros(size, dtype=jnp.uint32)
        zi = jnp.zeros(size, dtype=jnp.int32)
        batch = PacketBatch(src_ip=z32, dst_ip=z32, protocol=zi,
                            src_port=zi, dst_port=zi)
        # Fresh scratch per bucket: the jit entry points DONATE the
        # sessions argument.
        scratch = empty_sessions(self.sessions.capacity)
        if k == 1 and self.dispatch == "scan":
            result = pipeline_step_jit(
                self.acl, self.nat, self.route, scratch, batch, jnp.int32(1),
                self.infer)
        else:
            vectors = jax.tree_util.tree_map(
                lambda a: a.reshape((k, self._batch_size) + a.shape[1:]),
                batch)
            step = (
                pipeline_flat_safe_ts0_jit if self.dispatch == "flat-safe"
                else pipeline_flat_punt_ts0_jit
                if self.dispatch == "flat-punt"
                else pipeline_scan_ts0_jit
            )
            result = step(
                self.acl, self.nat, self.route, scratch, vectors,
                jnp.int32(0), self.infer)
        result.packed.block_until_ready()

    def prewarm_buckets(self) -> int:
        """Compile every pow2 dispatch bucket up to the ceiling against
        the CURRENT tables, so a load spike never stalls on jit
        compilation mid-traffic.  Returns the number of buckets
        actually compiled — 0 when everything was already warm (the
        ledger is process-global: N shards and repeated same-shape
        swaps pay once).  Mesh runners skip (GSPMD placement changes
        the cache key; their dispatch shapes are pre-placed at swap)."""
        if (self.acl is None or self.nat is None or self.route is None
                or self.mesh is not None):
            return 0
        compiled = 0
        k = 1
        while k <= self._max_vectors:
            sig = self._bucket_signature(k)
            if sig not in _PREWARMED:
                self._prewarm_one(k)
                _PREWARMED.add(sig)
                compiled += 1
            k *= 2
        return compiled

    # --------------------------------------------------------------- loop

    def _backlog_depth(self) -> int:
        """Ingress backlog in frames, or -1 when the source cannot
        report depth (the governor's saturation ramp stands in)."""
        hint = getattr(self.source, "backlog_hint", None)
        if hint is not None:
            try:
                return int(hint())
            except Exception:  # noqa: BLE001 - a flapping probe = unknown
                return -1
        try:
            return len(self.source)  # type: ignore[arg-type]
        except TypeError:
            return -1

    def _observe_harvest(self, k: int, t_admit: float, depth: int,
                         t_harvest: Optional[float] = None, ts: int = 0,
                         frames: int = 0, sent: int = 0,
                         denied: int = 0,
                         t_materialized: Optional[float] = None,
                         t_restored: Optional[float] = None) -> None:
        """Feed one per-dispatch wall-time sample to the governor, the
        latency histograms, and the flight recorder.  Unpipelined
        batches (admitted with nothing in flight) time the full
        admit→harvest round trip; pipelined ones use the inter-
        completion interval, which is exactly the per-dispatch wall in
        the saturated steady state.  A bucket's first-ever governor
        sample is discarded unless the bucket was pre-warmed — it may
        include jit compile time, which is not service time (the
        histograms keep it: a compile stall IS latency the frames
        experienced).

        ``t_harvest`` is the perf_counter the harvest took before
        materialising (the one clock call telemetry added, on the
        sanctioned harvest path — the dispatch path still takes
        exactly the timestamps the governor always took); the
        remaining arguments are host ints the harvest already
        computed, so this tap stays free of device syncs."""
        now = time.perf_counter()
        prev = self._last_harvest_t
        self._last_harvest_t = now
        self.telemetry.record_harvest(
            t_admit, t_harvest if t_harvest is not None else t_admit,
            now, frames,
        )
        # Round-chain attribution (pure arithmetic on stamps the harvest
        # already took — hot-path-sync clean): split this dispatch's
        # host wall into its rounds.  The intermediate stamps are only
        # taken on the real harvest paths; bench-style callers that
        # omit them record nothing (no fake zeros in the histograms).
        if t_harvest is not None:
            self.rounds["wait"].record_us((t_harvest - t_admit) * 1e6)
            if t_materialized is not None:
                self.rounds["materialize"].record_us(
                    (t_materialized - t_harvest) * 1e6)
                if t_restored is not None:
                    self.rounds["restore"].record_us(
                        (t_restored - t_materialized) * 1e6)
                    self.rounds["stitch"].record_us(
                        (now - t_restored) * 1e6)
        self.flight.note_dispatch(
            ts=ts, k=k, frames=frames, sent=sent, denied=denied,
            backlog=self.governor.backlog, inflight=depth,
            table_gen=self._table_gen, rt_us=(now - t_admit) * 1e6,
        )
        if k not in self._timed_k:
            self._timed_k.add(k)
            if self.mesh is not None or \
                    self._bucket_signature(k) not in _PREWARMED:
                return
        if depth == 0:
            self.governor.observe(k, now - t_admit)
        elif prev is not None and prev >= t_admit:
            self.governor.observe(k, now - prev)

    def poll(self) -> int:
        """One scheduling turn: admit new batches up to the in-flight
        window, then harvest the oldest completed batch.  Returns the
        number of frames transmitted this turn.

        With trivially-permissive tables the HOST BYPASS replaces the
        whole turn: fused native admit→route→harvest batches until the
        source idles — no device dispatch (see _refresh_bypass)."""
        if self._bypass_ready():
            sent_total = 0
            while True:
                consumed, sent = self._bypass_once()
                sent_total += sent
                # Re-check BETWEEN batches: a concurrent table swap
                # installing real ACL/NAT state must take effect on the
                # next batch, exactly as it would on the dispatch path —
                # under sustained ingress this loop may otherwise never
                # exit.
                if not consumed or not self._bypass_ready():
                    return sent_total
        admitted = True
        while len(self._inflight) < self.max_inflight and admitted:
            admitted = self._admit()
        if not self._inflight:
            return 0
        return self._harvest()

    def drain(self) -> int:
        """Run until the source is idle and all in-flight work is
        harvested; returns total frames transmitted."""
        total = 0
        while True:
            total += self.poll()
            if not self._inflight and not self._admit():
                return total

    def _admit(self) -> bool:
        if self._bypass_ready():
            # Bypass turns run whole batches inside poll; here (the
            # drain idle-probe) just report whether source frames are
            # pending so the caller loops back into poll.
            return len(self.source) > 0
        if self._native is not None:
            return self._admit_native()
        return self._admit_python()

    def _harvest(self) -> int:
        if self._native is not None:
            return self._harvest_native()
        return self._harvest_python()

    def _dispatch(self, batch: PacketBatch, k: int):
        """Dispatch one (k × batch_size)-packet batch through the jit
        pipeline, threading the session state on device; bumps the
        timestamp and runs the periodic session sweep.  Serialised on
        the DeviceSessionState lock: shard threads enqueue device work
        in a single total order so the session state threads cleanly
        (dispatch is async — the lock covers enqueue, not execution).

        Returns ``(result, ts)`` where ``ts`` is THIS batch's timestamp,
        read while the lock is held — another shard may bump the shared
        counter the moment the lock drops, so callers must not re-read
        ``self._ts`` for bookkeeping."""
        if self.faults.armed:
            # Injection sites fire BEFORE the state lock: a hang here
            # models this shard's dispatch thread wedging without
            # dragging the shared session lock (and so every other
            # shard) down with it.  The batch rides through AS-IS (no
            # materialisation): the injector only reads its fields when
            # a poison-match plan is armed, so unmatched arm modes
            # (hang, swap-fail drills) never pay a device→host sync on
            # the dispatch path.
            self.faults.fire(SITE_DISPATCH_HANG, shard=self.shard_index)
            self.faults.fire(
                SITE_DISPATCH_RAISE, shard=self.shard_index, batch=batch,
            )
        with self._state.lock:
            return self._dispatch_locked(batch, k), self._ts

    def _dispatch_locked(self, batch: PacketBatch, k: int):  # holds: lock
        prev_ts = self._ts
        self._ts += k
        if k == 1 and self.dispatch == "scan":
            # The flat disciplines handle k==1 through their own path
            # below: the plain flat step cannot restore (or detect-and-
            # punt) a reply sharing its ONE vector with the forward
            # flow; the re-probe pass can.
            if self.mesh is not None:
                from ..parallel.mesh import shard_batch

                batch = shard_batch(self.mesh, batch)
            result = pipeline_step_jit(
                self.acl, self.nat, self.route, self.sessions, batch,
                jnp.int32(self._ts), self.infer,
            )
        else:
            vectors = jax.tree_util.tree_map(
                lambda a: a.reshape((k, self.batch_size) + a.shape[1:]), batch
            )
            if self.mesh is not None:
                from ..parallel.mesh import shard_batch

                vectors = shard_batch(self.mesh, vectors)
            # Scalar base-ts entry points: the per-vector ts vector is
            # built INSIDE the program (a host-side arange per dispatch
            # costs a full extra round trip on a remote-TPU tunnel),
            # and the result comes back as ONE packed uint32 [4, K·V]
            # array — the harvest blocks on a single materialisation.
            step = (
                pipeline_flat_safe_ts0_jit if self.dispatch == "flat-safe"
                else pipeline_flat_punt_ts0_jit
                if self.dispatch == "flat-punt"
                else pipeline_scan_ts0_jit
            )
            result = step(
                self.acl, self.nat, self.route, self.sessions, vectors,
                jnp.int32(prev_ts), self.infer,
            )
        # Chain the session state into the next dispatch WITHOUT
        # materialising — keeps the device busy back-to-back.
        self.sessions = result.sessions
        self.counters.batches += 1
        if self.sweep_interval and (
            self._ts // self.sweep_interval != prev_ts // self.sweep_interval
        ):
            self.sessions = sweep_sessions(self.sessions, self._ts, self.sweep_max_age)
            with self._host_lock:  # slow-path dict is shared across shards
                self.slow.sweep(self._ts, self.sweep_max_age)
            # ClientIP affinity expiry: per-mapping timeouts are in
            # SECONDS; convert at the ts rate measured between sweeps
            # (first sweep only records the mark).
            import time as _time

            now = _time.monotonic()
            mark = self._state.sweep_mark
            if (
                (self.nat.has_affinity or self._state.aff_pinned)
                and mark is not None and now > mark[1]
            ):
                rate = (self._ts - mark[0]) / (now - mark[1])
                self.sessions = sweep_affinity(
                    self.sessions, self.nat, self._ts, rate
                )
                if not self.nat.has_affinity:
                    # Deleting the last ClientIP service leaves orphan
                    # pins: every sweep drops the unmapped ones, and
                    # once none remain the sweep stands down.
                    self._state.aff_pinned = (
                        affinity_occupancy(self.sessions) > 0
                    )
            self._state.sweep_mark = (self._ts, now)
            if not self._bypass_tables:
                # Residual sessions/pins blocked bypass eligibility at
                # the last table swap; they only decay via these
                # sweeps, so re-evaluate as they drain (the table
                # checks short-circuit before any device read when the
                # tables are non-trivial anyway).
                self._refresh_bypass()
        return result

    # ------------------------------------------------- fault containment

    def _dispatch_protected(self, batch: PacketBatch, k: int):
        """Dispatch with poisoned-batch quarantine: a batch that
        crashes dispatch is retried once whole (transient-error path),
        then BISECTED — sub-batches that still crash narrow to the
        offending frames, which are dropped + counted + captured for
        forensics while every other frame's verdict is kept.  A batch
        whose every frame 'crashes' is not data-dependent (the shard
        itself is sick) and the original error re-raises so shard
        supervision can eject the fault domain."""
        try:
            return self._dispatch(batch, k)
        except Exception as err:  # noqa: BLE001 - device errors are data here
            self.counters.dispatch_errors += 1
            self._last_fault_error = f"dispatch: {err}"
            if not self.quarantine:
                raise
            return self._quarantine_dispatch(batch, k, err)

    def _quarantine_dispatch(self, batch: PacketBatch, k: int, err: Exception):
        soa = {f: np.asarray(getattr(batch, f)) for f in _BATCH_FIELDS}
        total = len(soa["src_ip"])
        # Host-stitched packed rows in the device packing tail's layout:
        # rows a sub-dispatch never served default to deny + ROUTE_LOCAL
        # over the original headers (one packer owns the bit layout).
        zeros = np.zeros(total, dtype=np.uint32)
        out_pk = pack_verdicts_host(
            allowed=zeros, punt=zeros, reply_hit=zeros, dnat_hit=zeros,
            snat_hit=zeros, route=np.full(total, ROUTE_LOCAL, np.uint32),
            node_id=zeros, src_ip=soa["src_ip"], dst_ip=soa["dst_ip"],
            src_port=soa["src_port"], dst_port=soa["dst_port"],
        )
        poisoned: list = []
        last_ts = None
        # Root attempt = the whole-batch retry; halves push depth-first.
        stack = [np.arange(total)]
        while stack:
            idx = stack.pop()
            sub, sk = self._subbatch(soa, idx)
            try:
                res, ts = self._dispatch(sub, sk)
            except Exception as sub_err:  # noqa: BLE001
                self.counters.dispatch_errors += 1
                err = sub_err
                if len(idx) == 1:
                    poisoned.append(int(idx[0]))
                    continue
                mid = len(idx) // 2
                stack.append(idx[mid:])
                stack.append(idx[:mid])
                continue
            last_ts = ts
            m = len(idx)
            # ONE materialisation per surviving sub-dispatch (the
            # packed rows), stitched into the host result.
            out_pk[:, idx] = np.asarray(res.packed)[:, :m]
        if len(poisoned) >= total:
            # Nothing dispatched at all — a shard-level fault, not a
            # poisoned batch; surface it to the supervisor.
            raise err
        bad = np.array(sorted(poisoned), dtype=np.int64)
        if len(bad):
            out_pk[PACKED_WORD][bad] &= np.uint32(~np.uint32(VERDICT_ALLOWED))
            self.counters.quarantined_batches += 1
        result = _HostResult(packed=out_pk, poisoned_rows=bad)
        return result, (last_ts if last_ts is not None else self._ts)

    def _subbatch(self, soa, idx: np.ndarray):
        """Pack the selected rows into a fresh zero-padded batch sized
        to the smallest power-of-two vector count (same bucketing as
        admit, so no new compile shapes)."""
        m = len(idx)
        k = pow2_vectors(m, self.batch_size, self.max_vectors)
        size = k * self.batch_size
        arrs = {}
        for f, a in soa.items():
            padded = np.zeros(size, dtype=a.dtype)
            padded[:m] = a[idx]
            arrs[f] = jnp.asarray(padded)
        return PacketBatch(**arrs), k

    def _quarantine_rows(self, result, n: int, frame_of) -> int:
        """Shared harvest tail: count quarantined frames and capture
        them to the forensics pcap.  ``frame_of(row) -> bytes`` is
        engine-specific.  Returns how many live rows were poisoned (the
        caller excludes them from the denied counter)."""
        bad = getattr(result, "poisoned_rows", None)
        if bad is None or not len(bad):
            return 0
        live = bad[bad < n]
        if not len(live):
            return 0
        self.counters.dropped_poisoned += len(live)
        self._capture_forensics(live, frame_of, "quarantine")
        return len(live)

    def _capture_forensics(self, rows, frame_of, reason: str) -> None:
        """ONE crash-durable forensics capture for every quarantine
        class (poisoned batches AND inference-quarantined flows): the
        frames land in the quarantine pcap, flushed per batch (the
        capture exists precisely for the crash scenario), and the
        flight-recorder ring snapshots beside it — the last N
        dispatches' K/backlog/generation context NEXT TO the frames
        (same durability rules).  Takes (rows, frame_of) rather than
        materialised frames so the no-pcap case never pays the
        per-row native frame copies on the harvest path."""
        if not self.quarantine_pcap:
            return
        from .io import PcapWriter

        if self._quarantine_writer is None:
            self._quarantine_writer = PcapWriter(self.quarantine_pcap)
        self._quarantine_writer.send([frame_of(int(row)) for row in rows])
        self._quarantine_writer.flush()
        self.snapshot_flight(reason)

    def _apply_infer_verdicts(self, v, n: int, frame_of) -> int:
        """Shared harvest tail (ISSUE 14): account the inference
        verdicts the packed word carried and FIRE the bound actions.
        ``log`` and ``deprioritize`` are counted + surfaced (the trace
        ring carries the band per sampled packet; a deprioritized
        flow's scheduling is the egress sink's business — both engines
        keep identical verdicts).  ``quarantine`` steers the flow into
        the PR 3 forensics path: the frame is DENIED, captured to the
        quarantine pcap, and the flight-recorder ring is snapshotted
        beside it — same crash-durability rules as poisoned batches.
        Returns the number of rows denied here (excluded from
        dropped_denied like slow-path and poison drops).

        Pure host numpy over the already-unpacked verdict leaves — the
        scoring itself ran on device inside the dispatch program; this
        tail adds no device syncs (hot-path-sync stays clean)."""
        scored = v.scored[:n]
        if not scored.any():
            return 0
        self.counters.inference_scored += int(scored.sum())
        for band, count in zip(*np.unique(v.band[:n][scored],
                                          return_counts=True)):
            self._infer_bands[int(band)] += int(count)
        act = v.action[:n]
        self.counters.inference_logged += int((act == INFER_ACT_LOG).sum())
        self.counters.inference_deprioritized += int(
            (act == INFER_ACT_DEPRIORITIZE).sum())
        # Quarantine only rows that are still ALLOWED: a row the ACL
        # denied or the slow path already dropped is not "dropped by
        # quarantine" — counting it here would double-subtract it from
        # dropped_denied (driving that counter negative) and overstate
        # inference_quarantined with frames that were never going to
        # forward.
        rows = np.nonzero((act == INFER_ACT_QUARANTINE)
                          & v.allowed[:n])[0]
        if not len(rows):
            return 0
        # Deny AFTER the slow path ran: a reply restore must never
        # resurrect a quarantined flow's frame.
        v.allowed[rows] = False
        self.counters.inference_quarantined += len(rows)
        self._capture_forensics(rows, frame_of, "inference-quarantine")
        return len(rows)

    def sanitize_after_fault(self) -> None:
        """Reset the loop after a dispatch fault so the NEXT batch
        starts clean: in-flight batches are discarded (their frames are
        lost, exactly like a vswitch crash — transports retransmit) and
        the native loop is rebuilt, releasing arena pins a failed admit
        left behind.  Called by the shard supervisor on every error and
        before a probation rejoin."""
        self._inflight.clear()
        # Timing continuity is broken: the next inter-completion
        # interval would span the fault, poisoning the governor's fit.
        self._last_harvest_t = None
        if self._native is not None:
            self._rebuild_native()

    def close(self) -> None:
        """Release host-side resources: the forensics pcap handle and
        the native loop's frame arena.  Idempotent; the runner must not
        be polled afterwards.  (PcapWriter also closes on GC, but an
        explicit close is what keeps `make test-race`'s ResourceWarning
        gate quiet deterministically.)"""
        if self._quarantine_writer is not None:
            self._quarantine_writer.close()
            self._quarantine_writer = None
        if self._native is not None:
            self._native.close()
            self._native = None

    def health(self) -> Dict[str, object]:
        """This runner's fault-domain view (one shard's slice of the
        sharded engine's health report; the whole report for a solo
        runner) — surfaced via inspect() → REST /contiv/v1/health →
        `netctl health`."""
        return {
            "dispatch_errors": self.counters.dispatch_errors,
            "source_errors": self.counters.source_errors,
            "swap_rollbacks": self.counters.swap_rollbacks,
            "quarantine": {
                "enabled": self.quarantine,
                "batches": self.counters.quarantined_batches,
                "poisoned_frames": self.counters.dropped_poisoned,
                "pcap": self.quarantine_pcap or "",
            },
            "last_error": self._last_fault_error,
        }

    # ---------------------------------------------------------- telemetry

    def snapshot_flight(self, reason: str) -> Optional[str]:
        """Dump this runner's flight-recorder ring next to the forensic
        pcap (``<quarantine_pcap>.flight.jsonl``); returns the path, or
        None when no pcap destination is configured (nowhere to put
        forensics).  Called on poisoned-batch quarantine and — via the
        shard supervisor — on every ejection."""
        if not self.quarantine_pcap:
            return None
        path = self.quarantine_pcap + ".flight.jsonl"
        self.flight.snapshot_to(path, reason=reason, shard=self.shard_index)
        return path

    def latency_histograms(self):
        """{name: Log2Histogram} for the metrics exporter (host-only;
        the sharded engine merges across shards instead)."""
        return self.telemetry.histograms()

    def inference_bands(self):
        """Per-band score counts (the score log2-histogram) for the
        metrics exporter — copied on read, single harvest-side writer
        (the sharded engine sums across shards instead)."""
        return list(self._infer_bands)

    def inspect_inference(self) -> Dict[str, object]:
        """The inference pillar of inspect(): table state + per-action
        counters + the score log2-histogram.  Host values only — no
        device reads (the weights' shapes live in the pytree aux and
        host-side array metadata)."""
        infer = self.infer
        return {
            "enabled": bool(infer.enabled) if infer is not None else False,
            "pods": infer.num_pods if infer is not None else 0,
            "features": int(infer.w1.shape[0]) if infer is not None else 0,
            "hidden": int(infer.w1.shape[1]) if infer is not None else 0,
            "swaps": self.counters.inference_swaps,
            "scored": self.counters.inference_scored,
            "logged": self.counters.inference_logged,
            "deprioritized": self.counters.inference_deprioritized,
            "quarantined": self.counters.inference_quarantined,
            # Band k <=> score in [1 - 2^-k, 1 - 2^-(k+1)) — log2-
            # spaced in (1 - score), the resolution thresholds live in.
            "score_bands": self.inference_bands(),
        }

    def inspect_latency(self) -> Dict[str, object]:
        """The latency pillar of inspect(): per-histogram count/sum and
        p50/p90/p99/p99.9 — derived on read, no device access."""
        return {
            name: hist.snapshot()
            for name, hist in self.telemetry.histograms().items()
        }

    def dump_flight(self, limit: int = 0) -> Dict[str, object]:
        """On-demand flight-recorder dump (REST /contiv/v1/flight →
        `netctl flight`)."""
        return {
            "shards": [{
                "shard": self.shard_index,
                **self.flight.status(),
                "records": self.flight.dump(limit),
            }],
        }

    # ------------------------------------------------------- native engine

    def _admit_native(self) -> bool:
        if self.faults.armed:
            try:
                self.faults.fire(SITE_FRAME_SOURCE_ERROR, shard=self.shard_index)
            except FaultInjected as err:
                # A source error degrades (count + idle), never kills:
                # the NIC-flap semantics of the agent's uplink loop.
                self.counters.source_errors += 1
                self._last_fault_error = f"source: {err}"
                return False
        slot = self._slot_next
        # Governor: pick this admit's pow2 vector cap from the ring's
        # measured depth; the native admit bounds its read budget by it
        # (excess backlog stays queued for the next in-flight slot).
        k_cap = self.governor.choose_k(self._backlog_depth())
        c = np.zeros(NativeLoop.ADMIT_COUNTERS, dtype=np.uint64)
        n, k, soa = self._native.admit(slot, c, k_cap)
        self.counters.rx_frames += int(c[0])
        self.counters.rx_decapped += int(c[1])
        self.counters.dropped_foreign_vni += int(c[2])
        if n == 0:
            return bool(c[0])  # consumed (all foreign-VNI drops) vs idle
        self.governor.admitted(n, k_cap)
        self._slot_next = (slot + 1) % self._n_slots
        kb = k * self.batch_size
        batch = PacketBatch(
            src_ip=jnp.asarray(soa["src_ip"][:kb]),
            dst_ip=jnp.asarray(soa["dst_ip"][:kb]),
            protocol=jnp.asarray(soa["protocol"][:kb]),
            src_port=jnp.asarray(soa["src_port"][:kb]),
            dst_port=jnp.asarray(soa["dst_port"][:kb]),
        )
        t_admit = time.perf_counter()
        depth = len(self._inflight)
        result, batch_ts = self._dispatch_protected(batch, k)
        self._inflight.append((slot, n, soa, result, batch_ts,
                               k, t_admit, depth))
        return True

    def _unpack_harvest(self, pk: np.ndarray, n: int):
        """Shared by both harvest engines: unpack ONE materialised
        packed result into the 12 verdict leaves.  The slow path
        mutates verdicts/rewrites in place — the derived flag/tag/port
        leaves are fresh numpy either way, so only the two
        rewritten-IP rows (views into the materialised buffer) need a
        copy, and only when the slow path can actually fire (punts in
        this batch — straggler punts included — or live host
        sessions); the all-fast-path case stays zero-copy, counted as
        ``harvest_copy_saved_bytes``.  A shared slow path (sharded
        engine) always copies: its emptiness can change between this
        check and the locked slow-path pass."""
        mutable = self._shared_host or len(self.slow) > 0 or \
            bool((pk[PACKED_WORD][:n] & VERDICT_PUNT).any())
        if not mutable:
            self.counters.harvest_copy_saved_bytes += 8 * n
        return unpack_verdicts(pk, n, writable=mutable)

    def _harvest_native(self) -> int:
        # Harvest-start mark: together with _observe_harvest's existing
        # end-of-harvest perf_counter this bounds the "harvest stitch"
        # histogram (device block + host stitch) and the in-flight wait
        # — one clock call per BATCH on the sanctioned harvest path;
        # the dispatch path keeps its original timestamps untouched.
        t_h0 = time.perf_counter()
        slot, n, soa, result, ts, k, t_admit, depth = self._inflight.popleft()
        # Materialise (blocks on THIS batch only; newer ones stay
        # queued) — ONE device→host transfer: the packed uint32 [4, B]
        # verdict+rewrite array the jit's packing tail produced (the
        # 12 per-leaf np.asarray transfers this replaced each cost a
        # round trip on a remote-TPU tunnel).
        v = self._unpack_harvest(np.asarray(result.packed), n)
        rew = {
            "src_ip": v.src_ip,
            "dst_ip": v.dst_ip,
            # No pipeline stage rewrites the protocol — serve it from
            # the host-side original headers instead of the device.
            "protocol": soa["protocol"][:n],
            "src_port": v.src_port,
            "dst_port": v.dst_port,
        }
        # Orig 5-tuples are views into the slot's SoA buffers — stable
        # until the slot cycles, which cannot happen before this
        # harvest returns (n_slots > max_inflight).
        orig = {key: arr[:n] for key, arr in soa.items()}
        # Round-attribution stamps (harvest path — the sanctioned sync
        # side): everything above this line since t_h0 was the blocking
        # materialisation of the device program's outputs; the slow
        # path below is the host `restore` round.
        t_mat = time.perf_counter()
        slow_drops = self._slowpath_and_trace(
            orig, rew, v.allowed, v.route, v.node_id,
            v.punt, v.reply_hit, v.dnat_hit, v.snat_hit, ts, k,
            straggler=v.straggler, band=v.band, infer_action=v.action,
        )
        t_slow = time.perf_counter()
        poison_drops = self._quarantine_rows(
            result, n, lambda row: self._native.slot_frame(slot, row))
        infer_drops = self._apply_infer_verdicts(
            v, n, lambda row: self._native.slot_frame(slot, row))
        c = np.zeros(NativeLoop.HARVEST_COUNTERS, dtype=np.uint64)
        sent = self._native.harvest(
            slot, v.allowed, rew["src_ip"], rew["dst_ip"],
            rew["src_port"], rew["dst_port"], v.route, v.node_id,
            self.overlay.remote_ips, self.overlay.local_ip,
            self.overlay.local_node_id, c,
        )
        self.counters.tx_remote += int(c[0])
        self.counters.tx_local += int(c[1])
        self.counters.tx_host += int(c[2])
        # Denied excludes rows the slow path already counted, rows the
        # quarantine dropped as poisoned, and inference-quarantined
        # rows; rows permitted but unforwardable are parse failures,
        # not denials.
        denied = int(c[3])
        self.counters.dropped_denied += \
            denied - slow_drops - poison_drops - infer_drops
        self.counters.dropped_unparseable += int(c[4])
        self.counters.dropped_unroutable += int(c[5])
        if self._bypass_tables:
            # This batch was dispatched under PRE-swap tables and may
            # have created sessions/punts the swap-time eligibility
            # check could not see — re-derive before the next bypass.
            self._bypass_recheck = True
        self._observe_harvest(k, t_admit, depth, t_harvest=t_h0, ts=int(ts),
                              frames=n, sent=sent, denied=denied,
                              t_materialized=t_mat, t_restored=t_slow)
        return sent

    # ------------------------------------------------------- python engine

    def _admit_python(self) -> bool:
        k_cap = self.governor.choose_k(self._backlog_depth())
        try:
            if self.faults.armed:
                self.faults.fire(SITE_FRAME_SOURCE_ERROR, shard=self.shard_index)
            frames = self.source.recv_batch(self.batch_size * k_cap)
        except Exception as err:  # noqa: BLE001 - socket flap / injected
            # Source errors degrade (count + report idle) instead of
            # killing the loop — the uplink may recover next poll.
            self.counters.source_errors += 1
            self._last_fault_error = f"source: {err}"
            return False
        if not frames:
            return False
        self.counters.rx_frames += len(frames)
        # Pack once; every later stage works on views into this buffer.
        # bytearray.join builds the packed bytes in ONE pass and is
        # writable (the harvest rewrites headers in place), where the
        # old bytes-join + .copy() duplicated every batch — the counter
        # records the second copy that no longer happens.
        lens = np.array([len(f) for f in frames], dtype=np.uint32)
        offsets = np.zeros(len(frames), dtype=np.uint64)
        np.cumsum(lens[:-1], dtype=np.uint64, out=offsets[1:])
        buf = np.frombuffer(bytearray(b"").join(frames), dtype=np.uint8)
        self.counters.admit_copy_saved_bytes += buf.size
        # Overlay ingress: de-encapsulate VXLAN frames (offset math in
        # native code, zero copies).  Only our VNI belongs to this
        # overlay segment — foreign VNIs are dropped, preserving the
        # reference's one-bridge-domain-per-VNI isolation
        # (plugins/ipv4net/node.go vxlanBridgeDomain :482).
        in_off, in_len, vnis = self.shim.vxlan_decap_view(buf, offsets, lens)
        is_vxlan = vnis >= 0
        keep = ~is_vxlan | (vnis == self.overlay.vni)
        self.counters.rx_decapped += int((is_vxlan & keep).sum())
        self.counters.dropped_foreign_vni += int((~keep).sum())
        if not keep.all():
            in_off, in_len = in_off[keep], in_len[keep]
            if not len(in_off):
                return True  # batch consumed entirely by foreign-VNI drops
        # Governor feedback AFTER the VNI filter, like the native admit:
        # the histogram/ramp must record what is DISPATCHED, not what a
        # drop-heavy overlay read pulled off the socket.
        self.governor.admitted(len(in_off), k_cap)
        # Vector count for this dispatch: enough batch_size-pkt vectors
        # to hold the kept frames, bucketed to a power of two under the
        # governor's cap (bounded compiles; one sizing rule everywhere).
        k = pow2_vectors(len(in_off), self.batch_size, k_cap)
        fb = self.shim.parse_view(buf, in_off, in_len, pad_to=k * self.batch_size)
        batch = PacketBatch(
            src_ip=jnp.asarray(fb.batch.src_ip),
            dst_ip=jnp.asarray(fb.batch.dst_ip),
            protocol=jnp.asarray(fb.batch.protocol),
            src_port=jnp.asarray(fb.batch.src_port),
            dst_port=jnp.asarray(fb.batch.dst_port),
        )
        t_admit = time.perf_counter()
        depth = len(self._inflight)
        result, batch_ts = self._dispatch_protected(batch, k)
        self._inflight.append((fb, result, batch_ts, k, t_admit, depth))
        return True

    def _harvest_python(self) -> int:
        t_h0 = time.perf_counter()  # harvest-start mark; see _harvest_native
        fb, result, ts, k, t_admit, depth = self._inflight.popleft()
        n = fb.n
        # Materialise (blocks on THIS batch only; newer ones stay
        # queued) — ONE transfer, same packed layout as the native
        # engine, with the SAME conditional-copy gating: before ISSUE
        # 11 this engine unconditionally copied every leaf; now the
        # all-fast-path case is zero-copy here too, counted like
        # admit_copy_saved_bytes.
        v = self._unpack_harvest(np.asarray(result.packed), n)
        rew = {
            "src_ip": v.src_ip,
            "dst_ip": v.dst_ip,
            "protocol": np.asarray(fb.batch.protocol)[:n],
            "src_port": v.src_port,
            "dst_port": v.dst_port,
        }
        orig = {
            "src_ip": np.asarray(fb.batch.src_ip)[:n],
            "dst_ip": np.asarray(fb.batch.dst_ip)[:n],
            "protocol": np.asarray(fb.batch.protocol)[:n],
            "src_port": np.asarray(fb.batch.src_port)[:n],
            "dst_port": np.asarray(fb.batch.dst_port)[:n],
        }
        t_mat = time.perf_counter()  # round stamp; see _harvest_native
        slow_drops = self._slowpath_and_trace(
            orig, rew, v.allowed, v.route, v.node_id,
            v.punt, v.reply_hit, v.dnat_hit, v.snat_hit, ts, k,
            straggler=v.straggler, band=v.band, infer_action=v.action,
        )
        t_slow = time.perf_counter()
        poison_drops = self._quarantine_rows(result, n, fb.frame)
        infer_drops = self._apply_infer_verdicts(v, n, fb.frame)

        # -------------------------------------------- native apply + TX
        allowed, route_tag, node_id = v.allowed, v.route, v.node_id
        rew_batch = PacketBatch(
            src_ip=rew["src_ip"], dst_ip=rew["dst_ip"], protocol=rew["protocol"],
            src_port=rew["src_port"], dst_port=rew["dst_port"],
        )
        fwd = self.shim.apply_masked(fb, allowed, rew_batch)
        allowed_bool = allowed.astype(bool)
        # Pipeline/policy denies exclude rows the slow path already
        # counted, quarantined poisoned rows, and inference-quarantined
        # rows; rows permitted but unforwardable are parse failures
        # (non-IPv4 frames), not denials.
        denied = int((~allowed_bool).sum())
        self.counters.dropped_denied += \
            denied - slow_drops - poison_drops - infer_drops
        self.counters.dropped_unparseable += int((allowed_bool & (fwd == 0)).sum())

        is_remote = (route_tag == ROUTE_REMOTE).astype(np.uint8)
        out_buf, out_off, out_len, out_rows, unroutable = self.shim.vxlan_encap(
            fb, fwd, is_remote, node_id, self.overlay.remote_ips,
            self.overlay.local_ip, self.overlay.local_node_id, self.overlay.vni,
        )
        self.counters.dropped_unroutable += unroutable
        sent = 0
        if len(out_rows):
            remote_frames = [
                out_buf[int(out_off[j]):int(out_off[j]) + int(out_len[j])].tobytes()
                for j in range(len(out_rows))
            ]
            self.tx.send(remote_frames)
            self.counters.tx_remote += len(remote_frames)
            sent += len(remote_frames)

        local_rows = np.nonzero(fwd.astype(bool) & (route_tag == ROUTE_LOCAL))[0]
        if len(local_rows):
            frames = [fb.frame(int(i)) for i in local_rows]
            self.local.send(frames)
            self.counters.tx_local += len(frames)
            sent += len(frames)

        host_rows = np.nonzero(fwd.astype(bool) & (route_tag == ROUTE_HOST))[0]
        if len(host_rows):
            frames = [fb.frame(int(i)) for i in host_rows]
            self.host.send(frames)
            self.counters.tx_host += len(frames)
            sent += len(frames)
        if self._bypass_tables:
            self._bypass_recheck = True  # see _harvest_native
        self._observe_harvest(k, t_admit, depth, t_harvest=t_h0, ts=int(ts),
                              frames=n, sent=sent, denied=denied,
                              t_materialized=t_mat, t_restored=t_slow)
        return sent

    # ------------------------------------------------------ shared harvest

    def _slowpath_and_trace(
        self, orig, rew, allowed, route_tag, node_id,
        punt, reply_hit, dnat_hit, snat_hit, ts, k=0, straggler=None,
        band=None, infer_action=None,
    ) -> int:
        """Host slow path (straggler resolution, punt servicing, port
        fixups, reply restores) + sampled packet trace — shared by both
        engines.  Mutates ``rew``/``allowed``/``route_tag``/``node_id``
        (and, for resolved stragglers, the verdict masks) in place and
        returns the number of slow-path drops.  Guarded by the (shared)
        host lock: in the sharded engine the slow path's session dict is
        one structure for all shards, because a punted flow's reply may
        land on a different shard than its forward packet did.  ``k``
        is the governor-chosen vector count of this batch — stamped
        (with the table generation) into the packet trace so traces
        correlate with flight-recorder rows and propagation spans."""
        with self._host_lock:
            return self._slowpath_and_trace_locked(
                orig, rew, allowed, route_tag, node_id,
                punt, reply_hit, dnat_hit, snat_hit, ts, k, straggler,
                band, infer_action,
            )

    def _slowpath_and_trace_locked(
        self, orig, rew, allowed, route_tag, node_id,
        punt, reply_hit, dnat_hit, snat_hit, ts, k=0, straggler=None,
        band=None, infer_action=None,
    ) -> int:
        slow_drops = 0
        if straggler is not None and straggler.any():
            # flat-punt round-cut: the device probe DETECTED these
            # same-dispatch replies and punted instead of paying the
            # dependent restore rounds.  Their forward packets are in
            # this very batch — resolve host-side against the rows
            # whose device session survived the dispatch, producing
            # exactly the verdict flat-safe's on-device restore (or the
            # next dispatch) would have.  Runs BEFORE record_punts so a
            # resolved reply never records a bogus host session; misses
            # (crafted aliasing only) stay on the ordinary punt path.
            self.counters.straggler_punts += int(straggler.sum())
            fwd_mask = (dnat_hit | snat_hit) & allowed & ~punt \
                & ~reply_hit & ~straggler
            restored = resolve_stragglers(orig, rew, straggler, fwd_mask)
            for row, (s_ip, s_port, d_ip, d_port) in restored:
                rew["src_ip"][row] = s_ip
                rew["src_port"][row] = s_port
                rew["dst_ip"][row] = d_ip
                rew["dst_port"][row] = d_port
                allowed[row] = True          # reflective-ACL bypass
                reply_hit[row] = True
                dnat_hit[row] = False
                snat_hit[row] = False
                punt[row] = False
                route_tag[row], node_id[row] = self._route_of(d_ip)
            self.counters.straggler_restores += len(restored)
        if punt.any():
            self.counters.punts += int(punt.sum())
            outcome = self.slow.record_punts(orig, rew, punt, snat_hit, ts)
            for row, port in outcome.fixups:
                rew["src_port"][row] = port
            for row in outcome.drops:
                allowed[row] = False
            slow_drops = len(outcome.drops)
            self.counters.dropped_slowpath += slow_drops
        if len(self.slow):
            # Forward packets of flows with host port overrides.
            for row, port in self.slow.fixup_forward(orig, snat_hit & ~punt):
                rew["src_port"][row] = port
            # Replies that missed the device table.
            cand = ~reply_hit & ~dnat_hit & ~snat_hit
            restored = self.slow.restore_replies(orig, cand, ts)
            if restored:
                self.counters.host_restores += len(restored)
                for row, (s_ip, s_port, d_ip, d_port) in restored:
                    rew["src_ip"][row] = s_ip
                    rew["src_port"][row] = s_port
                    rew["dst_ip"][row] = d_ip
                    rew["dst_port"][row] = d_port
                    allowed[row] = True
                    route_tag[row], node_id[row] = self._route_of(d_ip)
        self.tracer.record_batch(
            ts, orig, rew, allowed, route_tag, node_id,
            dnat_hit, snat_hit, reply_hit, punt,
            table_gen=self._table_gen, k=k,
            band=band, infer_action=infer_action,
        )
        return slow_drops

    def _route_of(self, dst_ip: int) -> Tuple[int, int]:
        """Host-side mirror of the pipeline's node-ID route arithmetic
        (for slow-path-restored packets only).  The route scalars are
        cached host-side per table generation: reading them off the
        device per restored packet cost FIVE device→host round trips on
        the harvest path (found by the hot-path-sync checker),
        multiplied by the restore count under punt-heavy load."""
        cached = self._route_cache
        if cached is None:
            # One-time (per swap) device read — the same five scalars
            # _refresh_bypass reads at swap time.
            cached = self._route_cache = tuple(
                int(np.asarray(v))  # static: allow(hot-path-sync) — once per swap, not per packet
                for v in (
                    self.route.pod_subnet_base, self.route.pod_subnet_mask,
                    self.route.this_node_base, self.route.this_node_mask,
                    self.route.host_bits,
                )
            )
        base, mask, tbase, tmask, hbits = cached
        if (dst_ip & tmask) == tbase:
            return ROUTE_LOCAL, 0
        if (dst_ip & mask) == base:
            return ROUTE_REMOTE, (dst_ip - base) >> hbits
        return ROUTE_HOST, 0

    # ------------------------------------------------------------ metrics

    def metrics(self) -> Dict[str, int]:
        out = self.counters.as_dict()
        out.update(self.slow.counters.as_dict())
        with self._state.lock:
            # Occupancy reads must hold the state lock: a concurrent
            # dispatch donates the session buffers it sums over (REST
            # scrape vs datapath thread — found by the ISSUE 9 soak).
            out["datapath_sessions_active"] = \
                session_occupancy(self.sessions)
            out["datapath_affinity_active"] = \
                affinity_occupancy(self.sessions)
        out["datapath_slowpath_sessions_active"] = len(self.slow)
        out["datapath_inflight"] = len(self._inflight)
        out["datapath_governor_k"] = self.governor.current_k
        out["datapath_governor_backlog"] = self.governor.backlog
        out["datapath_governor_slo_breaches_total"] = \
            self.governor.slo_breaches
        return out

    def inspect(self) -> Dict[str, object]:
        """Live-datapath introspection for `netctl inspect` (the vppcli
        analog, reference plugins/netctl/cmd/root.go:55-134): classify
        tables, NAT tables, session/affinity occupancy, ring depths,
        dispatch configuration, punt/slow-path state — everything an
        operator would interrogate on a running VPP with `show acl`,
        `show nat44 sessions`, `show buffers`.

        Note: occupancy reads are device→host transfers; on a
        tunnel-attached TPU the first one switches the link into its
        slower transfer mode.  That is inherent to any live occupancy
        query (metrics() pays it too) — this is an operator endpoint,
        not a hot path."""
        acl = self.acl
        nat = self.nat
        with self._state.lock:  # vs concurrent dispatch donation (see metrics)
            sessions_active = session_occupancy(self.sessions)
            affinity_pins = affinity_occupancy(self.sessions)
        compile_stats: Dict[str, object] = {
            "acl_swaps": self.counters.acl_swaps,
            "nat_swaps": self.counters.nat_swaps,
            "route_swaps": self.counters.route_swaps,
        }
        if self.compile_stats_fn is not None:
            compile_stats.update(self.compile_stats_fn())
        return {
            "engine": self.engine,
            "dispatch": self.inspect_dispatch(),
            "health": self.health(),
            "compile": compile_stats,
            "classify": {
                "rules": getattr(acl, "num_rules", 0) if acl is not None else 0,
                "tables": getattr(acl, "num_tables", 0) if acl is not None else 0,
                "pods": getattr(acl, "num_pods", 0) if acl is not None else 0,
            },
            "nat": {
                "mappings": nat.num_mappings if nat is not None else 0,
                "bucket_size": nat.bucket_size if nat is not None else 0,
                "use_hmap": bool(nat.use_hmap) if nat is not None else False,
                "has_affinity": bool(nat.has_affinity) if nat is not None else False,
                "snat_enabled": bool(np.asarray(nat.snat_enabled))
                if nat is not None else False,
            },
            "sessions": {
                "capacity": self.sessions.capacity,
                "active": sessions_active,
                "affinity_pins": affinity_pins,
                "sweep_interval": self.sweep_interval,
                "sweep_max_age": self.sweep_max_age,
            },
            "slowpath": {
                "sessions": len(self.slow),
                **self.slow.counters.as_dict(),
            },
            "rings": self.inspect_rings(),
            "counters": self.counters.as_dict(),
            "trace": self.tracer.status(),
            "latency": self.inspect_latency(),
            "flight": self.flight.status(),
            "inference": self.inspect_inference(),
        }

    # Host-only inspect slices (NO device reads) — the sharded engine
    # collects these per shard while paying the occupancy transfers
    # exactly once, on the shard whose full inspect() it keeps.

    def inspect_dispatch(self) -> Dict[str, object]:
        return {
            "discipline": self.dispatch,
            "batch_size": self.batch_size,
            "max_vectors": self.max_vectors,
            "max_inflight": self.max_inflight,
            "inflight": len(self._inflight),
            "bypass_eligible": bool(self._bypass_tables),
            "bypass_batches": self.counters.bypass_batches,
            "device_batches": self.counters.batches,
            "ts": self._ts,
            "table_gen": self._table_gen,
            "mesh": str(self.mesh.shape) if self.mesh is not None else "",
            "governor": self.governor.snapshot(),
            "prewarm": self.prewarm,
            # Round-chain attribution (ISSUE 10 satellite): per-round
            # host-gap distributions of the dispatch chain — the
            # direct evidence for ROADMAP #1's round-fusion work.
            "rounds": {name: hist.snapshot()
                       for name, hist in self.rounds.items()},
        }

    def inspect_rings(self) -> Dict[str, Dict[str, int]]:
        def ring_info(ring) -> Dict[str, int]:
            if ring is None:
                return {}
            info: Dict[str, int] = {}
            try:
                info["frames"] = len(ring)
            except TypeError:
                pass
            dropped = getattr(ring, "dropped", None)
            if dropped is not None:
                info["dropped"] = int(dropped)
            return info

        return {
            "rx": ring_info(self.source),
            "tx_remote": ring_info(self.tx),
            "tx_local": ring_info(self.local),
            "tx_host": ring_info(self.host),
        }
