"""Packet tracing — sampled per-packet verdict traces.

Analog of VPP's packet trace (``scripts/vpptrace.sh`` wraps ``trace add
<node> 1000`` over the vppctl socket; the agent enables it via the
EnablePacketTrace config, contivconf.go:556).  The tracer rides the
datapath harvest: when enabled, every sample_every-th packet of each
harvested batch is recorded into a bounded ring — original and
rewritten 5-tuple, verdict, route tag and NAT/slow-path flags — and
exposed through REST (`/contiv/v1/trace`) and netctl.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import asdict, dataclass
from typing import Deque, Dict, List

from ..ops.packets import u32_to_ip
from ..ops.pipeline import ROUTE_DROP, ROUTE_HOST, ROUTE_LOCAL, ROUTE_REMOTE

DEFAULT_CAPACITY = 1000  # vpptrace.sh uses a 1000-packet buffer

_ROUTE_NAMES = {
    ROUTE_DROP: "drop",
    ROUTE_LOCAL: "local",
    ROUTE_REMOTE: "remote",
    ROUTE_HOST: "host",
}


@dataclass(frozen=True)
class TraceEntry:
    """One traced packet (the vppctl `show trace` record analog)."""

    seq: int
    batch_ts: int
    src: str
    dst: str
    protocol: int
    src_port: int
    dst_port: int
    rw_src: str
    rw_dst: str
    rw_src_port: int
    rw_dst_port: int
    allowed: bool
    route: str
    node_id: int
    dnat: bool
    snat: bool
    reply: bool
    punt: bool

    def as_dict(self) -> Dict:
        return asdict(self)


class PacketTracer:
    """Bounded, sampled trace ring; thread-safe (harvest vs REST)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._entries: Deque[TraceEntry] = collections.deque(maxlen=capacity)
        self.enabled = False
        self.sample_every = 1
        self._seq = 0    # recorded entries (trace sequence numbers)
        self._seen = 0   # every packet that passed while enabled
        self._skip = 0

    def enable(self, sample_every: int = 1, capacity: int = 0) -> None:
        with self._lock:
            self.sample_every = max(1, sample_every)
            if capacity > 0:
                self._entries = collections.deque(
                    self._entries, maxlen=capacity
                )
            self._skip = 0  # fresh sampling phase per enable
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._skip = 0

    @property
    def capacity(self) -> int:
        return self._entries.maxlen or 0

    def record_batch(
        self, batch_ts, orig, rew, allowed, route_tag, node_id,
        dnat, snat, reply, punt,
    ) -> None:
        """Record the sampled rows of one harvested batch; ``orig``/``rew``
        are the harvest's field->ndarray dicts."""
        if not self.enabled:
            return
        with self._lock:
            n = len(allowed)
            self._seen += n
            i = self._skip
            while i < n:
                self._seq += 1
                self._entries.append(
                    TraceEntry(
                        seq=self._seq,
                        batch_ts=int(batch_ts),
                        src=u32_to_ip(int(orig["src_ip"][i])),
                        dst=u32_to_ip(int(orig["dst_ip"][i])),
                        protocol=int(orig["protocol"][i]),
                        src_port=int(orig["src_port"][i]),
                        dst_port=int(orig["dst_port"][i]),
                        rw_src=u32_to_ip(int(rew["src_ip"][i])),
                        rw_dst=u32_to_ip(int(rew["dst_ip"][i])),
                        rw_src_port=int(rew["src_port"][i]),
                        rw_dst_port=int(rew["dst_port"][i]),
                        allowed=bool(allowed[i]),
                        route=_ROUTE_NAMES.get(int(route_tag[i]), "?"),
                        node_id=int(node_id[i]),
                        dnat=bool(dnat[i]),
                        snat=bool(snat[i]),
                        reply=bool(reply[i]),
                        punt=bool(punt[i]),
                    )
                )
                i += self.sample_every
            self._skip = (i - n) % self.sample_every

    def dump(self) -> List[Dict]:
        with self._lock:
            return [e.as_dict() for e in self._entries]

    def status(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "sample_every": self.sample_every,
                "capacity": self.capacity,
                "recorded": len(self._entries),
                "total_seen": self._seen,
            }
