"""Packet tracing — sampled per-packet verdict traces.

Analog of VPP's packet trace (``scripts/vpptrace.sh`` wraps ``trace add
<node> 1000`` over the vppctl socket; the agent enables it via the
EnablePacketTrace config, contivconf.go:556).  The tracer rides the
datapath harvest: when enabled, every sample_every-th packet of each
harvested batch is recorded into a bounded ring — original and
rewritten 5-tuple, verdict, route tag and NAT/slow-path flags — and
exposed through REST (`/contiv/v1/trace`) and netctl.
"""

from __future__ import annotations

import collections
import threading
from dataclasses import asdict, dataclass
from typing import Deque, Dict, List

from ..ops.packets import u32_to_ip
from ..ops.pipeline import ROUTE_DROP, ROUTE_HOST, ROUTE_LOCAL, ROUTE_REMOTE

DEFAULT_CAPACITY = 1000  # vpptrace.sh uses a 1000-packet buffer

_ROUTE_NAMES = {
    ROUTE_DROP: "drop",
    ROUTE_LOCAL: "local",
    ROUTE_REMOTE: "remote",
    ROUTE_HOST: "host",
}


@dataclass(frozen=True)
class TraceEntry:
    """One traced packet (the vppctl `show trace` record analog).

    ``table_gen`` and ``k`` (ISSUE 8) stamp the dispatch batch's table
    generation and governor-chosen vector count, so a trace row
    correlates directly with flight-recorder rows (same generation
    field) and with the propagation span that installed those tables."""

    seq: int
    batch_ts: int
    src: str
    dst: str
    protocol: int
    src_port: int
    dst_port: int
    rw_src: str
    rw_dst: str
    rw_src_port: int
    rw_dst_port: int
    allowed: bool
    route: str
    node_id: int
    dnat: bool
    snat: bool
    reply: bool
    punt: bool
    table_gen: int
    k: int
    # In-network inference stage (ISSUE 14): the packet's log2 score
    # band and the action code that fired (0 = none / not scored) —
    # the trace ring is where a single flagged flow's score is read
    # next to its verdict during a score-storm triage.
    infer_band: int
    infer_action: int

    def as_dict(self) -> Dict:
        return asdict(self)


class PacketTracer:
    """Bounded, sampled trace ring; thread-safe (harvest vs REST)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        # Raw per-packet tuples (see record_batch); formatted in dump().
        self._entries: Deque[tuple] = collections.deque(maxlen=capacity)
        self.enabled = False
        self.sample_every = 1
        self._seq = 0    # recorded entries (trace sequence numbers)
        self._seen = 0   # every packet that passed while enabled
        self._skip = 0

    def enable(self, sample_every: int = 1, capacity: int = 0) -> None:
        with self._lock:
            self.sample_every = max(1, sample_every)
            if capacity > 0:
                self._entries = collections.deque(
                    self._entries, maxlen=capacity
                )
            self._skip = 0  # fresh sampling phase per enable
            self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._skip = 0

    @property
    def capacity(self) -> int:
        return self._entries.maxlen or 0

    def record_batch(
        self, batch_ts, orig, rew, allowed, route_tag, node_id,
        dnat, snat, reply, punt, table_gen: int = 0, k: int = 0,
        band=None, infer_action=None,
    ) -> None:
        """Record the sampled rows of one harvested batch; ``orig``/``rew``
        are the harvest's field->ndarray dicts.  ``table_gen``/``k``
        are batch-constant correlation stamps (ISSUE 8).  The hot path
        stores raw int tuples; all string formatting is deferred to
        dump(), and the lock is held only for the ring appends."""
        if not self.enabled:
            return
        n = len(allowed)
        with self._lock:
            self._seen += n
            start = self._skip
            rows = list(range(start, n, self.sample_every))
            self._skip = (
                (start + len(rows) * self.sample_every) - n
            ) % self.sample_every if rows else (start - n) % self.sample_every
            base_seq = self._seq
            self._seq += len(rows)
        raw = [
            (
                base_seq + j + 1, int(batch_ts),
                int(orig["src_ip"][i]), int(orig["dst_ip"][i]),
                int(orig["protocol"][i]),
                int(orig["src_port"][i]), int(orig["dst_port"][i]),
                int(rew["src_ip"][i]), int(rew["dst_ip"][i]),
                int(rew["src_port"][i]), int(rew["dst_port"][i]),
                bool(allowed[i]), int(route_tag[i]), int(node_id[i]),
                bool(dnat[i]), bool(snat[i]), bool(reply[i]), bool(punt[i]),
                int(table_gen), int(k),
                0 if band is None else int(band[i]),
                0 if infer_action is None else int(infer_action[i]),
            )
            for j, i in enumerate(rows)
        ]
        with self._lock:
            self._entries.extend(raw)

    @staticmethod
    def _to_entry(r) -> TraceEntry:
        return TraceEntry(
            seq=r[0], batch_ts=r[1],
            src=u32_to_ip(r[2]), dst=u32_to_ip(r[3]), protocol=r[4],
            src_port=r[5], dst_port=r[6],
            rw_src=u32_to_ip(r[7]), rw_dst=u32_to_ip(r[8]),
            rw_src_port=r[9], rw_dst_port=r[10],
            allowed=r[11], route=_ROUTE_NAMES.get(r[12], "?"),
            node_id=r[13], dnat=r[14], snat=r[15], reply=r[16], punt=r[17],
            # Entries recorded before the ISSUE 8 stamps existed (an
            # enable spanning an agent upgrade) degrade to gen 0 / K 0;
            # pre-ISSUE-14 entries likewise degrade to band/action 0.
            table_gen=r[18] if len(r) > 18 else 0,
            k=r[19] if len(r) > 19 else 0,
            infer_band=r[20] if len(r) > 20 else 0,
            infer_action=r[21] if len(r) > 21 else 0,
        )

    def dump(self) -> List[Dict]:
        with self._lock:
            raw = list(self._entries)
        return [self._to_entry(r).as_dict() for r in raw]

    def status(self) -> Dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "sample_every": self.sample_every,
                "capacity": self.capacity,
                "recorded": len(self._entries),
                "total_seen": self._seen,
            }
