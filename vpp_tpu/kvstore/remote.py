"""Networked cluster store — KVStore served over gRPC.

Round-1 verdict item 5: the "etcd" was an in-process Python object, so
the SPMD story never crossed a socket.  This module serves a
:class:`~vpp_tpu.kvstore.store.KVStore` over gRPC (the role etcd's gRPC
API plays for the reference, consumed by
plugins/controller/dbwatcher.go:111-137) and provides a client that is
a drop-in for the in-process store:

- unary RPCs for get/put/delete/put_if_not_exists/compare_and_delete/
  list/snapshot_with_revision (values carried by the typed codec);
- a server-streaming Watch with revisions, feeding the same
  :class:`Watcher` queue interface dbwatcher polls;
- client-side reconnect with exponential backoff; after the stream
  re-subscribes, registered ``on_reconnect`` callbacks fire so the
  dbwatcher can resync (the reference's re-watch+resync on reconnect,
  dbwatcher.go:252-267).

The wire protocol is gRPC (HTTP/2) with codec-JSON messages, matching
the framework's other services (cni/rpc.py, extconfig/plugin.py): the
environment has no protoc service-stub generator, so services register
through ``grpc.method_handlers_generic_handler``.
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import grpc

from . import codec
from .store import KVStore, WatchEvent, Watcher

log = logging.getLogger(__name__)

SERVICE_NAME = "kvstore.KVStore"
DEFAULT_PORT = 12379  # etcd's 2379, out of the privileged/common range

# Status codes that mean "transport outage" (retry / fall back to the
# local mirror) — everything else is a server-side bug and must surface.
# Single source of truth; the dbwatcher's unary-path classifier imports
# this so stream and unary outage handling cannot drift.
OUTAGE_CODES = frozenset((
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.CANCELLED,
))


def _encode(msg: dict) -> bytes:
    return codec.encode(msg)


def _decode(data: bytes) -> dict:
    return codec.decode(data)


class KVStoreServer:
    """Serves one in-process KVStore to the cluster.

    Each Watch stream parks one thread of the server's pool for its whole
    life (sync gRPC streams a generator from a worker thread), so the pool
    is sized as ``max_watchers`` streaming slots PLUS a fixed reserve of
    unary workers — a watcher storm can never starve Get/Put/Snapshot.
    Watch registrations beyond ``max_watchers`` are rejected loudly with
    RESOURCE_EXHAUSTED instead of silently wedging the control plane.
    """

    UNARY_WORKERS = 16

    def __init__(self, store: KVStore, host: str = "127.0.0.1", port: int = 0,
                 max_watchers: int = 64):
        self.store = store
        self.host = host
        self.port = port
        self.max_watchers = max_watchers
        self._active_watchers = 0
        self._watch_lock = threading.Lock()
        self._server: Optional[grpc.Server] = None

    # ------------------------------------------------------------- handlers

    def _get(self, request: dict, context=None) -> dict:
        return {"value": self.store.get(request["key"])}

    def _put(self, request: dict, context=None) -> dict:
        return {"revision": self.store.put(request["key"], request["value"])}

    def _delete(self, request: dict, context=None) -> dict:
        return {"deleted": self.store.delete(request["key"])}

    def _put_if_not_exists(self, request: dict, context=None) -> dict:
        return {"created": self.store.put_if_not_exists(request["key"], request["value"])}

    def _compare_and_delete(self, request: dict, context=None) -> dict:
        return {"deleted": self.store.compare_and_delete(request["key"], request["expected"])}

    def _list(self, request: dict, context=None) -> dict:
        return {"items": self.store.list(request.get("prefix", ""))}

    def _snapshot(self, request: dict, context=None) -> dict:
        snap, rev = self.store.snapshot_with_revision(request["prefixes"])
        return {"snapshot": snap, "revision": rev}

    def _revision(self, request: dict, context=None) -> dict:
        return {"revision": self.store.revision}

    def _watch(self, request: dict, context) -> Iterable[dict]:
        """Server-streaming: a subscribe-ack, then one message per
        committed change.  The ack (empty key) proves the store-side
        watcher is registered, so a client that snapshots AFTER receiving
        it cannot lose events between snapshot and stream."""
        with self._watch_lock:
            if self._active_watchers >= self.max_watchers:
                log.error(
                    "watch limit reached (%d): rejecting new stream "
                    "(raise KVStoreServer(max_watchers=...))", self.max_watchers,
                )
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"watcher limit {self.max_watchers} reached",
                )
            self._active_watchers += 1
        watcher = None
        try:
            watcher = self.store.watch(request["prefixes"])
            yield {"key": "", "value": None, "prev_value": None,
                   "revision": self.store.revision}
            while context.is_active():
                ev = watcher.get(timeout=0.2)
                if ev is None:
                    continue
                yield {
                    "key": ev.key,
                    "value": ev.value,
                    "prev_value": ev.prev_value,
                    "revision": ev.revision,
                }
        finally:
            if watcher is not None:
                self.store.unwatch(watcher)
            with self._watch_lock:
                self._active_watchers -= 1

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        unary = {
            name: grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=_decode, response_serializer=_encode
            )
            for name, fn in [
                ("Get", self._get),
                ("Put", self._put),
                ("Delete", self._delete),
                ("PutIfNotExists", self._put_if_not_exists),
                ("CompareAndDelete", self._compare_and_delete),
                ("List", self._list),
                ("Snapshot", self._snapshot),
                ("Revision", self._revision),
            ]
        }
        unary["Watch"] = grpc.unary_stream_rpc_method_handler(
            self._watch, request_deserializer=_decode, response_serializer=_encode
        )
        self._server = grpc.server(futures.ThreadPoolExecutor(
            max_workers=self.max_watchers + self.UNARY_WORKERS))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, unary),)
        )
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()
        log.info("kvstore gRPC server on %s:%d", self.host, self.port)
        return self.port

    def stop(self, grace: float = 0.2) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class RemoteWatcher(Watcher):
    """Client side of a Watch stream; same queue interface as Watcher.

    The stream thread reconnects with backoff; every successful
    re-subscription after a drop invokes the owner's reconnect hooks
    (events during the outage are NOT replayed — the owner must resync,
    exactly like the reference after an etcd reconnect)."""

    def __init__(self, owner: "RemoteKVStore", prefixes: Tuple[str, ...]):
        super().__init__(prefixes)
        self._owner = owner
        self._subscribed = threading.Event()
        self._call = None  # current stream call, for cancel() on close
        self._thread = threading.Thread(
            target=self._stream_loop, name="kv-remote-watch", daemon=True
        )
        self._thread.start()

    def wait_subscribed(self, timeout: float = 5.0) -> bool:
        """Block until the server acknowledged the watch registration.
        Snapshot-after-subscribe callers (dbwatcher) use this to keep the
        no-event-lost-between-snapshot-and-stream guarantee across the
        socket."""
        return self._subscribed.wait(timeout)

    def close(self) -> None:
        self.closed = True
        call = self._call
        if call is not None:
            call.cancel()

    def _stream_loop(self) -> None:
        backoff = 0.05
        failed_before = False
        while not self.closed:
            try:
                stream = self._owner._stub_watch({"prefixes": list(self.prefixes)})
                self._call = stream
                for msg in stream:
                    if self.closed:
                        return
                    if msg["key"] == "":
                        # Subscribe-ack: the server-side watcher is live.
                        # If we are recovering from an outage (including
                        # one at startup), tell the owner so it can
                        # resync — outage events are never replayed.
                        self._subscribed.set()
                        backoff = 0.05
                        if failed_before:
                            failed_before = False
                            self._owner._fire_reconnect()
                        continue
                    self.queue.put(
                        WatchEvent(
                            key=msg["key"],
                            value=msg["value"],
                            prev_value=msg["prev_value"],
                            revision=msg["revision"],
                        )
                    )
            except grpc.RpcError as e:
                code_fn = getattr(e, "code", None)
                code = code_fn() if code_fn is not None else None
                if code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    # Server watcher limit hit — fail loudly (ADVICE r2);
                    # the backoff retry may still grab a freed slot.
                    log.error("watch stream rejected: %s", e)
                elif code not in OUTAGE_CODES:
                    # Not an outage: a server-side handler crash
                    # (UNKNOWN/INTERNAL) would otherwise retry silently
                    # forever while the watch is effectively dead.
                    log.warning("watch stream failed with %s: %s", code, e)
            finally:
                self._call = None
            if self.closed:
                return
            self._subscribed.clear()
            failed_before = True
            time.sleep(backoff)
            backoff = min(backoff * 2, 2.0)


class RemoteKVStore:
    """Drop-in KVStore client talking to a KVStoreServer.

    Raises ``grpc.RpcError`` on unary calls while the server is
    unreachable (callers like the dbwatcher fall back to their local
    mirror, dbwatcher.go:309-333).
    """

    _METHODS = (
        "Get", "Put", "Delete", "PutIfNotExists", "CompareAndDelete",
        "List", "Snapshot", "Revision",
    )

    def __init__(self, address: str, timeout: float = 5.0):
        self.address = address
        self.timeout = timeout
        self._channel = grpc.insecure_channel(address)
        self._calls = {
            m: self._channel.unary_unary(
                f"/{SERVICE_NAME}/{m}",
                request_serializer=_encode,
                response_deserializer=_decode,
            )
            for m in self._METHODS
        }
        self._watch_call = self._channel.unary_stream(
            f"/{SERVICE_NAME}/Watch",
            request_serializer=_encode,
            response_deserializer=_decode,
        )
        self._watchers: List[RemoteWatcher] = []
        self._reconnect_cbs: List[Callable[[], None]] = []

    def _rpc(self, method: str, request: dict) -> dict:
        return self._calls[method](request, timeout=self.timeout)

    def _stub_watch(self, request: dict):
        return self._watch_call(request)

    # ------------------------------------------------------------ interface

    def get(self, key: str) -> Optional[Any]:
        return self._rpc("Get", {"key": key})["value"]

    def put(self, key: str, value: Any) -> int:
        if value is None:
            raise ValueError("use delete() to remove a key")
        return self._rpc("Put", {"key": key, "value": value})["revision"]

    def delete(self, key: str) -> bool:
        return self._rpc("Delete", {"key": key})["deleted"]

    def put_if_not_exists(self, key: str, value: Any) -> bool:
        return self._rpc("PutIfNotExists", {"key": key, "value": value})["created"]

    def compare_and_delete(self, key: str, expected: Any) -> bool:
        return self._rpc("CompareAndDelete", {"key": key, "expected": expected})["deleted"]

    def list(self, prefix: str = "") -> List[Tuple[str, Any]]:
        return [tuple(item) for item in self._rpc("List", {"prefix": prefix})["items"]]

    def snapshot(self, prefixes: Iterable[str]) -> Dict[str, Any]:
        return self.snapshot_with_revision(prefixes)[0]

    def snapshot_with_revision(
        self, prefixes: Iterable[str]
    ) -> Tuple[Dict[str, Any], int]:
        resp = self._rpc("Snapshot", {"prefixes": list(prefixes)})
        return resp["snapshot"], resp["revision"]

    @property
    def revision(self) -> int:
        return self._rpc("Revision", {})["revision"]

    # -------------------------------------------------------------- watches

    def watch(self, prefixes: Iterable[str]) -> RemoteWatcher:
        watcher = RemoteWatcher(self, tuple(prefixes))
        self._watchers.append(watcher)
        return watcher

    def unwatch(self, watcher: Watcher) -> None:
        if isinstance(watcher, RemoteWatcher):
            watcher.close()  # cancels the stream; server unregisters
        else:
            watcher.closed = True
        if watcher in self._watchers:
            self._watchers.remove(watcher)

    def on_reconnect(self, callback: Callable[[], None]) -> None:
        """Register a hook fired after a watch stream re-subscribes
        following an outage (the dbwatcher resyncs here)."""
        self._reconnect_cbs.append(callback)

    def _fire_reconnect(self) -> None:
        for cb in list(self._reconnect_cbs):
            try:
                cb()
            except Exception:  # noqa: BLE001
                log.exception("reconnect callback failed")

    def close(self) -> None:
        for w in list(self._watchers):
            self.unwatch(w)
        self._channel.close()
