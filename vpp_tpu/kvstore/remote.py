"""Networked cluster store — KVStore served over gRPC.

Round-1 verdict item 5: the "etcd" was an in-process Python object, so
the SPMD story never crossed a socket.  This module serves a
:class:`~vpp_tpu.kvstore.store.KVStore` over gRPC (the role etcd's gRPC
API plays for the reference, consumed by
plugins/controller/dbwatcher.go:111-137) and provides a client that is
a drop-in for the in-process store:

- unary RPCs for get/put/delete/put_if_not_exists/compare_and_delete/
  list/snapshot_with_revision (values carried by the typed codec);
- a server-streaming Watch with revisions, feeding the same
  :class:`Watcher` queue interface dbwatcher polls;
- client-side reconnect with exponential backoff; after the stream
  re-subscribes, registered ``on_reconnect`` callbacks fire so the
  dbwatcher can resync (the reference's re-watch+resync on reconnect,
  dbwatcher.go:252-267).

The wire protocol is gRPC (HTTP/2) with codec-JSON messages, matching
the framework's other services (cni/rpc.py, extconfig/plugin.py): the
environment has no protoc service-stub generator, so services register
through ``grpc.method_handlers_generic_handler``.
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from concurrent import futures
from typing import Any, Callable, Dict, Iterable, List, Optional, Tuple

import grpc

from . import codec, compat
from .compat import IncompatibleVersion
from .store import KVStore, WatchEvent, Watcher

log = logging.getLogger(__name__)

SERVICE_NAME = "kvstore.KVStore"
DEFAULT_PORT = 12379  # etcd's 2379, out of the privileged/common range

# Status codes that mean "transport outage" (retry / fall back to the
# local mirror) — everything else is a server-side bug and must surface.
# Single source of truth; the dbwatcher's unary-path classifier imports
# this so stream and unary outage handling cannot drift.
OUTAGE_CODES = frozenset((
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.CANCELLED,
))

# An HA follower rejects client ops with FAILED_PRECONDITION and this
# details prefix, carrying the leader it currently follows ("" while an
# election is in flight).  The client parses it to re-home (vpp_tpu/
# kvstore/ha.py is the server side of the contract).
NOT_LEADER_PREFIX = "NOT_LEADER leader="

# An HA leader that applied a write locally but could not gather a
# replica-majority ack rejects it ABORTED with this details prefix: the
# op is INDETERMINATE (it stays in the leader's log and usually commits
# on a later replication tick).  The failover client auto-retries it
# only for idempotent ops.
NO_QUORUM_PREFIX = "NO_QUORUM "

# Ops safe to retry blindly on an indeterminate failure — re-running
# them cannot change the END STATE the caller asked for.  PutIfNotExists
# / CompareAndDelete are NOT here: a retry of an already-applied attempt
# would report created=False / deleted=False for its own write, and
# their returns gate conditional logic (id allocation, CAS loops) that
# must never be lied to.  Delete IS here as a deliberate trade: the
# retried end state (key absent) is identical, only the advisory
# deleted-flag can read False for the caller's own delete — and raising
# instead would turn every failover window into an exception in the
# ksr/extconfig/nodesync delete paths this subsystem exists to keep
# alive.
IDEMPOTENT_METHODS = frozenset(
    ("Get", "Put", "Delete", "List", "Snapshot", "Revision"))


def _code_of(err: Exception) -> Optional[grpc.StatusCode]:
    """The gRPC status code of an error, None when it has none (or
    producing it fails) — defensive because non-RpcError exceptions
    flow through the same handlers."""
    code_fn = getattr(err, "code", None)
    if code_fn is None:
        return None
    try:
        return code_fn()
    except Exception:  # noqa: BLE001 - errors without a code
        return None


def _status_of(err: Exception) -> Optional[tuple]:
    """``(status_code, details)`` of a gRPC error, None for anything
    that lacks either half."""
    code = _code_of(err)
    details_fn = getattr(err, "details", None)
    if code is None or details_fn is None:
        return None
    try:
        return code, (details_fn() or "")
    except Exception:  # noqa: BLE001 - errors without details
        return None


def no_quorum(err: Exception) -> bool:
    """True when ``err`` is an HA leader's NO_QUORUM rejection."""
    status = _status_of(err)
    return (status is not None
            and status[0] is grpc.StatusCode.ABORTED
            and status[1].startswith(NO_QUORUM_PREFIX))


def incompatible_version(err: Exception) -> Optional[tuple]:
    """``(got, floor)`` when ``err`` is a server's below-floor version
    refusal (ISSUE 13), else None.  Shares FAILED_PRECONDITION with
    NOT_LEADER — the details prefix disambiguates."""
    status = _status_of(err)
    if (status is None
            or status[0] is not grpc.StatusCode.FAILED_PRECONDITION):
        return None
    return compat.parse_incompatible(status[1])


def not_leader_hint(err: Exception) -> Optional[str]:
    """The leader address carried by a NOT_LEADER rejection, "" when the
    rejecting replica knows no leader yet, None for any other error."""
    status = _status_of(err)
    if (status is None
            or status[0] is not grpc.StatusCode.FAILED_PRECONDITION
            or not status[1].startswith(NOT_LEADER_PREFIX)):
        return None
    return status[1][len(NOT_LEADER_PREFIX):]


# Watch re-establishment backoff defaults (RemoteKVStore ctor knobs).
# Jitter is MULTIPLICATIVE: delay = base * uniform(1-j, 1+j).  Without
# it, every agent that lost its stream in the same outage retries on
# the same schedule — at cluster scale (the ISSUE 9 soak runs ~100
# agents) the recovering leader takes the whole fleet's re-subscribe
# burst in one instant, each stream parking a server worker thread.
WATCH_BACKOFF_INITIAL = 0.05
WATCH_BACKOFF_MAX = 2.0
WATCH_BACKOFF_JITTER = 0.5


def reconnect_backoff(
    attempt: int,
    initial: float = WATCH_BACKOFF_INITIAL,
    cap: float = WATCH_BACKOFF_MAX,
    jitter: float = WATCH_BACKOFF_JITTER,
    rng: Callable[[], float] = random.random,
) -> float:
    """Delay before watch re-establishment attempt ``attempt`` (1-based
    count of consecutive failures): capped exponential, then spread by
    the multiplicative jitter.  Pure function of (attempt, rng) so the
    schedule is unit-testable."""
    if attempt < 1:
        attempt = 1
    base = min(initial * (2.0 ** (attempt - 1)), cap)
    if jitter <= 0.0:
        return base
    return base * (1.0 - jitter + 2.0 * jitter * rng())


class LeaderUnavailable(ConnectionError):
    """Raised when a failover client exhausted its retry window without
    finding a serving leader.  Subclasses ConnectionError so the
    dbwatcher's outage classifier treats it as a transport outage (fall
    back to the local mirror), not a server bug."""


def _encode(msg: dict) -> bytes:
    return codec.encode(msg)


def _decode(data: bytes) -> dict:
    return codec.decode(data)


class KVStoreServer:
    """Serves one in-process KVStore to the cluster.

    Each Watch stream parks one thread of the server's pool for its whole
    life (sync gRPC streams a generator from a worker thread), so the pool
    is sized as ``max_watchers`` streaming slots PLUS a fixed reserve of
    unary workers — a watcher storm can never starve Get/Put/Snapshot.
    Watch registrations beyond ``max_watchers`` are rejected loudly with
    RESOURCE_EXHAUSTED instead of silently wedging the control plane.
    """

    UNARY_WORKERS = 16

    # Methods that run their OWN version handling instead of the
    # aborting gate: the HA replica protocol answers a below-floor peer
    # with a typed `{"incompatible": True, got, min}` reply the
    # leader's push loop classifies (loud log, no snapshot fallback) —
    # an abort here would reduce that to a generic RpcError→None and
    # the typed path would be unreachable over the real wire.
    SELF_VERSIONED: frozenset = frozenset()

    def __init__(self, store: KVStore, host: str = "127.0.0.1", port: int = 0,
                 max_watchers: int = 64):
        self.store = store
        self.host = host
        self.port = port
        self.max_watchers = max_watchers
        self._active_watchers = 0
        self._watch_lock = threading.Lock()
        self._server: Optional[grpc.Server] = None

    # ------------------------------------------------------------- handlers

    def _get(self, request: dict, context=None) -> dict:
        return {"value": self.store.get(request["key"])}

    def _put(self, request: dict, context=None) -> dict:
        return {"revision": self.store.put(request["key"], request["value"])}

    def _delete(self, request: dict, context=None) -> dict:
        return {"deleted": self.store.delete(request["key"])}

    def _put_if_not_exists(self, request: dict, context=None) -> dict:
        return {"created": self.store.put_if_not_exists(request["key"], request["value"])}

    def _compare_and_delete(self, request: dict, context=None) -> dict:
        return {"deleted": self.store.compare_and_delete(request["key"], request["expected"])}

    def _list(self, request: dict, context=None) -> dict:
        return {"items": self.store.list(request.get("prefix", ""))}

    def _snapshot(self, request: dict, context=None) -> dict:
        snap, rev = self.store.snapshot_with_revision(request["prefixes"])
        return {"snapshot": snap, "revision": rev}

    def _revision(self, request: dict, context=None) -> dict:
        return {"revision": self.store.revision}

    def _gate(self, context) -> None:
        """Pre-serve hook: the HA replica server aborts here when this
        process is not the leader (client ops are leader-only).  The
        standalone server serves unconditionally."""

    def _version_gate(self, request, context) -> None:
        """Refuse a below-floor peer BEFORE any state changes (ISSUE
        13): an explicit INCOMPATIBLE_VERSION rejection, never a
        best-effort decode.  Unstamped requests (legacy clients,
        in-process callers) pass — the floor fences explicit versions,
        not the pre-versioned lineage."""
        try:
            compat.check(request if isinstance(request, dict) else {})
        except IncompatibleVersion as err:
            if context is None:
                raise
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          compat.incompatible_details(err))

    def _versioned(self, fn: Callable) -> Callable:
        def handler(request, context=None):
            self._version_gate(request, context)
            return fn(request, context)
        return handler

    def _watch(self, request: dict, context) -> Iterable[dict]:
        """Server-streaming: a subscribe-ack, then one message per
        committed change.  The ack (empty key) proves the store-side
        watcher is registered, so a client that snapshots AFTER receiving
        it cannot lose events between snapshot and stream.

        ``since_revision`` (>= 0) asks for replay of the events committed
        after that revision, delivered between the ack and the live
        stream with nothing falling in between (store.watch_since is
        atomic).  The ack's ``resync`` flag reports whether the bounded
        event log still reached back that far; when it did not, the
        client must snapshot instead (the dbwatcher's reconnect resync).
        """
        self._version_gate(request, context)
        self._gate(context)
        with self._watch_lock:
            if self._active_watchers >= self.max_watchers:
                log.error(
                    "watch limit reached (%d): rejecting new stream "
                    "(raise KVStoreServer(max_watchers=...))", self.max_watchers,
                )
                context.abort(
                    grpc.StatusCode.RESOURCE_EXHAUSTED,
                    f"watcher limit {self.max_watchers} reached",
                )
            self._active_watchers += 1
        watcher = None
        try:
            since = request.get("since_revision", -1)
            watcher, missed = self.store.watch_since(request["prefixes"], since)
            yield {"key": "", "value": None, "prev_value": None,
                   "revision": self.store.revision,
                   "resync": missed is None}
            for ev in (missed or ()):
                yield {
                    "key": ev.key,
                    "value": ev.value,
                    "prev_value": ev.prev_value,
                    "revision": ev.revision,
                }
            while context.is_active():
                self._gate(context)
                ev = watcher.get(timeout=0.2)
                if ev is None:
                    continue
                yield {
                    "key": ev.key,
                    "value": ev.value,
                    "prev_value": ev.prev_value,
                    "revision": ev.revision,
                }
        finally:
            if watcher is not None:
                self.store.unwatch(watcher)
            with self._watch_lock:
                self._active_watchers -= 1

    # ------------------------------------------------------------ lifecycle

    def _unary_handlers(self) -> Dict[str, Callable]:
        """Method-name → handler; the HA replica server extends this."""
        return {
            "Get": self._get,
            "Put": self._put,
            "Delete": self._delete,
            "PutIfNotExists": self._put_if_not_exists,
            "CompareAndDelete": self._compare_and_delete,
            "List": self._list,
            "Snapshot": self._snapshot,
            "Revision": self._revision,
        }

    def _stream_handlers(self) -> Dict[str, Callable]:
        return {"Watch": self._watch}

    def start(self) -> int:
        unary = {
            name: grpc.unary_unary_rpc_method_handler(
                fn if name in self.SELF_VERSIONED else self._versioned(fn),
                request_deserializer=_decode, response_serializer=_encode
            )
            for name, fn in self._unary_handlers().items()
        }
        for name, fn in self._stream_handlers().items():
            unary[name] = grpc.unary_stream_rpc_method_handler(
                fn, request_deserializer=_decode, response_serializer=_encode
            )
        self._server = grpc.server(futures.ThreadPoolExecutor(
            max_workers=self.max_watchers + self.UNARY_WORKERS))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, unary),)
        )
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()
        log.info("kvstore gRPC server on %s:%d", self.host, self.port)
        return self.port

    def stop(self, grace: float = 0.2) -> None:
        if self._server is not None:
            # Block until shutdown actually completes: grpc's stop() is
            # async, and returning early leaves the listening socket
            # alive — a server restarted on the same port would then
            # share it via SO_REUSEPORT and old/new listeners would
            # split incoming connections (clients land on the corpse).
            self._server.stop(grace).wait(timeout=grace + 5.0)
            self._server = None

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"


class RemoteWatcher(Watcher):
    """Client side of a Watch stream; same queue interface as Watcher.

    The stream thread reconnects with backoff; every successful
    re-subscription after a drop invokes the owner's reconnect hooks so
    the owner can resync, exactly like the reference after an etcd
    reconnect.  Against an HA ensemble the re-subscription also carries
    the watcher's LAST-SEEN revision: the (new) leader replays the
    committed events after it from its bounded event log, so a leader
    failover loses no events even before the resync lands — and when
    the stream lands on a follower, the NOT_LEADER rejection re-homes
    it exactly like a unary call."""

    def __init__(self, owner: "RemoteKVStore", prefixes: Tuple[str, ...]):
        super().__init__(prefixes)
        self._owner = owner
        self._subscribed = threading.Event()
        self._call = None  # current stream call, for cancel() on close
        self.last_revision = -1  # highest event revision delivered
        self._thread = threading.Thread(
            target=self._stream_loop, name="kv-remote-watch", daemon=True
        )
        self._thread.start()

    def wait_subscribed(self, timeout: float = 5.0) -> bool:
        """Block until the server acknowledged the watch registration.
        Snapshot-after-subscribe callers (dbwatcher) use this to keep the
        no-event-lost-between-snapshot-and-stream guarantee across the
        socket."""
        return self._subscribed.wait(timeout)

    def close(self) -> None:
        self.closed = True
        call = self._call
        if call is not None:
            call.cancel()

    def _stream_loop(self) -> None:
        attempt = 0
        failed_before = False
        while not self.closed:
            address = self._owner.address
            try:
                stream = self._owner._stub_watch(
                    {"prefixes": list(self.prefixes),
                     "since_revision": self.last_revision},
                    address,
                )
                self._call = stream
                for msg in stream:
                    if self.closed:
                        return
                    if msg["key"] == "":
                        # Subscribe-ack: the server-side watcher is live.
                        # Recovering from an outage (including one at
                        # startup) still tells the owner to resync —
                        # replay covers this watcher's queue, the resync
                        # covers snapshot consumers, and events the
                        # bounded log no longer held (msg["resync"])
                        # are covered ONLY by the resync.
                        if msg.get("resync") and self.last_revision >= 0:
                            # The bounded event log no longer reached
                            # our cursor: the resync fired below is
                            # load-bearing, not belt-and-braces — any
                            # queue consumer without a resync hook has
                            # a hole here.  Loud so soak logs show it.
                            log.warning(
                                "watch replay gap at revision %d on %s: "
                                "resync is covering missed events",
                                self.last_revision, address)
                        diverged = self.last_revision > msg["revision"]
                        if diverged:
                            # Our cursor is AHEAD of the server: the
                            # events that advanced it came from a
                            # deposed leader's uncommitted writes,
                            # rolled back by a snapshot install.  The
                            # cursor means nothing on the survivors'
                            # timeline — adopt the server's revision
                            # (future replays anchor there) and resync,
                            # which re-reads the authoritative state.
                            #
                            # A bare revision cannot catch EQUAL-height
                            # divergence (new leader coincidentally at
                            # our inflated revision).  In practice the
                            # winner's election-key commit advances its
                            # revision before any client write can land,
                            # so the residue is a possible stale event
                            # in this queue, not a lost one — and the
                            # resync below heals every hook consumer
                            # (dbwatcher).  A watertight guard needs
                            # per-revision terms on the wire.
                            self.last_revision = msg["revision"]
                        self._subscribed.set()
                        attempt = 0
                        if failed_before or diverged:
                            failed_before = False
                            # The stream just survived an outage — the
                            # ensemble may have CHANGED underneath it
                            # (live membership change, ISSUE 13):
                            # refresh the failover list so the NEXT
                            # drop never strands on a replaced replica.
                            self._owner._refresh_members()
                            self._owner._fire_reconnect()
                        continue
                    self.last_revision = max(self.last_revision, msg["revision"])
                    self.queue.put(
                        WatchEvent(
                            key=msg["key"],
                            value=msg["value"],
                            prev_value=msg["prev_value"],
                            revision=msg["revision"],
                        )
                    )
            except grpc.RpcError as e:
                code = _code_of(e)
                hint = not_leader_hint(e)
                if hint is not None:
                    # Landed on an HA follower: re-home to its leader
                    # (or rotate while the election is still running).
                    self._owner._rehome(address, hint)
                elif code == grpc.StatusCode.RESOURCE_EXHAUSTED:
                    # Server watcher limit hit — fail loudly (ADVICE r2);
                    # the backoff retry may still grab a freed slot.
                    log.error("watch stream rejected: %s", e)
                elif code in OUTAGE_CODES:
                    self._owner._evict_target(address)
                    self._owner._rehome(address, None)
                else:
                    # Not an outage: a server-side handler crash
                    # (UNKNOWN/INTERNAL) would otherwise retry silently
                    # forever while the watch is effectively dead.
                    log.warning("watch stream failed with %s: %s", code, e)
            finally:
                self._call = None
            if self.closed:
                return
            self._subscribed.clear()
            failed_before = True
            attempt += 1
            if attempt % 3 == 0:
                # Persistent re-subscribe failures: the address list
                # itself may be stale (replica replaced at runtime) —
                # ask any answering member for the current ensemble.
                self._owner._refresh_members()
            # Capped exponential + jitter: after a cluster-wide outage
            # every agent's stream died in the same instant; the jitter
            # de-synchronizes the fleet's re-subscribe storms so a
            # recovering (or freshly elected) leader is not hit by all
            # N streams at once (ISSUE 9 satellite).
            time.sleep(reconnect_backoff(
                attempt,
                initial=self._owner.watch_backoff_initial,
                cap=self._owner.watch_backoff_max,
                jitter=self._owner.watch_backoff_jitter,
            ))


def channel_ready(channel: grpc.Channel) -> bool:
    """True when the channel's transport is connected (READY), read
    without triggering a connect attempt.  False on any doubt — the
    probe rides grpc internals, and doubt must let eviction proceed
    (a wrongly-kept dead channel is the hung-connect bug; a wrongly
    evicted one just redials)."""
    try:
        state = channel._channel.check_connectivity_state(False)
        return state == grpc.ChannelConnectivity.READY.value[0]
    except Exception:  # noqa: BLE001 - internal API probe
        return False


class _Target:
    """One server address: its channel and prepared call objects."""

    _METHODS = (
        "Get", "Put", "Delete", "PutIfNotExists", "CompareAndDelete",
        "List", "Snapshot", "Revision",
        # HA replica surface (UNIMPLEMENTED on a standalone server).
        "HaStatus", "LocalDump", "Replicate", "InstallSnapshot",
        # Live membership change (ISSUE 13; leader-gated).
        "AddReplica", "RemoveReplica",
    )

    def __init__(self, address: str):
        self.address = address
        # Cap gRPC's reconnect backoff (default grows 1s -> 120s): a
        # channel that saw one refused connect during an ensemble
        # cold-start or a replica restart would otherwise sit in
        # backoff for tens of seconds while every RPC on it fails
        # instantly — longer than the whole leader-failover window.
        self.channel = grpc.insecure_channel(address, options=[
            ("grpc.initial_reconnect_backoff_ms", 100),
            ("grpc.max_reconnect_backoff_ms", 1000),
        ])
        self.calls = {
            m: self.channel.unary_unary(
                f"/{SERVICE_NAME}/{m}",
                request_serializer=_encode,
                response_deserializer=_decode,
            )
            for m in self._METHODS
        }
        self.watch_call = self.channel.unary_stream(
            f"/{SERVICE_NAME}/Watch",
            request_serializer=_encode,
            response_deserializer=_decode,
        )


class RemoteKVStore:
    """Drop-in KVStore client talking to one KVStoreServer or an HA
    ensemble of them.

    Single address (the historical form): unary calls raise
    ``grpc.RpcError`` while the server is unreachable (callers like the
    dbwatcher fall back to their local mirror, dbwatcher.go:309-333).

    Multiple addresses ("a:1,b:2,c:3" or a list): the client follows
    the ensemble's leader.  A NOT_LEADER rejection re-homes to the
    hinted leader; an outage rotates to the next replica; both retry
    with bounded backoff until ``failover_deadline`` elapses, so a
    leader crash is invisible to callers of the idempotent ops as long
    as a new leader is elected inside the window.  Exhausting the
    window raises :class:`LeaderUnavailable` (a ConnectionError —
    classified as an outage by the dbwatcher, never as a server bug).

    A leader's ``NO_QUORUM`` rejection (ABORTED) is indeterminate — the
    write is applied on the leader and usually still commits — so it is
    auto-retried only for idempotent ops; ``put_if_not_exists`` /
    ``compare_and_delete`` surface it to the caller, whose retry could
    otherwise mis-read its own write as someone else's.
    """

    def __init__(self, address, timeout: float = 5.0,
                 failover_deadline: float = 8.0,
                 watch_backoff_initial: float = WATCH_BACKOFF_INITIAL,
                 watch_backoff_max: float = WATCH_BACKOFF_MAX,
                 watch_backoff_jitter: float = WATCH_BACKOFF_JITTER):
        if isinstance(address, str):
            addresses = [a.strip() for a in address.split(",") if a.strip()]
        else:
            addresses = [str(a) for a in address]
        if not addresses:
            raise ValueError("at least one store address required")
        self._addresses = addresses
        # Fixed at construction: a single-address client NEVER grows
        # into failover mode (a stray NOT_LEADER hint must not quietly
        # replace its documented fail-fast semantics).
        self._failover = len(addresses) > 1
        self.timeout = timeout
        self.failover_deadline = failover_deadline
        # Watch re-establishment schedule (see reconnect_backoff).
        self.watch_backoff_initial = watch_backoff_initial
        self.watch_backoff_max = watch_backoff_max
        self.watch_backoff_jitter = watch_backoff_jitter
        self._target_lock = threading.Lock()
        self._targets: Dict[str, _Target] = {}
        self._active = addresses[0]
        self._watchers: List[RemoteWatcher] = []
        self._reconnect_cbs: List[Callable[[], None]] = []

    @property
    def address(self) -> str:
        """The address currently served (the leader, once discovered)."""
        return self._active

    @property
    def addresses(self) -> List[str]:
        return list(self._addresses)

    def _target(self, address: Optional[str] = None) -> _Target:
        address = address or self._active
        with self._target_lock:
            target = self._targets.get(address)
            if target is None:
                target = self._targets[address] = _Target(address)
            return target

    def _rehome(self, failed: str, hint: Optional[str]) -> str:
        """Pick the next address after ``failed`` misbehaved: the
        NOT_LEADER hint wins; otherwise rotate through the ensemble.
        Serialized so concurrent failures converge on one choice.
        No-op for a single-address client — it stays pointed at its
        configured server, fail-fast, forever."""
        if not self._failover:
            return self._active
        with self._target_lock:
            if hint:
                if hint not in self._addresses:
                    self._addresses.append(hint)
                self._active = hint
            elif self._active == failed and len(self._addresses) > 1:
                idx = self._addresses.index(failed) if failed in self._addresses else -1
                self._active = self._addresses[(idx + 1) % len(self._addresses)]
            return self._active

    def _evict_target(self, address: str) -> None:
        """Drop the cached channel of an address that failed with a
        TRANSPORT outage, so the next attempt dials a fresh one.  A
        connect attempt started while the server port was not yet bound
        (ensemble cold-start, replica restart) can hang in some network
        stacks past any reconnect backoff, and every later RPC on the
        channel rides the same doomed attempt — a fresh channel
        connects immediately once the server is up.

        A deadline/cancel on a READY channel is exempt: the transport
        is healthy (the server is just slow), and closing the channel
        would also cancel a live Watch stream riding it — one slow
        Snapshot would then cost a full dbwatcher resync."""
        with self._target_lock:
            target = self._targets.get(address)
            if target is not None and channel_ready(target.channel):
                return
            self._targets.pop(address, None)
        if target is not None:
            try:
                target.channel.close()
            except Exception:  # noqa: BLE001 - eviction is best-effort
                pass

    def _call_once(self, address: str, method: str, request: dict,
                   timeout: Optional[float] = None) -> dict:
        """One attempt on the (cached) channel.  A concurrent outage
        eviction — the watch thread runs _evict_target too — can CLOSE
        the channel between the cache read and the invoke; grpc then
        raises ValueError, not RpcError.  A closed channel provably
        never sent the request, so ONE redial-and-retry is safe for any
        op, idempotent or not (found as a pre-existing `make test-race`
        flake while hardening the race battery in ISSUE 7)."""
        target = self._target(address)
        request = compat.stamp(dict(request))  # version stamp (ISSUE 13)
        timeout = timeout or self.timeout
        try:
            return target.calls[method](request, timeout=timeout)
        except ValueError as e:
            if "closed channel" not in str(e):
                raise
            # Drop the stale entry ourselves — the racing eviction may
            # have closed the channel before (or without) popping it.
            with self._target_lock:
                if self._targets.get(address) is target:
                    self._targets.pop(address, None)
            return self._target(address).calls[method](
                request, timeout=timeout)

    def _rpc(self, method: str, request: dict,
             timeout: Optional[float] = None) -> dict:
        if not self._failover:
            # Historical single-server semantics: one attempt, errors
            # surface immediately (the dbwatcher's mirror fallback and
            # the chaos tests depend on fail-fast here) — but an outage
            # still evicts the channel so the NEXT attempt redials.
            address = self._active
            try:
                return self._call_once(address, method, request, timeout)
            except grpc.RpcError as e:
                incompat = incompatible_version(e)
                if incompat is not None:
                    raise IncompatibleVersion(*incompat) from e
                if _code_of(e) in OUTAGE_CODES:
                    self._evict_target(address)
                raise
        deadline = time.monotonic() + self.failover_deadline
        backoff = 0.05
        last: Optional[Exception] = None
        attempts = 0
        while True:
            address = self._active
            try:
                return self._call_once(address, method, request, timeout)
            except grpc.RpcError as e:
                incompat = incompatible_version(e)
                if incompat is not None:
                    # A below-floor refusal is DETERMINISTIC — every
                    # replica applies the same floor; failover/retry
                    # would just re-refuse.  Surface it cleanly.
                    raise IncompatibleVersion(*incompat) from e
                attempts += 1
                hint = not_leader_hint(e)
                code = _code_of(e)
                outage = hint is None and code in OUTAGE_CODES
                if no_quorum(e):
                    # Indeterminate: the leader applied the op but could
                    # not prove a majority holds it (it usually still
                    # commits on a later tick).  Retrying is only safe
                    # for ops whose re-run observes the same outcome.
                    if method not in IDEMPOTENT_METHODS:
                        raise
                    # Stay homed: the rejecting replica IS the leader —
                    # rotating away would bounce off a follower's
                    # NOT_LEADER right back here, two wasted RPCs per
                    # retry during exactly the degraded window.
                    last = e
                elif (outage and code is not grpc.StatusCode.UNAVAILABLE
                        and method not in IDEMPOTENT_METHODS):
                    # DEADLINE_EXCEEDED / CANCELLED are just as
                    # indeterminate as NO_QUORUM: the request may have
                    # reached the leader and applied, and a blind re-run
                    # of a conditional op would mis-read its own write
                    # (created=False).  Only UNAVAILABLE — a connect-
                    # level failure, the request (almost certainly)
                    # never processed — stays retryable for them.
                    self._evict_target(address)
                    raise
                elif hint is None and not outage:
                    raise  # a real server bug — never masked by failover
                else:
                    last = e
                    if outage:
                        self._evict_target(address)
                    self._rehome(address, hint)
                    if outage and attempts % 3 == 0:
                        # Repeated outages can mean the configured list
                        # is STALE (a replica was replaced at runtime —
                        # ISSUE 13 membership change): ask any member
                        # that still answers for the current ensemble.
                        self._refresh_members()
            if time.monotonic() >= deadline:
                raise LeaderUnavailable(
                    f"no serving leader among {self._addresses} within "
                    f"{self.failover_deadline:.1f}s"
                ) from last
            time.sleep(backoff)
            backoff = min(backoff * 2, 0.5)

    def _stub_watch(self, request: dict, address: Optional[str] = None):
        return self._target(address).watch_call(compat.stamp(dict(request)))

    # --------------------------------------------------------- HA helpers

    def _probe_rpc(self, address: Optional[str], method: str,
                   request: dict, timeout: Optional[float] = None) -> dict:
        """A per-replica diagnostic RPC (HaStatus/LocalDump) with the
        same outage-eviction discipline as _rpc: these bypass failover
        on purpose (the caller targets ONE replica), but a channel
        dialed before that replica's port was bound hangs past any
        reconnect backoff (the PR 1 pathology) — without eviction every
        later probe of a healthy replica rides the doomed channel and
        reports UNAVAILABLE forever (found by the ISSUE 9 soak's
        leader-election wait)."""
        address = address or self._active
        try:
            return self._target(address).calls[method](
                compat.stamp(dict(request)), timeout=timeout or self.timeout)
        except grpc.RpcError as e:
            if _code_of(e) in OUTAGE_CODES:
                self._evict_target(address)
            raise

    def _refresh_members(self) -> bool:
        """Re-learn the ensemble member list from whichever replica
        still answers (ISSUE 13 satellite): the ctor address list is a
        BOOTSTRAP hint, not the membership source of truth — a replica
        replaced at runtime (live add/remove) would otherwise strand
        every long-lived watcher and failover loop on a dead address
        forever.  Replaces the address list wholesale (added members
        learned, removed ones pruned); never leaves it empty; no-op
        for single-address clients (their fail-fast semantics stand)."""
        if not self._failover:
            return False
        probe_timeout = min(self.timeout, 1.0)
        for addr in list(self._addresses):
            try:
                st = self._probe_rpc(addr, "HaStatus", {},
                                     timeout=probe_timeout)
            except Exception:  # noqa: BLE001 - dead/electing replica
                continue
            peers = [str(p) for p in (st.get("peers") or [])]
            if not peers:
                continue
            with self._target_lock:
                self._addresses = peers
                if self._active not in peers:
                    leader = st.get("leader") or ""
                    self._active = leader if leader in peers else peers[0]
            log.info("refreshed ensemble members from %s: %s", addr, peers)
            return True
        return False

    def members(self) -> List[str]:
        """The CURRENT ensemble member list as reported by a live
        replica (refreshing this client's failover list as a side
        effect); falls back to the locally-known addresses when no
        replica answers."""
        self._refresh_members()
        return self.addresses

    def add_replica(self, addr: str, timeout: float = 60.0) -> dict:
        """Grow the ensemble by one replica (leader-gated; the server
        snapshot-catches the learner up BEFORE it counts toward quorum
        — the call blocks for the catch-up, hence the long timeout).
        The server-side catch-up bound rides the request, slightly
        inside the RPC deadline so a timeout surfaces as the typed
        CATCHUP_TIMEOUT, not a raw DEADLINE_EXCEEDED."""
        result = self._rpc("AddReplica",
                           {"addr": addr, "timeout": 0.9 * timeout},
                           timeout=timeout)
        self._refresh_members()
        return result

    def remove_replica(self, addr: str, timeout: float = 60.0) -> dict:
        """Shrink the ensemble by one replica (leader-gated; removing
        the sitting leader performs an orderly handoff first)."""
        result = self._rpc("RemoveReplica",
                           {"addr": addr, "timeout": 0.9 * timeout},
                           timeout=timeout)
        self._refresh_members()
        return result

    def ha_status(self, address: Optional[str] = None) -> dict:
        """The HA election status of one replica (UNIMPLEMENTED on a
        standalone server)."""
        return self._probe_rpc(address, "HaStatus", {})

    def local_dump(self, prefix: str = "",
                   address: Optional[str] = None) -> dict:
        """A replica's LOCAL store view (served by followers too —
        possibly stale; the replication-lag observability surface)."""
        return self._probe_rpc(address, "LocalDump", {"prefix": prefix})

    # ------------------------------------------------------------ interface

    def get(self, key: str) -> Optional[Any]:
        return self._rpc("Get", {"key": key})["value"]

    def put(self, key: str, value: Any) -> int:
        if value is None:
            raise ValueError("use delete() to remove a key")
        return self._rpc("Put", {"key": key, "value": value})["revision"]

    def delete(self, key: str) -> bool:
        return self._rpc("Delete", {"key": key})["deleted"]

    def put_if_not_exists(self, key: str, value: Any) -> bool:
        return self._rpc("PutIfNotExists", {"key": key, "value": value})["created"]

    def compare_and_delete(self, key: str, expected: Any) -> bool:
        return self._rpc("CompareAndDelete", {"key": key, "expected": expected})["deleted"]

    def list(self, prefix: str = "") -> List[Tuple[str, Any]]:
        return [tuple(item) for item in self._rpc("List", {"prefix": prefix})["items"]]

    def snapshot(self, prefixes: Iterable[str]) -> Dict[str, Any]:
        return self.snapshot_with_revision(prefixes)[0]

    def snapshot_with_revision(
        self, prefixes: Iterable[str]
    ) -> Tuple[Dict[str, Any], int]:
        resp = self._rpc("Snapshot", {"prefixes": list(prefixes)})
        return resp["snapshot"], resp["revision"]

    @property
    def revision(self) -> int:
        return self._rpc("Revision", {})["revision"]

    # -------------------------------------------------------------- watches

    def watch(self, prefixes: Iterable[str]) -> RemoteWatcher:
        watcher = RemoteWatcher(self, tuple(prefixes))
        self._watchers.append(watcher)
        return watcher

    def unwatch(self, watcher: Watcher) -> None:
        if isinstance(watcher, RemoteWatcher):
            watcher.close()  # cancels the stream; server unregisters
        else:
            watcher.closed = True
        if watcher in self._watchers:
            self._watchers.remove(watcher)

    def on_reconnect(self, callback: Callable[[], None]) -> None:
        """Register a hook fired after a watch stream re-subscribes
        following an outage (the dbwatcher resyncs here)."""
        self._reconnect_cbs.append(callback)

    def _fire_reconnect(self) -> None:
        for cb in list(self._reconnect_cbs):
            try:
                cb()
            except Exception:  # noqa: BLE001
                log.exception("reconnect callback failed")

    def close(self) -> None:
        for w in list(self._watchers):
            self.unwatch(w)
        with self._target_lock:
            for target in self._targets.values():
                target.channel.close()
            self._targets.clear()
