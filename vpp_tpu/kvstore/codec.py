"""Typed JSON codec for cluster-store values.

The networked store (remote.py) and the sqlite mirror need to carry the
framework's model dataclasses over the wire — the role protobuf plays
for the reference's etcd values (plugins/ksr/model/*).  This codec
round-trips them through tagged JSON with full fidelity (tuples stay
tuples, enums stay enums, frozen dataclasses compare equal after a
round trip — dbwatcher's prev/new comparisons depend on it).

Decoding resolves classes by qualified name but ONLY from ``vpp_tpu.*``
modules: unlike pickle, a malicious store payload cannot name arbitrary
constructors.

Version-skew tolerance (ISSUE 13): during a rolling upgrade a reader
can receive a dataclass payload written by an ADJACENT version.

- Fields the reader does not know (a newer writer) are PRESERVED raw
  on the decoded object (``_codec_unknown``) and re-emitted on encode,
  so a decode→encode round trip through this process — e.g. the sqlite
  mirror replaying a record, or a value read-modified-written — is
  byte-identical: an old reader never strips a new writer's data.
  Unknown fields are deliberately kept in their raw jsonable form (not
  recursively decoded): their tags may name types this build does not
  have.
- Fields the writer did not send (an older writer) fall back to the
  dataclass defaults; a missing field WITHOUT a default is a refused
  decode (``ValueError`` naming the field and the skew suspicion) —
  never a half-constructed object.
"""

from __future__ import annotations

import dataclasses
import enum
import importlib
import ipaddress
import json
from typing import Any

_TAG_DC = "__dc__"
_TAG_ENUM = "__enum__"
_TAG_TUPLE = "__tuple__"
_TAG_SET = "__set__"
_TAG_FROZENSET = "__frozenset__"
_TAG_IP = "__ip__"
_TAG_MAP = "__map__"  # escape hatch for plain dicts using a reserved key

_RESERVED_KEYS = {
    _TAG_DC, _TAG_ENUM, _TAG_TUPLE, _TAG_SET, _TAG_FROZENSET, _TAG_IP, _TAG_MAP,
}

_ALLOWED_MODULE_PREFIX = "vpp_tpu."

_IP_TYPES = {
    "IPv4Address": ipaddress.IPv4Address,
    "IPv6Address": ipaddress.IPv6Address,
    "IPv4Network": ipaddress.IPv4Network,
    "IPv6Network": ipaddress.IPv6Network,
    "IPv4Interface": ipaddress.IPv4Interface,
    "IPv6Interface": ipaddress.IPv6Interface,
}


def _qualname(tp: type) -> str:
    return f"{tp.__module__}:{tp.__qualname__}"


def _resolve(qual: str) -> type:
    module_name, _, cls_path = qual.partition(":")
    if not (module_name.startswith(_ALLOWED_MODULE_PREFIX) or module_name == "vpp_tpu"):
        raise ValueError(f"refusing to resolve type outside vpp_tpu: {qual!r}")
    obj: Any = importlib.import_module(module_name)
    for part in cls_path.split("."):
        obj = getattr(obj, part)
    if not isinstance(obj, type):
        raise ValueError(f"{qual!r} does not name a class")
    return obj


def to_jsonable(value: Any) -> Any:
    """Encode ``value`` into JSON-serialisable tagged structures."""
    if value is None or isinstance(value, (bool, int, float, str)):
        return value
    if isinstance(value, enum.Enum):
        return {_TAG_ENUM: _qualname(type(value)), "name": value.name}
    if dataclasses.is_dataclass(value) and not isinstance(value, type):
        fields = {
            f.name: to_jsonable(getattr(value, f.name))
            for f in dataclasses.fields(value)
        }
        # Re-emit fields a newer writer sent that this build's class
        # does not declare (stashed raw by from_jsonable) — the
        # unknown-field round-trip half of the skew contract.
        unknown = getattr(value, "_codec_unknown", None)
        if unknown:
            fields.update(unknown)
        return {_TAG_DC: _qualname(type(value)), "fields": fields}
    if isinstance(value, tuple):
        return {_TAG_TUPLE: [to_jsonable(v) for v in value]}
    if isinstance(value, frozenset):
        return {_TAG_FROZENSET: sorted((to_jsonable(v) for v in value), key=repr)}
    if isinstance(value, set):
        return {_TAG_SET: sorted((to_jsonable(v) for v in value), key=repr)}
    if isinstance(value, list):
        return [to_jsonable(v) for v in value]
    if isinstance(value, dict):
        for k in value:
            if not isinstance(k, str):
                raise TypeError(f"non-string dict key not supported: {k!r}")
        if any(k in _RESERVED_KEYS for k in value):
            # A user dict colliding with a tag key: encode as a pair list.
            return {_TAG_MAP: [[k, to_jsonable(v)] for k, v in value.items()]}
        return {k: to_jsonable(v) for k, v in value.items()}
    for name, tp in _IP_TYPES.items():
        if type(value) is tp:
            return {_TAG_IP: name, "value": str(value)}
    raise TypeError(f"cannot encode {type(value).__name__}: {value!r}")


def from_jsonable(data: Any) -> Any:
    """Decode the output of :func:`to_jsonable`."""
    if data is None or isinstance(data, (bool, int, float, str)):
        return data
    if isinstance(data, list):
        return [from_jsonable(v) for v in data]
    if isinstance(data, dict):
        if _TAG_DC in data:
            cls = _resolve(data[_TAG_DC])
            if not dataclasses.is_dataclass(cls):
                raise ValueError(f"{data[_TAG_DC]!r} is not a dataclass")
            known = {f.name for f in dataclasses.fields(cls)}
            kwargs = {k: from_jsonable(v) for k, v in data["fields"].items()
                      if k in known}
            # Unknown fields stay RAW (their tags may name types this
            # version lacks) and ride the instance for re-encode.
            unknown = {k: v for k, v in data["fields"].items()
                       if k not in known}
            try:
                obj = cls(**kwargs)
            except TypeError as err:
                # An older writer omitted a field this version requires
                # without a default: refuse cleanly rather than invent
                # a value (the skew floor, not a corrupt decode).
                raise ValueError(
                    f"cannot decode {data[_TAG_DC]!r}: {err} — likely a "
                    "version-skewed writer omitting a newly-required "
                    "field (new fields need defaults)") from err
            if unknown:
                # object.__setattr__: the model dataclasses are frozen.
                object.__setattr__(obj, "_codec_unknown", unknown)
            return obj
        if _TAG_ENUM in data:
            cls = _resolve(data[_TAG_ENUM])
            if not issubclass(cls, enum.Enum):
                raise ValueError(f"{data[_TAG_ENUM]!r} is not an Enum")
            return cls[data["name"]]
        if _TAG_TUPLE in data:
            return tuple(from_jsonable(v) for v in data[_TAG_TUPLE])
        if _TAG_SET in data:
            return {from_jsonable(v) for v in data[_TAG_SET]}
        if _TAG_FROZENSET in data:
            return frozenset(from_jsonable(v) for v in data[_TAG_FROZENSET])
        if _TAG_IP in data:
            return _IP_TYPES[data[_TAG_IP]](data["value"])
        if _TAG_MAP in data:
            return {k: from_jsonable(v) for k, v in data[_TAG_MAP]}
        return {k: from_jsonable(v) for k, v in data.items()}
    raise TypeError(f"cannot decode {data!r}")


def encode(value: Any) -> bytes:
    return json.dumps(to_jsonable(value), sort_keys=True).encode()


def decode(data: bytes) -> Any:
    return from_jsonable(json.loads(data.decode()))
