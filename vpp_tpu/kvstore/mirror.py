"""Local sqlite mirror of the cluster store — the Bolt analog.

The reference mirrors every watched etcd key into a per-node Bolt DB so
an agent can resync from local state while etcd is unreachable
(plugins/controller/dbwatcher.go:111-137, runResyncFromLocalDB :309).
This is that component: the dbwatcher saves each remote snapshot here,
applies every streamed change, and falls back to :meth:`load` when the
remote store cannot be reached.

Corruption discipline (ISSUE 9 satellite): the mirror is a CACHE, never
the source of truth — a truncated file (agent SIGKILLed mid-write, disk
full), a garbage file, or an undecodable row must degrade to "no mirror"
(the dbwatcher then performs a full remote resync, whose save_snapshot
re-populates a fresh file) and must NEVER crash the agent.  Every sqlite
touch point therefore classifies ``sqlite3.Error`` as corruption,
quarantines the bad file by re-creating it in place, and reports the
operation as a miss.
"""

from __future__ import annotations

import logging
import os
import sqlite3
import threading
from typing import Any, Dict, Optional, Tuple

from . import codec, compat
from .store import WatchEvent

log = logging.getLogger(__name__)


class LocalMirror:
    """A revisioned key/value mirror in one sqlite file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self.recreated = 0  # corruption observability (soak evidence)
        with self._lock:
            self._conn = self._open_or_recreate()

    def _open_or_recreate(self) -> sqlite3.Connection:
        """Open the mirror file, re-creating it from scratch when the
        existing file is not a usable sqlite database.  Callers hold
        ``_lock``."""
        try:
            return self._open(self.path)
        except sqlite3.Error as err:
            log.warning(
                "mirror %s is corrupt (%s): discarding and re-creating "
                "(next resync repopulates it from the remote store)",
                self.path, err,
            )
            self.recreated += 1
            try:
                os.remove(self.path)
            except OSError:
                pass
            try:
                return self._open(self.path)
            except sqlite3.Error as err2:
                # Unremovable corrupt file (read-only/failing disk):
                # degrade to an in-memory cache — same discipline as
                # _reset_locked; a mirror must never fail agent boot.
                log.error(
                    "mirror %s cannot be re-created (%s): degrading to "
                    "an in-memory mirror", self.path, err2,
                )
                return self._open(":memory:")

    @staticmethod
    def _open(path: str) -> sqlite3.Connection:
        conn = sqlite3.connect(path, check_same_thread=False)
        try:
            conn.execute(
                "CREATE TABLE IF NOT EXISTS mirror (key TEXT PRIMARY KEY, value BLOB)"
            )
            conn.execute(
                "CREATE TABLE IF NOT EXISTS meta (name TEXT PRIMARY KEY, value INTEGER)"
            )
            conn.commit()
        except sqlite3.Error:
            conn.close()
            raise
        return conn

    def _reset_locked(self, cause: Exception) -> None:
        """Quarantine a mirror that failed mid-operation: close, delete,
        re-create empty.  Callers hold ``_lock``.  Must NEVER raise —
        it runs inside the corruption handlers; if even the re-create
        fails (unremovable corrupt file on a read-only disk), the
        mirror degrades to an in-memory cache for the process lifetime
        rather than crashing the agent."""
        log.warning(
            "mirror %s failed (%s): discarding and re-creating",
            self.path, cause,
        )
        self.recreated += 1
        try:
            self._conn.close()
        except sqlite3.Error:
            pass
        try:
            os.remove(self.path)
        except OSError:
            pass
        try:
            self._conn = self._open(self.path)
        except sqlite3.Error as err:
            log.error(
                "mirror %s cannot be re-created (%s): degrading to an "
                "in-memory mirror (no outage fallback across restarts)",
                self.path, err,
            )
            self._conn = self._open(":memory:")

    def save_snapshot(self, snap: Dict[str, Any], revision: int) -> None:
        """Replace the mirror contents with one consistent snapshot."""
        rows = [(k, codec.encode(v)) for k, v in snap.items()]
        with self._lock:
            try:
                self._write_snapshot(rows, revision)
            except sqlite3.Error as err:
                # Corrupt mirror: rebuild the file, then retry ONCE on
                # the fresh database; a second failure (disk full, dead
                # filesystem) is logged and swallowed — losing the cache
                # must not fail the resync that produced the snapshot.
                self._reset_locked(err)
                try:
                    self._write_snapshot(rows, revision)
                except sqlite3.Error as err2:
                    log.error("mirror %s unwritable: %s", self.path, err2)

    def _write_snapshot(self, rows, revision: int) -> None:
        self._conn.execute("DELETE FROM mirror")
        self._conn.executemany(
            "INSERT INTO mirror (key, value) VALUES (?, ?)", rows
        )
        self._conn.execute(
            "INSERT OR REPLACE INTO meta (name, value) VALUES ('revision', ?)",
            (revision,),
        )
        # Schema lineage stamp (ISSUE 13): load() refuses files outside
        # the supported window instead of mis-decoding them.
        self._conn.execute(
            "INSERT OR REPLACE INTO meta (name, value) VALUES ('format', ?)",
            (compat.mirror_format_version(),),
        )
        self._conn.commit()

    def apply_event(self, ev: WatchEvent) -> None:
        """Mirror one streamed change.

        A failed write leaves the mirror MISSING this event; advancing
        the recorded revision anyway would claim a completeness the file
        no longer has, so on failure the whole file is quarantined — the
        next remote snapshot rebuilds it consistently."""
        with self._lock:
            try:
                if ev.is_delete:
                    self._conn.execute("DELETE FROM mirror WHERE key = ?", (ev.key,))
                else:
                    self._conn.execute(
                        "INSERT OR REPLACE INTO mirror (key, value) VALUES (?, ?)",
                        (ev.key, codec.encode(ev.value)),
                    )
                self._conn.execute(
                    "INSERT OR REPLACE INTO meta (name, value) VALUES ('revision', ?)",
                    (ev.revision,),
                )
                self._conn.commit()
            except sqlite3.Error as err:
                self._reset_locked(err)

    def load(self) -> Optional[Tuple[Dict[str, Any], int]]:
        """The mirrored (snapshot, revision), or None if never populated
        — or if the file/contents are corrupt (the caller then treats
        the agent as mirror-less and resyncs from the remote store)."""
        with self._lock:
            try:
                fmt = self._conn.execute(
                    "SELECT value FROM meta WHERE name = 'format'"
                ).fetchone()
                # Missing stamp = legacy format 1 (pre-ISSUE-13 files).
                fmt_version = int(fmt[0]) if fmt is not None else 1
                rev = self._conn.execute(
                    "SELECT value FROM meta WHERE name = 'revision'"
                ).fetchone()
                if rev is None:
                    return None
                rows = self._conn.execute(
                    "SELECT key, value FROM mirror").fetchall()
                revision = int(rev[0])
            except (sqlite3.Error, TypeError, ValueError) as err:
                self._reset_locked(err)
                return None
        if not (compat.MIN_MIRROR_FORMAT <= fmt_version
                <= compat.MIRROR_FORMAT_VERSION):
            # Outside the supported window (a downgrade reading a newer
            # file, or a long-dead lineage): REFUSE cleanly — report
            # "no mirror" so the caller resyncs from the remote store.
            # The file itself is left alone; the next save_snapshot
            # rewrites it wholesale in this build's format.
            log.warning(
                "mirror %s format v%d outside supported window v%d..v%d: "
                "ignoring mirror (next resync rewrites it)",
                self.path, fmt_version,
                compat.MIN_MIRROR_FORMAT, compat.MIRROR_FORMAT_VERSION,
            )
            return None
        try:
            return {k: codec.decode(v) for k, v in rows}, revision
        except Exception as err:  # noqa: BLE001 - any decode failure = corrupt
            # Undecodable VALUE (truncated blob, stale codec): the rows
            # cannot be trusted as one consistent snapshot.
            with self._lock:
                self._reset_locked(err)
            return None

    def close(self) -> None:
        with self._lock:
            self._conn.close()
