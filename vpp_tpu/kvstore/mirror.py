"""Local sqlite mirror of the cluster store — the Bolt analog.

The reference mirrors every watched etcd key into a per-node Bolt DB so
an agent can resync from local state while etcd is unreachable
(plugins/controller/dbwatcher.go:111-137, runResyncFromLocalDB :309).
This is that component: the dbwatcher saves each remote snapshot here,
applies every streamed change, and falls back to :meth:`load` when the
remote store cannot be reached.
"""

from __future__ import annotations

import logging
import sqlite3
import threading
from typing import Any, Dict, Optional, Tuple

from . import codec
from .store import WatchEvent

log = logging.getLogger(__name__)


class LocalMirror:
    """A revisioned key/value mirror in one sqlite file."""

    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS mirror (key TEXT PRIMARY KEY, value BLOB)"
            )
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS meta (name TEXT PRIMARY KEY, value INTEGER)"
            )
            self._conn.commit()

    def save_snapshot(self, snap: Dict[str, Any], revision: int) -> None:
        """Replace the mirror contents with one consistent snapshot."""
        rows = [(k, codec.encode(v)) for k, v in snap.items()]
        with self._lock:
            self._conn.execute("DELETE FROM mirror")
            self._conn.executemany(
                "INSERT INTO mirror (key, value) VALUES (?, ?)", rows
            )
            self._conn.execute(
                "INSERT OR REPLACE INTO meta (name, value) VALUES ('revision', ?)",
                (revision,),
            )
            self._conn.commit()

    def apply_event(self, ev: WatchEvent) -> None:
        """Mirror one streamed change."""
        with self._lock:
            if ev.is_delete:
                self._conn.execute("DELETE FROM mirror WHERE key = ?", (ev.key,))
            else:
                self._conn.execute(
                    "INSERT OR REPLACE INTO mirror (key, value) VALUES (?, ?)",
                    (ev.key, codec.encode(ev.value)),
                )
            self._conn.execute(
                "INSERT OR REPLACE INTO meta (name, value) VALUES ('revision', ?)",
                (ev.revision,),
            )
            self._conn.commit()

    def load(self) -> Optional[Tuple[Dict[str, Any], int]]:
        """The mirrored (snapshot, revision), or None if never populated."""
        with self._lock:
            rev = self._conn.execute(
                "SELECT value FROM meta WHERE name = 'revision'"
            ).fetchone()
            if rev is None:
                return None
            rows = self._conn.execute("SELECT key, value FROM mirror").fetchall()
        return {k: codec.decode(v) for k, v in rows}, int(rev[0])

    def close(self) -> None:
        with self._lock:
            self._conn.close()
