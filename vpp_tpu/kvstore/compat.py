"""Protocol/schema versioning for rolling-upgrade skew (ISSUE 13).

A real fleet is never upgraded atomically: during a rolling agent (or
store-replica) upgrade, *adjacent versions coexist* — an old agent
heartbeats into a new store, a new leader replicates to an old
follower, a new agent reads a mirror file an old build wrote.  The
reference rides this out because etcd values are protobuf (unknown
fields round-trip) and the KSR/Bolt records carry schema lineage; this
module is that discipline for the framework's own wire and persistence
formats:

- ``PROTOCOL_VERSION`` is stamped (``pv``) on every heartbeat record,
  every store RPC request (client ops and the replica-to-replica
  Replicate/InstallSnapshot/HaStatus protocol), and — as
  ``MIRROR_FORMAT_VERSION`` — on every persisted sqlite mirror file.
- Decode is SKEW-TOLERANT inside the supported window: a reader never
  drops fields it does not understand (the codec preserves unknown
  dataclass fields and re-emits them byte-identically — see
  :mod:`.codec`), and never invents values for fields an older writer
  did not send (new fields need defaults; a missing required field is
  a refused decode, not a corrupt object).
- Below ``MIN_PROTOCOL_VERSION`` the peer is REFUSED cleanly — an
  explicit :class:`IncompatibleVersion` / ``INCOMPATIBLE_VERSION``
  gRPC rejection that names both versions — never a silent best-effort
  decode that corrupts state.
- ``VPP_TPU_COMPAT_SKEW`` (an integer offset, e.g. ``-1``) makes this
  process stamp itself as an emulated previous (or next) version, so
  tests and the soak's rolling-upgrade drill can run a
  "previous-version" peer against a current one without maintaining
  two checkouts.  A positive skew additionally writes an
  ``x_compat_probe`` field no current reader knows — the
  unknown-field-preservation property is then exercised end to end.

Version lineage (bump PROTOCOL_VERSION when the wire schema grows a
field peers must *tolerate*; bump MIN_PROTOCOL_VERSION only when a
version can no longer be decoded safely):

- 1: pre-HA single-server wire (PR 0); no version stamp.
- 2: HA replica protocol (PR 1) — Replicate/InstallSnapshot/HaStatus.
- 3: operational-resilience wire (ISSUE 13) — membership RPCs,
  drained heartbeat states, snapshot-carried peer lists.
"""

from __future__ import annotations

import os

PROTOCOL_VERSION = 3
MIN_PROTOCOL_VERSION = 2

# The sqlite mirror's on-disk lineage (1 = un-versioned legacy files,
# still readable; 2 = version-stamped).  A file outside the supported
# window reads as "no mirror" (full remote resync), never as a crash
# and never as a silently mis-decoded cache.
MIRROR_FORMAT_VERSION = 2
MIN_MIRROR_FORMAT = 1

SKEW_ENV = "VPP_TPU_COMPAT_SKEW"

# gRPC rejection details prefix for a below-floor peer (FAILED_
# PRECONDITION, like NOT_LEADER — the client classifies on the prefix).
INCOMPATIBLE_PREFIX = "INCOMPATIBLE_VERSION "


class IncompatibleVersion(Exception):
    """The peer's stamped protocol version is below the supported
    floor: the op was refused BEFORE any state changed."""

    def __init__(self, got: int, floor: int = MIN_PROTOCOL_VERSION,
                 context: str = ""):
        super().__init__(
            f"protocol version {got} below supported floor {floor}"
            + (f" ({context})" if context else ""))
        self.got = got
        self.floor = floor


def skew() -> int:
    """The emulated version offset (0 = current build).  Read per call:
    tests flip it with monkeypatch.setenv, subprocess drills inherit it
    through the environment."""
    raw = os.environ.get(SKEW_ENV, "")
    try:
        return int(raw) if raw else 0
    except ValueError:
        return 0


def effective_version() -> int:
    """The protocol version this process stamps on what it writes —
    PROTOCOL_VERSION shifted by the emulation knob, floored at 1 (there
    is no version 0 wire to emulate)."""
    return max(1, PROTOCOL_VERSION + skew())


def mirror_format_version() -> int:
    """The format version stamped into sqlite mirror files (skewed
    alongside the wire version so an emulated-old agent also writes an
    old-format mirror)."""
    return max(1, MIRROR_FORMAT_VERSION + skew())


def stamp(msg: dict) -> dict:
    """Stamp ``pv`` onto a wire message (mutates and returns it).
    Under a positive (future-version) skew, also plants a field no
    current reader knows — the probe that proves readers preserve,
    never drop, unknown fields."""
    msg["pv"] = effective_version()
    if skew() > 0:
        msg["x_compat_probe"] = {"emulated_pv": msg["pv"]}
    return msg


def check(msg: dict, context: str = "") -> int:
    """Validate a received message's version stamp; returns the peer's
    version (0 = unstamped legacy/in-process, accepted).  Raises
    :class:`IncompatibleVersion` below the floor — the refuse-cleanly
    contract: the caller must reject the op, not decode around it."""
    got = msg.get("pv")
    if got is None:
        return 0
    got = int(got)
    if got < MIN_PROTOCOL_VERSION:
        raise IncompatibleVersion(got, MIN_PROTOCOL_VERSION, context)
    return got


def incompatible_details(err: IncompatibleVersion) -> str:
    """The gRPC abort details for a refused peer."""
    return f"{INCOMPATIBLE_PREFIX}got={err.got} min={err.floor}"


def parse_incompatible(details: str):
    """``(got, floor)`` from a rejection's details, or None."""
    if not details.startswith(INCOMPATIBLE_PREFIX):
        return None
    out = {}
    for part in details[len(INCOMPATIBLE_PREFIX):].split():
        k, _, v = part.partition("=")
        try:
            out[k] = int(v)
        except ValueError:
            continue
    if "got" not in out or "min" not in out:
        return None
    return out["got"], out["min"]
