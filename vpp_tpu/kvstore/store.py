"""In-memory etcd-like KV store with watch + snapshot.

Plays the role of the reference's cluster state store (etcd accessed
through cn-infra kvdbsync; SURVEY.md §1 L6).  The interface is
deliberately etcd-shaped so a real etcd client can be slotted in behind
the same API for production deployments:

- revisioned ``put`` / ``delete`` / ``get``
- prefix ``list`` (consistent snapshot under one lock)
- ``put_if_not_exists`` — the atomic primitive nodesync uses for
  cluster-wide node-ID allocation (reference:
  plugins/nodesync/nodesync.go putIfNotExists :392)
- prefix watchers with per-watcher delivery queues (analog of the etcd
  watch channels consumed by plugins/controller/dbwatcher.go watchDB :231)

Thread-safe; watchers receive events in commit order.
"""

from __future__ import annotations

import collections
import queue
import threading
from dataclasses import dataclass
from typing import Any, Deque, Dict, Iterable, List, Optional, Tuple


class TxnFailed(Exception):
    """An atomic KV operation lost its race."""


@dataclass(frozen=True)
class WatchEvent:
    """A single change notification."""

    key: str
    value: Any  # None on delete
    prev_value: Any
    revision: int

    @property
    def is_delete(self) -> bool:
        return self.value is None


class Watcher:
    """A registered watch on a set of key prefixes.

    Consume with ``get(timeout)`` or iterate the underlying queue.
    """

    def __init__(self, prefixes: Tuple[str, ...]):
        self.prefixes = prefixes
        self.queue: "queue.Queue[WatchEvent]" = queue.Queue()
        self.closed = False

    def matches(self, key: str) -> bool:
        return any(key.startswith(p) for p in self.prefixes)

    def get(self, timeout: Optional[float] = None) -> Optional[WatchEvent]:
        try:
            return self.queue.get(timeout=timeout)
        except queue.Empty:
            return None


class KVStore:
    """The in-memory store.

    Keeps a bounded log of the most recent watch events (every revision
    bump appends exactly one, so retained revisions are contiguous).
    ``watch_since`` uses it to hand a re-subscribing watcher the events
    it missed — the etcd watch-from-revision semantics the HA client
    failover rides (see :mod:`.ha`).
    """

    def __init__(self, log_capacity: int = 4096):
        self._lock = threading.RLock()
        self._data: Dict[str, Any] = {}
        self._revision = 0
        self._watchers: List[Watcher] = []
        self._log: Deque[WatchEvent] = collections.deque(maxlen=log_capacity)

    # ------------------------------------------------------------------ basic

    def get(self, key: str) -> Optional[Any]:
        with self._lock:
            return self._data.get(key)

    def put(self, key: str, value: Any) -> int:
        if value is None:
            raise ValueError("use delete() to remove a key")
        with self._lock:
            prev = self._data.get(key)
            self._data[key] = value
            self._revision += 1
            self._notify(key, value, prev)
            return self._revision

    def delete(self, key: str) -> bool:
        with self._lock:
            if key not in self._data:
                return False
            prev = self._data.pop(key)
            self._revision += 1
            self._notify(key, None, prev)
            return True

    def put_if_not_exists(self, key: str, value: Any) -> bool:
        """Atomic create; returns False if the key already exists."""
        with self._lock:
            if key in self._data:
                return False
            self.put(key, value)
            return True

    def compare_and_delete(self, key: str, expected: Any) -> bool:
        """Delete only if the current value equals ``expected``."""
        with self._lock:
            if self._data.get(key) != expected:
                return False
            return self.delete(key)

    # ------------------------------------------------------------- snapshots

    def list(self, prefix: str = "") -> List[Tuple[str, Any]]:
        """Consistent snapshot of all (key, value) under ``prefix``."""
        with self._lock:
            return sorted(
                (k, v) for k, v in self._data.items() if k.startswith(prefix)
            )

    def snapshot(self, prefixes: Iterable[str]) -> Dict[str, Any]:
        """One consistent snapshot across several prefixes (used for the
        resync event; analog of dbwatcher.LoadKubeStateForResync :553)."""
        return self.snapshot_with_revision(prefixes)[0]

    def snapshot_with_revision(
        self, prefixes: Iterable[str]
    ) -> Tuple[Dict[str, Any], int]:
        """Snapshot plus the revision it corresponds to, read atomically
        (watch events up to this revision are covered by the snapshot)."""
        with self._lock:
            out: Dict[str, Any] = {}
            for prefix in prefixes:
                for k, v in self._data.items():
                    if k.startswith(prefix):
                        out[k] = v
            return out, self._revision

    @property
    def revision(self) -> int:
        with self._lock:
            return self._revision

    # --------------------------------------------------------------- watches

    def watch(self, prefixes: Iterable[str]) -> Watcher:
        watcher = Watcher(tuple(prefixes))
        with self._lock:
            self._watchers.append(watcher)
        return watcher

    def watch_since(
        self, prefixes: Iterable[str], since_revision: int
    ) -> Tuple[Watcher, Optional[List[WatchEvent]]]:
        """Register a watcher AND collect the matching events committed
        after ``since_revision``, atomically — nothing can fall between
        the replay and the live stream.

        Returns ``(watcher, missed)``.  ``missed`` is ``None`` when the
        bounded log no longer reaches back to ``since_revision`` (the
        caller must resync from a snapshot instead); a negative
        ``since_revision`` requests no replay at all (fresh subscribe).
        """
        with self._lock:
            watcher = Watcher(tuple(prefixes))
            self._watchers.append(watcher)
            if since_revision < 0:
                return watcher, []
            # Retained log revisions are contiguous: coverage holds iff
            # the caller's revision reaches the oldest retained event
            # (or the log is empty because nothing changed since).
            if self._log:
                covered = since_revision >= self._log[0].revision - 1
            else:
                covered = since_revision >= self._revision
            if not covered:
                return watcher, None
            missed = [
                ev for ev in self._log
                if ev.revision > since_revision and watcher.matches(ev.key)
            ]
            return watcher, missed

    def unwatch(self, watcher: Watcher) -> None:
        with self._lock:
            watcher.closed = True
            if watcher in self._watchers:
                self._watchers.remove(watcher)

    def _notify(self, key: str, value: Any, prev: Any) -> None:
        ev = WatchEvent(key=key, value=value, prev_value=prev, revision=self._revision)
        self._log.append(ev)
        for watcher in self._watchers:
            if not watcher.closed and watcher.matches(key):
                watcher.queue.put(ev)

    # ------------------------------------------------------------ HA hooks

    def replace(self, snapshot: Dict[str, Any], revision: int) -> None:
        """Wholesale state install (HA snapshot catch-up): the follower's
        contents, revision, and event log are replaced, NOT diffed —
        watchers see no events (the installing replica resyncs its
        consumers, exactly like a reconnecting remote client)."""
        with self._lock:
            self._data = dict(snapshot)
            self._revision = revision
            self._log.clear()
