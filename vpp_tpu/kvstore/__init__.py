from .store import KVStore, WatchEvent, Watcher, TxnFailed
from .mirror import LocalMirror
from .remote import KVStoreServer, LeaderUnavailable, RemoteKVStore

__all__ = [
    "KVStore", "WatchEvent", "Watcher", "TxnFailed",
    "LocalMirror", "KVStoreServer", "RemoteKVStore", "LeaderUnavailable",
]
