from .store import KVStore, WatchEvent, Watcher, TxnFailed

__all__ = ["KVStore", "WatchEvent", "Watcher", "TxnFailed"]
