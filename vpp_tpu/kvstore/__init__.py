from .store import KVStore, WatchEvent, Watcher, TxnFailed
from .mirror import LocalMirror
from .remote import KVStoreServer, RemoteKVStore

__all__ = [
    "KVStore", "WatchEvent", "Watcher", "TxnFailed",
    "LocalMirror", "KVStoreServer", "RemoteKVStore",
]
