"""Lease-based leader election for the replicated cluster store.

The reference rides clustered etcd, whose Raft gives it one leader per
term and ordered replication (SURVEY layer map: "Cluster state store —
etcd").  This module is the election half of the framework's analog
(:mod:`.ha` holds the replication half): a deterministic, lease-based
state machine kept free of I/O so every transition is unit-testable —
the replica drives it with peer statuses gathered over gRPC.

Protocol, in one paragraph: the leader asserts its lease by replicating
(possibly empty) log heartbeats every ``heartbeat_interval``; a
follower whose lease expires (no heartbeat for ``lease_timeout``)
campaigns by polling every peer's status.  A candidate wins only when
it can see a MAJORITY of the ensemble (itself included) and no
reachable peer outranks it — rank is ``(last_term, last_index,
revision, replica_id)``, so a replica missing committed log entries can
never take over (the committed-write-survival invariant), and equal
logs tie-break deterministically on replica id, converging concurrent
candidacies without randomized retry.  A leader that cannot reach a
majority for a full lease steps down (the partitioned-leader fence:
its writes already fail the majority-ack gate, stepping down stops it
serving stale reads forever).
"""

from __future__ import annotations

import dataclasses
import enum
import time
from typing import Dict, Iterable, List, Optional, Tuple


class Role(enum.Enum):
    FOLLOWER = "follower"
    CANDIDATE = "candidate"
    LEADER = "leader"


@dataclasses.dataclass(frozen=True)
class PeerStatus:
    """One replica's election-relevant state, as reported over gRPC."""

    replica_id: int
    address: str
    role: str            # Role.value
    term: int
    last_index: int      # replication log position
    last_term: int       # term of the last log entry
    revision: int        # store revision (tie-breaker rank component)
    leader: str = ""     # the leader this replica currently follows
    pv: int = 0          # stamped protocol version (0 = pre-versioned)

    def rank(self) -> Tuple[int, int, int, int]:
        """Election rank: log position first (committed entries must
        survive), then store revision, then id as the deterministic
        tie-break."""
        return (self.last_term, self.last_index, self.revision, self.replica_id)

    @classmethod
    def from_dict(cls, status: dict) -> "PeerStatus":
        """Build from a ``HaStatus`` wire dict (ignores extra keys)."""
        return cls(
            replica_id=status["replica_id"], address=status["address"],
            role=status["role"], term=status["term"],
            last_index=status["last_index"], last_term=status["last_term"],
            revision=status["revision"], leader=status.get("leader", ""),
            pv=int(status.get("pv", 0)),
        )


@dataclasses.dataclass
class ElectionConfig:
    heartbeat_interval: float = 0.1
    lease_timeout: float = 0.5

    def stagger(self, replica_id: int) -> float:
        """Per-replica candidacy delay added to the lease check, so
        replicas don't all campaign on the same tick (the deterministic
        rank converges ties anyway; the stagger just avoids the poll
        storm)."""
        return 0.3 * self.heartbeat_interval * (replica_id % 8)


class ElectionState:
    """The per-replica election bookkeeping.

    All methods are synchronous and side-effect-free beyond their own
    fields; the owning replica supplies the clock (``now``) so tests
    can drive time explicitly.
    """

    def __init__(self, replica_id: int, config: Optional[ElectionConfig] = None):
        self.replica_id = replica_id
        self.config = config or ElectionConfig()
        self.role = Role.FOLLOWER
        self.term = 0
        self.leader: str = ""
        self._lease_deadline = 0.0

    # ------------------------------------------------------------- lease

    def touch_lease(self, now: Optional[float] = None) -> None:
        now = time.monotonic() if now is None else now
        self._lease_deadline = (
            now + self.config.lease_timeout + self.config.stagger(self.replica_id)
        )

    def lease_expired(self, now: Optional[float] = None) -> bool:
        now = time.monotonic() if now is None else now
        return now >= self._lease_deadline

    # ------------------------------------------------------- transitions

    def observe_heartbeat(self, term: int, leader: str,
                          now: Optional[float] = None) -> bool:
        """A replication call arrived from ``leader``.  Accept (renew
        the lease, adopt the term, follow) iff the term is current or
        newer; a stale leader is rejected so it learns to step down.

        Within ONE term the first leader followed is sticky: an
        equal-term heartbeat from a DIFFERENT leader is rejected while
        our current leader's lease holds.  Without this, concurrent
        same-term winners under an asymmetric partition would both
        keep harvesting this replica's acks (each heartbeat re-homing
        it), both sustain "quorum", and one could snapshot away
        writes the other had already quorum-acknowledged.  The loser
        bleeds acks, fails its quorum gate, and steps down instead."""
        if term < self.term:
            return False
        if term == self.term and self.role is Role.FOLLOWER \
                and self.leader and self.leader != leader:
            return False
        if term > self.term or self.role is not Role.FOLLOWER \
                or self.leader != leader:
            self.term = term
            self.role = Role.FOLLOWER
            self.leader = leader
        self.touch_lease(now)
        return True

    def start_campaign(self) -> None:
        self.role = Role.CANDIDATE
        self.leader = ""

    def decide(self, me: PeerStatus, peers: Iterable[Optional[PeerStatus]],
               ensemble_size: int) -> Role:
        """One candidacy round: given the statuses gathered from every
        OTHER ensemble member (None = unreachable), either win, defer to
        an existing leader, or stand down and wait.

        Mutates role/term/leader accordingly and returns the new role.
        """
        reachable: List[PeerStatus] = [p for p in peers if p is not None]
        # Defer to any live leader at our term or newer.
        for p in reachable:
            if p.role == Role.LEADER.value and p.term >= self.term:
                self.observe_heartbeat(p.term, p.address)
                return self.role
            if p.leader and p.leader != me.address and p.term >= self.term:
                # A peer follows an equal-or-newer-term leader we could
                # not reach ourselves; wait for that leader's heartbeat
                # (or the peer's lease on it to lapse) rather than
                # elect AROUND it — winning here could seat a second
                # same-or-next-term leader that snapshots away entries
                # the followed leader already quorum-acknowledged.
                self.term = max(self.term, p.term)
                self.role = Role.FOLLOWER
                self.touch_lease()
                return self.role
        if (len(reachable) + 1) * 2 <= ensemble_size:
            # No quorum visible: keep candidating (a lone replica can
            # never elect itself — the split-brain fence).
            self.role = Role.CANDIDATE
            return self.role
        if any(p.rank() > me.rank() for p in reachable):
            # An outranking replica is alive; let it win.  Refresh our
            # lease so we re-campaign only if it fails to take over.
            self.role = Role.FOLLOWER
            self.touch_lease()
            return self.role
        self.role = Role.LEADER
        self.term += 1
        self.leader = me.address
        return self.role

    def step_down(self) -> None:
        self.role = Role.FOLLOWER
        self.leader = ""
        self.touch_lease()


def pick_leader(statuses: Iterable[Optional[PeerStatus]]) -> Optional[str]:
    """The address a CLIENT should talk to, given whatever statuses it
    could gather: a reported leader at the highest term wins; with no
    self-reported leader, the highest-ranked replica is the best guess
    (it is the one the ensemble will elect)."""
    live = [s for s in statuses if s is not None]
    if not live:
        return None
    leaders = [s for s in live if s.role == Role.LEADER.value]
    if leaders:
        return max(leaders, key=lambda s: s.term).address
    followed = [s.leader for s in live if s.leader]
    if followed:
        # Majority-followed leader hint (the leader itself may be
        # unreachable from the client but not from its followers).
        counts: Dict[str, int] = {}
        for addr in followed:
            counts[addr] = counts.get(addr, 0) + 1
        return max(counts, key=lambda a: counts[a])
    return max(live, key=lambda s: s.rank()).address
