"""Standalone cluster store server — the contiv-etcd analog.

The reference deploys etcd on the master (k8s/contiv-vpp.yaml
contiv-etcd StatefulSet); this serves the framework's KVStore over the
same gRPC surface the agents consume:

    python -m vpp_tpu.kvstore [--host 0.0.0.0] [--port 12379]
        [--snapshot /var/lib/vpp-tpu/store.db]

``--snapshot`` persists every change to a sqlite snapshot and reloads
it on startup (the etcd-data-volume analog), so a store restart
recovers the cluster state without waiting for KSR to re-reflect.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading

from .remote import DEFAULT_PORT, KVStoreServer
from .store import KVStore


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description="vpp-tpu cluster store server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("--snapshot", default="",
                        help="sqlite snapshot path (persistence across restarts)")
    parser.add_argument("--max-watchers", type=int, default=64)
    args = parser.parse_args(argv)

    store = KVStore()
    mirror = None
    if args.snapshot:
        from .mirror import LocalMirror

        mirror = LocalMirror(args.snapshot)
        loaded = mirror.load()
        if loaded is not None:
            snap, _rev = loaded
            for key, value in snap.items():
                store.put(key, value)
        # Persist continuously, coalescing bursts: drain every queued
        # change, then write ONE snapshot covering all of them (a KSR
        # initial reflection is hundreds of puts but one sqlite write).
        watcher = store.watch([""])

        def persist():
            while True:
                ev = watcher.get(timeout=0.5)
                if ev is None:
                    if watcher.closed:
                        return
                    continue
                while watcher.get(timeout=0.02) is not None:
                    pass  # drain the burst
                snap, rev = store.snapshot_with_revision([""])
                mirror.save_snapshot(snap, rev)

        threading.Thread(target=persist, name="store-persist", daemon=True).start()

    server = KVStoreServer(store, host=args.host, port=args.port,
                           max_watchers=args.max_watchers)
    port = server.start()
    print(json.dumps({"store": f"{args.host}:{port}",
                      "snapshot": args.snapshot or None}), flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
