"""Standalone cluster store server — the contiv-etcd analog.

The reference deploys etcd on the master (k8s/contiv-vpp.yaml
contiv-etcd StatefulSet); this serves the framework's KVStore over the
same gRPC surface the agents consume:

    python -m vpp_tpu.kvstore [--host 0.0.0.0] [--port 12379]
        [--snapshot /var/lib/vpp-tpu/store.db]

``--snapshot`` persists every change to a sqlite snapshot and reloads
it on startup (the etcd-data-volume analog), so a store restart
recovers the cluster state without waiting for KSR to re-reflect.

HA mode (the CLUSTERED etcd analog — vpp_tpu/kvstore/ha.py):

    python -m vpp_tpu.kvstore --port 12379 \\
        --join host1:12379,host2:12379,host3:12379

starts this process as one member of an N-replica ensemble: lease-based
leader election, ordered log replication, follower snapshot catch-up.
``--join`` lists EVERY member (self included — matched via
``--advertise``, or inferred when exactly ONE member's port equals
``--port``; ambiguous inference is an error, not a guess).  ``--replica-of host:port`` instead asks a running member
for the ensemble list and joins it — and when this replica is NOT in
that list, it GROWS the ensemble (ISSUE 13): it joins as a learner and
requests ``AddReplica`` from the leader, which snapshot-catches it up
before it ever counts toward quorum.  This is the one-command "add a
store replica to a running fleet" operator path (see docs/DEVGUIDE.md
"Planned operations").
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import threading


def _resolve_advertise(args, members) -> str:
    """The address this replica appears as inside --join."""
    if args.advertise:
        return args.advertise
    candidates = [m for m in members if m.endswith(f":{args.port}")]
    if len(candidates) == 1:
        return candidates[0]
    raise SystemExit(
        "cannot infer this replica's address from --join "
        f"(port {args.port} matches {len(candidates)} members); "
        "pass --advertise host:port"
    )


def main(argv=None) -> int:
    from .remote import DEFAULT_PORT, KVStoreServer, RemoteKVStore
    from .store import KVStore

    parser = argparse.ArgumentParser(description="vpp-tpu cluster store server")
    parser.add_argument("--host", default="0.0.0.0")
    parser.add_argument("--port", type=int, default=DEFAULT_PORT)
    parser.add_argument("--snapshot", default="",
                        help="sqlite snapshot path (persistence across restarts)")
    parser.add_argument("--max-watchers", type=int, default=64)
    parser.add_argument("--join", default="",
                        help="comma-separated FULL ensemble member list "
                             "(self included) — starts HA replica mode")
    parser.add_argument("--replica-of", default="",
                        help="address of a running ensemble member to "
                             "fetch the member list from and join")
    parser.add_argument("--advertise", default="",
                        help="this replica's address as listed in --join "
                             "(inferred from --port when unambiguous)")
    parser.add_argument("--heartbeat-interval", type=float, default=0.1,
                        help="leader heartbeat period, seconds")
    parser.add_argument("--lease-timeout", type=float, default=0.5,
                        help="leader lease; followers campaign after this "
                             "long without a heartbeat")
    args = parser.parse_args(argv)

    store = KVStore()
    mirror = None
    if args.snapshot:
        from .mirror import LocalMirror

        mirror = LocalMirror(args.snapshot)
        loaded = mirror.load()
        if loaded is not None:
            snap, _rev = loaded
            for key, value in snap.items():
                store.put(key, value)
        # Persist continuously, coalescing bursts: drain every queued
        # change, then write ONE snapshot covering all of them (a KSR
        # initial reflection is hundreds of puts but one sqlite write).
        watcher = store.watch([""])

        def persist():
            while True:
                ev = watcher.get(timeout=0.5)
                if ev is None:
                    if watcher.closed:
                        return
                    continue
                while watcher.get(timeout=0.02) is not None:
                    pass  # drain the burst
                snap, rev = store.snapshot_with_revision([""])
                mirror.save_snapshot(snap, rev)

        threading.Thread(target=persist, name="store-persist", daemon=True).start()

    members = [m.strip() for m in args.join.split(",") if m.strip()]
    grow_via = ""
    if args.replica_of and not members:
        probe = RemoteKVStore(args.replica_of, timeout=5.0)
        try:
            members = list(probe.ha_status(args.replica_of)["peers"])
        finally:
            probe.close()
        advertise = args.advertise or (
            f"{'127.0.0.1' if args.host == '0.0.0.0' else args.host}"
            f":{args.port}")
        if advertise not in members:
            # Not listed: this is a GROW, not a rejoin — join as a
            # learner and ask the leader to adopt us (below, once the
            # server is bound and serving the replica protocol).
            grow_via = args.replica_of
            members = sorted(members + [advertise])

    replica = None
    if members:
        from .ha import HAReplica

        replica = HAReplica(
            host=args.host, port=args.port,
            advertise=_resolve_advertise(args, members),
            store=store,
            heartbeat_interval=args.heartbeat_interval,
            lease_timeout=args.lease_timeout,
            max_watchers=args.max_watchers,
        )
        replica.bind()
        replica.join(members)
        server = replica.server
        port = server.port
        if grow_via:
            # AddReplica blocks for the snapshot catch-up; the
            # leader-following client re-homes off NOT_LEADER hints.
            client = RemoteKVStore(
                ",".join(m for m in members if m != replica.address),
                timeout=60.0)
            try:
                result = client.add_replica(replica.address, timeout=60.0)
                print(json.dumps({"add_replica": result}), flush=True)
            finally:
                client.close()
    else:
        server = KVStoreServer(store, host=args.host, port=args.port,
                               max_watchers=args.max_watchers)
        port = server.start()
    print(json.dumps({"store": f"{args.host}:{port}",
                      "snapshot": args.snapshot or None,
                      "ensemble": members or None,
                      "advertise": replica.address if replica else None}),
          flush=True)

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        signal.signal(sig, lambda *_: stop.set())
    stop.wait()
    if replica is not None:
        replica.stop()
    else:
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
