"""HA replicated cluster store — the clustered-etcd analog.

The reference deploys etcd as a multi-member cluster (contiv-etcd
StatefulSet) so the cluster state store survives a master crash; the
framework's single ``KVStoreServer`` process had no such story
(VERDICT r5 "missing" #4).  This module adds it:

- an N-replica ensemble where ONE leader (elected by the lease protocol
  in :mod:`.election`) serves every client op and replicates each
  mutation as an ordered log of ``put`` / ``delete`` /
  ``put_if_not_exists`` / ``compare_and_delete`` entries to its
  followers — every replica applies the same ops in the same order to
  the same starting state, so store contents AND revisions stay
  bit-identical across the ensemble;
- a quorum-ack commit gate: the leader answers a client write only
  after a majority of replicas (itself included) hold the entry, so an
  acknowledged write survives any single-replica SIGKILL — the next
  leader is always the highest-ranked log, which must contain it;
- snapshot catch-up: a follower whose log position cannot be reconciled
  entry-by-entry (fresh join, rejoin after a crash, deposed leader with
  an uncommitted suffix) receives one wholesale snapshot install and
  then follows the log again;
- follower client-op rejection with a leader hint
  (``NOT_LEADER leader=<addr>``), which is what the multi-address
  ``RemoteKVStore`` failover re-homes on.

Leader reads are lease-bounded: a partitioned leader stops serving
after ``lease_timeout`` without follower quorum (it steps down), so
stale reads are bounded by the lease — the same trade clustered etcd
makes for lease-based (non-quorum) reads.

Live membership change (ISSUE 13, etcd's member add/remove analog):
the ensemble can grow and shrink at runtime, one server at a time —

- ``add_replica``: the joiner enters as a non-voting LEARNER; the
  leader snapshot-catches it up and only THEN commits a ``member-add``
  log entry (quorum over the old voters — a not-yet-caught-up replica
  can never ack toward quorum, so a membership change can never seat a
  voter missing committed writes);
- ``remove_replica``: a ``member-remove`` entry; removing the sitting
  leader first pushes every survivor fully up to date (zero lost
  committed writes), commits the removal, then steps down so the
  survivors elect among themselves (orderly handoff);
- membership rides the REPLICATED LOG (snapshot installs carry the
  voting peer list), so every replica converges on the same member set
  the same way it converges on store contents; one change in flight at
  a time (``MembershipChangeInProgress`` otherwise).

Every replica-to-replica message is version-stamped and floor-checked
(:mod:`.compat`): a below-floor peer is refused with an explicit
``incompatible`` reply, never fed entries it may mis-decode.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from concurrent import futures as _futures
from typing import Any, Callable, Dict, List, Optional, Set

import grpc

from . import compat
from .compat import IncompatibleVersion
from .election import ElectionConfig, ElectionState, PeerStatus, Role
from .remote import (
    NO_QUORUM_PREFIX,
    NOT_LEADER_PREFIX,
    OUTAGE_CODES,
    KVStoreServer,
    _code_of,
    _Target,
    channel_ready,
)
from .store import KVStore

log = logging.getLogger(__name__)

# The replicated key the sitting leader publishes itself under — the
# observability/debug surface for "who is leader" (clients re-home on
# NOT_LEADER hints and need no key read; netctl and tests read this).
ELECTION_KEY = "/vpp-tpu/ha/leader"


class NotLeader(Exception):
    """This replica cannot serve a client op; ``leader`` is its best
    hint for who can ("" while an election is running)."""

    def __init__(self, leader: str = ""):
        super().__init__(f"not the leader (leader={leader or '?'})")
        self.leader = leader


class NoQuorum(Exception):
    """A write could not be acknowledged by a replica majority."""


class MembershipChangeInProgress(Exception):
    """A second add/remove was requested while one is still running —
    the one-server-at-a-time rule (joint consensus is out of scope;
    single-server changes are safe only serially)."""


class CatchupTimeout(Exception):
    """A joining replica could not be caught up within the deadline;
    it was dropped from the learner set and never counted toward
    quorum — the ensemble is unchanged."""


@dataclasses.dataclass(frozen=True)
class LogEntry:
    """One replicated mutation.  ``index`` is dense and 1-based; the
    (index, term) pair is the replication cursor replicas reconcile on."""

    index: int
    term: int
    op: str
    args: Dict[str, Any]

    def to_wire(self) -> dict:
        return {"index": self.index, "term": self.term,
                "op": self.op, "args": self.args}

    @staticmethod
    def from_wire(msg: dict) -> "LogEntry":
        return LogEntry(index=msg["index"], term=msg["term"],
                        op=msg["op"], args=msg["args"])


class _FollowerState:
    """Leader-side bookkeeping for one follower.

    Raft's nextIndex/matchIndex split: ``next`` is the optimistic push
    cursor (where to slice the log for the next Replicate), ``match``
    is confirmed replication — raised ONLY by a Replicate/
    InstallSnapshot response.  commit() quorum-counts ``match`` alone;
    counting an optimistic cursor would let a deposed-and-re-elected
    leader acknowledge a write no follower holds."""

    def __init__(self, next_index: int):
        self.next = next_index        # guarded-by: lock — optimistic log-slice cursor
        self.match = 0                # guarded-by: lock — highest index confirmed by an RPC ack
        self.acked_at = 0.0           # guarded-by: lock — monotonic time of the last ack
        self.lock = threading.Lock()  # serializes pushes to this follower


class HAReplica:
    """One member of the replicated store ensemble."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        advertise: str = "",
        store: Optional[KVStore] = None,
        heartbeat_interval: float = 0.1,
        lease_timeout: float = 0.5,
        log_capacity: int = 4096,
        max_watchers: int = 64,
    ):
        self.store = store if store is not None else KVStore()
        self._advertise = advertise
        self.server = ReplicaServer(self, host=host, port=port,
                                    max_watchers=max_watchers)
        self._config = ElectionConfig(heartbeat_interval=heartbeat_interval,
                                      lease_timeout=lease_timeout)
        # Follower pushes must give up well inside a heartbeat period,
        # or one dead peer would stall the announcements that keep the
        # OTHER followers' leases alive.
        self._replicate_timeout = max(
            0.05, min(heartbeat_interval, lease_timeout / 3.0))
        # A client write may need several push rounds to find quorum — a
        # follower can be mid-snapshot-install (its push lock held by
        # the tick loop) right after an election, and one failed round
        # must not surface as NO_QUORUM to the caller.
        self._commit_timeout = 2.0 * lease_timeout
        self.peers: List[str] = []  # guarded-by: _state_lock — VOTING members (live membership mutates it)
        self.replica_id = 0         # guarded-by: _state_lock — position in sorted(peers)
        self._el: Optional[ElectionState] = None
        self._state_lock = threading.RLock()
        self._log: List[LogEntry] = []     # guarded-by: _state_lock
        self._log_capacity = log_capacity
        # The log starts after (base_index, base_term).
        self._base_index = 0   # guarded-by: _state_lock
        self._base_term = 0    # guarded-by: _state_lock
        self._last_index = 0   # guarded-by: _state_lock
        self._last_term = 0    # guarded-by: _state_lock
        # Election-rank cursor: the tail of entries KNOWN replicated —
        # quorum-acked own writes, or entries received from a leader.
        # A deposed leader's unacknowledged suffix is excluded, so it
        # cannot outrank a follower holding a quorum-acked entry it
        # lacks (the committed-write-survival invariant).
        self._rank_index = 0   # guarded-by: _state_lock
        self._rank_term = 0    # guarded-by: _state_lock
        # A replica that has never reconciled with a leader in this
        # process must take a snapshot install before following the log:
        # its store may hold state (sqlite preseed) the log cursor knows
        # nothing about, and a matching (0, 0) cursor would silently
        # merge diverged stores.
        self._virgin = True    # guarded-by: _state_lock
        # Live membership (ISSUE 13): ``peers`` holds VOTING members
        # only; a joining replica sits in ``_learners`` (pushed like a
        # follower, excluded from every quorum count) until its
        # snapshot catch-up completes and the member-add entry commits.
        self._learners: Set[str] = set()       # guarded-by: _state_lock
        self._membership_inflight = ""         # guarded-by: _state_lock — one change at a time
        self._removed = False                  # guarded-by: _state_lock — this replica left the ensemble
        self.membership_events: List[dict] = []  # guarded-by: _state_lock — applied changes (drill evidence)
        self._followers: Dict[str, _FollowerState] = {}  # guarded-by: _state_lock — map mutations (entry FIELDS ride each entry's own lock)
        # Peer channel cache: dialed/evicted from the tick loop, pool
        # pushes, AND client commit threads concurrently — its own lock
        # (NOT _state_lock: _peer_call blocks on the network and must
        # never hold the state lock across an RPC).
        self._peer_targets: Dict[str, _Target] = {}  # guarded-by: _peers_lock
        self._peers_lock = threading.Lock()
        self._last_quorum_at = 0.0  # guarded-by: _state_lock
        self._stop_event = threading.Event()
        self._tick_thread: Optional[threading.Thread] = None
        self._pool: Optional[_futures.ThreadPoolExecutor] = None

    # ----------------------------------------------------------- lifecycle

    @property
    def address(self) -> str:
        return self._advertise or self.server.address

    def bind(self) -> str:
        """Start the gRPC server; returns the advertised address (the
        two-phase start lets an ensemble of port-0 replicas learn each
        other's ports before any election begins)."""
        port = self.server.start()
        if not self._advertise:
            host = self.server.host
            self._advertise = f"{'127.0.0.1' if host == '0.0.0.0' else host}:{port}"
        return self._advertise

    def join(self, peers: List[str]) -> None:
        """Enter the ensemble (the full member list, self included) and
        start electing.  replica_id is the position in the sorted member
        list — identical on every replica without coordination."""
        if self.address not in peers:
            raise ValueError(f"{self.address} not in ensemble {peers}")
        with self._state_lock:
            self.peers = sorted(peers)
            self.replica_id = self.peers.index(self.address)
            self._el = ElectionState(self.replica_id, self._config)
            self._el.touch_lease()
        self._pool = _futures.ThreadPoolExecutor(
            max_workers=max(2, 2 * len(self.peers)),
            thread_name_prefix=f"ha-{self.replica_id}",
        )
        self._tick_thread = threading.Thread(
            target=self._tick_loop, name=f"ha-tick-{self.replica_id}", daemon=True
        )
        self._tick_thread.start()

    def stop(self) -> None:
        """Graceful shutdown (process exit)."""
        self.kill(grace=0.2)

    def kill(self, grace: float = 0.0) -> None:
        """Abrupt shutdown — the in-process SIGKILL analog: no step-down
        courtesy, no final heartbeat; peers must detect the silence."""
        self._stop_event.set()
        self.server.stop(grace=grace)
        if self._tick_thread is not None:
            self._tick_thread.join(timeout=2.0)
        if self._pool is not None:
            self._pool.shutdown(wait=False)
        # Snapshot under the peers lock, then close outside it: pool
        # workers shut down with wait=False can still be inside
        # _peer_call dialing (a straggler's channel then leaks until
        # process exit, which kill() is anyway).
        with self._peers_lock:
            targets = list(self._peer_targets.values())
            self._peer_targets.clear()
        for target in targets:
            target.channel.close()

    # ------------------------------------------------------------- queries

    @property
    def role(self) -> Role:
        with self._state_lock:
            return self._el.role if self._el is not None else Role.FOLLOWER

    @property
    def is_leader(self) -> bool:
        return self.role is Role.LEADER

    def status(self) -> dict:
        with self._state_lock:
            el = self._el
            return {
                "replica_id": self.replica_id,
                "address": self.address,
                "role": (el.role.value if el else Role.FOLLOWER.value),
                "term": (el.term if el else 0),
                # Election rank rides the KNOWN-replicated cursor, not
                # the raw log tail — see _rank_index.
                "last_index": self._rank_index,
                "last_term": self._rank_term,
                "revision": self.store.revision,
                "leader": (el.leader if el else ""),
                "peers": list(self.peers),
                "learners": sorted(self._learners),
                "membership_inflight": self._membership_inflight,
                "removed": self._removed,
                "pv": compat.effective_version(),
            }

    def _status_as_peer(self) -> PeerStatus:
        return PeerStatus.from_dict(self.status())

    def abort_if_not_leader(self, context) -> None:
        with self._state_lock:
            if self._el is not None and self._el.role is Role.LEADER:
                return
            leader = self._el.leader if self._el is not None else ""
        if context is None:
            raise NotLeader(leader)
        context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                      NOT_LEADER_PREFIX + (leader if leader != self.address else ""))

    # ------------------------------------------------------- the write path

    def commit(self, op: str, args: Dict[str, Any]) -> Any:
        """Apply one client mutation: local apply + log append under the
        state lock, then parallel replication to followers, answering
        only once a majority of the ensemble holds the entry.

        A ``NoQuorum`` raise is INDETERMINATE, not a rollback: the
        entry stays applied locally and keeps replicating on later
        ticks, so it usually commits anyway (etcd's deadline-exceeded
        semantics).  The client surfaces it as ``ABORTED NO_QUORUM``
        and auto-retries only idempotent ops."""
        with self._state_lock:
            if self._el is None or self._el.role is not Role.LEADER:
                raise NotLeader(self._el.leader if self._el else "")
            entry = LogEntry(index=self._last_index + 1, term=self._el.term,
                             op=op, args=args)
            voters_before = list(self.peers)  # pre-apply voting set
            result = self._apply_op(op, args)
            self._append(entry)
        # Quorum base for THIS entry (ISSUE 13): a membership entry is
        # never helped across the line by the member it is ABOUT —
        # member-add is counted over the OLD voters (the caught-up
        # joiner's ack must not vote its own membership in), and
        # member-remove over the SURVIVORS (the departing member's own
        # copy must not vote its removal out — leader self-removal
        # included, so a removal can only commit held by a true
        # survivor majority).  The snapshot also keeps the base stable
        # if peers mutate while this loop runs.
        if op == "member-remove":
            base = [p for p in voters_before if p != args["addr"]]
        else:
            base = voters_before
        self_votes = self.address in base
        others = [p for p in base if p != self.address]
        needed = len(base) // 2 + 1
        deadline = time.monotonic() + self._commit_timeout
        while True:
            # A follower acks by its match cursor reaching the entry —
            # however it got there (our push or a concurrent tick push).
            followers = self._followers
            acked = (1 if self_votes else 0) + sum(
                1 for addr in others
                if (fs := followers.get(addr)) is not None
                and fs.match >= entry.index
            )
            if acked >= needed:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise NoQuorum(f"{acked}/{len(base)} acks for {op}")
            lagging = [
                addr for addr in others
                if (fs := followers.get(addr)) is None
                or fs.match < entry.index
            ]
            _futures.wait(
                [self._pool.submit(self._push, addr) for addr in lagging],
                timeout=min(remaining, 4 * self._replicate_timeout),
            )
        with self._state_lock:
            # A majority holds everything up to this entry: it (and all
            # before it) now counts toward this replica's election rank.
            if entry.index > self._rank_index:
                self._rank_index, self._rank_term = entry.index, entry.term
        return result

    def _apply_op(self, op: str, args: Dict[str, Any]) -> Any:
        s = self.store
        if op == "put":
            return s.put(args["key"], args["value"])
        if op == "delete":
            return s.delete(args["key"])
        if op == "put_if_not_exists":
            return s.put_if_not_exists(args["key"], args["value"])
        if op == "compare_and_delete":
            return s.compare_and_delete(args["key"], args["expected"])
        if op in ("member-add", "member-remove"):
            return self._apply_membership(op, args)
        raise ValueError(f"unknown replicated op {op!r}")

    def _apply_membership(self, op: str,
                          args: Dict[str, Any]) -> List[str]:  # holds: _state_lock
        """Apply a membership log entry.  Callers hold ``_state_lock``
        (commit() and handle_replicate() both apply under it) — the
        voting set, replica id and removal flag change as ONE unit.
        Membership rides the replicated log, so every replica applies
        the same changes in the same order — member sets converge
        exactly like store contents."""
        addr = args["addr"]
        if op == "member-add":
            if addr not in self.peers:
                self.peers = sorted(self.peers + [addr])
            self._learners.discard(addr)
        else:
            self.peers = [p for p in self.peers if p != addr]
            self._learners.discard(addr)
            self._followers.pop(addr, None)
            if addr == self.address:
                # This replica left the ensemble: go dormant (no
                # campaigns, client ops keep getting NOT_LEADER) — the
                # operator stops the process at leisure.
                self._removed = True
        if self.address in self.peers:
            self.replica_id = self.peers.index(self.address)
            if self._el is not None:
                self._el.replica_id = self.replica_id
        self.membership_events.append({
            "op": op, "addr": addr, "peers": list(self.peers),
            "at": time.time(),
        })
        log.info("%s applied %s %s -> peers=%s",
                 self.address, op, addr, self.peers)
        return list(self.peers)

    def _append(self, entry: LogEntry) -> None:  # holds: _state_lock
        self._log.append(entry)
        self._last_index = entry.index
        self._last_term = entry.term
        while len(self._log) > self._log_capacity:
            dropped = self._log.pop(0)
            self._base_index = dropped.index
            self._base_term = dropped.term

    # ----------------------------------------------------- leader → follower

    def _peer_call(self, addr: str, method: str, request: dict,
                   timeout: Optional[float] = None) -> Optional[dict]:
        # Get-or-dial under the peers lock: _peer_call runs on the tick
        # loop, pool pushes and client commit threads at once, and the
        # unguarded check-then-dial raced — two threads could both dial
        # the same peer and one _Target's channel leaked open (found by
        # the lock-discipline checker).  The RPC itself runs unlocked.
        with self._peers_lock:
            target = self._peer_targets.get(addr)
            if target is None:
                target = self._peer_targets[addr] = _Target(addr)
        try:
            return target.calls[method](
                request, timeout=timeout or self._replicate_timeout)
        except ValueError as e:
            # A concurrent eviction (or kill()) closed the cached
            # channel between the lock release and the invoke — grpc
            # raises ValueError, not RpcError.  The request was never
            # sent; report push failure, the next tick redials fresh.
            if "closed channel" not in str(e):
                raise
            with self._peers_lock:
                if self._peer_targets.get(addr) is target:
                    self._peer_targets.pop(addr, None)
            return None
        except grpc.RpcError as e:
            code = _code_of(e)
            if code in OUTAGE_CODES and not channel_ready(target.channel):
                # Redial the peer on the next tick: a connect attempt
                # started before the peer's port was bound (ensemble
                # cold-start, replica restart) can hang past any
                # reconnect backoff, and the tick loop would keep
                # riding the same doomed channel forever.  A deadline
                # on a READY channel is just a slow peer — redialing
                # a healthy transport buys nothing.  Evict only OUR
                # target: a concurrent caller may already have redialed.
                with self._peers_lock:
                    if self._peer_targets.get(addr) is target:
                        self._peer_targets.pop(addr, None)
                try:
                    target.channel.close()
                except Exception:  # noqa: BLE001 - eviction is best-effort
                    pass
            elif code not in OUTAGE_CODES:
                log.warning("peer %s %s failed: %s", addr, method, code)
            return None

    def _push(self, addr: str) -> bool:
        """Bring one follower up to date (entries if its cursor is in
        our log, a snapshot install otherwise); returns ack success.

        The per-follower lock is acquired with a bounded wait: a
        follower hung mid-snapshot-install would otherwise collect one
        blocked pool thread per tick until the pool starves and
        heartbeats to HEALTHY followers stop — deposing a live leader."""
        fs = self._followers.get(addr)
        if fs is None:
            return False
        if not fs.lock.acquire(timeout=self._replicate_timeout):
            return False  # a push to this follower is already in flight
        try:
            with self._state_lock:
                if self._el is None or self._el.role is not Role.LEADER:
                    return False
                term = self._el.term
                cursor = fs.next
                if cursor < self._base_index or cursor > self._last_index:
                    entries = None  # cursor outside the retained log
                else:
                    entries = [e.to_wire()
                               for e in self._log[cursor - self._base_index:]]
                    prev_term = (self._base_term if cursor == self._base_index
                                 else self._log[cursor - self._base_index - 1].term)
            if entries is None:
                return self._install_snapshot(addr, fs, term)
            resp = self._peer_call(addr, "Replicate", compat.stamp({
                "term": term,
                "leader": self.address,
                "prev_index": cursor,
                "prev_term": prev_term,
                "entries": entries,
            }))
            if resp is None:
                return False
            if resp.get("incompatible"):
                # The follower refused our protocol version (or we
                # refused its floor): no entries were applied; shipping
                # a snapshot would be refused identically.  Loud — this
                # is an operator problem (finish the rolling upgrade),
                # not a transient.
                log.error("follower %s refused replication: its floor "
                          "is v%s, we stamped v%s", addr,
                          resp.get("min"), resp.get("got"))
                return False
            if resp["term"] > term:
                with self._state_lock:
                    if self._el is not None and resp["term"] > self._el.term:
                        # static: allow(lock-discipline) — _el.term writes serialize on _state_lock (held here)
                        self._el.term = resp["term"]
                        self._el.step_down()
                return False
            if resp.get("ok"):
                fs.next = fs.match = resp["last_index"]  # static: allow(lock-discipline) — fs.lock held via the bounded acquire above
                fs.acked_at = time.monotonic()  # static: allow(lock-discipline) — fs.lock held via the bounded acquire above
                return True
            if resp.get("needs_snapshot"):
                # The mismatch reply carries the follower's actual tail.
                # A lost ack leaves fs.next stale while the follower
                # really did apply — when its tail is still inside our
                # retained log, a cursor reset + entry resend beats a
                # wholesale snapshot.  A second mismatch AT the
                # follower's own tail means diverged terms (or a virgin
                # follower): only then ship the snapshot.
                tail = resp.get("last_index", -1)
                with self._state_lock:
                    in_log = self._base_index <= tail <= self._last_index
                if tail != cursor and in_log:
                    fs.next = tail  # static: allow(lock-discipline) — fs.lock held via the bounded acquire above
                    return False  # re-push from the new cursor next round
                return self._install_snapshot(addr, fs, term)
            # Rejected outright (e.g. the follower stays sticky to its
            # same-term leader): no ack, and no point shipping a
            # snapshot it would reject too.
            return False
        finally:
            fs.lock.release()

    def _install_snapshot(self, addr: str, fs: _FollowerState,
                          term: int) -> bool:  # holds: lock

        with self._state_lock:
            snap, rev = self.store.snapshot_with_revision([""])
            payload = compat.stamp({
                "term": term,
                "leader": self.address,
                "snapshot": snap,
                "revision": rev,
                "last_index": self._last_index,
                "last_term": self._last_term,
                # Config-in-snapshot: membership entries compacted out
                # of the log still reach catching-up replicas.
                "peers": list(self.peers),
            })
        resp = self._peer_call(addr, "InstallSnapshot", payload,
                               timeout=4 * self._replicate_timeout)
        if resp is None or not resp.get("ok"):
            if resp is not None and resp.get("incompatible"):
                log.error("follower %s refused snapshot install: its "
                          "floor is v%s, we stamped v%s", addr,
                          resp.get("min"), resp.get("got"))
            return False
        fs.next = fs.match = payload["last_index"]
        fs.acked_at = time.monotonic()
        return True

    # ------------------------------------------------- membership change

    def _begin_membership(self, addr: str) -> None:  # holds: _state_lock
        if self._membership_inflight:
            raise MembershipChangeInProgress(
                f"{self._membership_inflight} change still in flight "
                "(one server at a time)")
        self._membership_inflight = addr

    def _end_membership(self) -> None:
        with self._state_lock:
            self._membership_inflight = ""

    def add_replica(self, addr: str, timeout: float = 60.0) -> dict:
        """Grow the ensemble by one replica (which must already be
        bound, joined, and serving the replica protocol on ``addr``).

        Protocol: the joiner enters as a non-voting LEARNER — it is
        pushed (snapshot install + log entries) like any follower but
        excluded from every quorum count.  Only once its confirmed
        replication cursor reaches the leader's CURRENT log tail is the
        ``member-add`` entry committed (quorum over the OLD voters), at
        which point it becomes a voter everywhere the entry applies.
        A replica that cannot catch up within ``timeout`` is dropped
        and the ensemble is unchanged (:class:`CatchupTimeout`)."""
        with self._state_lock:
            if self._el is None or self._el.role is not Role.LEADER:
                raise NotLeader(self._el.leader if self._el else "")
            if addr in self.peers:
                return {"already_member": True, "peers": list(self.peers)}
            self._begin_membership(addr)
            self._learners.add(addr)
            fs = self._followers.get(addr)
            if fs is None:
                fs = self._followers[addr] = _FollowerState(
                    next_index=self._last_index)
        try:
            deadline = time.monotonic() + timeout
            while True:
                with self._state_lock:
                    if self._el.role is not Role.LEADER:
                        raise NotLeader(self._el.leader)
                    target = self._last_index
                if fs.match >= target:
                    # Caught up THROUGH the tail sampled this round —
                    # the log may grow again immediately (live write
                    # traffic), but so may any voter's lag; from here
                    # the joiner follows like everyone else.
                    break
                if time.monotonic() >= deadline:
                    raise CatchupTimeout(
                        f"{addr} reached index {fs.match}/{target} "
                        f"within {timeout:.1f}s; ensemble unchanged")
                self._push(addr)
                time.sleep(min(0.02, self._config.heartbeat_interval))
            caught_up_index = fs.match
            # The membership entry's quorum is counted over the OLD
            # voters (commit() snapshots the pre-apply voting set and
            # excludes the member the entry is about), so the literal
            # below is enforced, not aspirational: the joiner's own
            # ack can never vote its membership in.
            peers = self.commit("member-add", {"addr": addr})
            return {
                "added": addr,
                "peers": peers,
                "caught_up_index": caught_up_index,
                "member_index": self._last_index,
                "learner_votes_counted": False,
            }
        finally:
            with self._state_lock:
                if addr in self._learners:
                    # The member-add never APPLIED (catch-up timeout, or
                    # deposed before commit's local apply): roll the
                    # learner back so no phantom learner lingers in the
                    # follower map / status forever.  Once the entry
                    # applied, _apply_membership already promoted the
                    # learner — even a NoQuorum raise after that point
                    # is Raft-indeterminate (the entry usually still
                    # commits on later ticks) and must NOT be rolled
                    # back here.
                    self._learners.discard(addr)
                    self._followers.pop(addr, None)
            self._end_membership()

    def remove_replica(self, addr: str, timeout: float = 60.0) -> dict:
        """Shrink the ensemble by one replica via a ``member-remove``
        log entry.  Removing the sitting leader (``addr`` == our own
        address) is the ORDERLY-HANDOFF path: every survivor is pushed
        fully up to date first (zero lost committed writes — the next
        leader provably holds everything), the removal commits, the
        entry is pushed to ALL survivors, and only then does the leader
        step down so the survivors elect among themselves."""
        with self._state_lock:
            if self._el is None or self._el.role is not Role.LEADER:
                raise NotLeader(self._el.leader if self._el else "")
            if addr not in self.peers:
                return {"not_member": True, "peers": list(self.peers)}
            if len(self.peers) <= 2:
                # A 2→1 shrink leaves a single replica that can never
                # again form a majority with anyone — refuse (etcd
                # refuses the same way for quorum loss).
                raise ValueError(
                    f"refusing to shrink {len(self.peers)} -> "
                    f"{len(self.peers) - 1}: the survivor set could "
                    "not form a quorum")
            self._begin_membership(addr)
        self_removal = addr == self.address
        try:
            survivors = [p for p in self.peers
                         if p not in (addr, self.address)]
            with self._state_lock:
                fs_removed = self._followers.get(addr)
            if self_removal:
                # Handoff precondition: at least the whole survivor set
                # pushed to our tail, so no committed write exists only
                # on the departing leader.
                self._sync_survivors(survivors, timeout / 2)
            peers = self.commit("member-remove", {"addr": addr})
            if not self_removal and fs_removed is not None:
                # Farewell push: the local apply above dropped the
                # removed replica from peers AND its follower state, so
                # the regular push fan-out will never tell it it left.
                # Re-insert the state transiently and ship the entry —
                # else the corpse keeps campaigning on a stale member
                # list forever.  Best effort: a dead replica that
                # rejoins later learns its removal from any survivor's
                # snapshot/entries.
                with self._state_lock:
                    self._followers.setdefault(addr, fs_removed)
                try:
                    for _ in range(3):
                        if self._push(addr):
                            break
                finally:
                    with self._state_lock:
                        self._followers.pop(addr, None)
            if self_removal:
                # The removal entry itself must reach every survivor
                # (not just a quorum) before the handoff: a survivor
                # elected without it would still count the corpse as a
                # voter.  Best effort within the deadline — quorum
                # already holds it, so a straggler catches up later.
                self._sync_survivors(survivors, timeout / 2,
                                     required=False)
                with self._state_lock:
                    self._el.step_down()
                log.info("%s removed itself; stepped down for the "
                         "survivor election", self.address)
            return {
                "removed": addr,
                "peers": peers,
                "handoff": self_removal,
                "remove_index": self._last_index,
            }
        finally:
            self._end_membership()

    def _sync_survivors(self, survivors: List[str], timeout: float,
                        required: bool = True) -> None:
        """Push until every survivor's confirmed cursor reaches our
        CURRENT tail; raise (``required``) or warn on the deadline."""
        deadline = time.monotonic() + timeout
        while True:
            with self._state_lock:
                target = self._last_index
            followers = self._followers
            lagging = [
                p for p in survivors
                if (fs := followers.get(p)) is None or fs.match < target
            ]
            if not lagging:
                return
            if time.monotonic() >= deadline:
                if required:
                    raise NoQuorum(
                        f"survivors {lagging} not caught up to index "
                        f"{target}; refusing the leader handoff")
                log.warning("handoff proceeding with lagging survivors "
                            "%s (quorum holds the entry)", lagging)
                return
            _futures.wait(
                [self._pool.submit(self._push, p) for p in lagging],
                timeout=4 * self._replicate_timeout,
            )
            time.sleep(min(0.02, self._config.heartbeat_interval))

    # ----------------------------------------------------- follower handlers

    def handle_replicate(self, request: dict) -> dict:
        try:
            compat.check(request, "replicate")
        except IncompatibleVersion as err:
            # Refuse cleanly: entries from a below-floor leader must
            # never be applied on a best-effort decode.  The reply
            # names both versions so the leader logs WHY.
            return {"ok": False, "incompatible": True,
                    "got": err.got, "min": err.floor,
                    "term": self._el.term if self._el else 0,
                    "last_index": self._last_index}
        with self._state_lock:
            if self._el is None or not self._el.observe_heartbeat(
                    request["term"], request["leader"]):
                return {"ok": False, "term": self._el.term if self._el else 0,
                        "last_index": self._last_index}
            if (self._virgin
                    or request["prev_index"] != self._last_index
                    or request["prev_term"] != self._last_term):
                return {"ok": False, "term": self._el.term,
                        "needs_snapshot": True, "last_index": self._last_index}
            for raw in request["entries"]:
                entry = LogEntry.from_wire(raw)
                self._apply_op(entry.op, entry.args)
                self._append(entry)
            # Leader-fed entries count toward this replica's rank.
            self._rank_index, self._rank_term = self._last_index, self._last_term
            return {"ok": True, "term": self._el.term,
                    "last_index": self._last_index,
                    "revision": self.store.revision}

    def handle_install_snapshot(self, request: dict) -> dict:
        try:
            compat.check(request, "install-snapshot")
        except IncompatibleVersion as err:
            return {"ok": False, "incompatible": True,
                    "got": err.got, "min": err.floor,
                    "term": self._el.term if self._el else 0}
        with self._state_lock:
            if self._el is None or not self._el.observe_heartbeat(
                    request["term"], request["leader"]):
                return {"ok": False, "term": self._el.term if self._el else 0}
            self.store.replace(request["snapshot"], request["revision"])
            self._log = []
            self._base_index = self._last_index = request["last_index"]
            self._base_term = self._last_term = request["last_term"]
            self._rank_index, self._rank_term = self._last_index, self._last_term
            self._virgin = False
            # Snapshots carry the voting member set (Raft's config-in-
            # snapshot): a membership entry compacted out of the log
            # must still reach a catching-up replica.  A learner not in
            # the list stays a learner — _removed is set ONLY by a
            # member-remove entry naming this replica, never by a list
            # it simply is not in yet.
            peers = request.get("peers")
            if peers:
                self.peers = sorted(str(p) for p in peers)
                if self.address in self.peers:
                    self.replica_id = self.peers.index(self.address)
                    self._el.replica_id = self.replica_id
            return {"ok": True, "term": self._el.term,
                    "last_index": self._last_index,
                    "revision": self.store.revision}

    # ------------------------------------------------------------- election

    def _tick_loop(self) -> None:
        while not self._stop_event.is_set():
            try:
                self._tick()
            except Exception:  # noqa: BLE001 - the loop must survive
                log.exception("ha tick failed on %s", self.address)
            self._stop_event.wait(self._config.heartbeat_interval)

    def _tick(self) -> None:
        with self._state_lock:
            role = self._el.role
            removed = self._removed
        if role is Role.LEADER:
            # A removed leader keeps leading until remove_replica's
            # orderly handoff steps it down explicitly — stopping here
            # would strand the removal commit mid-replication.
            self._lead()
        elif removed:
            return  # dormant: a removed replica never campaigns
        elif role is Role.FOLLOWER:
            if self._el.lease_expired():
                with self._state_lock:
                    self._el.start_campaign()
                self._campaign()
        else:
            self._campaign()

    def _lead(self) -> None:
        with self._state_lock:
            voters = [p for p in self.peers if p != self.address]
            learners = sorted(self._learners)
        others = voters + [a for a in learners if a not in voters]
        if others:
            # Bounded wait: a straggler (hung snapshot install, half-dead
            # peer) keeps running on its pool thread, but heartbeats to
            # the healthy followers — and catch-up pushes to learners —
            # must go out next tick regardless.
            _futures.wait(
                [self._pool.submit(self._push, p) for p in others],
                timeout=self._config.heartbeat_interval,
            )
        now = time.monotonic()
        # Lease freshness counts VOTERS only: a freshly-acking learner
        # must not keep a leader alive that lost its voting majority
        # (the not-yet-a-member-can't-vote invariant, lease edition).
        fresh = sum(
            1 for addr, fs in self._followers.items()
            if addr in voters
            and now - fs.acked_at < self._config.lease_timeout
        )
        with self._state_lock:
            if (1 + fresh) * 2 > len(self.peers):
                self._last_quorum_at = now
            elif now - self._last_quorum_at > self._config.lease_timeout:
                # Partitioned from the majority: writes already fail the
                # quorum gate; stepping down also fences lease reads.
                log.warning("%s: lost follower quorum, stepping down",
                            self.address)
                self._el.step_down()

    def _campaign(self) -> None:
        others = [p for p in self.peers if p != self.address]
        statuses: List[Optional[PeerStatus]] = []
        for resp in self._pool.map(
                lambda a: self._peer_call(a, "HaStatus", compat.stamp({})),
                others):
            statuses.append(None if resp is None else PeerStatus.from_dict(resp))
        with self._state_lock:
            role = self._el.decide(self._status_as_peer(), statuses,
                                   len(self.peers))
        if role is Role.LEADER:
            self._on_elected()

    def _on_elected(self) -> None:
        with self._state_lock:
            term = self._el.term
            self._el.leader = self.address
            self._virgin = False
            # Optimistic push cursors (Raft-style): in-sync followers
            # ack the first heartbeat untouched; stale ones reconcile
            # down to a snapshot install.  match starts at 0 — nothing
            # is quorum-countable until a follower actually responds.
            self._followers = {
                p: _FollowerState(next_index=self._last_index)
                for p in self.peers if p != self.address
            }
            self._last_quorum_at = time.monotonic()
        log.info("%s elected leader (term %d, log index %d)",
                 self.address, term, self._last_index)
        # Announce before anything else: the heartbeat freshens follower
        # leases so their own candidacies stand down.
        others = [p for p in self.peers if p != self.address]
        if others:
            _futures.wait(
                [self._pool.submit(self._push, p) for p in others],
                timeout=self._config.heartbeat_interval,
            )
        try:
            self.commit("put", {
                "key": ELECTION_KEY,
                "value": {"address": self.address, "term": term,
                          "replica_id": self.replica_id},
            })
        except (NotLeader, NoQuorum) as e:
            # Best-effort observability write; losing it changes nothing
            # (clients re-home on NOT_LEADER hints, not on this key).
            log.warning("election key write skipped: %s", e)


class ReplicaServer(KVStoreServer):
    """The gRPC surface of one HA replica: the standard KVStore service
    (leader-gated, writes through the replication commit) plus the
    replica-to-replica protocol (HaStatus / Replicate / InstallSnapshot)
    and the follower-readable LocalDump."""

    # The replica protocol answers version skew ITSELF with typed
    # `incompatible` replies (see handle_replicate) — the generic
    # aborting gate would make that path unreachable over the wire.
    SELF_VERSIONED = frozenset({"Replicate", "InstallSnapshot"})

    def __init__(self, replica: HAReplica, host: str = "127.0.0.1",
                 port: int = 0, max_watchers: int = 64):
        super().__init__(replica.store, host=host, port=port,
                         max_watchers=max_watchers)
        self.replica = replica

    # Leader gate for reads and watch registration/streaming.
    def _gate(self, context) -> None:
        self.replica.abort_if_not_leader(context)

    def _get(self, request: dict, context=None) -> dict:
        self._gate(context)
        return super()._get(request, context)

    def _list(self, request: dict, context=None) -> dict:
        self._gate(context)
        return super()._list(request, context)

    def _snapshot(self, request: dict, context=None) -> dict:
        self._gate(context)
        return super()._snapshot(request, context)

    def _revision(self, request: dict, context=None) -> dict:
        self._gate(context)
        return super()._revision(request, context)

    # Writes ride the replicated commit.
    def _commit(self, context, op: str, args: dict) -> Any:
        try:
            return self.replica.commit(op, args)
        except NotLeader as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          NOT_LEADER_PREFIX + e.leader)
        except NoQuorum as e:
            # ABORTED, not UNAVAILABLE: the op is INDETERMINATE (applied
            # locally, may still commit).  The client must not blindly
            # retry non-idempotent ops on it — see remote._rpc.
            context.abort(grpc.StatusCode.ABORTED, NO_QUORUM_PREFIX + str(e))

    def _put(self, request: dict, context=None) -> dict:
        return {"revision": self._commit(
            context, "put", {"key": request["key"], "value": request["value"]})}

    def _delete(self, request: dict, context=None) -> dict:
        return {"deleted": self._commit(
            context, "delete", {"key": request["key"]})}

    def _put_if_not_exists(self, request: dict, context=None) -> dict:
        return {"created": self._commit(
            context, "put_if_not_exists",
            {"key": request["key"], "value": request["value"]})}

    def _compare_and_delete(self, request: dict, context=None) -> dict:
        return {"deleted": self._commit(
            context, "compare_and_delete",
            {"key": request["key"], "expected": request["expected"]})}

    # Live membership change (ISSUE 13) — leader-gated like writes.
    def _membership(self, context, fn: Callable, addr: str,
                    timeout: float) -> dict:
        try:
            return fn(addr, timeout=timeout)
        except NotLeader as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          NOT_LEADER_PREFIX + e.leader)
        except MembershipChangeInProgress as e:
            context.abort(grpc.StatusCode.FAILED_PRECONDITION,
                          f"MEMBERSHIP_BUSY {e}")
        except CatchupTimeout as e:
            context.abort(grpc.StatusCode.ABORTED, f"CATCHUP_TIMEOUT {e}")
        except (NoQuorum, ValueError) as e:
            context.abort(grpc.StatusCode.ABORTED, str(e))

    def _add_replica(self, request: dict, context=None) -> dict:
        # The catch-up is bounded WELL inside the client's RPC deadline
        # so a timeout surfaces as a typed CATCHUP_TIMEOUT, not a
        # DEADLINE_EXCEEDED whose server half keeps running.
        return self._membership(context, self.replica.add_replica,
                                request["addr"],
                                float(request.get("timeout", 45.0)))

    def _remove_replica(self, request: dict, context=None) -> dict:
        return self._membership(context, self.replica.remove_replica,
                                request["addr"],
                                float(request.get("timeout", 45.0)))

    # Replica-to-replica protocol + follower-readable introspection.
    def _ha_status(self, request: dict, context=None) -> dict:
        return self.replica.status()

    def _replicate(self, request: dict, context=None) -> dict:
        return self.replica.handle_replicate(request)

    def _install_snapshot(self, request: dict, context=None) -> dict:
        return self.replica.handle_install_snapshot(request)

    def _local_dump(self, request: dict, context=None) -> dict:
        return {
            "items": self.store.list(request.get("prefix", "")),
            "revision": self.store.revision,
            "role": self.replica.role.value,
            "address": self.replica.address,
        }

    def _unary_handlers(self) -> Dict[str, Callable]:
        handlers = super()._unary_handlers()
        handlers.update({
            "HaStatus": self._ha_status,
            "Replicate": self._replicate,
            "InstallSnapshot": self._install_snapshot,
            "LocalDump": self._local_dump,
            "AddReplica": self._add_replica,
            "RemoveReplica": self._remove_replica,
        })
        return handlers


class HAEnsemble:
    """An in-process N-replica ensemble — the test/dev harness (the
    OS-process form is ``python -m vpp_tpu.kvstore --join ...``)."""

    def __init__(self, n: int = 3, host: str = "127.0.0.1",
                 heartbeat_interval: float = 0.05,
                 lease_timeout: float = 0.4, **replica_kw):
        self.heartbeat_interval = heartbeat_interval
        self.lease_timeout = lease_timeout
        self._replica_kw = replica_kw
        self._host = host
        self.replicas: List[HAReplica] = [
            HAReplica(host=host, heartbeat_interval=heartbeat_interval,
                      lease_timeout=lease_timeout, **replica_kw)
            for _ in range(n)
        ]
        self.addresses = [r.bind() for r in self.replicas]
        for r in self.replicas:
            r.join(list(self.addresses))

    def client(self, **kw) -> "RemoteKVStore":
        from .remote import RemoteKVStore

        return RemoteKVStore(",".join(self.addresses), **kw)

    def leader(self) -> Optional[HAReplica]:
        for r in self.replicas:
            if not r._stop_event.is_set() and r.is_leader:
                return r
        return None

    def wait_leader(self, timeout: float = 10.0) -> HAReplica:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            leader = self.leader()
            if leader is not None:
                return leader
            time.sleep(0.02)
        raise TimeoutError("no leader elected")

    def kill_leader(self) -> HAReplica:
        """SIGKILL-equivalent on the sitting leader; returns the corpse
        (its address stays in the ensemble for a later restart)."""
        leader = self.wait_leader()
        leader.kill()
        return leader

    def restart(self, address: str) -> HAReplica:
        """Bring a killed replica back on its old address (the rejoin /
        catch-up path)."""
        host, port = address.rsplit(":", 1)
        idx = self.addresses.index(address)
        replica = HAReplica(host=host, port=int(port), advertise=address,
                            heartbeat_interval=self.heartbeat_interval,
                            lease_timeout=self.lease_timeout,
                            **self._replica_kw)
        replica.bind()
        replica.join(list(self.addresses))
        self.replicas[idx] = replica
        return replica

    # ------------------------------------------- live membership (ISSUE 13)

    def grow(self, timeout: float = 30.0) -> HAReplica:
        """Add one BRAND-NEW empty replica to the running ensemble:
        bind it, join it (peers = current members + itself — it idles
        as a deferring candidate until the leader adopts it), then run
        the leader's learner catch-up + member-add protocol."""
        replica = HAReplica(host=self._host,
                            heartbeat_interval=self.heartbeat_interval,
                            lease_timeout=self.lease_timeout,
                            **self._replica_kw)
        addr = replica.bind()
        replica.join(sorted(self.addresses + [addr]))
        leader = self.wait_leader()
        leader.add_replica(addr, timeout=timeout)
        self.replicas.append(replica)
        self.addresses.append(addr)
        return replica

    def shrink(self, address: Optional[str] = None,
               timeout: float = 30.0) -> HAReplica:
        """Remove one member (default: the sitting LEADER — the orderly
        handoff path) and kill its process; returns the corpse."""
        leader = self.wait_leader()
        address = address or leader.address
        leader.remove_replica(address, timeout=timeout)
        idx = self.addresses.index(address)
        corpse = self.replicas[idx]
        corpse.kill()
        del self.replicas[idx]
        del self.addresses[idx]
        return corpse

    def stop(self) -> None:
        for r in self.replicas:
            r.kill()
