"""CRD data models.

Analogs of the reference's CRDs
(``plugins/crd/pkg/apis/{nodeconfig,telemetry}/v1/types.go``), plus the
reproduction-native inference policy:

- ``NodeConfig`` — per-node configuration overrides consumed by the
  config merge (file < NodeConfig CRD < STN-reported < runtime);
- ``TelemetryReport`` — the output of periodic cluster validation;
- ``InferPolicy`` — the in-network inference plane's policy surface
  (ISSUE 14): enable per-vector DNN scoring per namespace, bind score
  thresholds to log/deprioritize/quarantine actions, optionally ship
  model weights inline.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Tuple

from ..models.common import freeze_mapping


@dataclass(frozen=True)
class NodeInterfaceConfig:
    """One data-plane interface override (nodeconfig/v1 InterfaceConfig)."""

    name: str
    ip: str = ""                 # CIDR; empty = from IPAM arithmetic
    use_dhcp: bool = False


@dataclass(frozen=True)
class NodeConfig:
    """Per-node config override (nodeconfig/v1 NodeConfigSpec)."""

    name: str                     # node name (CRD object name)
    main_interface: NodeInterfaceConfig = NodeInterfaceConfig(name="")
    other_interfaces: Tuple[NodeInterfaceConfig, ...] = ()
    gateway: str = ""
    nat_external_traffic: bool = False
    stealth_interface: str = ""   # StealInterface (STN mode)


# InferPolicy (ISSUE 14) lives with the typed models — it is a
# REFLECTED resource (registry entry "inferpolicy": the CRD controller
# publishes validated specs into the store; every agent's DBWatcher
# delivers them as KubeStateChange events) — re-exported here beside
# the other CRD shapes.
from ..models.infer import InferPolicy  # noqa: F401  (re-export)


@dataclass(frozen=True)
class ValidationReport:
    """One validator's findings for one node (telemetry/v1 NodeReport)."""

    node: str
    category: str                 # "l2" | "l3" | ...
    errors: Tuple[str, ...] = ()

    @property
    def ok(self) -> bool:
        return not self.errors


@dataclass(frozen=True)
class NodeCollectionStatus:
    """Per-node collection lifecycle in a report: whether the latest
    crawl reached the agent, whether its data is retained-stale, and
    which collection revision produced the data."""

    node: str
    reachable: bool = True
    stale: bool = False
    data_revision: int = 0
    errors: Tuple[str, ...] = ()


@dataclass(frozen=True)
class TelemetryReport:
    """Cluster-wide validation outcome (telemetry/v1 TelemetryReport)."""

    revision: int = 0
    reports: Tuple[ValidationReport, ...] = ()
    nodes: Tuple[NodeCollectionStatus, ...] = ()

    @property
    def error_count(self) -> int:
        return sum(len(r.errors) for r in self.reports)

    def summary(self) -> Mapping[str, int]:
        per_category: dict = {}
        for r in self.reports:
            per_category[r.category] = per_category.get(r.category, 0) + len(r.errors)
        return freeze_mapping(per_category)
