"""CRD controllers — the informer + rate-limited-workqueue pattern.

Analog of the reference's CRD controllers
(``plugins/crd/controller/nodeconfig/node_config_controller.go:45-210``):
an informer (ListWatch subscription + object cache) enqueues keys into a
rate-limited work queue; a worker processes them, requeueing failures
with backoff up to ``maxRetries = 5`` before giving up (workqueue
Forget/NumRequeues/AddRateLimited semantics).
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading
import time
from typing import Callable, Dict, Optional

from ..ksr.listwatch import K8sListWatch
from .models import InferPolicy, NodeConfig, NodeInterfaceConfig
from .validator import validate_infer_policy

log = logging.getLogger(__name__)

MAX_RETRIES = 5  # node_config_controller.go:45


class WorkQueue:
    """Rate-limited work queue (client-go util/workqueue analog):
    de-duplicates queued items, tracks per-item requeue counts, and
    re-adds failures after an exponential delay."""

    def __init__(self, base_delay: float = 0.005, max_delay: float = 1.0):
        self._queue: "queue_mod.Queue[object]" = queue_mod.Queue()
        self._lock = threading.Lock()
        self._queued: set = set()
        self._active: set = set()       # popped, processing not finished
        self._backoff = 0               # items waiting in retry timers
        self._requeues: Dict[object, int] = {}
        self.base_delay = base_delay
        self.max_delay = max_delay

    def add(self, item) -> None:
        with self._lock:
            if item in self._queued:
                return
            self._queued.add(item)
        self._queue.put(item)

    def add_rate_limited(self, item) -> None:
        """Re-add after a backoff derived from the item's requeue count."""
        with self._lock:
            self._requeues[item] = self._requeues.get(item, 0) + 1
            self._backoff += 1
            delay = min(
                self.base_delay * (2 ** (self._requeues[item] - 1)),
                self.max_delay,
            )

        def fire():
            with self._lock:
                self._backoff -= 1
            self.add(item)

        timer = threading.Timer(delay, fire)
        timer.daemon = True
        timer.start()

    def num_requeues(self, item) -> int:
        with self._lock:
            return self._requeues.get(item, 0)

    def forget(self, item) -> None:
        with self._lock:
            self._requeues.pop(item, None)

    def get(self, timeout: float = 0.1):
        """Pop the next item; it stays "active" until done(item)."""
        try:
            item = self._queue.get(timeout=timeout)
        except queue_mod.Empty:
            return None
        with self._lock:
            self._queued.discard(item)
            self._active.add(item)
        return item

    def done(self, item) -> None:
        with self._lock:
            self._active.discard(item)

    def idle(self) -> bool:
        with self._lock:
            return not self._queued and not self._active and self._backoff == 0


class CrdController:
    """One CRD kind: informer cache + work queue + worker."""

    def __init__(
        self,
        kind: str,
        list_watch: K8sListWatch,
        process: Callable[[str, Optional[Dict]], None],
        max_retries: int = MAX_RETRIES,
        base_delay: float = 0.005,
    ):
        self.kind = kind
        self.list_watch = list_watch
        self.process = process
        self.max_retries = max_retries
        self.queue = WorkQueue(base_delay=base_delay)
        self._objects: Dict[str, Dict] = {}  # informer cache: key -> object
        self._lock = threading.Lock()
        self._synced = False
        self._stop = threading.Event()
        self._worker: Optional[threading.Thread] = None
        self.processed = 0
        self.dropped = 0  # items that exhausted their retries

    @staticmethod
    def _key(obj: Dict) -> str:
        meta = obj.get("metadata", {})
        ns = meta.get("namespace", "")
        name = meta.get("name", "")
        return f"{ns}/{name}" if ns else name

    # ------------------------------------------------------------- informer

    def _on_change(self, event: str, obj: Dict, old_obj: Optional[Dict]) -> None:
        key = self._key(obj)
        if not key:
            return
        with self._lock:
            if event == "delete":
                self._objects.pop(key, None)
            else:
                self._objects[key] = obj
        self.queue.add(key)

    def has_synced(self) -> bool:
        return self._synced

    # ------------------------------------------------------------ lifecycle

    def start(self) -> None:
        self.list_watch.subscribe(self.kind, self._on_change)
        for obj in self.list_watch.list(self.kind):
            key = self._key(obj)
            if key:
                with self._lock:
                    self._objects[key] = obj
                self.queue.add(key)
        self._synced = True
        self._worker = threading.Thread(
            target=self._run, name=f"crd-{self.kind}", daemon=True
        )
        self._worker.start()

    def stop(self) -> None:
        self._stop.set()
        unsubscribe = getattr(self.list_watch, "unsubscribe", None)
        if unsubscribe is not None:
            unsubscribe(self.kind, self._on_change)
        if self._worker is not None:
            self._worker.join(timeout=2)

    # --------------------------------------------------------------- worker

    def _run(self) -> None:
        while not self._stop.is_set():
            key = self.queue.get(timeout=0.1)
            if key is None:
                continue
            with self._lock:
                obj = self._objects.get(key)  # None = deleted
            try:
                self.process(key, obj)
            except Exception as e:  # noqa: BLE001 - retried with backoff
                if self.queue.num_requeues(key) < self.max_retries:
                    log.warning("crd %s: processing %s failed (%s); requeueing",
                                self.kind, key, e)
                    self.queue.add_rate_limited(key)
                else:
                    log.error("crd %s: giving up on %s after %d retries: %s",
                              self.kind, key, self.max_retries, e)
                    self.queue.forget(key)
                    self.dropped += 1
            else:
                self.queue.forget(key)
                self.processed += 1
            finally:
                self.queue.done(key)

    def wait_idle(self, timeout: float = 5.0) -> bool:
        """Wait until nothing is queued, processing, or in retry backoff."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            if self.queue.idle():
                return True
            time.sleep(0.01)
        return False


# ----------------------------------------------------------- NodeConfig CRD


def parse_node_config(name: str, obj: Optional[Dict]) -> Optional[NodeConfig]:
    """nodeconfig/v1 NodeConfigSpec JSON → NodeConfig model
    (pkg/apis/nodeconfig/v1/types.go:44-56 field names)."""
    if obj is None:
        return None
    spec = obj.get("spec", {}) or {}

    def iface(d: Dict) -> NodeInterfaceConfig:
        return NodeInterfaceConfig(
            name=d.get("interfaceName", ""),
            ip=d.get("ip", ""),
            use_dhcp=bool(d.get("useDHCP", False)),
        )

    return NodeConfig(
        name=name,
        main_interface=iface(spec.get("mainVPPInterface", {}) or {}),
        other_interfaces=tuple(
            iface(d) for d in spec.get("otherVPPInterfaces", []) or []
        ),
        gateway=spec.get("gateway", ""),
        nat_external_traffic=bool(spec.get("natExternalTraffic", False)),
        stealth_interface=spec.get("stealInterface", ""),
    )


def make_node_config_controller(
    list_watch: K8sListWatch, crd_plugin, kind: str = "nodeconfigs",
) -> CrdController:
    """The NodeConfig controller: CRD objects → parse → CRDPlugin (store
    publish + NodeConfigChange events)."""

    def process(key: str, obj: Optional[Dict]) -> None:
        name = key.rsplit("/", 1)[-1]
        config = parse_node_config(name, obj)
        if config is None:
            crd_plugin.delete_node_config(name)
        else:
            crd_plugin.apply_node_config(config)

    return CrdController(kind, list_watch, process)


# ---------------------------------------------------------- InferPolicy CRD


def parse_infer_policy(name: str, obj: Optional[Dict]) -> Optional[InferPolicy]:
    """inferpolicy/v1 spec JSON → InferPolicy model (ISSUE 14).  The
    spec is VALIDATED first — an invalid object raises ValueError (the
    work queue retries then drops it; a typo'd action must never reach
    the device compiler)."""
    if obj is None:
        return None
    spec = obj.get("spec", {}) or {}
    errors = validate_infer_policy(spec)
    if errors:
        raise ValueError(
            f"invalid InferPolicy {name!r}: " + "; ".join(errors))
    model = spec.get("model")
    return InferPolicy(
        name=name,
        namespaces=tuple(spec.get("namespaces") or ()),
        threshold=int(spec.get("threshold", 6)),
        action=spec.get("action", "log"),
        enabled=bool(spec.get("enabled", True)),
        model=dict(model) if model is not None else None,
    )


def make_infer_policy_controller(
    list_watch: K8sListWatch, crd_plugin, kind: str = "inferpolicies",
) -> CrdController:
    """The InferPolicy controller: CRD objects → validate + parse →
    CRDPlugin (store publish + InferPolicyChange events, consumed by
    the InferencePlugin's render path)."""

    def process(key: str, obj: Optional[Dict]) -> None:
        name = key.rsplit("/", 1)[-1]
        policy = parse_infer_policy(name, obj)
        if policy is None:
            crd_plugin.delete_infer_policy(name)
        else:
            crd_plugin.apply_infer_policy(policy)

    return CrdController(kind, list_watch, process)
