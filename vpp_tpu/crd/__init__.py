"""CRD plugin: NodeConfig + InferPolicy + TelemetryReport, cluster-wide
validation."""

from .models import (
    InferPolicy,
    NodeConfig,
    NodeInterfaceConfig,
    TelemetryReport,
    ValidationReport,
)
from .telemetry import NodeSnapshot, TelemetryCache
from .validator import L2Validator, L3Validator, validate_infer_policy
from .plugin import CRDPlugin, InferPolicyChange, NodeConfigChange

__all__ = [
    "CRDPlugin",
    "InferPolicy",
    "InferPolicyChange",
    "L2Validator",
    "L3Validator",
    "NodeConfig",
    "NodeConfigChange",
    "NodeInterfaceConfig",
    "NodeSnapshot",
    "TelemetryCache",
    "TelemetryReport",
    "ValidationReport",
    "validate_infer_policy",
]
