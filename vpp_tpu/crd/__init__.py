"""CRD plugin: NodeConfig + TelemetryReport, cluster-wide validation."""

from .models import NodeConfig, NodeInterfaceConfig, TelemetryReport, ValidationReport
from .telemetry import NodeSnapshot, TelemetryCache
from .validator import L2Validator, L3Validator
from .plugin import CRDPlugin, NodeConfigChange

__all__ = [
    "CRDPlugin",
    "L2Validator",
    "L3Validator",
    "NodeConfig",
    "NodeConfigChange",
    "NodeInterfaceConfig",
    "NodeSnapshot",
    "TelemetryCache",
    "TelemetryReport",
    "ValidationReport",
]
