"""Telemetry cache — cluster-wide state collection with lifecycle.

Analog of ``plugins/crd/cache/telemetry_cache.go`` (:109-515): on every
collection cycle each agent's REST API is crawled (``collectAgentInfo``
:257 — ipam, scheduler dump, node/pod registries, plus the live
datapath introspection when present) and the snapshots are handed to
the validators (``validateCluster`` :229).

Report LIFECYCLE (VERDICT r4 item 9, matching the reference's cache):

- snapshots update IN PLACE each cycle, tagged with the collection
  revision that produced them;
- an UNREACHABLE node keeps its last-good data, marked ``stale`` with
  the current cycle's errors — the reference's cache likewise retains
  a node's report until the node returns or departs (a down agent is
  a finding, not a blank);
- a DEPARTED node (gone from the agent set, which the plugin prunes
  from the cluster store's VppNode registry) is removed outright.

The HTTP fetch is injectable so tests can wire snapshots directly (the
reference tests use datastore fixtures the same way).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)


@dataclass
class NodeSnapshot:
    """Everything collected from one agent (vpp_data_store analog)."""

    name: str
    ipam: Dict[str, Any] = field(default_factory=dict)
    dump: List[Dict[str, Any]] = field(default_factory=list)  # scheduler dump
    nodes: List[Dict[str, Any]] = field(default_factory=list)
    pods: List[Dict[str, Any]] = field(default_factory=list)
    # Live datapath introspection (/contiv/v1/inspect) — optional: an
    # agent without an attached datapath serves 404 here, which is not
    # a collection failure.
    datapath: Dict[str, Any] = field(default_factory=dict)
    errors: List[str] = field(default_factory=list)  # collection failures
    # Lifecycle: the collection cycle whose data this is, and whether
    # the node was unreachable in the LATEST cycle (data retained).
    revision: int = 0
    stale: bool = False

    # -------------------------------------------------------- dump helpers

    def applied(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        """key -> applied value for all APPLIED dump entries under prefix."""
        out = {}
        for v in self.dump:
            if v.get("state") == "APPLIED" and v.get("key", "").startswith(prefix):
                out[v["key"]] = v.get("applied") or {}
        return out


def _http_fetch(server: str, path: str) -> Any:
    with urllib.request.urlopen(f"http://{server}{path}", timeout=10) as resp:
        return json.loads(resp.read().decode())


_REQUIRED = (
    ("ipam", "/contiv/v1/ipam"),
    ("dump", "/scheduler/dump"),
    ("nodes", "/contiv/v1/nodes"),
    ("pods", "/contiv/v1/pods"),
)
_OPTIONAL = (
    ("datapath", "/contiv/v1/inspect"),
)


def _endpoint_absent(err: Exception) -> bool:
    """True when an OPTIONAL endpoint simply does not exist on this
    agent (no datapath attached → 404) — the only failure an optional
    fetch may swallow; a 500/timeout on a PRESENT endpoint is a finding
    like any other."""
    import urllib.error

    if isinstance(err, FileNotFoundError):
        return True
    return isinstance(err, urllib.error.HTTPError) and err.code == 404


class TelemetryCache:
    """Collects per-node snapshots from agent REST endpoints, with
    update-in-place / retain-stale / prune-departed lifecycle."""

    def __init__(self, fetch: Optional[Callable[[str, str], Any]] = None):
        self.fetch = fetch if fetch is not None else _http_fetch
        self.snapshots: Dict[str, NodeSnapshot] = {}
        self.revision = 0

    def collect(self, agents: Dict[str, str]) -> Dict[str, NodeSnapshot]:
        """One crawl of every agent (name -> "host:port").  Collection
        failures are recorded per node, never raised (a down node is a
        finding); see the module docstring for the lifecycle rules."""
        self.revision += 1
        for name, server in sorted(agents.items()):
            snap = NodeSnapshot(name=name, revision=self.revision)
            for attr, path in _REQUIRED:
                try:
                    setattr(snap, attr, self.fetch(server, path))
                except Exception as err:  # noqa: BLE001
                    snap.errors.append(f"collecting {path}: {err}")
            for attr, path in _OPTIONAL:
                try:
                    setattr(snap, attr, self.fetch(server, path))
                except Exception as err:  # noqa: BLE001
                    if not _endpoint_absent(err):
                        snap.errors.append(f"collecting {path}: {err}")
            prev = self.snapshots.get(name)
            if not snap.errors or prev is None:
                # A fresh, fully-collected snapshot is authoritative
                # (constructed stale=False).
                self.snapshots[name] = snap
            else:
                # Unreachable (or partially failed) with history: keep
                # the last-good data, surface THIS cycle's errors.
                prev.stale = True
                prev.errors = snap.errors
        # Departed nodes: prune outright.
        for name in set(self.snapshots) - set(agents):
            del self.snapshots[name]
        return self.snapshots
