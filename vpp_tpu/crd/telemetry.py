"""Telemetry cache — cluster-wide state collection.

Analog of ``plugins/crd/cache/telemetry_cache.go`` (:109-515): on every
collection cycle each agent's REST API is crawled (``collectAgentInfo``
:257 — ipam, scheduler dump, node/pod registries) and the snapshots are
handed to the validators (``validateCluster`` :229).

The HTTP fetch is injectable so tests can wire snapshots directly (the
reference tests use datastore fixtures the same way).
"""

from __future__ import annotations

import json
import logging
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

log = logging.getLogger(__name__)


@dataclass
class NodeSnapshot:
    """Everything collected from one agent (vpp_data_store analog)."""

    name: str
    ipam: Dict[str, Any] = field(default_factory=dict)
    dump: List[Dict[str, Any]] = field(default_factory=list)  # scheduler dump
    nodes: List[Dict[str, Any]] = field(default_factory=list)
    pods: List[Dict[str, Any]] = field(default_factory=list)
    errors: List[str] = field(default_factory=list)  # collection failures

    # -------------------------------------------------------- dump helpers

    def applied(self, prefix: str) -> Dict[str, Dict[str, Any]]:
        """key -> applied value for all APPLIED dump entries under prefix."""
        out = {}
        for v in self.dump:
            if v.get("state") == "APPLIED" and v.get("key", "").startswith(prefix):
                out[v["key"]] = v.get("applied") or {}
        return out


def _http_fetch(server: str, path: str) -> Any:
    with urllib.request.urlopen(f"http://{server}{path}", timeout=10) as resp:
        return json.loads(resp.read().decode())


class TelemetryCache:
    """Collects per-node snapshots from agent REST endpoints."""

    def __init__(self, fetch: Optional[Callable[[str, str], Any]] = None):
        self.fetch = fetch if fetch is not None else _http_fetch
        self.snapshots: Dict[str, NodeSnapshot] = {}

    def collect(self, agents: Dict[str, str]) -> Dict[str, NodeSnapshot]:
        """Crawl every agent (name -> "host:port"); collection failures
        are recorded per node, not raised (a down node is a finding)."""
        self.snapshots = {}
        for name, server in sorted(agents.items()):
            snap = NodeSnapshot(name=name)
            for attr, path in (
                ("ipam", "/contiv/v1/ipam"),
                ("dump", "/scheduler/dump"),
                ("nodes", "/contiv/v1/nodes"),
                ("pods", "/contiv/v1/pods"),
            ):
                try:
                    setattr(snap, attr, self.fetch(server, path))
                except Exception as err:  # noqa: BLE001
                    snap.errors.append(f"collecting {path}: {err}")
            self.snapshots[name] = snap
        return self.snapshots
