"""Cluster-wide L2/L3 validators.

Analogs of ``plugins/crd/validator/l2/l2_validator.go`` (:49 — ARP/BD/
L2FIB cross-node checks) and ``validator/l3/l3_validator.go`` (:78 —
VRF route checks), operating on the telemetry snapshots.

The checks are *cross-node consistency* invariants of the full-mesh
overlay (SURVEY.md §2.4): every node must have exactly one vxlan bridge
domain with a BVI, one vxlan tunnel + L2FIB + ARP entry per other node
— and the MAC/IP in node A's entries for node B must match what node B
itself configured.  L3: a route to every other node's pod subnet, and a
/32 + TAP pair for every locally allocated pod IP.
"""

from __future__ import annotations

from typing import Dict, List

from ..ipv4net.model import (
    ARP_PREFIX,
    BD_PREFIX,
    IF_PREFIX,
    L2FIB_PREFIX,
    ROUTE_PREFIX,
)
from ..ipv4net.plugin import VXLAN_BD_NAME, VXLAN_BVI_NAME
from .models import ValidationReport
from .telemetry import NodeSnapshot


def _node_id(snap: NodeSnapshot) -> int:
    return int(snap.ipam.get("nodeId", 0))


def _bvi_iface(snap: NodeSnapshot) -> Dict:
    return snap.applied(IF_PREFIX).get(IF_PREFIX + VXLAN_BVI_NAME, {})


class L2Validator:
    """Bridge-domain / VXLAN / L2FIB / ARP mesh validation."""

    category = "l2"

    def validate(self, snapshots: Dict[str, NodeSnapshot]) -> List[ValidationReport]:
        reports = []
        for name, snap in sorted(snapshots.items()):
            errors: List[str] = list(snap.errors)
            if not snap.errors:
                errors += self._validate_node(snap, snapshots)
            reports.append(ValidationReport(node=name, category=self.category,
                                            errors=tuple(errors)))
        return reports

    def _validate_node(self, snap: NodeSnapshot,
                       all_snaps: Dict[str, NodeSnapshot]) -> List[str]:
        errors: List[str] = []
        ifaces = snap.applied(IF_PREFIX)
        bds = snap.applied(BD_PREFIX)
        fibs = snap.applied(L2FIB_PREFIX)
        arps = snap.applied(ARP_PREFIX)

        # Exactly one vxlan BD, with the BVI attached (l2_validator.go :166).
        bd = bds.get(BD_PREFIX + VXLAN_BD_NAME)
        if bd is None or len(bds) != 1:
            errors.append(f"expected exactly one bridge domain {VXLAN_BD_NAME!r}, "
                          f"have {sorted(bds)}")
            return errors
        if bd.get("bvi_interface") != VXLAN_BVI_NAME:
            errors.append(f"bridge domain BVI is {bd.get('bvi_interface')!r}, "
                          f"expected {VXLAN_BVI_NAME!r}")

        others = {n: s for n, s in all_snaps.items()
                  if n != snap.name and not s.errors}
        for other_name, other in sorted(others.items()):
            oid = _node_id(other)
            vxlan_name = f"vxlan{oid}"
            # Tunnel interface present, pointing at the other node's IP
            # (vxlanIfToOtherNode analog).
            tunnel = ifaces.get(IF_PREFIX + vxlan_name)
            if tunnel is None:
                errors.append(f"missing vxlan tunnel to node {other_name} (id {oid})")
                continue
            expect_dst = other.ipam.get("nodeIP", "")
            if tunnel.get("vxlan_dst") != expect_dst:
                errors.append(
                    f"vxlan{oid} dst {tunnel.get('vxlan_dst')} != node "
                    f"{other_name} IP {expect_dst}")
            if vxlan_name not in tuple(bd.get("interfaces", ())):
                errors.append(f"vxlan{oid} not attached to {VXLAN_BD_NAME}")

            # The other node's BVI identity, as IT configured it.
            other_bvi = _bvi_iface(other)
            other_mac = other_bvi.get("physical_address", "")
            other_ips = other_bvi.get("ip_addresses") or []
            other_ip = str(other_ips[0]).split("/")[0] if other_ips else ""

            # L2FIB entry for the other node's BVI MAC via the tunnel
            # (ValidateL2FibEntries :441 remote-entry check).
            fib = fibs.get(f"{L2FIB_PREFIX}{VXLAN_BD_NAME}/{other_mac}")
            if fib is None:
                errors.append(f"missing L2FIB entry for node {other_name} "
                              f"BVI MAC {other_mac}")
            elif fib.get("outgoing_interface") != vxlan_name:
                errors.append(f"L2FIB for {other_name} exits "
                              f"{fib.get('outgoing_interface')}, expected {vxlan_name}")

            # ARP entry binding the other BVI IP to its MAC
            # (ValidateArpTables cross-node check).
            arp = arps.get(f"{ARP_PREFIX}{VXLAN_BVI_NAME}/{other_ip}")
            if arp is None:
                errors.append(f"missing ARP for node {other_name} BVI IP {other_ip}")
            elif arp.get("physical_address") != other_mac:
                errors.append(
                    f"ARP MAC for {other_name} is {arp.get('physical_address')}, "
                    f"node itself uses {other_mac}")

        # K8s view vs collected view (ValidateK8sNodeInfo :525).
        known = {n.get("name") for n in snap.nodes}
        expected = set(all_snaps)
        if not expected <= known:
            errors.append(f"node registry out of sync: missing {sorted(expected - known)}")
        return errors


class L3Validator:
    """VRF route validation (routes to remote subnets + local pod /32s)."""

    category = "l3"

    def validate(self, snapshots: Dict[str, NodeSnapshot]) -> List[ValidationReport]:
        reports = []
        for name, snap in sorted(snapshots.items()):
            errors: List[str] = list(snap.errors)
            if not snap.errors:
                errors += self._validate_node(snap, snapshots)
            reports.append(ValidationReport(node=name, category=self.category,
                                            errors=tuple(errors)))
        return reports

    def _validate_node(self, snap: NodeSnapshot,
                       all_snaps: Dict[str, NodeSnapshot]) -> List[str]:
        errors: List[str] = []
        routes = snap.applied(ROUTE_PREFIX)
        route_dsts = {r.get("dst_network") for r in routes.values()}
        ifaces = snap.applied(IF_PREFIX)

        # Route to every other node's pod subnet (l3_validator.go remote
        # pod-subnet route check).
        for other_name, other in sorted(all_snaps.items()):
            if other_name == snap.name or other.errors:
                continue
            subnet = other.ipam.get("podSubnetThisNode", "")
            if subnet and subnet not in route_dsts:
                errors.append(f"no route to node {other_name} pod subnet {subnet}")

        # Every locally allocated pod IP has a /32 route and a TAP
        # (ValidatePodInfo analog).
        for pod, ip in sorted((snap.ipam.get("allocatedPodIPs") or {}).items()):
            if f"{ip}/32" not in route_dsts:
                errors.append(f"no /32 route for pod {pod} ({ip})")
            ns, _, pname = pod.partition("/")
            tap_key = IF_PREFIX + f"tap-{ns}-{pname}"
            if tap_key not in ifaces:
                errors.append(f"no TAP interface for pod {pod}")
        return errors
