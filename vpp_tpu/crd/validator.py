"""Cluster-wide L2/L3 validators.

Analogs of ``plugins/crd/validator/l2/l2_validator.go`` (:49 — ARP/BD/
L2FIB cross-node checks) and ``validator/l3/l3_validator.go`` (:78 —
VRF route checks), operating on the telemetry snapshots.

The checks are *cross-node consistency* invariants of the full-mesh
overlay (SURVEY.md §2.4): every node must have exactly one vxlan bridge
domain with a BVI, one vxlan tunnel + L2FIB + ARP entry per other node
— and the MAC/IP in node A's entries for node B must match what node B
itself configured.  L3: a route to every other node's pod subnet, and a
/32 + TAP pair for every locally allocated pod IP.
"""

from __future__ import annotations

from typing import Dict, List

from ..ipv4net.model import (
    ARP_PREFIX,
    BD_PREFIX,
    IF_PREFIX,
    L2FIB_PREFIX,
    ROUTE_PREFIX,
)
from ..ipv4net.plugin import VXLAN_BD_NAME, VXLAN_BVI_NAME, VXLAN_VNI
from .models import ValidationReport
from .telemetry import NodeSnapshot

# ----------------------------------------------------- InferPolicy spec

# Valid action verbs of the in-network inference plane (ISSUE 14) and
# the band range the packed verdict word can carry (3 bits).
INFER_ACTIONS = ("log", "deprioritize", "quarantine")
INFER_BAND_MAX = 7
# ops.infer.INFER_FEATURES, kept literal: this validator must stay
# importable without jax (it runs in the CRD controller of
# datapath-less agents); a test pins it against the ops constant.
_INFER_FEATURE_ROWS = 16


def validate_infer_policy(spec) -> List[str]:
    """Spec-level validation of one InferPolicy CRD object (the admission
    role of the reference's CRD validation webhooks): returns a list of
    human-readable errors, empty when the spec is deployable.  The CRD
    controller refuses to apply a failing spec — a typo'd action or a
    ragged weight matrix must never reach the device compiler."""
    errors: List[str] = []
    if not isinstance(spec, dict):
        return [f"spec must be an object, got {type(spec).__name__}"]
    namespaces = spec.get("namespaces")
    if not isinstance(namespaces, (list, tuple)) or not namespaces or \
            not all(isinstance(ns, str) and ns for ns in namespaces):
        errors.append("namespaces must be a non-empty list of namespace "
                      "names")
    threshold = spec.get("threshold", 6)
    if not isinstance(threshold, int) or isinstance(threshold, bool) or \
            not 0 <= threshold <= INFER_BAND_MAX:
        errors.append(
            f"threshold must be a score band 0..{INFER_BAND_MAX}, "
            f"got {threshold!r}")
    action = spec.get("action", "log")
    if action not in INFER_ACTIONS:
        errors.append(
            f"action must be one of {', '.join(INFER_ACTIONS)}, "
            f"got {action!r}")
    model = spec.get("model")
    if model is not None:
        errors.extend(_validate_model(model))
    return errors


def _validate_model(model) -> List[str]:
    if not isinstance(model, dict):
        return [f"model must be an object, got {type(model).__name__}"]
    errors: List[str] = []
    for field_name in ("w1", "b1", "w2", "b2"):
        if field_name not in model:
            errors.append(f"model.{field_name} is required")
    if errors:
        return errors
    w1, b1, w2, b2 = model["w1"], model["b1"], model["w2"], model["b2"]
    if not isinstance(w1, (list, tuple)) or \
            len(w1) != _INFER_FEATURE_ROWS or \
            not all(isinstance(r, (list, tuple)) for r in w1):
        return [f"model.w1 must be a {_INFER_FEATURE_ROWS}-row matrix "
                "(one row per datapath feature)"]
    widths = {len(r) for r in w1}
    if len(widths) != 1:
        return ["model.w1 rows are ragged"]
    hidden = widths.pop()
    if hidden < 1:
        return ["model.w1 must have at least one hidden column"]
    for name, vec in (("b1", b1), ("w2", w2)):
        if not isinstance(vec, (list, tuple)) or len(vec) != hidden:
            errors.append(
                f"model.{name} must be a vector of the hidden width "
                f"({hidden})")
    flat = [x for r in w1 for x in r]
    for name, values in (("w1", flat), ("b1", b1), ("w2", w2),
                         ("b2", [b2])):
        if isinstance(values, (list, tuple)) and not all(
                isinstance(x, (int, float)) and not isinstance(x, bool)
                and x == x and abs(x) != float("inf") for x in values):
            errors.append(f"model.{name} must contain finite numbers")
    return errors


def _node_id(snap: NodeSnapshot) -> int:
    return int(snap.ipam.get("nodeId", 0))


def _bvi_iface(snap: NodeSnapshot) -> Dict:
    return snap.applied(IF_PREFIX).get(IF_PREFIX + VXLAN_BVI_NAME, {})


class L2Validator:
    """Bridge-domain / VXLAN / L2FIB / ARP mesh validation."""

    category = "l2"

    def validate(self, snapshots: Dict[str, NodeSnapshot]) -> List[ValidationReport]:
        reports = []
        for name, snap in sorted(snapshots.items()):
            errors: List[str] = list(snap.errors)
            if not snap.errors:
                errors += self._validate_node(snap, snapshots)
            reports.append(ValidationReport(node=name, category=self.category,
                                            errors=tuple(errors)))
        return reports

    def _validate_node(self, snap: NodeSnapshot,
                       all_snaps: Dict[str, NodeSnapshot]) -> List[str]:
        errors: List[str] = []
        ifaces = snap.applied(IF_PREFIX)
        bds = snap.applied(BD_PREFIX)
        fibs = snap.applied(L2FIB_PREFIX)
        arps = snap.applied(ARP_PREFIX)

        # Exactly one vxlan BD, with the BVI attached (l2_validator.go :166).
        bd = bds.get(BD_PREFIX + VXLAN_BD_NAME)
        if bd is None or len(bds) != 1:
            errors.append(f"expected exactly one bridge domain {VXLAN_BD_NAME!r}, "
                          f"have {sorted(bds)}")
            return errors
        if bd.get("bvi_interface") != VXLAN_BVI_NAME:
            errors.append(f"bridge domain BVI is {bd.get('bvi_interface')!r}, "
                          f"expected {VXLAN_BVI_NAME!r}")

        # Identity maps for the mark-and-sweep passes: every node's BVI
        # MAC and IP, as each node itself configured them.
        mac_to_node: Dict[str, str] = {}
        ip_to_node: Dict[str, str] = {}
        for node_name, other in all_snaps.items():
            if other.errors:
                continue
            bvi = _bvi_iface(other)
            mac = bvi.get("physical_address", "")
            ips = bvi.get("ip_addresses") or []
            if mac:
                mac_to_node[mac] = node_name
            if ips:
                ip_to_node[str(ips[0]).split("/")[0]] = node_name

        this_ip = snap.ipam.get("nodeIP", "")
        others = {n: s for n, s in all_snaps.items()
                  if n != snap.name and not s.errors}
        for other_name, other in sorted(others.items()):
            oid = _node_id(other)
            vxlan_name = f"vxlan{oid}"
            # Tunnel interface present, pointing at the other node's IP
            # (vxlanIfToOtherNode analog).
            tunnel = ifaces.get(IF_PREFIX + vxlan_name)
            if tunnel is None:
                errors.append(f"missing vxlan tunnel to node {other_name} (id {oid})")
                continue
            expect_dst = other.ipam.get("nodeIP", "")
            if tunnel.get("vxlan_dst") != expect_dst:
                errors.append(
                    f"vxlan{oid} dst {tunnel.get('vxlan_dst')} != node "
                    f"{other_name} IP {expect_dst}")
            # VNI + source checks (ValidateBridgeDomains :247 VNI, :258
            # src-address checks); fields default-pass when a snapshot
            # predates them.
            vni = tunnel.get("vxlan_vni", VXLAN_VNI)
            if vni != VXLAN_VNI:
                errors.append(f"invalid VNI for {vxlan_name}: got {vni}, "
                              f"expected {VXLAN_VNI}")
            src = tunnel.get("vxlan_src", this_ip)
            if this_ip and src != this_ip:
                errors.append(f"{vxlan_name} src {src} is not this node's "
                              f"IP {this_ip}")
            if vxlan_name not in tuple(bd.get("interfaces", ())):
                errors.append(f"vxlan{oid} not attached to {VXLAN_BD_NAME}")

            # The other node's BVI identity, as IT configured it.
            other_bvi = _bvi_iface(other)
            other_mac = other_bvi.get("physical_address", "")
            other_ips = other_bvi.get("ip_addresses") or []
            other_ip = str(other_ips[0]).split("/")[0] if other_ips else ""

            # L2FIB entry for the other node's BVI MAC via the tunnel
            # (ValidateL2FibEntries :441 remote-entry check).
            fib = fibs.get(f"{L2FIB_PREFIX}{VXLAN_BD_NAME}/{other_mac}")
            if fib is None:
                errors.append(f"missing L2FIB entry for node {other_name} "
                              f"BVI MAC {other_mac}")
            elif fib.get("outgoing_interface") != vxlan_name:
                errors.append(f"L2FIB for {other_name} exits "
                              f"{fib.get('outgoing_interface')}, expected {vxlan_name}")

            # ARP entry binding the other BVI IP to its MAC
            # (ValidateArpTables cross-node check).
            arp = arps.get(f"{ARP_PREFIX}{VXLAN_BVI_NAME}/{other_ip}")
            if arp is None:
                errors.append(f"missing ARP for node {other_name} BVI IP {other_ip}")
            elif arp.get("physical_address") != other_mac:
                errors.append(
                    f"ARP MAC for {other_name} is {arp.get('physical_address')}, "
                    f"node itself uses {other_mac}")

        # Dangling-entry sweeps (the reference's mark-and-sweep passes).
        #
        # L2FIB entries in the vxlan BD whose MAC belongs to NO live
        # node's BVI are stale state from departed/renumbered nodes
        # (ValidateL2FibEntries :514 "dangling L2Fib entry").
        for key, fib in sorted(fibs.items()):
            if not key.startswith(f"{L2FIB_PREFIX}{VXLAN_BD_NAME}/"):
                continue
            mac = key.rsplit("/", 1)[1]
            if mac not in mac_to_node:
                errors.append(
                    f"dangling L2FIB entry {VXLAN_BD_NAME}/{mac} - "
                    f"no node for entry found")
            else:
                # The exit tunnel must lead to the node owning the MAC.
                out_if = fib.get("outgoing_interface", "")
                tun = ifaces.get(IF_PREFIX + out_if)
                if tun is not None and "vxlan_dst" in tun:
                    owner = mac_to_node[mac]
                    owner_ip = all_snaps[owner].ipam.get("nodeIP", "")
                    if tun["vxlan_dst"] != owner_ip:
                        errors.append(
                            f"L2FIB entry {VXLAN_BD_NAME}/{mac}: exit tunnel "
                            f"{out_if} leads to {tun['vxlan_dst']}, but the "
                            f"MAC belongs to node {owner} ({owner_ip})")

        # ARP entries on the BVI whose IP/MAC map to no node, or to
        # DIFFERENT nodes (ValidateArpTables :126 "MAC -> node X,
        # IP -> node Y" and the stale-entry detection :62).
        for key, arp in sorted(arps.items()):
            if not key.startswith(f"{ARP_PREFIX}{VXLAN_BVI_NAME}/"):
                continue
            ip = key.rsplit("/", 1)[1]
            mac = arp.get("physical_address", "")
            mac_node = mac_to_node.get(mac)
            ip_node = ip_to_node.get(ip)
            if mac_node is None and ip_node is None:
                errors.append(f"dangling ARP entry {ip} ({mac}) - "
                              f"no node for entry found")
            elif mac_node != ip_node:
                errors.append(f"inconsistent ARP entry {ip}: MAC -> node "
                              f"{mac_node}, IP -> node {ip_node}")

        # K8s view vs collected view, BOTH directions
        # (ValidateK8sNodeInfo :525).
        known = {n.get("name") for n in snap.nodes}
        expected = set(all_snaps)
        if not expected <= known:
            errors.append(f"node registry out of sync: missing {sorted(expected - known)}")
        if known - expected:
            errors.append(
                f"node registry out of sync: unknown nodes "
                f"{sorted(known - expected)} (no telemetry counterpart)")
        return errors


class L3Validator:
    """VRF route validation (routes to remote subnets + local pod /32s)."""

    category = "l3"

    def validate(self, snapshots: Dict[str, NodeSnapshot]) -> List[ValidationReport]:
        reports = []
        for name, snap in sorted(snapshots.items()):
            errors: List[str] = list(snap.errors)
            if not snap.errors:
                errors += self._validate_node(snap, snapshots)
            reports.append(ValidationReport(node=name, category=self.category,
                                            errors=tuple(errors)))
        return reports

    def _validate_node(self, snap: NodeSnapshot,
                       all_snaps: Dict[str, NodeSnapshot]) -> List[str]:
        import ipaddress

        errors: List[str] = []
        routes = snap.applied(ROUTE_PREFIX)
        by_dst = {r.get("dst_network"): r for r in routes.values()}
        route_dsts = set(by_dst)
        ifaces = snap.applied(IF_PREFIX)

        # Route to every other node's pod subnet, with the NEXT HOP
        # checked against the other node's BVI address — the wrong next
        # hop blackholes cross-node pod traffic just as surely as a
        # missing route (l3_validator.go remote pod-subnet route check
        # incl. next-hop validation :78).
        for other_name, other in sorted(all_snaps.items()):
            if other_name == snap.name or other.errors:
                continue
            subnet = other.ipam.get("podSubnetThisNode", "")
            if not subnet:
                continue
            route = by_dst.get(subnet)
            if route is None:
                errors.append(f"no route to node {other_name} pod subnet {subnet}")
                continue
            other_ips = _bvi_iface(other).get("ip_addresses") or []
            other_bvi_ip = str(other_ips[0]).split("/")[0] if other_ips else ""
            next_hop = route.get("next_hop")
            if other_bvi_ip and next_hop is not None and next_hop != other_bvi_ip:
                errors.append(
                    f"route to {other_name} pod subnet {subnet} has next hop "
                    f"{next_hop}, expected that node's BVI {other_bvi_ip}")

        # Every locally allocated pod IP has a /32 route and a TAP
        # (ValidatePodInfo analog).
        allocated = snap.ipam.get("allocatedPodIPs") or {}
        for pod, ip in sorted(allocated.items()):
            if f"{ip}/32" not in route_dsts:
                errors.append(f"no /32 route for pod {pod} ({ip})")
            ns, _, pname = pod.partition("/")
            tap_key = IF_PREFIX + f"tap-{ns}-{pname}"
            if tap_key not in ifaces:
                errors.append(f"no TAP interface for pod {pod}")

        # Dangling sweeps (the reference's mark-and-sweep over pod
        # state, l2_validator.go :575-704 "dangling pod-facing tap"
        # applied to our routes + taps):
        allocated_ips = set(allocated.values())
        this_subnet = snap.ipam.get("podSubnetThisNode", "")
        try:
            pod_net = ipaddress.ip_network(this_subnet) if this_subnet else None
        except ValueError:
            pod_net = None
        for dst, route in sorted(by_dst.items()):
            if not dst or not str(dst).endswith("/32") or pod_net is None:
                continue
            ip = str(dst)[:-3]
            try:
                in_pod_subnet = ipaddress.ip_address(ip) in pod_net
            except ValueError:
                continue
            if in_pod_subnet and ip not in allocated_ips:
                errors.append(f"dangling /32 route {dst} - "
                              f"no allocated pod for entry found")
        expected_taps = {
            IF_PREFIX + "tap-{}-{}".format(*pod.partition("/")[::2])
            for pod in allocated
        }
        for key, iface in sorted(ifaces.items()):
            name = key[len(IF_PREFIX):]
            if not name.startswith("tap-") or name.startswith("tap-vpp"):
                continue
            if key not in expected_taps:
                errors.append(
                    f"dangling pod-facing tap interface {name!r} - "
                    f"no allocated pod for entry found")
        return errors
