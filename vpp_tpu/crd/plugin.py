"""CRD plugin — NodeConfig reflection + periodic telemetry validation.

Analog of ``plugins/crd/plugin_impl_crd.go`` (:53) with the two
controllers (``controller/{nodeconfig,telemetry}``): NodeConfig objects
are applied into the cluster store (consumed by the config merge, which
sees them as ``NodeConfigChange`` events — contivconf_api.go :273), and
a periodic cycle collects every agent's telemetry, runs the L2/L3
validators and publishes a ``TelemetryReport``.
"""

from __future__ import annotations

import logging
import threading
from typing import Dict, Optional

from ..controller.api import UpdateEvent
from ..kvstore import KVStore
from ..models import registry
from ..models.registry import NODESYNC_PREFIX
from .models import (
    InferPolicy,
    NodeCollectionStatus,
    NodeConfig,
    TelemetryReport,
)
from .telemetry import TelemetryCache
from .validator import L2Validator, L3Validator

log = logging.getLogger(__name__)

NODECONFIG_PREFIX = "/vpp-tpu/crd/nodeconfig/"
# The inferpolicy prefix is the REGISTRY's (ISSUE 14): publishing under
# it makes the policy watched state — every agent's DBWatcher delivers
# it as a KubeStateChange, so one CRD write enrolls the whole fleet.
INFERPOLICY_PREFIX = registry.resource("inferpolicy").key_prefix
TELEMETRY_KEY = "/vpp-tpu/crd/telemetry-report"


class NodeConfigChange(UpdateEvent):
    """A node's config override changed (contivconf_api.go :273)."""

    name = "Node Config Change"

    def __init__(self, node: str, prev: Optional[NodeConfig], new: Optional[NodeConfig]):
        super().__init__()
        self.node = node
        self.prev = prev
        self.new = new

    def __str__(self) -> str:
        op = "update"
        if self.prev is None:
            op = "add"
        elif self.new is None:
            op = "delete"
        return f"{self.name} [{op} {self.node}]"


class InferPolicyChange(UpdateEvent):
    """An in-network inference policy changed (ISSUE 14).  Unlike
    NodeConfigChange this is CLUSTER-scoped — every node's datapath
    enrolls the policy's namespaces — so it is always emitted to the
    local event loop, never filtered by node name."""

    name = "Infer Policy Change"

    def __init__(self, policy_name: str, prev: Optional[InferPolicy],
                 new: Optional[InferPolicy]):
        super().__init__()
        self.policy_name = policy_name
        self.prev = prev
        self.new = new

    def __str__(self) -> str:
        op = "update"
        if self.prev is None:
            op = "add"
        elif self.new is None:
            op = "delete"
        return f"{self.name} [{op} {self.policy_name}]"


class CRDPlugin:
    """NodeConfig store access + the telemetry collection cycle."""

    def __init__(
        self,
        store: KVStore,
        cache: Optional[TelemetryCache] = None,
        collection_interval: float = 60.0,
        event_loop=None,
        node_name: str = "",
    ):
        self.store = store
        self.cache = cache if cache is not None else TelemetryCache()
        self.collection_interval = collection_interval
        self.event_loop = event_loop
        self.node_name = node_name
        self.validators = [L2Validator(), L3Validator()]
        self.agents: Dict[str, str] = {}  # node name -> REST "host:port"
        self._revision = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ NodeConfig

    def apply_node_config(self, config: NodeConfig) -> None:
        """CRD create/update → cluster store (nodeconfig controller)."""
        prev = self.store.get(NODECONFIG_PREFIX + config.name)
        self.store.put(NODECONFIG_PREFIX + config.name, config)
        self._emit_nodeconfig(config.name, prev, config)

    def delete_node_config(self, name: str) -> None:
        prev = self.store.get(NODECONFIG_PREFIX + name)
        if self.store.delete(NODECONFIG_PREFIX + name):
            self._emit_nodeconfig(name, prev, None)

    def get_node_config(self, name: str) -> Optional[NodeConfig]:
        return self.store.get(NODECONFIG_PREFIX + name)

    def _emit_nodeconfig(self, name, prev, new) -> None:
        # Only this node's override matters to the local event loop
        # (the reference filters by ServiceLabel).
        if self.event_loop is not None and (not self.node_name or name == self.node_name):
            self.event_loop.push_event(NodeConfigChange(name, prev, new))

    # ----------------------------------------------------------- InferPolicy

    def apply_infer_policy(self, policy: InferPolicy) -> None:
        """Validated CRD create/update → cluster store + local event
        (ISSUE 14; the inferpolicy controller calls this)."""
        prev = self.store.get(INFERPOLICY_PREFIX + policy.name)
        self.store.put(INFERPOLICY_PREFIX + policy.name, policy)
        if self.event_loop is not None:
            self.event_loop.push_event(
                InferPolicyChange(policy.name, prev, policy))

    def delete_infer_policy(self, name: str) -> None:
        prev = self.store.get(INFERPOLICY_PREFIX + name)
        if self.store.delete(INFERPOLICY_PREFIX + name):
            if self.event_loop is not None:
                self.event_loop.push_event(InferPolicyChange(name, prev, None))

    def get_infer_policy(self, name: str) -> Optional[InferPolicy]:
        return self.store.get(INFERPOLICY_PREFIX + name)

    # ------------------------------------------------------------- telemetry

    def register_agent(self, node_name: str, server: str) -> None:
        # Plain dict assignment: atomic under the GIL.  Readers snapshot
        # (run_validation) — iterating the live dict from the timer
        # thread while a registration lands would raise "dictionary
        # changed size during iteration".
        self.agents[node_name] = server

    def unregister_agent(self, node_name: str) -> None:
        self.agents.pop(node_name, None)

    def _prune_departed(self) -> None:
        """Drop agents whose VppNode left the cluster store — node
        departure prunes its telemetry (telemetry_cache.go report
        lifecycle).  Only enforced when the store HAS a node registry:
        a harness that registered agents without publishing VppNodes
        keeps its explicit set."""
        entries = self.store.list(NODESYNC_PREFIX + "vppnode/")
        if not entries:
            return
        alive = {getattr(node, "name", "") for _, node in entries}
        for name in list(self.agents):
            if name not in alive:
                log.info("telemetry: pruning departed node %s", name)
                # pop, not del: a concurrent unregister_agent may have
                # removed the name between the snapshot and here.
                self.agents.pop(name, None)

    def run_validation(self) -> TelemetryReport:
        """One collection + validation cycle (telemetry controller
        tick): prune departed nodes, crawl every agent (update-in-place
        snapshots; unreachable nodes keep last-good data marked stale),
        validate, publish the report update-in-place."""
        self._prune_departed()
        snapshots = self.cache.collect(dict(self.agents))
        reports = []
        for validator in self.validators:
            reports.extend(validator.validate(snapshots))
        self._revision += 1
        statuses = tuple(
            NodeCollectionStatus(
                node=name,
                reachable=not snap.errors,
                stale=snap.stale,
                data_revision=snap.revision,
                errors=tuple(snap.errors),
            )
            for name, snap in sorted(snapshots.items())
        )
        report = TelemetryReport(revision=self._revision,
                                 reports=tuple(reports), nodes=statuses)
        self.store.put(TELEMETRY_KEY, report)
        if report.error_count:
            log.warning("telemetry validation: %d errors %s",
                        report.error_count, dict(report.summary()))
        return report

    def latest_report(self) -> Optional[TelemetryReport]:
        return self.store.get(TELEMETRY_KEY)

    # ------------------------------------------------------------- lifecycle

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._loop, name="crd-telemetry", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _loop(self) -> None:
        while not self._stop.wait(self.collection_interval):
            try:
                self.run_validation()
            except Exception:  # noqa: BLE001
                log.exception("telemetry cycle failed")
