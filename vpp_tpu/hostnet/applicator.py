"""Linux host-network applicator — real netlink state from ipv4net KVs.

The production counterpart of the test harness's MockHostFIB: a
TxnScheduler applicator that translates the typed connectivity models
(`vpp_tpu/ipv4net/model.py`) into actual Linux networking via iproute2
— the role the reference's vendored linuxv2/vppv2 configurators play
against netlink and the VPP binary API (SURVEY §1 L2).

Mapping (each is the closest kernel-native analog of the VPP object):

  Interface TAP/VETH w/ namespace  -> veth pair, peer moved into the
                                      pod netns as host_if_name, addr
                                      on the peer (podVPPTap analog)
  Interface TAP w/o namespace      -> veth pair kept in the root ns
                                      (host-interconnect tap-vpp1/2)
  Interface LOOPBACK               -> dummy link (BVI analog)
  Interface VXLAN                  -> vxlan link (id/remote/local/4789)
  Interface DPDK                   -> existing NIC: addr/mtu/up only
  BridgeDomain                     -> bridge link + enslaved members
  Route                            -> ip route replace (VRF n>0 maps to
                                      routing table 1000+n)
  ArpEntry                         -> ip neigh replace (permanent)
  L2FibEntry                       -> bridge fdb static entry
  VrfTable                         -> no-op marker (tables are implicit)

All commands can be confined to a dedicated network namespace
(``netns=...``) so tests run against real kernel state without touching
the host's networking; production uses the root namespace.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import subprocess
import time
from typing import List, Optional

from ..ipv4net.model import (
    CONFIG_PREFIX,
    ArpEntry,
    BridgeDomain,
    Interface,
    InterfaceType,
    L2FibEntry,
    Route,
    VrfTable,
)
from ..scheduler.scheduler import Applicator

log = logging.getLogger(__name__)

# Linux IFNAMSIZ is 16 (15 usable chars).
IFNAMSIZ = 15


class IpCmdError(RuntimeError):
    pass


def _sanitize_ns(name: str) -> str:
    """A filesystem-safe netns name for KubeState-only pods."""
    return "pod-" + "".join(c if c.isalnum() or c == "-" else "-" for c in name)


def _resolve_netns(namespace: str):
    """Classify a CNI-supplied namespace reference.

    Returns ("name", n) for registered netns names, ("pid", p) for
    /proc/<pid>/ns/net paths, ("path", p) for other nsfs paths.
    """
    if not namespace.startswith("/"):
        return ("name", namespace if "/" not in namespace else _sanitize_ns(namespace))
    parts = namespace.strip("/").split("/")
    if len(parts) == 4 and parts[0] == "proc" and parts[2] == "ns" and parts[3] == "net":
        return ("pid", parts[1])
    if namespace.startswith("/var/run/netns/") or namespace.startswith("/run/netns/"):
        return ("name", namespace.rsplit("/", 1)[1])
    return ("path", namespace)


def _vrf_table(vrf: int) -> List[str]:
    return ["table", str(1000 + vrf)] if vrf else []


class LinuxNetApplicator(Applicator):
    """Applies config/* KVs to the kernel via iproute2."""

    prefix = CONFIG_PREFIX

    def __init__(self, netns: Optional[str] = None, create_netns: bool = False):
        self.netns = netns
        self._bd_bridge: dict = {}   # bridge-domain name -> actual bridge dev
        # bridge dev -> member names, so members created AFTER their BD
        # (partial-BD semantics / replay ordering) still get enslaved.
        self._bd_members: dict = {}
        # Transaction batching (VERDICT r3 item 8): between begin_txn and
        # end_txn, iproute2 operations are buffered and flushed as a few
        # `ip/bridge -batch` executions instead of one fork per object —
        # a 100-pod resync is a handful of execs, not hundreds.  Outside
        # a transaction bracket (None) every call executes immediately,
        # preserving the direct-call semantics tests rely on.  Entries:
        #   ("ip", pod_ns|None, args, check)   — an ip(8) line
        #   ("bridge", None, args, check)      — a bridge(8) line
        #   ("link_add", None, (name, args), True) — EEXIST-tolerant add
        self._batch: Optional[list] = None
        # Count of subprocess executions (observability for tests/bench).
        self.exec_count = 0
        # Pod namespaces THIS applicator created (`ip netns add` for
        # KubeState-only pods): ns name -> set of Interface model names
        # placed inside.  Deleted again when the LAST such interface
        # goes, so they cannot accumulate across pod churn nor tear
        # down a shared multi-interface pod ns early.  Set-based (not a
        # counter) so scheduler retries/replays stay idempotent.
        self._created_netns: dict = {}
        if netns and create_netns:
            subprocess.run(["ip", "netns", "add", netns], check=False,
                           capture_output=True)
            self._ip(["link", "set", "lo", "up"])

    # ------------------------------------------------------------- plumbing

    def _run(self, args: List[str], check: bool = True) -> str:
        cmd = ["ip", "netns", "exec", self.netns] + args if self.netns else args
        self.exec_count += 1
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if check and proc.returncode != 0:
            raise IpCmdError(f"{' '.join(cmd)}: {proc.stderr.strip()}")
        return proc.stdout

    def _ip(self, args: List[str], check: bool = True) -> str:
        return self._run(["ip"] + args, check=check)

    def _ip_json(self, args: List[str]):
        out = self._run(["ip", "-json"] + args)
        return json.loads(out) if out.strip() else []

    def _link_add(self, name: str, args: List[str]) -> None:
        """`ip link add` that tolerates ONLY idempotent replay ("File
        exists" for a device of the SAME type) — a genuinely failed
        creation (missing module, bad address, name conflict with a
        different device type) raises, entering the TxnScheduler's
        FAILED/retry machinery instead of being recorded APPLIED."""
        try:
            self._ip(["link", "add"] + args)
        except IpCmdError as e:
            if "File exists" not in str(e):
                raise
            # EEXIST fires for ANY device with this name; accept the
            # replay only if the existing device is the requested kind
            # (a stale bridge named like our vxlan would blackhole).
            want = args[args.index("type") + 1] if "type" in args else None
            info = json.loads(self._run(
                ["ip", "-details", "-json", "link", "show", name]))
            have = (info[0].get("linkinfo") or {}).get("info_kind") if info else None
            if want is not None and have != want:
                raise IpCmdError(
                    f"link add {name}: exists as {have!r}, wanted {want!r}")

    # ------------------------------------------------------ txn batching

    def begin_txn(self) -> None:
        self._batch = []
        self._netns_known = None  # refreshed lazily per transaction

    def end_txn(self) -> None:
        self._flush_batch()

    def _q_netns_add(self, ref: str, owner: str) -> None:
        """Queue a pod-netns creation (tracked for later cleanup).
        Batched mode snapshots ``ip netns list`` once per txn to decide
        created-by-us; immediate mode keeps the original add-and-check
        behavior."""
        if self._batch is None:
            created = subprocess.run(["ip", "netns", "add", ref],
                                     capture_output=True, check=False)
            self.exec_count += 1
            if created.returncode == 0 or ref in self._created_netns:
                self._created_netns.setdefault(ref, set()).add(owner)
            return
        if self._netns_known is None:
            out = subprocess.run(["ip", "netns", "list"],
                                 capture_output=True, text=True)
            self.exec_count += 1
            self._netns_known = {
                line.split()[0] for line in out.stdout.splitlines() if line.strip()
            }
        if ref in self._netns_known:
            if ref in self._created_netns:
                self._created_netns[ref].add(owner)
            return
        self._netns_known.add(ref)
        self._created_netns.setdefault(ref, set()).add(owner)
        self._batch.append(("netns_add", None, ["netns", "add", ref], False))

    def _q_ip(self, args: List[str], check: bool = True,
              pod_ns: Optional[str] = None) -> None:
        """Queue (or, outside a txn, immediately run) one ip(8) line.
        ``pod_ns`` runs the line inside a registered pod netns."""
        if self._batch is None:
            if pod_ns:
                self._ip(["netns", "exec", pod_ns, "ip"] + args, check=check)
            else:
                self._ip(args, check=check)
            return
        self._batch.append(("ip", pod_ns, args, check))

    def _q_bridge(self, args: List[str], check: bool = True) -> None:
        if self._batch is None:
            self._run(["bridge"] + args, check=check)
            return
        self._batch.append(("bridge", None, args, check))

    def _q_link_add(self, name: str, args: List[str]) -> None:
        if self._batch is None:
            self._link_add(name, args)
            return
        self._batch.append(("link_add", None, (name, args), True))

    def _batch_cmd(self, tool: str, pod_ns: Optional[str]) -> List[str]:
        # Pod netns names are globally registered, so a pod-ns batch
        # runs as `ip -n <pod>` directly; only root-group batches need
        # the applicator's confinement ns.  The -n flag avoids the
        # `ip netns exec` wrapper's extra mount-namespace setup.
        # pod_ns == "" forces NO namespace at all (netns-add lines run
        # in the root mount namespace regardless of confinement).
        ns = None if pod_ns == "" else (pod_ns or self.netns)
        cmd = [tool]
        if ns:
            cmd += ["-n", ns]
        return cmd + ["-batch", "-"]

    def _flush_batch(self) -> None:
        entries, self._batch = (self._batch or []), None
        if not entries:
            return
        # Group into batch files preserving relative order per group:
        # root-ns ip lines first (link adds + netns moves), then each
        # pod ns's configure lines, then bridge(8) fdb lines.
        groups: dict = {}
        for kind, pod_ns, payload, check in entries:
            if kind == "netns_add":
                tool = "ip-nsadd"
            elif kind == "bridge":
                tool = "bridge"
            else:
                tool = "ip"
            groups.setdefault((tool, pod_ns), []).append((kind, payload, check))
        errors: List[str] = []
        # Order: pod-netns creations (root mount ns), then the root-ns
        # ip group (creates devices + moves them into pod namespaces),
        # then all pod-ns lines (one shell pass), then bridge(8) lines.
        nsadds = groups.pop(("ip-nsadd", None), None)
        root = groups.pop(("ip", None), None)
        bridge = groups.pop(("bridge", None), None)
        if nsadds:
            errors += self._run_batch_group("ip", "", nsadds)
        if root:
            errors += self._run_batch_group("ip", None, root)
        if groups:
            errors += self._run_pod_groups(groups)
        if bridge:
            errors += self._run_batch_group("bridge", None, bridge)
        if errors:
            raise IpCmdError("; ".join(errors))

    def _run_pod_groups(self, pod_groups: dict) -> List[str]:
        """All pod-namespace lines of this txn through ONE shell pass
        (`ip -n <pod> ...` per line; one fork per line inside a single
        subprocess instead of one Python subprocess per pod).  Failing
        check=True lines re-run individually for their real stderr.

        The shell pass (and each retry) runs under the applicator's
        confinement netns exactly like the immediate path: pod netns
        NAMES resolve identically everywhere (the registry is per mount
        namespace, shared), but `ip -n` still executes in the invoking
        netns first — confinement-local state (e.g. which devices are
        visible to a relative `link set ... netns` move) must not
        diverge between txn and non-txn modes."""
        import shlex

        cmds = []
        for (_tool, pod_ns), lines in pod_groups.items():
            for _kind, payload, check in lines:
                cmds.append((pod_ns, payload, check))
        script = "\n".join(
            "ip -n " + shlex.quote(ns) + " "
            + " ".join(shlex.quote(str(a)) for a in payload)
            + f" || echo VTFAIL:{i}"
            for i, (ns, payload, _check) in enumerate(cmds)
        )
        shell = ["sh", "-c", script]
        if self.netns:
            shell = ["ip", "netns", "exec", self.netns] + shell
        self.exec_count += 1
        proc = subprocess.run(shell, capture_output=True, text=True)
        if proc.stderr.strip():
            log.debug("pod-ns batch stderr: %s", proc.stderr.strip())
        errors: List[str] = []
        if proc.returncode != 0:
            # Every script line is `cmd || echo VTFAIL:<i>`, so a clean
            # pass exits 0 even when commands fail — a nonzero rc means
            # the SHELL itself broke (confinement netns vanished, exec
            # privilege lost, killed midway): un-marked lines may never
            # have run at all.  Surface it so the txn fails and the
            # scheduler retries; silence here would report success with
            # nothing applied.  Marked lines still retry below for
            # their real stderr.
            errors.append(
                f"pod-ns batch shell failed (rc={proc.returncode}): "
                f"{proc.stderr.strip()}")
        for line in proc.stdout.splitlines():
            if not line.startswith("VTFAIL:"):
                continue
            ns, payload, check = cmds[int(line.split(":", 1)[1])]
            if not check:
                continue
            self.exec_count += 1
            retry_cmd = ["ip", "-n", ns] + [str(a) for a in payload]
            if self.netns:
                retry_cmd = ["ip", "netns", "exec", self.netns] + retry_cmd
            retry = subprocess.run(retry_cmd, capture_output=True, text=True)
            if retry.returncode != 0:
                errors.append(
                    f"ip -n {ns} {' '.join(str(a) for a in payload)}: "
                    f"{retry.stderr.strip()}")
        return errors

    def _run_batch_group(self, tool: str, pod_ns: Optional[str],
                         lines: list) -> List[str]:
        """One `-batch` execution per contiguous run of lines; a batch
        stops at its first failing line, whose ORIGINAL per-command
        semantics are applied (check=False lines are simply skipped;
        link_add lines get their EEXIST-with-same-type tolerance), and
        the batch resumes after it — lines never double-apply and
        non-idempotent steps (renames, netns moves) stay exact."""
        import re

        def render(kind, payload):
            if kind == "link_add":
                return "link add " + " ".join(payload[1])
            return " ".join(payload)

        errors: List[str] = []
        idx = 0
        while idx < len(lines):
            chunk = lines[idx:]
            text = "\n".join(render(k, p) for k, p, _ in chunk) + "\n"
            self.exec_count += 1
            proc = subprocess.run(
                self._batch_cmd(tool, pod_ns), input=text,
                capture_output=True, text=True,
            )
            if proc.returncode == 0:
                break
            match = re.search(r"Command failed [^:]*:(\d+)", proc.stderr)
            if match is None:
                # Some subcommands (e.g. `neigh del` of an already-gone
                # entry) exit WITHOUT the `Command failed -:N` marker,
                # so the failure cannot be attributed to a line and the
                # batch's progress is unknown — run the remaining lines
                # individually with their original per-command
                # semantics.  Idempotent `replace`-style lines tolerate
                # any partial progress the batch made; the two
                # NON-idempotent line shapes (renames, netns moves)
                # fail with "Cannot find device" when the batch already
                # performed them, which is indistinguishable from their
                # post-success state — tolerated, with any genuine
                # problem surfacing on the later lines that reference
                # the move/rename TARGET.
                def already_done(payload, stderr: str) -> bool:
                    p = [str(a) for a in payload]
                    return ("Cannot find device" in stderr
                            and len(p) >= 2 and p[:2] == ["link", "set"]
                            and ("netns" in p or "name" in p))

                for kind, payload, check in chunk:
                    if kind == "link_add":
                        try:
                            self._link_add(*payload)
                        except IpCmdError as e:
                            errors.append(str(e))
                        continue
                    if pod_ns == "":
                        self.exec_count += 1
                        single = subprocess.run(
                            [tool] + [str(a) for a in payload],
                            capture_output=True, text=True)
                        failed = single.returncode != 0
                        stderr = single.stderr
                    else:
                        try:
                            self._run([tool] + [str(a) for a in payload])
                            failed, stderr = False, ""
                        except IpCmdError as e:
                            failed, stderr = True, str(e)
                    if failed and check and not already_done(payload, stderr):
                        errors.append(
                            f"{render(kind, payload)}: {stderr.strip()}")
                break
            fail = idx + int(match.group(1)) - 1
            kind, payload, check = lines[fail]
            detail = proc.stderr.strip().splitlines()
            detail = detail[0] if detail else "unknown error"
            if kind == "link_add":
                try:
                    self._link_add(*payload)
                except IpCmdError as e:
                    errors.append(str(e))
            elif check:
                errors.append(f"{render(kind, payload)}: {detail}")
            idx = fail + 1
        return errors

    @staticmethod
    def ifname(name: str) -> str:
        """Kernel-safe interface name: model names longer than IFNAMSIZ
        get a deterministic hash suffix so distinct long names cannot
        silently collide after truncation."""
        if len(name) <= IFNAMSIZ:
            return name
        digest = hashlib.sha1(name.encode()).hexdigest()[:5]
        return f"{name[:IFNAMSIZ - 6]}-{digest}"

    # ----------------------------------------------------------- applicator

    def create(self, key: str, value) -> None:
        if isinstance(value, Interface):
            self._create_interface(value)
        elif isinstance(value, Route):
            if value.via_vrf is not None:
                # Inter-VRF leak: a `throw` route ends the lookup in this
                # table and falls through to the target table's rules —
                # the Linux analog of the reference's via-VRF routes.
                self._q_ip(["route", "replace", "throw", value.dst_network]
                           + _vrf_table(value.vrf))
                return
            self._q_ip(["route", "replace", value.dst_network]
                       + (["via", value.next_hop] if value.next_hop else [])
                       + (["dev", self.ifname(value.outgoing_interface)]
                          if value.outgoing_interface else [])
                       + _vrf_table(value.vrf))
        elif isinstance(value, ArpEntry):
            self._q_ip(["neigh", "replace", value.ip_address,
                        "lladdr", value.physical_address,
                        "dev", self.ifname(value.interface), "nud", "permanent"])
        elif isinstance(value, BridgeDomain):
            # The BVI is an addressed bridge device (see _create_interface
            # LOOPBACK); the bridge domain is realised by enslaving the
            # member tunnels INTO it, so L2 flooding reaches the BVI's
            # address — the faithful Linux rendering of VPP's BD + BVI.
            # Without a BVI, a standalone bridge under the BD's name is
            # created instead.
            br = self.ifname(value.bvi_interface or value.name)
            # No link_exists guard: _link_add handles EEXIST itself and
            # verifies a pre-existing device is actually a bridge.
            self._q_link_add(br, [br, "type", "bridge"])
            self._q_ip(["link", "set", br, "up"])
            self._bd_bridge[self.ifname(value.name)] = br
            self._bd_members[br] = {self.ifname(m) for m in value.interfaces}
            for member in value.interfaces:
                self._q_ip(["link", "set", self.ifname(member), "master", br],
                           check=False)
        elif isinstance(value, L2FibEntry):
            self._q_bridge(["fdb", "replace", value.physical_address,
                            "dev", self.ifname(value.outgoing_interface),
                            "master", "static"], check=False)
        elif isinstance(value, VrfTable):
            pass  # tables are implicit in route commands
        else:
            raise IpCmdError(f"unsupported value for {key}: {type(value).__name__}")

    def delete(self, key: str, value) -> None:
        if isinstance(value, Interface):
            if value.vrf:
                self._ip(["rule", "del", "iif", self.ifname(value.name),
                          "lookup", str(1000 + value.vrf)], check=False)
            self._ip(["link", "del", self.ifname(value.name)], check=False)
            if value.namespace:
                # Remove pod namespaces WE created (`ip netns add` in
                # _create_veth) so they do not accumulate across churn.
                kind, ref = _resolve_netns(value.namespace)
                members = (self._created_netns.get(ref)
                           if kind == "name" else None)
                if members is not None:
                    members.discard(value.name)
                    if not members:
                        subprocess.run(["ip", "netns", "del", ref],
                                       capture_output=True, check=False)
                        del self._created_netns[ref]
        elif isinstance(value, Route):
            self._q_ip(["route", "del", value.dst_network] + _vrf_table(value.vrf),
                       check=False)
        elif isinstance(value, ArpEntry):
            self._q_ip(["neigh", "del", value.ip_address,
                        "dev", self.ifname(value.interface)], check=False)
        elif isinstance(value, BridgeDomain):
            br = self._bd_bridge.pop(self.ifname(value.name), None)
            if br == self.ifname(value.bvi_interface or ""):
                # The bridge IS the BVI: detach members, keep the device
                # (it is owned by its own Interface KV).
                for member in value.interfaces:
                    self._ip(["link", "set", self.ifname(member), "nomaster"],
                             check=False)
            else:
                self._ip(["link", "del", br or self.ifname(value.name)],
                         check=False)
        elif isinstance(value, L2FibEntry):
            self._q_bridge(["fdb", "del", value.physical_address,
                            "dev", self.ifname(value.outgoing_interface),
                            "master"], check=False)

    # ------------------------------------------------------------ interfaces

    @staticmethod
    def _wait_holder_in_ns(holder: subprocess.Popen, ns_path: str,
                           timeout: float = 2.0) -> None:
        """Block until the holder child has setns()'d into ``ns_path``.
        Moving the link by PID before that would silently drop it into
        OUR namespace instead of the pod's."""
        target = os.stat(ns_path)
        deadline = time.monotonic() + timeout
        while True:
            try:
                st = os.stat(f"/proc/{holder.pid}/ns/net")
                if (st.st_ino, st.st_dev) == (target.st_ino, target.st_dev):
                    return
            except OSError:
                pass
            if holder.poll() is not None:
                raise IpCmdError(f"nsenter holder for {ns_path} exited "
                                 f"rc={holder.returncode}")
            if time.monotonic() > deadline:
                raise IpCmdError(f"timed out entering netns {ns_path}")
            time.sleep(0.005)

    def _create_interface(self, iface: Interface) -> None:
        name = self.ifname(iface.name)
        if iface.type in (InterfaceType.TAP, InterfaceType.VETH, InterfaceType.MEMIF):
            self._create_veth(iface, name)
            return
        if iface.type is InterfaceType.LOOPBACK:
            # BVI analog: an addressed BRIDGE device — tunnels enslave
            # into it (BridgeDomain create), putting the L3 address
            # exactly where VPP's bridge-virtual-interface sits.
            self._q_link_add(name, [name, "type", "bridge"])
        elif iface.type is InterfaceType.VXLAN:
            self._q_link_add(name, [name, "type", "vxlan",
                             "id", str(iface.vxlan_vni),
                             "local", iface.vxlan_src, "remote", iface.vxlan_dst,
                             "dstport", "4789"])
        elif iface.type is InterfaceType.DPDK:
            pass  # physical NIC: must already exist
        self._finish_link(name, iface)

    def _create_veth(self, iface: Interface, name: str) -> None:
        """veth pair: host side keeps the model name; the peer becomes
        host_if_name, optionally moved into the pod netns, and carries
        the addresses (the pod's eth0 side)."""
        peer_tmp = f"vp-{abs(hash(name)) % 0xFFFFFF:06x}"[:IFNAMSIZ]
        peer_name = self.ifname(iface.host_if_name or f"{name}-p")
        if iface.namespace:
            kind, ref = _resolve_netns(iface.namespace)
            if kind == "name":
                # Registered-name pod netns (the KubeState/resync path):
                # the whole sequence is batchable — netns creations run
                # as one root-MOUNT-ns batch (creating them under
                # `ip netns exec` would strand the bind mount in a
                # private mount ns), the veth peer is created DIRECTLY
                # inside the pod ns (`peer name X netns REF` — ~40x
                # cheaper than create-then-move, which pays a full
                # cross-ns device re-registration), and only peer
                # up/addresses/lo remain as pod-ns lines (one shell
                # pass for ALL pods of the txn).
                self._q_netns_add(ref, iface.name)
                self._q_link_add(
                    name, [name, "type", "veth",
                           "peer", "name", peer_name, "netns", ref])
                for addr in iface.ip_addresses:
                    self._q_ip(["addr", "replace", addr, "dev", peer_name],
                               pod_ns=ref)
                self._q_ip(["link", "set", peer_name, "up"], pod_ns=ref)
                self._q_ip(["link", "set", "lo", "up"], check=False,
                           pod_ns=ref)
                self._finish_link(name, iface, skip_addrs=True)
                return
            self._link_add(name, [name, "type", "veth", "peer", "name", peer_tmp])
            if kind == "pid":
                # CNI handed us /proc/<pid>/ns/net: move by PID, then
                # configure through nsenter on the path.
                self._ip(["link", "set", peer_tmp, "netns", ref])
                ns = ["nsenter", f"--net=/proc/{ref}/ns/net", "ip"]
            else:
                # An arbitrary nsfs path: iproute2's `netns` argument
                # accepts only a registered name or a PID, so hold the
                # target ns open with a child process and move the link
                # by that child's PID.
                holder = subprocess.Popen(
                    ["nsenter", f"--net={ref}", "sleep", "30"],
                    stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
                try:
                    self._wait_holder_in_ns(holder, ref)
                    self._ip(["link", "set", peer_tmp, "netns", str(holder.pid)])
                finally:
                    holder.terminate()
                    holder.wait()
                ns = ["nsenter", f"--net={ref}", "ip"]
            self._run(ns + ["link", "set", peer_tmp, "name", peer_name])
            for addr in iface.ip_addresses:
                self._run(ns + ["addr", "replace", addr, "dev", peer_name])
            self._run(ns + ["link", "set", peer_name, "up"])
            self._run(ns + ["link", "set", "lo", "up"], check=False)
        else:
            self._q_link_add(
                name, [name, "type", "veth", "peer", "name", peer_tmp])
            if peer_name != peer_tmp:
                self._q_ip(["link", "set", peer_tmp, "name", peer_name])
            for addr in iface.ip_addresses:
                self._q_ip(["addr", "replace", addr, "dev", peer_name])
            self._q_ip(["link", "set", peer_name, "up"])
        self._finish_link(name, iface, skip_addrs=True)

    def _finish_link(self, name: str, iface: Interface, skip_addrs: bool = False) -> None:
        if iface.physical_address:
            self._q_ip(["link", "set", name, "address", iface.physical_address],
                       check=False)
        if iface.mtu:
            self._q_ip(["link", "set", name, "mtu", str(iface.mtu)], check=False)
        if not skip_addrs:
            for addr in iface.ip_addresses:
                self._q_ip(["addr", "replace", addr, "dev", name])
        if iface.enabled:
            self._q_ip(["link", "set", name, "up"], check=False)
        # Late BD attach: if a bridge domain already claims this device,
        # enslave it now (partial-BD semantics — members attach as they
        # appear, whatever the creation order).
        for br, members in self._bd_members.items():
            if name in members:
                self._q_ip(["link", "set", name, "master", br], check=False)
        if iface.vrf:
            # Steer ingress from this interface into its VRF's routing
            # table (the lightweight Linux analog of VRF membership; the
            # via_vrf `throw` routes fall through to later rules).
            self._q_ip(["rule", "del", "iif", name,
                        "lookup", str(1000 + iface.vrf)], check=False)
            self._q_ip(["rule", "add", "iif", name,
                        "lookup", str(1000 + iface.vrf),
                        "priority", str(10000 + iface.vrf)], check=False)

    # ------------------------------------------------------ drift readback

    @staticmethod
    def _norm_dst(dst: str) -> str:
        """Kernel route-dump normalization: /32 is shown bare and the
        zero route as 'default'."""
        if dst in ("0.0.0.0/0", "default"):
            return "default"
        return dst[:-3] if dst.endswith("/32") else dst

    def _actual_index(self, applied):
        """One bulk southbound readback (a handful of `ip -j` execs,
        never per-key): links+kinds+masters, addresses, routes of every
        table the applied values use, neighbors, bridge fdb, and the
        pod-namespace link/address sets for namespaces referenced by
        applied interfaces."""
        links = {}
        for l in self._ip_json(["-details", "link", "show"]):
            info = l.get("linkinfo") or {}
            links[l.get("ifname")] = {
                "kind": info.get("info_kind"),
                "vni": (info.get("info_data") or {}).get("id"),
                "master": l.get("master"),
                "up": "UP" in (l.get("flags") or []),
            }
        addrs = {}
        for l in self._ip_json(["addr", "show"]):
            addrs[l.get("ifname")] = {
                f"{a.get('local')}/{a.get('prefixlen')}"
                for a in l.get("addr_info") or []
                if a.get("family") == "inet"
            }
        tables = {0}
        for value in applied.values():
            if isinstance(value, Route):
                tables.add(value.vrf)
        routes = {}
        for vrf in tables:
            entries = {}
            try:
                dump = self._ip_json(["route", "show"] + _vrf_table(vrf))
            except IpCmdError:
                dump = []  # table does not exist (no routes yet)
            for r in dump:
                entries[self._norm_dst(r.get("dst", ""))] = {
                    "via": r.get("gateway", ""),
                    "dev": r.get("dev", ""),
                    "throw": r.get("type") == "throw",
                }
            routes[vrf] = entries
        neighs = {}
        for n in self._ip_json(["neigh", "show"]):
            if "PERMANENT" in (n.get("state") or []):
                neighs[(n.get("dst"), n.get("dev"))] = (
                    (n.get("lladdr") or "").lower()
                )
        fdb = set()
        try:
            out = self._run(["bridge", "-j", "fdb", "show"], check=False)
            for e in json.loads(out) if out.strip() else []:
                fdb.add(((e.get("mac") or "").lower(), e.get("ifname")))
        except Exception:  # noqa: BLE001 - no bridge module/cmd: skip fdb
            fdb = None
        pod_links = {}
        for value in applied.values():
            if not isinstance(value, Interface) or not value.namespace:
                continue
            kind, ref = _resolve_netns(value.namespace)
            if kind != "name" or ref in pod_links:
                continue
            try:
                dump = self._run(["ip", "-n", ref, "-json", "addr", "show"])
                entries = {}
                for l in (json.loads(dump) if dump.strip() else []):
                    entries[l.get("ifname")] = {
                        f"{a.get('local')}/{a.get('prefixlen')}"
                        for a in l.get("addr_info") or []
                        if a.get("family") == "inet"
                    }
                pod_links[ref] = entries
            except IpCmdError:
                pod_links[ref] = None  # namespace itself is GONE
        return links, addrs, routes, neighs, fdb, pod_links

    def verify(self, applied):
        """Southbound drift detection (kvscheduler SB-refresh analog):
        bulk-read the kernel state back and report applied keys whose
        actual config is missing or diverged — a deleted pod veth, a
        route dropped with its device, a vanished pod netns, an
        unenslaved bridge member.  The scheduler repairs exactly these
        (delete-remnant + re-create) instead of replaying everything."""
        links, addrs, routes, neighs, fdb, pod_links = (
            self._actual_index(applied))
        drifted = set()
        for key, value in applied.items():
            if isinstance(value, Interface):
                if not self._verify_interface(value, links, addrs, pod_links):
                    drifted.add(key)
            elif isinstance(value, Route):
                entry = routes.get(value.vrf, {}).get(
                    self._norm_dst(value.dst_network))
                ok = entry is not None
                if ok and value.via_vrf is not None:
                    ok = entry["throw"]
                elif ok:
                    if value.next_hop and entry["via"] != value.next_hop:
                        ok = False
                    if (value.outgoing_interface
                            and entry["dev"] != self.ifname(
                                value.outgoing_interface)):
                        ok = False
                if not ok:
                    drifted.add(key)
            elif isinstance(value, ArpEntry):
                have = neighs.get(
                    (value.ip_address, self.ifname(value.interface)))
                if have != value.physical_address.lower():
                    drifted.add(key)
            elif isinstance(value, BridgeDomain):
                br = self.ifname(value.bvi_interface or value.name)
                link = links.get(br)
                if link is None or link["kind"] != "bridge":
                    drifted.add(key)
                    continue
                for member in value.interfaces:
                    mname = self.ifname(member)
                    mlink = links.get(mname)
                    # A missing member is the member Interface's own
                    # drift; an EXISTING member must be enslaved here.
                    if mlink is not None and mlink["master"] != br:
                        drifted.add(key)
                        break
            elif isinstance(value, L2FibEntry):
                if fdb is not None and (
                    value.physical_address.lower(),
                    self.ifname(value.outgoing_interface),
                ) not in fdb:
                    drifted.add(key)
            # VrfTable: implicit in route commands, nothing to verify.
        return drifted

    def _verify_interface(self, iface: Interface, links, addrs,
                          pod_links) -> bool:
        name = self.ifname(iface.name)
        expect_kind = {
            InterfaceType.TAP: "veth",
            InterfaceType.VETH: "veth",
            InterfaceType.MEMIF: "veth",
            InterfaceType.LOOPBACK: "bridge",
            InterfaceType.VXLAN: "vxlan",
        }.get(iface.type)
        link = links.get(name)
        if iface.type is InterfaceType.DPDK:
            return link is not None  # physical NIC: presence only
        if link is None or (expect_kind and link["kind"] != expect_kind):
            return False
        if iface.type is InterfaceType.VXLAN and iface.vxlan_vni:
            if link["vni"] != iface.vxlan_vni:
                return False
        if iface.enabled and not link["up"]:
            return False
        veth_pair = iface.type in (
            InterfaceType.TAP, InterfaceType.VETH, InterfaceType.MEMIF)
        if veth_pair and iface.namespace:
            kind, ref = _resolve_netns(iface.namespace)
            if kind != "name":
                return True  # pid/path namespaces are not re-inspectable
            ns_links = pod_links.get(ref)
            if ns_links is None:
                return False  # the pod netns itself is gone
            peer = self.ifname(iface.host_if_name or f"{name}-p")
            peer_addrs = ns_links.get(peer)
            if peer_addrs is None:
                return False
            if not iface.dhcp and not set(iface.ip_addresses) <= peer_addrs:
                return False
            return True
        want_addrs = set(iface.ip_addresses)
        if veth_pair:
            # Namespace-less pair: addresses live on the peer.
            peer = self.ifname(iface.host_if_name or f"{name}-p")
            have = addrs.get(peer)
            if have is None:
                return False
            return iface.dhcp or want_addrs <= have
        if want_addrs and not iface.dhcp:
            return want_addrs <= addrs.get(name, set())
        return True

    # -------------------------------------------------------------- queries

    def link_exists(self, name: str) -> bool:
        try:
            self._ip(["link", "show", self.ifname(name)])
            return True
        except IpCmdError:
            return False

    def routes(self, vrf: int = 0):
        return self._ip_json(["route", "show"] + _vrf_table(vrf))

    def neighbors(self):
        return self._ip_json(["neigh", "show"])

    def addrs(self, name: str):
        return self._ip_json(["addr", "show", "dev", self.ifname(name)])

    def close(self, delete_netns: bool = False) -> None:
        if self.netns and delete_netns:
            subprocess.run(["ip", "netns", "del", self.netns],
                           capture_output=True, check=False)
