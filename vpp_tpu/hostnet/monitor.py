"""Production netlink-event sources, built on iproute2 streaming.

Fills the two injected seams that previously had only test fakes
(VERDICT r3 item 7):

- :class:`IpRouteSource` — a concrete BGPReflector ``RouteSource``:
  lists the host routing table (``ip -j route show``) and streams
  subsequent changes (``ip -o monitor route``), the role the
  reference's rtnetlink subscription plays in
  ``plugins/bgpreflector/bgpreflector.go watchRoutes :151``.
- :class:`DhcpAddressSource` — watches the main interface's addresses
  (``ip -o monitor address``) and pushes :class:`DHCPLeaseChange`
  events when a global IPv4 address appears/changes — the
  DHCP-lease-notification path of ``plugins/contivconf`` /
  ``ipv4net handleDHCPNotification`` (node.go :188-240), fed by
  whatever DHCP client manages the uplink.

Both are netns-confinable (``ip -n <netns> ...``) so the
netns-isolated tests drive them exactly like production, and both
consume the ``ip`` binary's one-line monitor stream instead of per-
event process forks.
"""

from __future__ import annotations

import ipaddress
import json
import logging
import subprocess
import threading
from typing import Callable, Iterable, List, Optional

from ..bgpreflector.plugin import BIRD_PROTO_NUMBER, RouteEvent, RouteEventType

log = logging.getLogger(__name__)

# iproute2 protocol names (rt_protos) -> numbers, for the subset that
# can appear on learned routes; numeric strings pass through.
_RT_PROTOS = {
    "unspec": 0, "redirect": 1, "kernel": 2, "boot": 3, "static": 4,
    "gated": 8, "ra": 9, "mrt": 10, "zebra": 11, "bird": 12,
    "dnrouted": 13, "xorp": 14, "ntk": 15, "dhcp": 16, "bgp": 186,
    "isis": 187, "ospf": 188, "rip": 189, "eigrp": 192,
}


def _proto_number(name) -> int:
    if name is None:
        return 0
    text = str(name)
    if text.isdigit():
        return int(text)
    return _RT_PROTOS.get(text, 0)


class _IpMonitor:
    """One ``ip -o monitor <object>`` subprocess, line-streamed to a
    callback from a reader thread."""

    def __init__(self, obj: str, on_line: Callable[[str], None],
                 netns: Optional[str] = None):
        self._cmd = ["ip"]
        if netns:
            self._cmd += ["-n", netns]
        self._cmd += ["-o", "monitor", obj]
        self._on_line = on_line
        self._proc: Optional[subprocess.Popen] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._proc = subprocess.Popen(
            self._cmd, stdout=subprocess.PIPE, stderr=subprocess.DEVNULL,
            text=True, bufsize=1,
        )
        self._thread = threading.Thread(target=self._pump, daemon=True)
        self._thread.start()

    def _pump(self) -> None:
        assert self._proc is not None and self._proc.stdout is not None
        for line in self._proc.stdout:
            line = line.strip()
            if not line:
                continue
            try:
                self._on_line(line)
            except Exception:  # keep the stream alive past one bad line
                log.exception("monitor line handler failed: %r", line)

    def stop(self) -> None:
        proc, self._proc = self._proc, None
        if proc is not None:
            proc.terminate()
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:  # pragma: no cover
                proc.kill()
        thread, self._thread = self._thread, None
        pump_exited = True
        if thread is not None:
            thread.join(timeout=5)
            pump_exited = not thread.is_alive()
        if proc is not None and proc.stdout is not None and pump_exited:
            # Close ONLY once the pump thread actually exited — closing
            # under a still-blocked reader raises inside it.  A wedged
            # pump (handler stuck >5s) keeps its pipe and falls to GC
            # instead; a leaked pipe on the clean path would trip the
            # test-race ResourceWarning gate.
            proc.stdout.close()


def _parse_route_line(line: str) -> Optional[RouteEvent]:
    """One ``ip -o monitor route`` line -> RouteEvent (None = not a
    unicast route change we track)."""
    deleted = False
    if line.startswith("Deleted "):
        deleted = True
        line = line[len("Deleted "):]
    fields = line.split()
    if not fields or fields[0] in ("local", "broadcast", "multicast"):
        return None
    dst = fields[0]
    if dst == "unreachable" or ":" in dst:  # v6 / special: out of scope
        return None
    if dst == "default":
        dst = "0.0.0.0/0"
    values = dict(zip(fields[1::2], fields[2::2]))
    gateway = values.get("via", "")
    proto = _proto_number(values.get("proto", "0"))
    try:
        ipaddress.ip_network(dst, strict=False)
    except ValueError:
        return None
    return RouteEvent(
        type=RouteEventType.DELETE if deleted else RouteEventType.ADD,
        dst_network=dst,
        gateway=gateway,
        protocol=proto,
    )


class IpRouteSource:
    """BGPReflector RouteSource over iproute2 (list + monitor)."""

    def __init__(self, netns: Optional[str] = None):
        self.netns = netns
        self._monitor: Optional[_IpMonitor] = None

    def _ip(self, *args: str) -> List:
        cmd = ["ip"]
        if self.netns:
            cmd += ["-n", self.netns]
        cmd += ["-j", *args]
        out = subprocess.run(cmd, capture_output=True, text=True, check=True)
        return json.loads(out.stdout or "[]")

    def list_routes(self) -> Iterable[RouteEvent]:
        """Current unicast v4 routes (the RouteList analog)."""
        events = []
        for route in self._ip("route", "show"):
            dst = route.get("dst", "")
            if dst == "default":
                dst = "0.0.0.0/0"
            gateway = route.get("gateway", "")
            if not gateway:
                continue
            events.append(RouteEvent(
                type=RouteEventType.ADD,
                dst_network=dst,
                gateway=gateway,
                protocol=_proto_number(route.get("protocol")),
            ))
        return events

    def subscribe(self, handler: Callable[[RouteEvent], None]) -> None:
        def on_line(line: str) -> None:
            ev = _parse_route_line(line)
            if ev is not None:
                handler(ev)

        self._monitor = _IpMonitor("route", on_line, netns=self.netns)
        self._monitor.start()

    def close(self) -> None:
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None


class DhcpAddressSource:
    """DHCP-lease notifications from address-change events on the main
    interface.  Whatever DHCP client manages the uplink installs the
    leased address; this source turns that install into the
    DHCPLeaseChange event ipv4net consumes (UseDHCP mode)."""

    def __init__(self, interface: str, event_loop,
                 netns: Optional[str] = None):
        self.interface = interface
        self.event_loop = event_loop
        self.netns = netns
        self._monitor: Optional[_IpMonitor] = None

    def _default_gateway(self) -> str:
        cmd = ["ip"]
        if self.netns:
            cmd += ["-n", self.netns]
        cmd += ["-j", "route", "show", "default"]
        try:
            routes = json.loads(subprocess.run(
                cmd, capture_output=True, text=True, check=True
            ).stdout or "[]")
        except (subprocess.CalledProcessError, ValueError):
            return ""
        for route in routes:
            if route.get("dev") == self.interface and route.get("gateway"):
                return route["gateway"]
        return ""

    def _on_line(self, line: str) -> None:
        # "N: IFACE    inet A.B.C.D/LEN [brd ...] scope global ..."
        fields = line.split()
        if len(fields) < 4 or "inet" not in fields:
            return
        if line.startswith("Deleted"):
            return  # lease loss: the next lease re-renders
        iface = fields[1].rstrip(":")
        if iface != self.interface:
            return
        at = fields.index("inet")
        address = fields[at + 1]
        if "scope" in fields and fields[fields.index("scope") + 1] != "global":
            return
        from ..ipv4net.plugin import DHCPLeaseChange

        self.event_loop.push_event(DHCPLeaseChange(
            interface=self.interface,
            ip_address=address,
            gateway=self._default_gateway(),
        ))

    def start(self) -> None:
        self._monitor = _IpMonitor("address", self._on_line, netns=self.netns)
        self._monitor.start()

    def stop(self) -> None:
        if self._monitor is not None:
            self._monitor.stop()
            self._monitor = None
