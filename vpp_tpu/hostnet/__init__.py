from .applicator import LinuxNetApplicator

__all__ = ["LinuxNetApplicator"]
