"""Dashboard view models — the data-shaping behind the SPA's panels.

VERDICT r4 item 7: the dashboard's data pipelines used to live as
inline JS in ``static/index.html`` where nothing could test them.  The
shaping now happens HERE, as pure functions over the agents' REST
payloads (scheduler dump, ipam, trace), served to the page as ready
view models by the proxy's ``/api/views/<node>`` route — the page
renders rows, nothing more.  Regression coverage lives in
``tests/test_uibackend.py``; a broken view pipeline fails there, not
silently in a browser.

Reference analog: the per-view data services of the Angular SPA
(ui/src/app/{bridge-domain,pod-network,vswitch-diagram}).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

CONFIG_PREFIX = "/vpp-tpu/config/"


def _applied_by_prefix(dump: List[dict], prefix: str) -> Dict[str, dict]:
    """APPLIED values under ``prefix``, keyed by the key remainder
    (the JS ``dumpByPrefix`` this replaces)."""
    out: Dict[str, dict] = {}
    for v in dump:
        state = v.get("state")
        state_name = state.get("name") if isinstance(state, dict) else state
        if str(state_name).upper().endswith("APPLIED") and v.get(
            "key", ""
        ).startswith(prefix):
            out[v["key"][len(prefix):]] = v.get("applied") or {}
    return out


def shape_config_views(dump: List[dict],
                       pod_ips: Dict[str, str]) -> Dict[str, Any]:
    """Slice a scheduler dump into the bridge-domain, L2FIB,
    pod-network and vswitch-diagram view models."""
    p = CONFIG_PREFIX
    ifaces = _applied_by_prefix(dump, p + "interface/")
    bds = _applied_by_prefix(dump, p + "bd/")
    fibs = _applied_by_prefix(dump, p + "l2fib/")
    arps = _applied_by_prefix(dump, p + "arp/")
    routes = _applied_by_prefix(dump, p + "route/")

    bd_rows = [
        {"name": name, "bvi": bd.get("bvi_interface") or "",
         "members": list(bd.get("interfaces") or ())}
        for name, bd in sorted(bds.items())
    ]
    fib_rows = []
    for key, fe in sorted(fibs.items()):
        bd, _, mac = key.partition("/")
        fib_rows.append({"mac": mac or key, "bd": bd,
                         "interface": fe.get("outgoing_interface") or ""})

    route_dsts = {r.get("dst_network") for r in routes.values()}
    arp_ips = {k.rsplit("/", 1)[-1] for k in arps}
    podnet_rows = []
    for pod, ip in sorted(pod_ips.items()):
        ns, _, name = pod.partition("/")
        tap = f"tap-{ns}-{name}"
        podnet_rows.append({
            "pod": pod,
            "ip": str(ip),
            "tap": tap,
            "tap_ok": tap in ifaces,
            "route_ok": f"{ip}/32" in route_dsts,
            "arp_ok": str(ip) in arp_ips,
        })

    # vswitch diagram classification: spine BD + BVI, host-side
    # interconnects, vxlan tunnels, pod taps.
    bvi = next((bd.get("bvi_interface") for bd in bds.values()
                if bd.get("bvi_interface")), "")
    bd_name = next(iter(sorted(bds)), "")

    def itype(info: dict) -> str:
        t = info.get("type")
        return (t.get("name") if isinstance(t, dict) else str(t or "")).upper()

    tunnels = [
        {"name": n, "dst": i.get("vxlan_dst") or "",
         "vni": i.get("vxlan_vni")}
        for n, i in sorted(ifaces.items())
        if n.startswith("vxlan") and n != bvi
    ]
    taps = [
        {"name": n, "addresses": list(i.get("ip_addresses") or ())}
        for n, i in sorted(ifaces.items())
        if n.startswith("tap-") and not n.startswith("tap-vpp")
    ]
    host = [
        {"name": n, "addresses": list(i.get("ip_addresses") or ())}
        for n, i in sorted(ifaces.items())
        if n.startswith("tap-vpp") or itype(i).endswith("DPDK")
    ]
    return {
        "bds": bd_rows,
        "l2fib": fib_rows,
        "podnet": podnet_rows,
        "vswitch": {
            "bd": bd_name,
            "bvi": bvi,
            "bvi_addresses": list(
                (ifaces.get(bvi) or {}).get("ip_addresses") or ()),
            "members": list((bds.get(bd_name) or {}).get("interfaces") or ()),
            "host": host,
            "tunnels": tunnels,
            "taps": taps,
        },
    }


def shape_services(dump: List[dict]) -> List[dict]:
    """Service view rows (the ui/src/app services view analog): one row
    per DNAT mapping under the scheduler's ``tpu/nat/service/`` keys —
    VIP/port/proto, the weighted backend ring, ClientIP affinity."""
    rows = []
    for key, mappings in sorted(
        _applied_by_prefix(dump, "tpu/nat/service/").items()
    ):
        for m in mappings or ():
            backends = ", ".join(
                f"{b[0]}:{b[1]}" + (f" x{b[2]}" if b[2] != 1 else "")
                for b in (m.get("backends") or ())
            )
            rows.append({
                "service": key,
                "vip": f"{m.get('external_ip')}:{m.get('external_port')}",
                "protocol": {6: "tcp", 17: "udp"}.get(
                    m.get("protocol"), str(m.get("protocol"))),
                "backends": backends,
                "affinity": (f"{m.get('session_affinity_timeout')}s"
                             if m.get("session_affinity_timeout") else ""),
            })
    return rows


def shape_policies(dump: List[dict]) -> List[dict]:
    """Policy view rows (the ui/src/app policies view analog): one row
    per pod entry under ``tpu/acl/pod/`` — the compiled ingress/egress
    rule counts the classify tables carry for it."""
    rows = []
    for key, entry in sorted(_applied_by_prefix(dump, "tpu/acl/pod/").items()):
        # Entry shape: (pod_ip_u32, ingress_rules, egress_rules).
        ingress = entry[1] if isinstance(entry, (list, tuple)) and len(entry) > 1 else ()
        egress = entry[2] if isinstance(entry, (list, tuple)) and len(entry) > 2 else ()
        rows.append({
            "pod": key,
            "ingress_rules": len(ingress or ()),
            "egress_rules": len(egress or ()),
        })
    return rows


def shape_trace(entries: List[dict],
                filter_ip: Optional[str] = None,
                limit: int = 20) -> List[dict]:
    """Trace rows for the panel, newest first — optionally filtered to
    one pod's IP (the click-a-pod drill-down): an entry matches when
    the IP appears as its original or rewritten src/dst."""
    if filter_ip:
        entries = [
            e for e in entries
            if filter_ip in (e.get("src"), e.get("dst"),
                             e.get("rw_src"), e.get("rw_dst"))
        ]
    rows = []
    for e in entries[-limit:][::-1]:
        rows.append({
            "seq": e.get("seq"),
            "src": f"{e.get('src')}:{e.get('src_port')}",
            "dst": f"{e.get('dst')}:{e.get('dst_port')}",
            "rewritten": f"{e.get('rw_dst')}:{e.get('rw_dst_port')}",
            "allowed": bool(e.get("allowed")),
            "route": (e.get("route") or "")
            + (f"#{e.get('node_id')}" if e.get("route") == "remote" else ""),
            "flags": ",".join(
                f for f in ("dnat", "snat", "reply", "punt") if e.get(f)),
        })
    return rows


def shape_dispatch(inspect: Optional[dict]) -> Dict[str, Any]:
    """The dashboard's dispatch panel: the adaptive-coalesce state an
    operator watches during a load event — current K vs ceiling,
    ingress backlog, the learned dispatch-time model, the chosen-K
    histogram and SLO breaches.  Empty for agents without a live
    datapath (the page hides the panel)."""
    if not inspect:
        return {}
    dp = inspect.get("dispatch") or {}
    gov = dp.get("governor") or {}
    led = gov.get("ledger") or {}
    placement = dp.get("placement") or {}
    return {
        "engine": inspect.get("engine", ""),
        "discipline": dp.get("discipline", ""),
        "batch_size": dp.get("batch_size", 0),
        "max_vectors": dp.get("max_vectors", 0),
        "inflight": dp.get("inflight", 0),
        "max_inflight": dp.get("max_inflight", 0),
        "bypass": bool(dp.get("bypass_eligible")),
        "device_batches": dp.get("device_batches", 0),
        "prewarm": bool(dp.get("prewarm")),
        "governor": {
            "mode": "adaptive" if gov.get("enabled") else "fixed",
            "current_k": gov.get("current_k", 0),
            "ceiling": gov.get("ceiling", 0),
            "backlog": gov.get("backlog", 0),
            "window": gov.get("window", 0),
            "slo_us": gov.get("slo_us", 0),
            "slo_cap": gov.get("slo_cap", 0),
            "slo_breaches": gov.get("slo_breaches", 0),
            "decisions": gov.get("decisions", 0),
            "samples": gov.get("samples", 0),
            "floor_us": gov.get("floor_us"),
            "vec_us": gov.get("vec_us"),
            "k_histogram": gov.get("k_histogram") or {},
            # Sharded engines report per-shard K/backlog (each shard
            # has its own rings); solo runners omit them.
            "per_shard_k": gov.get("per_shard_k") or [],
            "per_shard_backlog": gov.get("per_shard_backlog") or [],
            "ledger_constrained": gov.get("ledger_constrained", 0),
        },
        # Global coalesce-SLO budget ledger (sharded engines, ISSUE
        # 12): the shared pool the per-shard caps are computed
        # against — empty for solo runners (the panel hides the row).
        "ledger": {
            "slo_us": led.get("slo_us", 0),
            "committed_us": led.get("committed_us", 0),
            "per_shard_claim_us": led.get("per_shard_claim_us") or [],
            "constrained_total": led.get("constrained_total", 0),
        } if led else {},
        # CPU/NUMA placement of the admit shards (opt-in affinity map
        # next to what each worker actually applied).
        "placement": {
            "shard_cores": placement.get("shard_cores") or [],
            "applied": placement.get("applied") or [],
            "host_cores": placement.get("host_cores", 0),
        } if placement else {},
    }


def shape_latency(inspect: Optional[dict]) -> Dict[str, Any]:
    """The dashboard's latency panel (ISSUE 8): the four datapath
    histograms' counts and p50/p90/p99/p99.9 — the `show runtime`
    clocks analog an operator reads during a latency event.  Every key
    consumed here is produced by ``DataplaneRunner.inspect`` /
    ``inspect_latency`` / ``Log2Histogram.snapshot`` — the obs-parity
    checker enforces the schema so this panel can never silently go
    blank.  Empty for agents without a live datapath."""
    if not inspect:
        return {}
    lat = inspect.get("latency") or {}
    out: Dict[str, Any] = {}
    for name in ("admit_wait", "dispatch_rt", "harvest", "frame_e2e"):
        h = lat.get(name) or {}
        out[name] = {
            "count": h.get("count", 0),
            "sum_us": h.get("sum_us", 0.0),
            "p50": h.get("p50", 0.0),
            "p90": h.get("p90", 0.0),
            "p99": h.get("p99", 0.0),
            "p999": h.get("p999", 0.0),
        }
    flight = inspect.get("flight") or {}
    out["flight"] = {
        "recorded": flight.get("recorded", 0),
        "capacity": flight.get("capacity", 0),
        "dispatches_total": flight.get("dispatches_total", 0),
    }
    return out


def shape_inference(inspect: Optional[dict]) -> Dict[str, Any]:
    """The dashboard's inference panel (ISSUE 14): the in-network
    scoring plane an operator reads during a score storm — enrollment
    state, per-action firing counters, and the score log2-histogram
    (band k = score >= 1 - 2^-k).  Every key consumed here is produced
    by ``DataplaneRunner.inspect_inference`` (sharded engines merge the
    same schema) — the obs-parity checker holds the pair together so
    the panel can never silently go blank.  Empty for agents without a
    live datapath (the page hides the panel)."""
    if not inspect:
        return {}
    inf = inspect.get("inference") or {}
    return {
        "enabled": bool(inf.get("enabled")),
        "pods": inf.get("pods", 0),
        "features": inf.get("features", 0),
        "hidden": inf.get("hidden", 0),
        "swaps": inf.get("swaps", 0),
        "scored": inf.get("scored", 0),
        "logged": inf.get("logged", 0),
        "deprioritized": inf.get("deprioritized", 0),
        "quarantined": inf.get("quarantined", 0),
        "score_bands": inf.get("score_bands") or [],
    }


def shape_cluster(summary: Optional[dict]) -> Dict[str, Any]:
    """The dashboard's cluster panel (ISSUE 10): the fleet rollup an
    operator reads when the question is "is the CLUSTER healthy" —
    reachability (gaps named, with last-seen ages), cluster-merged
    latency percentiles, straggler nodes, and the freshest stitched
    propagation spans.  Every key consumed here is produced by the
    aggregator (``ClusterScraper.summary`` and the telemetry stitch/
    skew helpers) — the obs-parity checker holds the two together so
    this panel can never silently go blank.  Empty when no aggregator
    ran (single-node deployments hide the panel)."""
    if not summary:
        return {}
    lat = summary.get("latency") or {}
    skew = summary.get("skew") or {}
    rows = []
    for r in summary.get("per_node") or []:
        rows.append({
            "node": r.get("node", ""),
            "ok": bool(r.get("ok")),
            "error": r.get("error", ""),
            "last_seen_age_s": r.get("last_seen_age_s"),
            "shards_serving": r.get("shards_serving"),
            "shards_total": r.get("shards_total"),
            "events": r.get("events", 0),
            "event_errors": r.get("event_errors", 0),
            "healing_pending": bool(r.get("healing_pending")),
            "healing_failed": r.get("healing_failed", 0),
            "p99_dispatch_us": r.get("p99_dispatch_us"),
        })
    spans = []
    for sp in (summary.get("spans") or [])[:8]:
        spans.append({
            "revision": sp.get("revision", 0),
            "event": sp.get("event", ""),
            "nodes": sp.get("nodes", 0),
            "p50_lag_us": sp.get("p50_lag_us", 0.0),
            "p99_lag_us": sp.get("p99_lag_us", 0.0),
            "last_lag_us": sp.get("last_lag_us", 0.0),
            "last_node": sp.get("last_node", ""),
            "stragglers": sp.get("stragglers") or [],
        })
    latency = {}
    for name in ("admit_wait", "dispatch_rt", "harvest", "frame_e2e"):
        h = lat.get(name) or {}
        latency[name] = {
            "count": h.get("count", 0),
            "p50": h.get("p50", 0.0),
            "p99": h.get("p99", 0.0),
            "p999": h.get("p999", 0.0),
        }
    return {
        "nodes_total": summary.get("nodes_total", 0),
        "nodes_ok": summary.get("nodes_ok", 0),
        "nodes_unreachable": summary.get("nodes_unreachable", 0),
        "gaps": summary.get("gaps") or [],
        "per_node": rows,
        "latency": latency,
        "skew": {
            "metric": skew.get("metric", ""),
            "cluster_median_us": skew.get("cluster_median_us", 0.0),
            "stragglers": skew.get("stragglers") or [],
        },
        "spans": spans,
    }


def shape_views(dump: List[dict], ipam: dict, trace: dict,
                trace_ip: Optional[str] = None,
                inspect: Optional[dict] = None) -> Dict[str, Any]:
    """The full ``/api/views/<node>`` payload."""
    pod_ips = (ipam or {}).get("allocatedPodIPs") or {}
    out = shape_config_views(dump or [], pod_ips)
    out["services"] = shape_services(dump or [])
    out["policies"] = shape_policies(dump or [])
    out["config_kvs"] = len(dump or [])
    out["trace"] = {
        "status": (trace or {}).get("status") or {},
        "filter_ip": trace_ip or "",
        "rows": shape_trace((trace or {}).get("entries") or [], trace_ip),
    }
    out["dispatch"] = shape_dispatch(inspect)
    out["latency"] = shape_latency(inspect)
    out["inference"] = shape_inference(inspect)
    return out
