"""UI backend — authenticated reverse proxy for the web dashboard.

Analog of ``cmd/contiv-ui-backend/main.go`` (329 LoC): a single
entry point the browser UI talks to, with basic auth and three proxied
route families (k8sHandler :118, contivHandler :149, netctlHandler
:192):

- ``/api/k8s/<path>``          -> the K8s API server (bearer token
                                  appended, like the service-account
                                  token mount);
- ``/api/contiv/<node>/<path>``-> the named node agent's REST API
                                  (AgentRestServer), resolved through
                                  an injectable node directory;
- ``/api/netctl``              -> POST {"args": [...]} executes a
                                  netctl command and returns its
                                  output (the reference shells out to
                                  the netctl binary via the CRD pod);
- ``/`` and ``/static/...``    -> the bundled dashboard
                                  (vpp_tpu/uibackend/static/), the
                                  Angular-SPA replacement.

Auth follows the reference: an empty credential map disables basic
auth (Config.IsBasicAuthOK :93).  TLS is delegated to the deployment
(terminate in front, e.g. k8s ingress) rather than in-process.
"""

from __future__ import annotations

import base64
import hmac
import json
import logging
import os
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from pathlib import Path
from typing import Callable, Dict, Optional

log = logging.getLogger(__name__)

_STATIC_DIR = Path(__file__).parent / "static"
_CONTENT_TYPES = {
    ".html": "text/html; charset=utf-8",
    ".js": "application/javascript",
    ".css": "text/css",
    ".svg": "image/svg+xml",
}


class UIBackend:
    """The proxy server.

    ``node_directory`` maps node name -> "host:port" of its agent REST
    server; ``k8s_base_url``/``k8s_token`` configure the K8s API proxy;
    ``netctl_runner(args) -> (exit_code, output)`` executes netctl
    commands (defaults to the in-process netctl CLI).
    """

    def __init__(
        self,
        node_directory: Callable[[str], Optional[str]],
        list_nodes: Optional[Callable[[], list]] = None,
        k8s_base_url: str = "",
        k8s_token: str = "",
        basic_auth: Optional[Dict[str, str]] = None,
        netctl_runner: Optional[Callable[[list], tuple]] = None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.node_directory = node_directory
        self.list_nodes = list_nodes
        self.k8s_base_url = k8s_base_url.rstrip("/")
        self.k8s_token = k8s_token
        self.basic_auth = basic_auth or {}
        self.netctl_runner = netctl_runner or self._run_netctl
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        # One fleet scraper for the backend's lifetime (ISSUE 10): its
        # last-seen map persists across /api/cluster requests, so the
        # panel's gap rows carry real ages (a per-request scraper would
        # report every outage as "never seen").
        self._scraper = None
        self._scraper_lock = threading.Lock()

    def _cluster_scraper(self):
        from ..statscollector.cluster import ClusterScraper

        def servers():
            out = {}
            for name in self.list_nodes():
                server = self.node_directory(name)
                if server:
                    out[name] = server
            return out

        with self._scraper_lock:
            if self._scraper is None:
                self._scraper = ClusterScraper(servers)
            return self._scraper

    # ----------------------------------------------------------------- auth

    def check_auth(self, header: Optional[str]) -> bool:
        """Empty credential map = auth disabled (main.go :93-96)."""
        if not self.basic_auth:
            return True
        if not header or not header.startswith("Basic "):
            return False
        try:
            user, _, pw = (
                base64.b64decode(header[len("Basic "):]).decode().partition(":")
            )
        except Exception:
            return False
        expected = self.basic_auth.get(user)
        if expected is None:
            # Burn comparable time for unknown users; never authenticate
            # them (an empty-string fallback would let "ghost:" in).
            hmac.compare_digest(pw.encode(), pw.encode())
            return False
        # Compare UTF-8 bytes: compare_digest on str raises TypeError for
        # non-ASCII input, which would crash the handler thread.
        return hmac.compare_digest(expected.encode(), pw.encode())

    # --------------------------------------------------------------- routes

    @staticmethod
    def _run_netctl(args: list) -> tuple:
        import contextlib
        import io

        from ..netctl.cli import main as netctl_main

        out = io.StringIO()
        try:
            with contextlib.redirect_stderr(out):
                code = netctl_main([str(a) for a in args], out=out)
        except SystemExit as exc:  # argparse error paths
            code = int(exc.code or 0)
        return code, out.getvalue()

    def _proxy(
        self,
        url: str,
        method: str,
        body: Optional[bytes],
        token: str = "",
        content_type: str = "",
    ):
        req = urllib.request.Request(url, data=body, method=method)
        if token:
            req.add_header("Authorization", f"Bearer {token}")
        if content_type:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req, timeout=15) as resp:
                return resp.status, resp.headers.get_content_type(), resp.read()
        except urllib.error.HTTPError as exc:
            return exc.code, "text/plain", exc.read()
        except OSError as exc:
            return 502, "text/plain", str(exc).encode()

    def handle(
        self,
        path: str,
        method: str,
        body: Optional[bytes],
        auth_header,
        query: str = "",
        content_type: str = "",
    ):
        """Route one request; returns (status, content_type, payload)."""
        if not self.check_auth(auth_header):
            return 401, "text/plain", b"Unauthorized."

        suffix = f"?{query}" if query else ""
        if path.startswith("/api/k8s/"):
            if not self.k8s_base_url:
                return 502, "text/plain", b"k8s API proxy not configured"
            target = f"{self.k8s_base_url}/{path[len('/api/k8s/'):]}{suffix}"
            return self._proxy(target, method, body, self.k8s_token, content_type)

        if path.startswith("/api/contiv/"):
            rest = path[len("/api/contiv/"):]
            node, _, agent_path = rest.partition("/")
            server = self.node_directory(node)
            if server is None:
                return 404, "text/plain", f"unknown node {node!r}".encode()
            return self._proxy(
                f"http://{server}/{agent_path}{suffix}", method, body,
                content_type=content_type,
            )

        if path == "/api/nodes-directory":
            names = sorted(self.list_nodes()) if self.list_nodes else []
            return 200, "application/json", json.dumps(names).encode()

        if path == "/api/cluster":
            # The fleet panel (ISSUE 10): one concurrent sweep over
            # every agent in the directory, shaped for the dashboard.
            # Unreachable agents arrive as gap rows inside the payload
            # — the page renders partial fleets, it never blanks.
            from .views import shape_cluster

            if self.list_nodes is None:
                return 502, "text/plain", b"no node directory"
            shaped = shape_cluster(self._cluster_scraper().summary())
            return 200, "application/json", json.dumps(shaped).encode()

        if path.startswith("/api/views/"):
            # Shaped dashboard view models (vpp_tpu/uibackend/views.py):
            # the data pipelines behind the config/trace panels run HERE
            # (testable Python), not in the page's JS.  ?trace_ip=<ip>
            # filters the trace panel to one pod (click-a-pod
            # drill-down).
            node = path[len("/api/views/"):]
            server = self.node_directory(node)
            if server is None:
                return 404, "text/plain", f"unknown node {node!r}".encode()
            from urllib.parse import parse_qs

            from .views import shape_views

            trace_ip = (parse_qs(query).get("trace_ip") or [""])[0]
            errors: dict = {}

            def agent_json(label: str, agent_path: str):
                status, _, payload = self._proxy(
                    f"http://{server}/{agent_path}", "GET", None)
                if status != 200:
                    errors[label] = (
                        f"HTTP {status}: "
                        f"{payload.decode(errors='replace')[:200]}")
                    return None
                try:
                    return json.loads(payload.decode())
                except json.JSONDecodeError as exc:
                    errors[label] = f"bad JSON: {exc}"
                    return None

            dump = agent_json("dump", "scheduler/dump")
            ipam = agent_json("ipam", "contiv/v1/ipam")
            trace = agent_json("trace", "contiv/v1/trace")
            if len(errors) == 3:
                # The agent is unreachable outright: surface it as an
                # error, never as a healthy-looking empty dashboard.
                return (502, "text/plain",
                        f"agent {node!r}: {errors['dump']}".encode())
            # Dispatch/governor panel: optional — an agent without a
            # live datapath 404s here, which must not error the page
            # (the panel just hides).
            inspect = agent_json("inspect", "contiv/v1/inspect")
            if inspect is None:
                errors.pop("inspect", None)
            shaped = shape_views(dump or [], ipam or {}, trace or {},
                                 trace_ip=trace_ip or None,
                                 inspect=inspect)
            # Partial failures reach the page per panel (the JS renders
            # them into the affected tables instead of empty rows).
            shaped["errors"] = errors
            return 200, "application/json", json.dumps(shaped).encode()

        if path == "/api/netctl":
            if method != "POST":
                return 405, "text/plain", b"POST {\"args\": [...]}"
            try:
                payload_in = json.loads(body or b"{}")
            except json.JSONDecodeError:
                return 400, "text/plain", b"invalid JSON"
            if not isinstance(payload_in, dict) or not isinstance(
                payload_in.get("args", []), list
            ):
                return 400, "text/plain", b'expected {"args": [...]}'
            args = payload_in.get("args", [])
            # Optional node targeting (the dashboard's netctl console):
            # resolve the node name to its agent address and pass it as
            # --server, unless the caller already provided one (either
            # argparse form — "--server host" or "--server=host").
            target = payload_in.get("node", "")
            if target and not isinstance(target, str):
                return 400, "text/plain", b'"node" must be a string'
            has_server = any(
                isinstance(a, str) and (a == "--server"
                                        or a.startswith("--server="))
                for a in args
            )
            if target and not has_server:
                server = self.node_directory(target)
                if server is None:
                    return (404, "text/plain",
                            f"unknown node {target!r}".encode())
                args = list(args) + ["--server", server]
            code, output = self.netctl_runner(args)
            payload = json.dumps({"exit_code": code, "output": output}).encode()
            return 200, "application/json", payload

        return self._serve_static(path)

    def _serve_static(self, path: str):
        name = "index.html" if path in ("", "/") else path.lstrip("/")
        target = (_STATIC_DIR / name).resolve()
        static_root = _STATIC_DIR.resolve()
        if not (target == static_root or str(target).startswith(str(static_root) + os.sep)) or not target.is_file():
            return 404, "text/plain", b"not found"
        ctype = _CONTENT_TYPES.get(target.suffix, "application/octet-stream")
        return 200, ctype, target.read_bytes()

    # --------------------------------------------------------------- server

    def start(self) -> int:
        backend = self

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self, method):
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else None
                path, _, query = self.path.partition("?")
                status, ctype, payload = backend.handle(
                    path,
                    method,
                    body,
                    self.headers.get("Authorization"),
                    query=query,
                    content_type=self.headers.get("Content-Type") or "",
                )
                self.send_response(status)
                if status == 401:
                    self.send_header(
                        "WWW-Authenticate", "Basic realm=vpp-tpu-ui"
                    )
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_PATCH(self):
                self._dispatch("PATCH")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def log_message(self, fmt, *args):
                log.debug("ui-backend: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="ui-backend", daemon=True
        )
        self._thread.start()
        log.info("ui-backend listening on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
