from .proxy import UIBackend

__all__ = ["UIBackend"]
