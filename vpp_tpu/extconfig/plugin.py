"""External-config gRPC plugin.

Analog of ``plugins/grpc`` (contiv-grpc): a gRPC server through which
an external agent injects non-K8s config — arbitrary data-plane KVs
merged with the K8s-derived config by the controller.  Behaviors pinned
to the reference:

- ``ChangeSvc.Put`` / ``Delete`` (grpc_plugin.go :135): incremental
  changes, applied to the cluster store under the external-config
  prefix (the controller turns them into ExternalConfigChange events);
- ``ResyncSvc.Resync`` (:183): full replacement of the external config;
- the current snapshot is persisted locally — sqlite standing in for
  the reference's Bolt ``/var/bolt/grpc.db`` (:74-128) — so a restart
  can reload external config before any client reconnects
  (``GetConfigSnapshot``, the ExternalConfigSource contract used at
  plugin_controller.go:248);
- values are JSON documents (the proto-message analog at this
  boundary).

The wire protocol mirrors vpp_tpu.cni.rpc: gRPC with JSON-encoded
messages through generic method handlers.
"""

from __future__ import annotations

import json
import logging
import sqlite3
import threading
from concurrent import futures
from typing import Any, Dict, Optional

import grpc

from ..controller.dbwatcher import EXTERNAL_CONFIG_PREFIX
from ..kvstore import KVStore

log = logging.getLogger(__name__)

SERVICE_NAME = "config.ExternalConfig"
DEFAULT_PORT = 9112


def _encode(msg: dict) -> bytes:
    return json.dumps(msg).encode()


def _decode(data: bytes) -> dict:
    return json.loads(data.decode())


class SnapshotDB:
    """Local persistence of the external-config snapshot (Bolt analog)."""

    def __init__(self, path: str = ":memory:"):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS extconfig (key TEXT PRIMARY KEY, value TEXT)"
            )
            self._conn.commit()

    def put(self, key: str, value: Any) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO extconfig (key, value) VALUES (?, ?)",
                (key, json.dumps(value)),
            )
            self._conn.commit()

    def delete(self, key: str) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM extconfig WHERE key = ?", (key,))
            self._conn.commit()

    def replace_all(self, values: Dict[str, Any]) -> None:
        with self._lock:
            self._conn.execute("DELETE FROM extconfig")
            self._conn.executemany(
                "INSERT INTO extconfig (key, value) VALUES (?, ?)",
                [(k, json.dumps(v)) for k, v in values.items()],
            )
            self._conn.commit()

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            rows = self._conn.execute("SELECT key, value FROM extconfig").fetchall()
        return {k: json.loads(v) for k, v in rows}

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class ExternalConfigPlugin:
    """The gRPC NB config server + ExternalConfigSource."""

    def __init__(self, store: KVStore, db_path: str = ":memory:",
                 port: int = DEFAULT_PORT, host: str = "127.0.0.1"):
        self.store = store
        self.db = SnapshotDB(db_path)
        self.port = port
        self.host = host
        self._server: Optional[grpc.Server] = None

    # ----------------------------------------------- ExternalConfigSource

    def get_config_snapshot(self) -> Dict[str, Any]:
        """The persisted external config (GetConfigSnapshot :97) — used to
        pre-seed the store before the first resync after a restart."""
        return {EXTERNAL_CONFIG_PREFIX + k: v for k, v in self.db.snapshot().items()}

    def preseed_store(self) -> None:
        """Load the persisted snapshot into the cluster store (the restart
        path: external config survives even if no client reconnects)."""
        for key, value in self.get_config_snapshot().items():
            self.store.put(key, value)

    # ------------------------------------------------------------ handlers

    def _put(self, request: dict, context=None) -> dict:
        key, value = request.get("key", ""), request.get("value")
        if not key or value is None:
            return {"ok": False, "error": "key and value required"}
        self.db.put(key, value)
        self.store.put(EXTERNAL_CONFIG_PREFIX + key, value)
        return {"ok": True}

    def _delete(self, request: dict, context=None) -> dict:
        key = request.get("key", "")
        if not key:
            return {"ok": False, "error": "key required"}
        self.db.delete(key)
        self.store.delete(EXTERNAL_CONFIG_PREFIX + key)
        return {"ok": True}

    def _resync(self, request: dict, context=None) -> dict:
        """Full replacement (ResyncSvc.Resync :183): stale keys deleted."""
        values = request.get("values", {})
        if not isinstance(values, dict):
            return {"ok": False, "error": "values must be an object"}
        old = set(self.db.snapshot())
        self.db.replace_all(values)
        for key in old - set(values):
            self.store.delete(EXTERNAL_CONFIG_PREFIX + key)
        for key, value in values.items():
            self.store.put(EXTERNAL_CONFIG_PREFIX + key, value)
        return {"ok": True, "count": len(values)}

    def _get(self, request: dict, context=None) -> dict:
        return {"ok": True, "values": self.db.snapshot()}

    # ------------------------------------------------------------ lifecycle

    def start(self) -> int:
        handlers = {
            name: grpc.unary_unary_rpc_method_handler(
                fn, request_deserializer=_decode, response_serializer=_encode
            )
            for name, fn in [
                ("Put", self._put),
                ("Delete", self._delete),
                ("Resync", self._resync),
                ("Get", self._get),
            ]
        }
        self._server = grpc.server(futures.ThreadPoolExecutor(max_workers=4))
        self._server.add_generic_rpc_handlers(
            (grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),)
        )
        self.port = self._server.add_insecure_port(f"{self.host}:{self.port}")
        self._server.start()
        log.info("external-config gRPC server on %s:%d", self.host, self.port)
        return self.port

    def stop(self, grace: float = 1.0) -> None:
        if self._server is not None:
            self._server.stop(grace)
            self._server = None
        self.db.close()


# ------------------------------------------------------------------ client


def _call(target: str, method: str, request: dict, timeout: float = 10.0) -> dict:
    with grpc.insecure_channel(target) as channel:
        rpc = channel.unary_unary(
            f"/{SERVICE_NAME}/{method}",
            request_serializer=_encode,
            response_deserializer=_decode,
        )
        return rpc(request, timeout=timeout)


def ext_config_put(target: str, key: str, value: Any) -> dict:
    return _call(target, "Put", {"key": key, "value": value})


def ext_config_delete(target: str, key: str) -> dict:
    return _call(target, "Delete", {"key": key})


def ext_config_resync(target: str, values: Dict[str, Any]) -> dict:
    return _call(target, "Resync", {"values": values})


def ext_config_get(target: str) -> dict:
    return _call(target, "Get", {})
