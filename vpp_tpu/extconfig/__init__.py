"""External (non-K8s) configuration source — gRPC NB API."""

from .plugin import (
    ExternalConfigPlugin,
    ext_config_get,
    ext_config_put,
    ext_config_resync,
)

__all__ = [
    "ExternalConfigPlugin",
    "ext_config_get",
    "ext_config_put",
    "ext_config_resync",
]
