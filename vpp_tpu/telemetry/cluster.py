"""Cluster-scope telemetry math — span stitching + cross-node merges.

ISSUE 10 tentpole, pillar 1: PR 6 gave every agent a per-node
propagation story (one controller's event → compile → swap → adoption),
but a 50–100-node cluster's operational question is different — *when
one policy/service write lands in the store, how long until EVERY node
serves it, and which nodes straggle?*  The answer needs no cross-agent
protocol: the HA store replicates revisions bit-identically (PR 1), the
watch delivery threads each write's revision into the controller event
(dbwatcher), and the event's span records it (``Span.revision``).  One
write therefore leaves N spans — one per agent — all carrying the SAME
revision, and stitching is a pure host-side group-by over the agents'
``/contiv/v1/spans`` dumps.

This module is deliberately free of any I/O: it takes the span dicts /
histogram snapshots the REST surfaces already serve and produces the
cluster views.  The scraping half (concurrent REST polling, partial-
failure tolerance) lives in :mod:`vpp_tpu.statscollector.cluster`.

Stitched-span semantics: per revision, the anchor is the EARLIEST span
start across nodes (the closest observable proxy for the store commit —
the first agent whose watch delivered the write); each node's
*adoption lag* is its span's completion (start + total) minus that
anchor.  first/last/p50/p99 lags summarize the propagation wavefront,
and a node whose lag exceeds ``straggler_factor ×`` the cluster median
is named a straggler.  Wall clocks across agents are only comparable to
the cluster's clock-sync quality — same box in the harnesses, NTP in
production — which is exactly the resolution fleet operators act on.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from .hist import LATENCY_HISTOGRAMS, Log2Histogram

# A node is a straggler when its adoption lag (or latency percentile)
# exceeds this factor times the cluster median — k=3 keeps ordinary
# jitter quiet while real stalls (GC pause, store reconnect, compile
# storm) are an order of magnitude out.
DEFAULT_STRAGGLER_FACTOR = 3.0


def _pct(sorted_values: List[float], q: float) -> float:
    """Exact percentile over a small sorted list (nearest-rank); the
    cluster has tens of nodes, not millions of samples — no buckets."""
    if not sorted_values:
        return 0.0
    idx = max(0, min(len(sorted_values) - 1,
                     int(round(q * (len(sorted_values) - 1)))))
    return sorted_values[idx]


def stitch_spans(
    per_node_spans: Dict[str, List[dict]],
    min_nodes: int = 2,
    straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
    limit: int = 0,
) -> List[dict]:
    """Group every node's span dumps by store revision into cluster
    propagation spans.

    ``per_node_spans`` maps node name → that agent's span dicts (the
    ``spans`` list of ``GET /contiv/v1/spans``).  Revisions seen on
    fewer than ``min_nodes`` nodes are dropped (a lone span stitches
    nothing).  Returns newest-first, ``limit``-bounded when > 0.
    """
    by_rev: Dict[int, Dict[str, dict]] = {}
    for node, spans in per_node_spans.items():
        for span in spans or ():
            rev = int(span.get("revision") or 0)
            if rev <= 0:
                continue
            # One event per (node, revision): a node replaying the same
            # revision (mirror resync) keeps its LATEST span — the one
            # describing the state it currently serves.
            slot = by_rev.setdefault(rev, {})
            prev = slot.get(node)
            if prev is None or span.get("started", 0) >= prev.get("started", 0):
                slot[node] = span

    out: List[dict] = []
    for rev in sorted(by_rev, reverse=True):
        nodes = by_rev[rev]
        if len(nodes) < min_nodes:
            continue
        t0 = min(float(s.get("started") or 0.0) for s in nodes.values())
        lags = []
        for node, span in nodes.items():
            done = (float(span.get("started") or 0.0)
                    + float(span.get("total_us") or 0.0) / 1e6)
            lags.append((node, max(0.0, (done - t0) * 1e6)))
        lags.sort(key=lambda nl: nl[1])
        lag_values = [us for _, us in lags]
        median = _pct(lag_values, 0.5)
        stragglers = [
            {"node": node, "lag_us": round(us, 1)}
            for node, us in lags
            if median > 0 and us > straggler_factor * median
        ]
        first_node, first_lag = lags[0]
        last_node, last_lag = lags[-1]
        sample = nodes[last_node]
        out.append({
            "revision": rev,
            "event": sample.get("event", ""),
            "detail": sample.get("detail", ""),
            "nodes": len(nodes),
            "node_names": [node for node, _ in lags],
            "propagated_nodes": sum(
                1 for s in nodes.values() if s.get("propagated")),
            "anchor": round(t0, 6),
            "first_node": first_node,
            "first_lag_us": round(first_lag, 1),
            "last_node": last_node,
            "last_lag_us": round(last_lag, 1),
            "p50_lag_us": round(median, 1),
            "p99_lag_us": round(_pct(lag_values, 0.99), 1),
            "stragglers": stragglers,
        })
        if limit > 0 and len(out) >= limit:
            break
    return out


def merge_latency_snapshots(
    per_node_latency: Dict[str, dict],
    names: Iterable[str] = LATENCY_HISTOGRAMS,
) -> Dict[str, dict]:
    """Merge N agents' ``inspect()["latency"]`` sections into cluster
    distributions: per pillar, sum the raw log2 buckets every snapshot
    now carries and re-derive the percentiles — the same merge-on-read
    the sharded engine does across shards, one level up."""
    out: Dict[str, dict] = {}
    for name in names:
        hists = [
            Log2Histogram.from_buckets(
                ((lat or {}).get(name) or {}).get("buckets"),
                ((lat or {}).get(name) or {}).get("sum_us") or 0.0)
            for lat in per_node_latency.values()
        ]
        out[name] = Log2Histogram().merged(hists).snapshot()
    return out


def latency_skew(
    per_node_latency: Dict[str, dict],
    metric: str = "dispatch_rt",
    quantile_key: str = "p99",
    straggler_factor: float = DEFAULT_STRAGGLER_FACTOR,
) -> dict:
    """Node-skew detection: a node whose ``metric`` ``p99`` exceeds
    ``straggler_factor ×`` the cluster median of that percentile is a
    straggler — the per-node view fleet dashboards page on."""
    per_node: List[dict] = []
    values: List[float] = []
    for node in sorted(per_node_latency):
        snap = (per_node_latency[node] or {}).get(metric) or {}
        value = float(snap.get(quantile_key) or 0.0)
        if snap.get("count"):
            values.append(value)
        per_node.append({"node": node, "value_us": round(value, 1),
                         "samples": int(snap.get("count") or 0)})
    values.sort()
    median = _pct(values, 0.5)
    stragglers = [
        row for row in per_node
        if row["samples"] and median > 0
        and row["value_us"] > straggler_factor * median
    ]
    return {
        "metric": metric,
        "quantile": quantile_key,
        "factor": straggler_factor,
        "cluster_median_us": round(median, 1),
        "per_node": per_node,
        "stragglers": stragglers,
    }
