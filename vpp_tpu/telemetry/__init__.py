"""End-to-end telemetry (ISSUE 8): datapath latency histograms,
control-plane propagation spans, and the per-shard flight recorder.

Three pillars, one design rule — the hot path pays arithmetic only:

- :mod:`.hist` — lock-free single-writer log2 latency histograms fed
  from the perf_counter timestamps the coalesce governor already takes
  (zero new clock calls or host↔device syncs on the dispatch path);
  merged on read, percentiles derived on read.
- :mod:`.spans` — a span minted per controller event, stages stamped
  through the whole propagation chain (handlers → compile → swap →
  per-shard adoption) via a thread-local, totals folded into the
  config-propagation histogram.
- :mod:`.flight` — a bounded per-shard ring of dispatch records,
  snapshotted next to the forensic pcap on ejection/quarantine.
- :mod:`.cluster` — the fleet-scope math (ISSUE 10): cross-node span
  stitching by store revision, bucket-exact histogram merges across
  agents, node-skew/straggler detection.  Pure functions; the REST
  scraping lives in :mod:`vpp_tpu.statscollector.cluster`.
"""

from .cluster import latency_skew, merge_latency_snapshots, stitch_spans
from .flight import FlightRecorder
from .hist import LATENCY_HISTOGRAMS, LatencyRecorder, Log2Histogram
from .spans import SpanTracker, current_span_id, record_stage

__all__ = [
    "FlightRecorder",
    "LATENCY_HISTOGRAMS",
    "LatencyRecorder",
    "Log2Histogram",
    "SpanTracker",
    "current_span_id",
    "latency_skew",
    "merge_latency_snapshots",
    "record_stage",
    "stitch_spans",
]
