"""Flight recorder — the last N dispatches, readable after the crash.

Before ISSUE 8, a shard ejection left exactly one artifact: the
forensic pcap of the poisoned frames.  *What the shard was doing* in
the seconds before — how deep its coalesce ran, how far the backlog
had grown, which table generation it served, what the verdict mix
looked like — was gone with the abandoned worker thread.  The flight
recorder is a per-shard bounded ring of per-dispatch records, appended
at harvest (single writer, no locks, raw ints only — the same
discipline as the packet tracer) and

- **snapshotted automatically** next to the forensic pcap on shard
  ejection and poisoned-batch quarantine (JSONL, one snapshot object
  per line, appended + flushed so it survives the crash it documents),
- **dumpable on demand** via REST ``/contiv/v1/flight`` and
  ``netctl flight`` for live post-mortems.

Record fields: monotonic sequence, the batch's session timestamp, the
governor-chosen K, frame/sent/denied counts, the measured ingress
backlog, the in-flight depth at admit, the table generation the batch
dispatched under (correlates with spans + ``netctl trace``), and the
admit→harvest round trip in µs.
"""

from __future__ import annotations

import collections
import datetime
import json
import threading
from typing import Deque, Dict, List, Optional

DEFAULT_CAPACITY = 256

FIELDS = ("seq", "ts", "k", "frames", "sent", "denied", "backlog",
          "inflight", "table_gen", "rt_us")

# Snapshot appends serialize process-wide: the sharded engine hands
# every shard the same quarantine_pcap, so N shards' snapshots target
# ONE .flight.jsonl — a quarantine (shard executor thread) racing an
# ejection (supervisor thread) would otherwise interleave buffered
# writes mid-line and corrupt the very post-mortem a fault storm needs.
_SNAPSHOT_LOCK = threading.Lock()


class FlightRecorder:
    """Bounded per-shard dispatch ring; lock-free single-writer append
    (the shard's worker), read-side copy for dumps (REST thread) — a
    deque append racing a list() copy is safe under the GIL, and a
    dump that misses the newest record is one poll stale, not wrong."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._ring: Deque[tuple] = collections.deque(maxlen=capacity)
        self._seq = 0  # lock-free: single-writer int; dumps read it monotonic
        # Sequence high-water mark of the last snapshot: snapshots are
        # INCREMENTAL (only records newer than the previous snapshot),
        # so a poison storm that quarantines every batch appends a few
        # new rows per snapshot instead of re-dumping the whole ring —
        # the full history is the concatenation of the JSONL lines.
        self._snap_seq = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def __len__(self) -> int:
        return len(self._ring)

    def note_dispatch(self, ts: int, k: int, frames: int, sent: int,
                      denied: int, backlog: int, inflight: int,
                      table_gen: int, rt_us: float) -> None:
        """Append one harvested dispatch.  Plain ints/floats only —
        callers must pass host values (hot-path-sync clean)."""
        self._seq += 1
        self._ring.append((self._seq, ts, k, frames, sent, denied,
                           backlog, inflight, table_gen, round(rt_us, 1)))

    # --------------------------------------------------------------- read

    def dump(self, limit: int = 0) -> List[Dict]:
        rows = list(self._ring)
        if limit > 0:
            rows = rows[-limit:]
        return [dict(zip(FIELDS, row)) for row in rows]

    def status(self) -> Dict:
        return {
            "recorded": len(self._ring),
            "capacity": self.capacity,
            "dispatches_total": self._seq,
        }

    def snapshot_to(self, path: str, reason: str, shard: int = 0) -> None:
        """Append one snapshot object (JSONL) and flush — the forensic
        write next to the quarantine pcap.  Appending (not truncating)
        preserves earlier ejections' context in the same post-mortem
        file; flushing makes it crash-durable like the pcap.  Only
        records NEWER than the previous snapshot are written (see
        ``_snap_seq``); a snapshot with nothing new still writes its
        header line so every ejection/quarantine leaves a timestamped
        mark.  Wall time via datetime (time.time() is banned from
        anything the harvest path can reach)."""
        rows = [r for r in self.dump() if r["seq"] > self._snap_seq]
        self._snap_seq = self._seq
        record = {
            "reason": reason,
            "shard": shard,
            "at": datetime.datetime.now(datetime.timezone.utc).isoformat(),
            "records": rows,
        }
        line = json.dumps(record) + "\n"
        with _SNAPSHOT_LOCK:
            with open(path, "a") as fh:
                fh.write(line)
                fh.flush()
