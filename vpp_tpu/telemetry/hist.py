"""Log2-bucketed latency histograms — the `show runtime` clocks analog.

VPP's per-node runtime stats expose clocks/vectors per graph node; the
reproduction's datapath exposed only point-in-time gauges until ISSUE 8.
These recorders turn the perf_counter timestamps the runner ALREADY
takes for the coalesce governor into latency *distributions* —
p50/p90/p99/p99.9 derived on read — without adding a single
host↔device sync or clock call to the dispatch path.

Design constraints (they shape everything here):

- **Single-writer record path, no locks.**  Each shard's worker thread
  owns its recorder; ``record_us`` is a couple of integer adds into a
  fixed-size list.  Readers (REST, /metrics scrapes, the sharded
  inspect) MERGE on read: they copy the counts under the GIL and sum
  across shards.  A reader racing the writer may observe a snapshot
  that is one sample stale or whose ``count`` is one ahead of the
  bucket sum — bounded, self-healing skew, the price of a lock-free
  hot path (VPP's per-worker counters make the same trade).
- **Fixed size, zero allocation.**  ``N_BUCKETS`` pow2 buckets over
  microseconds: bucket *i* holds samples in ``(2^(i-1), 2^i] µs``
  (bucket 0 = ≤1 µs, the last bucket is the +Inf catch-all).  40
  buckets cover 1 µs to ~76 hours — every latency this datapath can
  produce — in 40 ints.
- **Percentiles on read.**  Log2 buckets bound any quantile to within
  2× — exactly the resolution operators act on (is p99 600 µs or
  1.2 ms?) — and the read-side linear interpolation inside the bucket
  reports a smooth estimate rather than a stairstep.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

# Bucket upper bounds in µs: 1<<0 .. 1<<(N_BUCKETS-2), then +Inf.
N_BUCKETS = 40

PERCENTILES = (("p50", 0.50), ("p90", 0.90), ("p99", 0.99), ("p999", 0.999))


class Log2Histogram:
    """Fixed-size log2-bucketed recorder (µs domain).

    Writer side: :meth:`record_us` / :meth:`record_s` — lock-free,
    single writer by contract.  Reader side: :meth:`snapshot` /
    :meth:`merged` — copy + derive, never blocks the writer.
    """

    __slots__ = ("counts", "count", "sum_us")

    def __init__(self):
        # counts is only ever mutated in place (never rebound) so a
        # concurrent reader's reference stays valid.
        self.counts: List[int] = [0] * N_BUCKETS  # lock-free: single-writer ints; readers copy under the GIL
        self.count = 0       # lock-free: see counts
        self.sum_us = 0.0    # lock-free: see counts

    # ------------------------------------------------------------ writer

    def record_us(self, us: float, weight: int = 1) -> None:
        """Record one sample of ``us`` microseconds (``weight`` lets a
        batch-granular sample stand for its frames).  Pure int/float
        arithmetic — safe on the harvest path."""
        if us < 0.0:
            us = 0.0
        idx = int(us).bit_length()
        if idx >= N_BUCKETS:
            idx = N_BUCKETS - 1
        self.counts[idx] += weight
        self.count += weight
        self.sum_us += us * weight

    def record_s(self, seconds: float, weight: int = 1) -> None:
        self.record_us(seconds * 1e6, weight)

    # ------------------------------------------------------------ reader

    @staticmethod
    def bound_us(idx: int) -> float:
        """Upper bound of bucket ``idx`` in µs (+Inf for the last)."""
        if idx >= N_BUCKETS - 1:
            return float("inf")
        return float(1 << idx)

    def merged(self, others: Iterable["Log2Histogram"]) -> "Log2Histogram":
        """A fresh histogram holding this one plus ``others`` (the
        sharded engine's read-side merge)."""
        out = Log2Histogram()
        for h in (self, *others):
            counts = list(h.counts)  # one GIL-atomic-ish copy per shard
            for i, c in enumerate(counts):
                out.counts[i] += c
            out.count += sum(counts)  # consistent with the copied buckets
            out.sum_us += h.sum_us
        return out

    def percentile_us(self, q: float,
                      counts: Optional[List[int]] = None) -> float:
        """The q-quantile (0 < q <= 1) in µs, linearly interpolated
        inside the winning log2 bucket; 0.0 when empty."""
        counts = list(self.counts) if counts is None else counts
        total = sum(counts)
        if total <= 0:
            return 0.0
        target = q * total
        cum = 0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            lo = 0.0 if i == 0 else float(1 << (i - 1))
            hi = self.bound_us(i)
            if cum + c >= target:
                if hi == float("inf"):
                    return lo  # the catch-all has no upper edge
                frac = (target - cum) / c
                return lo + (hi - lo) * frac
            cum += c
        return self.bound_us(N_BUCKETS - 1)

    def snapshot(self) -> Dict[str, object]:
        """One consistent read: count, sum and the standard quantiles.
        Keys here are the schema contract the dashboard's
        ``shape_latency`` and the metrics exporter consume — the
        obs-parity checker holds them together."""
        counts = list(self.counts)
        total = sum(counts)
        # Literal keys on purpose: the obs-parity checker pins the
        # dashboard's shape_latency and the metrics exporter to exactly
        # this schema (a loop over PERCENTILES would be invisible to it).
        return {
            "count": total,
            "sum_us": round(self.sum_us, 1),
            "p50": round(self.percentile_us(0.50, counts), 1),
            "p90": round(self.percentile_us(0.90, counts), 1),
            "p99": round(self.percentile_us(0.99, counts), 1),
            "p999": round(self.percentile_us(0.999, counts), 1),
            # Sparse raw buckets ([index, count] pairs), so a REMOTE
            # reader — the ISSUE 10 cluster aggregator scraping N
            # agents' REST — can merge distributions EXACTLY instead of
            # averaging percentiles (which has no meaning): cluster p99
            # comes from summed buckets, same math as the per-node read.
            "buckets": [[i, c] for i, c in enumerate(counts) if c],
        }

    @classmethod
    def from_buckets(cls, buckets, sum_us: float = 0.0) -> "Log2Histogram":
        """Rebuild a histogram from a snapshot's sparse ``buckets`` list
        (the aggregator's wire→merge path); tolerates None/empty."""
        out = cls()
        for pair in buckets or ():
            idx, c = int(pair[0]), int(pair[1])
            if 0 <= idx < N_BUCKETS and c > 0:
                out.counts[idx] += c
                out.count += c
        out.sum_us = float(sum_us)
        return out

    def cumulative(self) -> Tuple[List[Tuple[str, float]], float]:
        """Prometheus exposition shape: ([(le, cumulative_count)...]
        ending at +Inf, sum) — the HistogramMetricFamily contract so
        PromQL ``histogram_quantile`` works out of the box."""
        counts = list(self.counts)
        sum_us = self.sum_us
        cum = 0.0
        buckets: List[Tuple[str, float]] = []
        for i, c in enumerate(counts):
            cum += c
            le = "+Inf" if i == N_BUCKETS - 1 else str(float(1 << i))
            buckets.append((le, cum))
        return buckets, sum_us


# The four datapath latency pillars (ISSUE 8).  Names are the schema:
# inspect()["latency"][<name>], datapath_latency_<name>_us in /metrics.
LATENCY_HISTOGRAMS = (
    # dispatch submission → harvest begin: the wait behind the
    # in-flight window (≈0 when unpipelined).
    "admit_wait",
    # dispatch submission → harvest complete: the batch's full
    # admit→harvest round trip.
    "dispatch_rt",
    # harvest begin → harvest complete: the sanctioned host block —
    # device materialisation + slow path + rewrite + TX stitch.
    "harvest",
    # the per-FRAME view of the round trip: the batch sample weighted
    # by its frame count, so deep-coalesce batches count per frame
    # (sampled at batch granularity — per-frame clocks would cost a
    # clock call per packet).
    "frame_e2e",
)


class LatencyRecorder:
    """The per-runner (per-shard, single-writer) recorder set.

    ``record_harvest`` is the ONE tap: it receives the timestamps the
    harvest already holds (``t_admit`` from the governor's timing fit,
    the harvest-start/-end perf_counter pair) and fans them into the
    four histograms.  ``enabled=False`` turns the tap into a no-op —
    the A/B switch the bench overhead check flips."""

    __slots__ = ("enabled", "admit_wait", "dispatch_rt", "harvest",
                 "frame_e2e")

    def __init__(self, enabled: bool = True):
        self.enabled = enabled  # lock-free: bool flip; a racing batch lands in whichever mode it saw
        self.admit_wait = Log2Histogram()
        self.dispatch_rt = Log2Histogram()
        self.harvest = Log2Histogram()
        self.frame_e2e = Log2Histogram()

    def record_harvest(self, t_admit: float, t_harvest: float,
                       t_done: float, frames: int) -> None:
        """Fan one harvested batch's timestamps into the histograms.
        Arithmetic only — no clocks, no syncs (hot-path-sync clean)."""
        if not self.enabled:
            return
        wait_us = (t_harvest - t_admit) * 1e6
        if wait_us < 0.0:
            wait_us = 0.0
        rt_us = (t_done - t_admit) * 1e6
        self.admit_wait.record_us(wait_us)
        self.dispatch_rt.record_us(rt_us)
        self.harvest.record_us((t_done - t_harvest) * 1e6)
        if frames > 0:
            self.frame_e2e.record_us(rt_us, weight=frames)

    def histograms(self) -> Dict[str, Log2Histogram]:
        return {name: getattr(self, name) for name in LATENCY_HISTOGRAMS}

    @staticmethod
    def merged(recorders: Iterable["LatencyRecorder"]) -> Dict[str, Log2Histogram]:
        """Read-side merge across shards: {name: merged histogram}."""
        recs = list(recorders)
        if not recs:
            return {name: Log2Histogram() for name in LATENCY_HISTOGRAMS}
        head, tail = recs[0], recs[1:]
        return {
            name: getattr(head, name).merged(getattr(r, name) for r in tail)
            for name in LATENCY_HISTOGRAMS
        }
