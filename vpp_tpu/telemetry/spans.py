"""Propagation spans — "how long from the K8s event to the device?".

The reproduction could always answer *what* was configured (scheduler
dump, event history) but never *how long propagation took*: a policy
event flows controller → processor → renderer → applicator compile →
device swap → per-shard adoption, and before ISSUE 8 none of those
stages left a duration anywhere.  A :class:`SpanTracker` span is minted
when the controller dequeues an event; every downstream stage stamps a
(name, duration) pair into it through a thread-local — the whole chain
runs on the controller's event-loop thread (commit included), so no
context needs to be threaded through the processor/renderer/applicator
signatures.  The span id also rides the transaction
(``Txn.span_id`` → ``RecordedTxn``) so the event history, the
scheduler txn log and the span ring correlate.

Completed spans land in a bounded ring (REST ``/contiv/v1/spans`` /
``netctl spans``) and every span that reached a compile-or-deeper stage
records its total into the **config-propagation histogram** — the
control plane's answer to the datapath's latency pillars, exported as
``controlplane_config_propagation_us``.

Stage vocabulary (flat list, stamped in execution order):

    handler:<name>    one event handler's processing (processor +
                      renderer work happens inside)
    compile:acl|nat   applicator table compile, mode=full|delta|cached
    swap:acl|nat      the on_compiled device swap (runner update_tables)
    adopt:shard<i>    one shard's table adoption inside the swap
    commit            the whole scheduler commit (brackets the above)

Threading: spans are control-plane only.  ``start``/``finish`` run on
the event-loop thread; ``dump``/``status`` on REST threads — the ring
is guarded by a lock (this is not a hot path).
"""

from __future__ import annotations

import collections
import threading
import time
from dataclasses import dataclass, field
from typing import Deque, Dict, List, Optional, Tuple

from .hist import Log2Histogram

DEFAULT_CAPACITY = 256
MAX_STAGES = 128  # a 100-shard adopt fan-out must not grow unbounded

# The one thread-local connecting the controller to the stages below
# it.  Multiple agents in one process are fine: each controller has its
# own loop thread, so each thread sees only its own span.
_current = threading.local()

# Stages that prove config actually moved toward the device — only
# spans reaching one of these advance the propagation histogram
# (handler-only spans are control-plane bookkeeping, not propagation).
_PROPAGATION_PREFIXES = ("compile:", "swap:", "adopt:")


@dataclass
class Span:
    """One event's propagation record."""

    span_id: int
    name: str
    detail: str = ""
    started: float = 0.0         # wall clock, for display only + stitching
    _t0: float = 0.0             # perf_counter base
    stages: List[Tuple[str, float, Dict]] = field(default_factory=list)
    total_us: float = 0.0
    # The cluster-store revision that triggered this event (ISSUE 10):
    # 0 for events that did not come off the store (shutdown, healing
    # timers); for watch-delivered changes and resyncs it is the SAME
    # number on every agent that saw the write — the key the cluster
    # aggregator stitches cross-node spans on.
    revision: int = 0

    def stamp(self, stage: str, dur_s: float, **extra) -> None:
        if len(self.stages) < MAX_STAGES:
            self.stages.append((stage, dur_s * 1e6, extra))

    @property
    def propagated(self) -> bool:
        return any(s.startswith(_PROPAGATION_PREFIXES)
                   for s, _, _ in self.stages)

    def as_dict(self) -> Dict:
        return {
            "span_id": self.span_id,
            "event": self.name,
            "detail": self.detail,
            # 6 decimals (µs resolution): cross-node adoption lags are
            # sub-millisecond on one box, and the stitcher subtracts
            # these wall stamps — 3 decimals quantized every lag to ms.
            "started": round(self.started, 6),
            "total_us": round(self.total_us, 1),
            "propagated": self.propagated,
            "revision": self.revision,
            "stages": [
                {"stage": s, "us": round(us, 1), **extra}
                for s, us, extra in self.stages
            ],
        }


def record_stage(stage: str, dur_s: float, **extra) -> None:
    """Stamp a stage into the CURRENT thread's active span (no-op when
    none is active — e.g. a scheduler retry timer firing outside an
    event, or a standalone runner in a bench)."""
    span = getattr(_current, "span", None)
    if span is not None:
        span.stamp(stage, dur_s, **extra)


def current_span_id() -> int:
    """The active span's id, 0 when none (what Txn picks up)."""
    span = getattr(_current, "span", None)
    return span.span_id if span is not None else 0


class SpanTracker:
    """Bounded ring of completed propagation spans + the end-to-end
    config-propagation histogram.  One per controller."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        self._lock = threading.Lock()
        self._ring: Deque[Span] = collections.deque(maxlen=capacity)
        self._seq = 0
        self.started_total = 0
        self.propagated_total = 0
        self.propagation = Log2Histogram()  # written under _lock (finish)

    # ---------------------------------------------------------- lifecycle

    def start(self, name: str, detail: str = "",
              revision: int = 0) -> Span:
        """Mint a span and make it the thread's current one."""
        with self._lock:
            self._seq += 1
            self.started_total += 1
            span_id = self._seq
        span = Span(
            span_id=span_id, name=name, detail=detail,
            started=time.time(), _t0=time.perf_counter(),
            revision=revision,
        )
        _current.span = span
        return span

    def finish(self, span: Span) -> None:
        """Close the span: compute the total, ring-append when any
        stage stamped (no-op events leave no record), advance the
        propagation histogram when config reached compile-or-deeper."""
        if getattr(_current, "span", None) is span:
            _current.span = None
        span.total_us = (time.perf_counter() - span._t0) * 1e6
        if not span.stages:
            return
        with self._lock:
            self._ring.append(span)
            if span.propagated:
                self.propagated_total += 1
                self.propagation.record_us(span.total_us)

    # -------------------------------------------------------------- read

    def dump(self, limit: int = 0) -> List[Dict]:
        with self._lock:
            spans = list(self._ring)
        if limit > 0:
            spans = spans[-limit:]
        return [s.as_dict() for s in spans]

    def status(self) -> Dict:
        with self._lock:
            recorded = len(self._ring)
            capacity = self._ring.maxlen or 0
            snap = self.propagation.snapshot()
        return {
            "spans_started": self.started_total,
            "spans_propagated": self.propagated_total,
            "recorded": recorded,
            "capacity": capacity,
            "propagation_us": snap,
        }
