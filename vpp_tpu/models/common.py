"""Shared model primitives."""

from __future__ import annotations

import enum

from dataclasses import dataclass


@dataclass(frozen=True)
class Label:
    """A key/value label attached to a K8s object.

    Analog of the repeated ``Label`` message in the reference's
    pod.proto / policy.proto / namespace.proto.
    """

    key: str
    value: str = ""


class ProtocolType(enum.IntEnum):
    """L4 protocol, using IANA protocol numbers.

    The reference uses two enums (TCP=0/UDP=1 in protos,
    TCP=6/UDP=17 in the service renderer API); here a single IANA-numbered
    enum is used everywhere, with ANY/OTHER sentinels for the policy layer
    (reference: plugins/policy/renderer/api.go:170-186).
    """

    TCP = 6
    UDP = 17
    # Some non-TCP, non-UDP traffic (ICMP in tests).
    OTHER = 255
    # Any L4 protocol, or pure L3 traffic (ports ignored).
    ANY = 0

    @classmethod
    def parse(cls, s) -> "ProtocolType":
        """Normalize a protocol spec. None/"" (proto3 default) means TCP,
        matching K8s semantics; "ANY" is explicit."""
        if isinstance(s, ProtocolType):
            return s
        if s is None or s == "":
            return cls.TCP
        s = str(s).upper()
        if s in ("TCP", "6"):
            return cls.TCP
        if s in ("UDP", "17"):
            return cls.UDP
        if s == "ANY":
            return cls.ANY
        return cls.OTHER


def labels_to_dict(labels) -> dict:
    """Collapse a list of Label (or (k, v) tuples) into a dict."""
    out = {}
    for item in labels or ():
        if isinstance(item, Label):
            out[item.key] = item.value
        else:
            k, v = item
            out[k] = v
    return out


class FrozenDict(dict):
    """An immutable dict (picklable, unlike MappingProxyType — local
    snapshots serialize KV values)."""

    def _blocked(self, *a, **k):
        raise TypeError("FrozenDict is immutable")

    __setitem__ = __delitem__ = _blocked
    clear = pop = popitem = setdefault = update = _blocked

    def __reduce__(self):
        return (FrozenDict, (dict(self),))


def freeze_mapping(m) -> FrozenDict:
    """Freeze a mapping so frozen dataclasses holding it are genuinely
    immutable snapshots (KV-store values are shared across watchers)."""
    if isinstance(m, FrozenDict):
        return m
    return FrozenDict(dict(m or {}))
