"""VppNode model — analog of plugins/nodesync/vppnode/vppnode.proto.

Describes one data-plane node of the cluster: its allocated integer ID
and the IPs of its TPU-pipeline interfaces, as published by nodesync
(reference: plugins/nodesync/nodesync.go PublishNodeIPs :122).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple


@dataclass(frozen=True)
class VppNode:
    """Data-plane view of a cluster node.

    ``id`` is the cluster-unique positive integer allocated by nodesync;
    IPAM derives all of the node's subnets from it
    (plugins/ipam/ipam.go dissectSubnetForNode :584).
    """

    id: int
    name: str
    # IP addresses (with prefix length, "a.b.c.d/len") of this node's
    # main data-plane interface.
    ip_addresses: Tuple[str, ...] = ()
    # Management IPs (no mask) used for node-to-node control traffic.
    mgmt_ip_addresses: Tuple[str, ...] = ()
