"""Registry of resources reflected into the KV store.

Analog of ``dbresources/dbresources.go:44-90`` in the reference: one
entry per reflected resource, carrying the resource keyword, the key
prefix under which instances are stored, and the model type.  Extending
the watched state = adding one entry here (same extension contract as
the reference).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional, Type

from .endpoints import Endpoints
from .infer import InferPolicy
from .namespace import Namespace
from .node import Node
from .pod import Pod
from .policy import Policy
from .service import Service
from .sfc import Sfc
from .vppnode import VppNode

# Root prefix of everything the control plane keeps in the KV store
# (reference: /vnf-agent/contiv-ksr/k8s/...).
KSR_PREFIX = "/vpp-tpu/ksr/k8s/"
NODESYNC_PREFIX = "/vpp-tpu/nodesync/"
# CRD-published resources (the contiv-crd analog writes here).
CRD_PREFIX = "/vpp-tpu/crd/"


@dataclass(frozen=True)
class DbResource:
    """One reflected resource kind."""

    keyword: str
    key_prefix: str
    model: Type
    # Builds the instance key suffix from a model instance.
    key_suffix: Callable[[object], str]


def _namespaced(obj) -> str:
    return f"{obj.namespace}/{obj.name}"


DB_RESOURCES = (
    DbResource("namespace", KSR_PREFIX + "namespace/", Namespace, lambda o: o.name),
    DbResource("pod", KSR_PREFIX + "pod/", Pod, _namespaced),
    DbResource("policy", KSR_PREFIX + "policy/", Policy, _namespaced),
    DbResource("service", KSR_PREFIX + "service/", Service, _namespaced),
    DbResource("endpoints", KSR_PREFIX + "endpoints/", Endpoints, _namespaced),
    DbResource("node", KSR_PREFIX + "node/", Node, lambda o: o.name),
    DbResource("sfc", KSR_PREFIX + "sfc/", Sfc, lambda o: f"{o.namespace}/{o.pod}"),
    DbResource("vppnode", NODESYNC_PREFIX + "vppnode/", VppNode, lambda o: str(o.id)),
    # ISSUE 14: InferPolicy CRDs are WATCHED state like pods/policies —
    # the CRD controller publishes validated specs here, and every
    # agent's DBWatcher delivers them as KubeStateChange events, so one
    # CRD write enrolls every node's datapath (and its store revision
    # anchors cluster-stitchable propagation spans).
    DbResource("inferpolicy", CRD_PREFIX + "inferpolicy/", InferPolicy,
               lambda o: o.name),
)

_BY_KEYWORD = {r.keyword: r for r in DB_RESOURCES}
_BY_MODEL = {r.model: r for r in DB_RESOURCES}


def resource(keyword: str) -> DbResource:
    return _BY_KEYWORD[keyword]


def resource_for_key(key: str) -> Optional[DbResource]:
    """Find the resource whose prefix covers ``key`` (longest match)."""
    best = None
    for r in DB_RESOURCES:
        if key.startswith(r.key_prefix):
            if best is None or len(r.key_prefix) > len(best.key_prefix):
                best = r
    return best


def key_for(obj) -> str:
    """Full KV key for a model instance."""
    r = _BY_MODEL[type(obj)]
    return r.key_prefix + r.key_suffix(obj)
