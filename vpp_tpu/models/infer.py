"""InferPolicy model — the in-network inference plane's CRD (ISSUE 14).

Lives with the other typed models (not under ``crd/``) because it is a
REFLECTED resource: the CRD controller validates + publishes instances
into the cluster store under the registry prefix, and every agent's
DBWatcher delivers them as ``KubeStateChange("inferpolicy", ...)``
events — the same store-fanout path pods and network policies ride, so
ONE CRD write enrolls every node's datapath (with the write's store
revision anchoring cluster-stitchable propagation spans).
``vpp_tpu.crd.models`` re-exports it beside the other CRD shapes.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Mapping, Optional, Tuple


@dataclass(frozen=True)
class InferPolicy:
    """In-network inference policy — enables per-vector DNN scoring for
    a set of namespaces and binds a score threshold to an action.

    ``threshold`` is a log2 score band (0..7): the action fires when
    the device scorer's band reaches it, i.e. when
    ``score >= 1 - 2^-threshold``.  ``action`` is one of ``log``,
    ``deprioritize``, ``quarantine`` (the quarantine path drops the
    frame, captures it to the forensics pcap and snapshots the flight
    recorder).  ``model`` optionally carries the MLP weights inline
    (``{"w1","b1","w2","b2"}`` nested lists, 16 feature rows); a
    policy without weights enrolls its namespaces against whichever
    model another policy ships."""

    name: str                          # CRD object name
    namespaces: Tuple[str, ...] = ()   # enrolled namespaces
    threshold: int = 6                 # score band 0..7
    action: str = "log"                # log | deprioritize | quarantine
    enabled: bool = True
    model: Optional[Mapping] = None    # inline MLP weights (JSON shape)
