"""Node model — analog of plugins/ksr/model/node/node.proto."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Tuple

from .common import freeze_mapping


@dataclass(frozen=True)
class NodeAddress:
    """One address of a node. ``type`` follows K8s NodeAddress types."""

    TYPE_HOSTNAME = "Hostname"
    TYPE_EXTERNAL_IP = "ExternalIP"
    TYPE_INTERNAL_IP = "InternalIP"

    address: str
    type: str = TYPE_INTERNAL_IP


@dataclass(frozen=True)
class Node:
    """A K8s node as reflected from the API server."""

    name: str
    addresses: Tuple[NodeAddress, ...] = ()
    pod_cidr: str = ""
    labels: Mapping[str, str] = field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "labels", freeze_mapping(self.labels))

    def internal_ip(self) -> str:
        for addr in self.addresses:
            if addr.type == NodeAddress.TYPE_INTERNAL_IP:
                return addr.address
        return ""
