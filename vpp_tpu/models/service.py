"""Service model — analog of plugins/ksr/model/service/service.proto."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Optional, Tuple

from .common import ProtocolType, freeze_mapping


@dataclass(frozen=True, order=True)
class ServiceID:
    name: str
    namespace: str

    def __str__(self) -> str:
        return f"{self.namespace}/{self.name}"


@dataclass(frozen=True)
class ServicePort:
    """One exposed service port (service.proto ServicePort).

    ``target_port`` may be an int (port number), a str (named container
    port looked up on the backend pod) or None (identity map from
    ``port``).
    """

    name: str = ""
    protocol: ProtocolType = ProtocolType.TCP
    port: int = 0
    target_port: Optional[object] = None  # int | str | None
    node_port: int = 0

    def __post_init__(self):
        object.__setattr__(self, "protocol", ProtocolType.parse(self.protocol))


@dataclass(frozen=True)
class Service:
    """A K8s Service (service.proto Service)."""

    name: str
    namespace: str = "default"
    ports: Tuple[ServicePort, ...] = ()
    selector: Mapping[str, str] = field(default_factory=dict)
    cluster_ip: str = ""
    service_type: str = "ClusterIP"  # ClusterIP | NodePort | LoadBalancer | ExternalName
    external_ips: Tuple[str, ...] = ()
    lb_ingress_ips: Tuple[str, ...] = ()
    session_affinity: str = "None"  # None | ClientIP
    session_affinity_timeout: int = 0
    external_traffic_policy: str = "Cluster"  # Cluster | Local

    def __post_init__(self):
        object.__setattr__(self, "selector", freeze_mapping(self.selector))

    @property
    def id(self) -> ServiceID:
        return ServiceID(name=self.name, namespace=self.namespace)

    @property
    def is_headless(self) -> bool:
        return self.cluster_ip in ("None", "none")
