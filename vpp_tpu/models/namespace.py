"""Namespace model — analog of plugins/ksr/model/namespace/namespace.proto."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping

from .common import freeze_mapping


@dataclass(frozen=True)
class Namespace:
    """A K8s namespace with its cluster-scoped labels."""

    name: str
    labels: Mapping[str, str] = field(default_factory=dict)

    def __post_init__(self):
        object.__setattr__(self, "labels", freeze_mapping(self.labels))
