"""Pod model — analog of plugins/ksr/model/pod/pod.proto."""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Mapping, Tuple

from .common import ProtocolType, freeze_mapping


@dataclass(frozen=True, order=True)
class PodID:
    """Unique pod identifier (namespace + name).

    Analog of ``podmodel.ID`` in the reference
    (plugins/ksr/model/pod/id.go).
    """

    name: str
    namespace: str

    def __str__(self) -> str:
        return f"{self.namespace}/{self.name}"

    @classmethod
    def parse(cls, s: str) -> "PodID":
        """Parse "namespace/name"; a bare name gets the default namespace."""
        ns, sep, name = s.partition("/")
        if not sep:
            return cls(name=s, namespace="default")
        return cls(name=name, namespace=ns)


@dataclass(frozen=True)
class ContainerPort:
    """A network port in a single container (pod.proto Container.Port)."""

    name: str = ""
    host_port: int = 0
    container_port: int = 0
    protocol: ProtocolType = ProtocolType.TCP
    host_ip_address: str = ""

    def __post_init__(self):
        object.__setattr__(self, "protocol", ProtocolType.parse(self.protocol))


@dataclass(frozen=True)
class Container:
    """A single application container run within a pod (pod.proto Container)."""

    name: str = ""
    ports: Tuple[ContainerPort, ...] = ()


@dataclass(frozen=True)
class Pod:
    """A pod as reflected from the K8s API (pod.proto Pod).

    ``labels`` is a plain mapping (the proto's repeated Label collapsed).
    ``ip_address`` is empty until allocated; ``host_ip_address`` is empty
    until scheduled.
    """

    name: str
    namespace: str = "default"
    labels: Mapping[str, str] = field(default_factory=dict)
    ip_address: str = ""
    host_ip_address: str = ""
    containers: Tuple[Container, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "labels", freeze_mapping(self.labels))

    @property
    def id(self) -> PodID:
        return PodID(name=self.name, namespace=self.namespace)

    def container_port_by_name(self, port_name: str, protocol: ProtocolType):
        """Resolve a named port to its number, or None.

        Used when policies/services reference ports by name
        (reference: plugins/policy/configurator/configurator_impl.go
        getMatchingPorts; service processor target-port resolution).
        """
        for container in self.containers:
            for port in container.ports:
                if port.name == port_name and port.protocol == protocol:
                    return port.container_port
        return None
