"""NetworkPolicy model — analog of plugins/ksr/model/policy/policy.proto.

Semantics notes carried over from the reference schema (policy.proto):

- A *null* label selector matches nothing; an *empty* selector matches all
  objects (in its scope).  match_labels and match_expressions are ANDed.
- PolicyType defaults: policies containing an egress section affect egress;
  all policies affect ingress unless policy_type says EGRESS only.
- An IngressRule/EgressRule matches traffic iff it matches (any of ports)
  AND (any of peers); an empty ports list means "all ports", an empty
  peers list means "all sources/destinations".
- IPBlock.except entries are CIDRs *inside* the block that must be
  excluded.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Mapping, Optional, Tuple

from .common import ProtocolType, freeze_mapping


@dataclass(frozen=True, order=True)
class PolicyID:
    name: str
    namespace: str

    def __str__(self) -> str:
        return f"{self.namespace}/{self.name}"


class ExpressionOperator(enum.Enum):
    """Operator of a label match-expression (policy.proto LabelExpression)."""

    IN = "In"
    NOT_IN = "NotIn"
    EXISTS = "Exists"
    DOES_NOT_EXIST = "DoesNotExist"


@dataclass(frozen=True)
class LabelExpression:
    key: str
    operator: ExpressionOperator
    values: Tuple[str, ...] = ()


@dataclass(frozen=True)
class LabelSelector:
    """A label query over a set of resources (policy.proto LabelSelector).

    match_labels and match_expressions are ANDed together.  The *empty*
    selector (no labels, no expressions) matches everything in scope.
    Use ``None`` where the reference uses a nil selector (matches nothing).
    """

    match_labels: Mapping[str, str] = field(default_factory=dict)
    match_expressions: Tuple[LabelExpression, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "match_labels", freeze_mapping(self.match_labels))

    @property
    def is_empty(self) -> bool:
        return not self.match_labels and not self.match_expressions


class PolicyType(enum.IntEnum):
    """Which traffic directions the policy restricts (policy.proto)."""

    DEFAULT = 0
    INGRESS = 1
    EGRESS = 2
    INGRESS_AND_EGRESS = 3


@dataclass(frozen=True)
class PolicyPort:
    """A port selector (policy.proto Port).

    ``port`` may be an int (number), a str (named port, resolved against
    the destination pod's container ports) or None (match all ports on
    the protocol).
    """

    protocol: ProtocolType = ProtocolType.TCP
    port: Optional[object] = None  # int | str | None

    def __post_init__(self):
        object.__setattr__(self, "protocol", ProtocolType.parse(self.protocol))


@dataclass(frozen=True)
class IPBlock:
    """A CIDR with optional excluded sub-CIDRs (policy.proto IPBlock)."""

    cidr: str
    except_cidrs: Tuple[str, ...] = ()


@dataclass(frozen=True)
class Peer:
    """A traffic peer: exactly one of pods / namespaces / ip_block.

    (policy.proto Peer.)  ``pods`` selects pods in the policy's namespace;
    ``namespaces`` selects all pods in matching namespaces; ``ip_block``
    matches by CIDR.
    """

    pods: Optional[LabelSelector] = None
    namespaces: Optional[LabelSelector] = None
    ip_block: Optional[IPBlock] = None


@dataclass(frozen=True)
class IngressRule:
    """Allows traffic matching (any of ports) AND (any of from_peers)."""

    ports: Tuple[PolicyPort, ...] = ()
    from_peers: Tuple[Peer, ...] = ()


@dataclass(frozen=True)
class EgressRule:
    """Allows traffic matching (any of ports) AND (any of to_peers)."""

    ports: Tuple[PolicyPort, ...] = ()
    to_peers: Tuple[Peer, ...] = ()


@dataclass(frozen=True)
class Policy:
    """A K8s NetworkPolicy (policy.proto Policy)."""

    name: str
    namespace: str = "default"
    labels: Mapping[str, str] = field(default_factory=dict)
    # Pods this policy applies to; empty selector = all pods in namespace.
    pods: LabelSelector = field(default_factory=LabelSelector)
    policy_type: PolicyType = PolicyType.DEFAULT
    ingress_rules: Tuple[IngressRule, ...] = ()
    egress_rules: Tuple[EgressRule, ...] = ()

    def __post_init__(self):
        object.__setattr__(self, "labels", freeze_mapping(self.labels))

    @property
    def id(self) -> PolicyID:
        return PolicyID(name=self.name, namespace=self.namespace)

    @property
    def applies_to_ingress(self) -> bool:
        """Per policy.proto PolicyType doc: everything but EGRESS-only
        restricts ingress."""
        return self.policy_type in (
            PolicyType.DEFAULT,
            PolicyType.INGRESS,
            PolicyType.INGRESS_AND_EGRESS,
        )

    @property
    def applies_to_egress(self) -> bool:
        """EGRESS / INGRESS_AND_EGRESS restrict egress; DEFAULT restricts
        egress iff the policy has an egress section."""
        if self.policy_type in (PolicyType.EGRESS, PolicyType.INGRESS_AND_EGRESS):
            return True
        return self.policy_type == PolicyType.DEFAULT and len(self.egress_rules) > 0
