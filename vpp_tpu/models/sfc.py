"""SFC pod marker model.

Analog of the reference's ``plugins/ksr/model/sfc/sfc.proto``: pods
labeled ``sfc=true`` are reflected as a tiny {pod, node} record under
their own key prefix, feeding service-function-chaining consumers."""

from __future__ import annotations

from dataclasses import dataclass


@dataclass(frozen=True)
class Sfc:
    """sfc.proto Sfc message (:22-31): pod name + scheduled node."""

    pod: str
    node: str = ""
    namespace: str = "default"
