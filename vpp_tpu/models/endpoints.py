"""Endpoints model — analog of plugins/ksr/model/endpoints/endpoints.proto."""

from __future__ import annotations

from dataclasses import dataclass
from typing import Tuple

from .common import ProtocolType
from .pod import PodID


@dataclass(frozen=True)
class EndpointAddress:
    """A single endpoint IP (endpoints.proto EndpointAddress).

    ``target_pod`` replaces the proto's generic ObjectReference: in the
    reference the reference is (almost) always to a Pod and the service
    processor resolves it to one (processor_impl.go getTargetPort).
    """

    ip: str
    node_name: str = ""
    host_name: str = ""
    target_pod: PodID = None  # type: ignore[assignment]


@dataclass(frozen=True)
class EndpointPort:
    """A single endpoint port (endpoints.proto EndpointPort)."""

    name: str = ""
    port: int = 0
    protocol: ProtocolType = ProtocolType.TCP

    def __post_init__(self):
        object.__setattr__(self, "protocol", ProtocolType.parse(self.protocol))


@dataclass(frozen=True)
class EndpointSubset:
    """Addresses × ports product group (endpoints.proto EndpointSubset)."""

    addresses: Tuple[EndpointAddress, ...] = ()
    not_ready_addresses: Tuple[EndpointAddress, ...] = ()
    ports: Tuple[EndpointPort, ...] = ()


@dataclass(frozen=True)
class Endpoints:
    """Endpoints implementing a service; keyed like the Service."""

    name: str
    namespace: str = "default"
    subsets: Tuple[EndpointSubset, ...] = ()
