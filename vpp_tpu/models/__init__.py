"""K8s-state data models — the lingua franca of the framework.

Analog of the reference's ``plugins/ksr/model/*`` protobuf schemas
(pod.proto, policy.proto, service.proto, endpoints.proto, namespace.proto,
node.proto) and of ``dbresources/dbresources.go:44-90`` (the registry of
resources reflected into the KV store).  Implemented as frozen Python
dataclasses instead of protobuf: values stored in the KV store are
immutable snapshots.
"""

from .common import Label, ProtocolType
from .namespace import Namespace
from .pod import Pod, PodID, Container, ContainerPort
from .policy import (
    Policy,
    PolicyID,
    PolicyType,
    LabelSelector,
    LabelExpression,
    ExpressionOperator,
    PolicyPort,
    Peer,
    IPBlock,
    IngressRule,
    EgressRule,
)
from .service import Service, ServiceID, ServicePort
from .endpoints import Endpoints, EndpointSubset, EndpointAddress, EndpointPort
from .infer import InferPolicy
from .node import Node, NodeAddress
from .sfc import Sfc
from .vppnode import VppNode
from .registry import DbResource, DB_RESOURCES, resource_for_key, key_for

__all__ = [
    "Label",
    "ProtocolType",
    "Namespace",
    "Pod",
    "PodID",
    "Container",
    "ContainerPort",
    "Policy",
    "PolicyID",
    "PolicyType",
    "LabelSelector",
    "LabelExpression",
    "ExpressionOperator",
    "PolicyPort",
    "Peer",
    "IPBlock",
    "IngressRule",
    "EgressRule",
    "Service",
    "ServiceID",
    "ServicePort",
    "Endpoints",
    "InferPolicy",
    "EndpointSubset",
    "EndpointAddress",
    "EndpointPort",
    "Node",
    "NodeAddress",
    "Sfc", "VppNode",
    "DbResource",
    "DB_RESOURCES",
    "resource_for_key",
    "key_for",
]
