"""BGPReflector — mirrors BGP-learned host routes into the data plane."""

from .plugin import BGPReflector, BGPRouteUpdate, RouteEvent, RouteSource

__all__ = ["BGPReflector", "BGPRouteUpdate", "RouteEvent", "RouteSource"]
