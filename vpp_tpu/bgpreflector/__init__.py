"""BGPReflector — mirrors BGP-learned host routes into the data plane."""

from .plugin import (
    BGPReflector,
    BGPRouteUpdate,
    RouteEvent,
    RouteEventType,
    RouteSource,
)

__all__ = [
    "BGPReflector",
    "BGPRouteUpdate",
    "RouteEvent",
    "RouteEventType",
    "RouteSource",
]
