"""BGPReflector plugin.

Analog of ``plugins/bgpreflector/bgpreflector.go``: watches the host
routing table for BGP-learned routes (the BIRD protocol number in the
reference, ``watchRoutes`` :151) and mirrors them into the data plane's
main VRF (``vppRoute`` :188) — adds/deletes arrive as
``BGPRouteUpdate`` events (bgpreflector_api.go :34), full state is
re-reflected on resync.

The netlink subscription is abstracted as :class:`RouteSource`; tests
and non-Linux hosts inject a mock.  A production source can shell out
to ``ip monitor route`` or bind rtnetlink directly.
"""

from __future__ import annotations

import enum
import ipaddress
import logging
from dataclasses import dataclass
from typing import Callable, Iterable, Optional, Protocol

from ..controller.api import EventHandler, UpdateEvent
from ..ipv4net.model import Route

log = logging.getLogger(__name__)

# Routes installed by the BIRD BGP daemon carry this routing-protocol
# number (the reference's birdRouteProtoNumber).
BIRD_PROTO_NUMBER = 12


class RouteEventType(enum.Enum):
    ADD = "add"
    DELETE = "delete"


@dataclass(frozen=True)
class RouteEvent:
    """One host routing-table change (netlink.RouteUpdate analog)."""

    type: RouteEventType
    dst_network: str
    gateway: str
    protocol: int = BIRD_PROTO_NUMBER


class RouteSource(Protocol):
    """Where host routes come from (netlink in production, mock in tests)."""

    def list_routes(self) -> Iterable[RouteEvent]:
        """Current routing table (RouteList analog)."""
        ...

    def subscribe(self, handler: Callable[[RouteEvent], None]) -> None:
        """Stream subsequent changes (RouteSubscribe analog)."""
        ...


class BGPRouteUpdate(UpdateEvent):
    """Event carrying one BGP route add/delete (bgpreflector_api.go :34)."""

    name = "BGP Route Update"

    def __init__(self, type_: RouteEventType, dst_network: str, gateway: str):
        super().__init__()
        self.type = type_
        self.dst_network = dst_network
        self.gateway = gateway

    def __str__(self) -> str:
        return f"{self.name} [{self.type.value} {self.dst_network} via {self.gateway}]"


def _is_valid_route(dst: str, gw: str) -> bool:
    """isValidRoute analog: needs a destination and a specified gateway."""
    if not dst or not gw:
        return False
    try:
        if ipaddress.ip_address(gw).is_unspecified:
            return False
        ipaddress.ip_network(dst, strict=False)
    except ValueError:
        return False
    return True


class BGPReflector(EventHandler):
    name = "bgpreflector"

    def __init__(self, config, route_source: Optional[RouteSource] = None,
                 event_loop=None):
        self.config = config  # NetworkConfig (routing + interface sections)
        self.route_source = route_source
        self.event_loop = event_loop

    # ------------------------------------------------------------ lifecycle

    def init(self) -> None:
        """Subscribe to host routing-table changes (watchRoutes :151)."""
        if self.route_source is not None:
            self.route_source.subscribe(self._on_route_change)

    def _on_route_change(self, ev: RouteEvent) -> None:
        if ev.protocol != BIRD_PROTO_NUMBER:
            return
        if not _is_valid_route(ev.dst_network, ev.gateway):
            return
        if self.event_loop is not None:
            self.event_loop.push_event(
                BGPRouteUpdate(ev.type, ev.dst_network, ev.gateway)
            )

    # ---------------------------------------------------------------- route

    def _data_plane_route(self, dst_network: str, gateway: str) -> Route:
        """vppRoute analog: BGP route → main-VRF route out the uplink."""
        return Route(
            dst_network=str(ipaddress.ip_network(dst_network, strict=False)),
            next_hop=gateway,
            outgoing_interface=self.config.interface.main_interface,
            vrf=self.config.routing.main_vrf_id,
        )

    # --------------------------------------------------------------- events

    def handles_event(self, event) -> bool:
        return isinstance(event, BGPRouteUpdate) or event.method.is_resync

    def resync(self, event, kube_state, resync_count, txn) -> None:
        """Reflect the whole current table (Resync :100-113)."""
        if self.route_source is None:
            return
        for ev in self.route_source.list_routes():
            if ev.protocol != BIRD_PROTO_NUMBER:
                continue
            if not _is_valid_route(ev.dst_network, ev.gateway):
                continue
            route = self._data_plane_route(ev.dst_network, ev.gateway)
            txn.put(route.key, route)

    def update(self, event, txn) -> str:
        if not isinstance(event, BGPRouteUpdate):
            return ""
        route = self._data_plane_route(event.dst_network, event.gateway)
        if event.type is RouteEventType.ADD:
            txn.put(route.key, route)
            return "BGP route Add"
        txn.delete(route.key)
        return "BGP route Delete"
