"""obs-parity — counters, inspect schema, and REST routes stay live.

Dead observability rots silently: a counter that is exported but never
incremented reads as "always zero, nothing wrong"; one incremented but
never exported is invisible at 3am; a dashboard key the agent stopped
producing renders as a blank panel.  Three sub-checks:

1. **Counter liveness** — every field of a ``*Counters`` dataclass
   must be incremented/assigned somewhere outside its class body (the
   export side is structural: ``as_dict`` walks all fields), and every
   counter dataclass must have an ``as_dict`` exporter.
2. **Schema parity** — every key the dashboard's
   ``views.shape_dispatch`` consumes (``dp.get("...")`` /
   ``gov.get("...")`` / ``inspect.get("...")``) must be produced as a
   literal key by ``DataplaneRunner.inspect_dispatch`` /
   ``CoalesceGovernor.snapshot`` / ``DataplaneRunner.inspect``; and
   every literal gauge key the solo ``metrics()`` emits must also be
   emitted by the sharded ``_aggregate_counters`` (the two views must
   never drift).
3. **Route liveness** — every REST path literal routed in
   ``rest/server.py`` must be referenced by netctl, the UI proxy, or a
   test (``reference_dirs``, default ``tests/``, is scanned as raw
   text so the CLI finds test consumers without indexing them).
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .core import Checker, Finding, Project, register

DEFAULT_SCHEMA_PAIRS = (
    # (consumer func qualname suffix, producer func qualname suffixes)
    # ISSUE 12 ledger/placement rows ride the same pair: the dashboard
    # reads the global-budget ledger snapshot and the CPU placement
    # map the sharded engine's inspect produces — a renamed ledger key
    # would blank the budget row exactly during the saturation event
    # it exists to explain.
    ("shape_dispatch", ("DataplaneRunner.inspect_dispatch",
                        "CoalesceGovernor.snapshot",
                        "GovernorLedger.snapshot",
                        "ShardedDataplane.inspect",
                        "DataplaneRunner.inspect")),
    # ISSUE 8 telemetry surfaces: the dashboard latency panel and the
    # Prometheus exporters read the SAME snapshot schemas the inspect()
    # pillar produces — a histogram field renamed on one side goes
    # blank on the other, which is exactly what this catches.
    ("shape_latency", ("DataplaneRunner.inspect",
                       "ShardedDataplane.inspect",
                       "Log2Histogram.snapshot",
                       "FlightRecorder.status")),
    ("_DatapathCollector.collect", ("Log2Histogram.snapshot",)),
    ("_SpanCollector.collect", ("SpanTracker.status",)),
    # ISSUE 9 controller-resilience surfaces: the Prometheus collector
    # and the `netctl health` renderer both read Controller.status()'s
    # literal schema (plus, for netctl, the REST health merge and the
    # datapath health sections) — a renamed counter goes dark on every
    # surface at once, which is exactly what this pins.
    ("_ControllerCollector.collect", ("Controller.status",)),
    ("cmd_health", ("Controller.status",
                    "AgentRestServer.get_health",
                    "DataplaneRunner.health",
                    "ShardedDataplane.health",
                    # ISSUE 13: the drain FSM's status rides the health
                    # dict (`drain:` line in netctl health); the literal
                    # schema lives in the locked helper.
                    "DrainCoordinator._status_locked")),
    # ISSUE 14 inference surfaces: the dashboard's inference panel and
    # the `netctl inspect` inference line both read the literal schema
    # of DataplaneRunner.inspect_inference (the sharded merge reuses
    # it) — a renamed action counter or band key would blank the score
    # histogram on every surface at once, during exactly the score
    # storm it exists to explain.
    ("shape_inference", ("DataplaneRunner.inspect_inference",
                         "DataplaneRunner.inspect",
                         "ShardedDataplane.inspect_inference")),
    ("_render_inference", ("DataplaneRunner.inspect_inference",)),
    # ISSUE 10 cluster surfaces: the dashboard's cluster panel and the
    # `netctl cluster` subcommands both read the fleet aggregator's
    # literal schema (ClusterScraper.summary rows + gaps, the stitched
    # spans, the skew report, merged histogram snapshots) — a renamed
    # aggregator key would blank the fleet view on every surface at
    # once, during exactly the incident it exists for.
    ("shape_cluster", ("ClusterScraper.summary",
                       "ClusterScraper._gaps",
                       "stitch_spans",
                       "latency_skew",
                       "Log2Histogram.snapshot")),
    ("cmd_cluster", ("ClusterScraper.summary",
                     "ClusterScraper._gaps",
                     "stitch_spans",
                     "latency_skew",
                     "Log2Histogram.snapshot")),
)
DEFAULT_METRICS_PAIR = ("DataplaneRunner.metrics",
                        "ShardedDataplane._aggregate_counters")
DEFAULT_REST_MODULE = "vpp_tpu.rest.server"
DEFAULT_REFERENCE_DIRS = ("tests",)


def _find_funcs(project: Project, suffix: str):
    """Every (sf, FunctionDef) whose qualname ends with ``suffix``."""
    cls_name, _, fn_name = suffix.rpartition(".")
    for sf in project.files.values():
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.ClassDef) and (
                    not cls_name or node.name == cls_name):
                for item in node.body:
                    if isinstance(item, ast.FunctionDef) and \
                            item.name == fn_name:
                        yield sf, item
            elif not cls_name and isinstance(node, ast.FunctionDef) and \
                    node.name == fn_name:
                yield sf, node


def _literal_keys_produced(func: ast.AST) -> Set[str]:
    """String keys a function produces: dict-literal keys and
    ``x["key"] = ...`` subscript stores."""
    keys: Set[str] = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Dict):
            for k in node.keys:
                if isinstance(k, ast.Constant) and isinstance(k.value, str):
                    keys.add(k.value)
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for t in targets:
                if isinstance(t, ast.Subscript) and \
                        isinstance(t.slice, ast.Constant) and \
                        isinstance(t.slice.value, str):
                    keys.add(t.slice.value)
    return keys


def _literal_keys_consumed(func: ast.AST) -> List[Tuple[str, int]]:
    """(key, line) for every ``.get("key")`` call and ``x["key"]``
    subscript READ in a consumer function."""
    out: List[Tuple[str, int]] = []
    for node in ast.walk(func):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "get" and node.args and \
                isinstance(node.args[0], ast.Constant) and \
                isinstance(node.args[0].value, str):
            out.append((node.args[0].value, node.lineno))
    return out


@register
class ObservabilityParityChecker(Checker):
    rule = "obs-parity"
    description = (
        "counters are incremented AND exported, the inspect schema "
        "covers the dashboard's reads, and every REST route has a "
        "netctl / proxy / test consumer"
    )

    def __init__(
        self,
        schema_pairs=DEFAULT_SCHEMA_PAIRS,
        metrics_pair=DEFAULT_METRICS_PAIR,
        rest_module: str = DEFAULT_REST_MODULE,
        reference_dirs: Sequence[str] = DEFAULT_REFERENCE_DIRS,
    ):
        self.schema_pairs = schema_pairs
        self.metrics_pair = metrics_pair
        self.rest_module = rest_module
        self.reference_dirs = reference_dirs

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        findings.extend(self._check_counters(project))
        findings.extend(self._check_schema(project))
        findings.extend(self._check_metrics_parity(project))
        findings.extend(self._check_routes(project))
        return findings

    # -------------------------------------------------- counter liveness

    def _check_counters(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        # field -> (path, line) of declaration, per counters class
        decls: Dict[str, List[Tuple[str, str, int]]] = {}
        for sf in project.files.values():
            for node in ast.walk(sf.tree):
                if not (isinstance(node, ast.ClassDef)
                        and node.name.endswith("Counters")):
                    continue
                has_exporter = any(
                    isinstance(i, ast.FunctionDef) and i.name == "as_dict"
                    for i in node.body)
                if not has_exporter:
                    findings.append(Finding(
                        rule=self.rule, path=sf.path, line=node.lineno,
                        message=f"counter class {node.name} has no "
                                "as_dict exporter — its counts never "
                                "reach /metrics or inspect()",
                    ))
                for item in node.body:
                    if isinstance(item, ast.AnnAssign) and \
                            isinstance(item.target, ast.Name):
                        decls.setdefault(item.target.id, []).append(
                            (node.name, sf.path, item.lineno))
        if not decls:
            return findings
        # Any write `<something>.<field> op=` outside the class bodies.
        written: Set[str] = set()
        for sf in project.files.values():
            for node in ast.walk(sf.tree):
                if isinstance(node, (ast.Assign, ast.AugAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if isinstance(t, ast.Attribute):
                            written.add(t.attr)
        for field, sites in sorted(decls.items()):
            if field in written:
                continue
            for cls, path, line in sites:
                findings.append(Finding(
                    rule=self.rule, path=path, line=line,
                    message=(
                        f"dead counter: {cls}.{field} is exported but "
                        "never incremented anywhere — delete it or wire "
                        "the increment"
                    ),
                ))
        return findings

    # ---------------------------------------------------- schema parity

    def _check_schema(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        for consumer_name, producer_names in self.schema_pairs:
            consumers = list(_find_funcs(project, consumer_name))
            if not consumers:
                continue
            produced: Set[str] = set()
            found_producer = False
            for pname in producer_names:
                for _, func in _find_funcs(project, pname):
                    found_producer = True
                    produced |= _literal_keys_produced(func)
            if not found_producer:
                continue
            for sf, func in consumers:
                for key, line in _literal_keys_consumed(func):
                    if key not in produced:
                        findings.append(Finding(
                            rule=self.rule, path=sf.path, line=line,
                            message=(
                                f"{consumer_name}() reads key {key!r} "
                                f"that no producer "
                                f"({', '.join(producer_names)}) emits — "
                                "the panel renders blank"
                            ),
                        ))
        return findings

    def _check_metrics_parity(self, project: Project) -> List[Finding]:
        solo_name, sharded_name = self.metrics_pair
        solo = next(iter(_find_funcs(project, solo_name)), None)
        sharded = next(iter(_find_funcs(project, sharded_name)), None)
        if solo is None or sharded is None:
            return []
        solo_sf, solo_fn = solo
        solo_keys = {k for k in _literal_keys_produced(solo_fn)
                     if k.startswith("datapath_")}
        sharded_keys = _literal_keys_produced(sharded[1])
        out = []
        for key in sorted(solo_keys - sharded_keys):
            out.append(Finding(
                rule=self.rule, path=solo_sf.path, line=solo_fn.lineno,
                message=(
                    f"metrics drift: solo {solo_name.split('.')[-1]}() "
                    f"emits {key!r} but the sharded "
                    f"{sharded_name.split('.')[-1]}() does not — the "
                    "gauge vanishes when a node goes multi-shard"
                ),
            ))
        return out

    # ---------------------------------------------------- route liveness

    def _check_routes(self, project: Project) -> List[Finding]:
        rest_sf = project.by_module(self.rest_module)
        if rest_sf is None:
            return []
        route_fn = None
        for node in ast.walk(rest_sf.tree):
            if isinstance(node, ast.FunctionDef) and node.name == "_route":
                route_fn = node
                break
        if route_fn is None:
            return []
        routes: List[Tuple[str, int]] = []
        for node in ast.walk(route_fn):
            if isinstance(node, ast.Constant) and \
                    isinstance(node.value, str) and \
                    node.value.startswith("/"):
                routes.append((node.value, node.lineno))
        corpus = self._reference_corpus(project, exclude=rest_sf.path)
        out = []
        for path, line in sorted(set(routes)):
            needle = path.rstrip("/")
            if not needle:
                continue
            if any(needle in text for text in corpus):
                continue
            # Consumers may build subpaths dynamically
            # (`f".../cni/{action}"`): the parent prefix counts ONLY in
            # a dynamic-construction shape — immediately followed by an
            # interpolation or a closing quote (string concatenation).
            # A plain sibling-route literal must NOT suppress.
            parent = needle.rsplit("/", 1)[0] + "/"
            markers = (parent + "{", parent + '"', parent + "'")
            if len(parent) > 1 and any(
                    m in text for m in markers for text in corpus):
                continue
            out.append(Finding(
                rule=self.rule, path=rest_sf.path, line=line,
                message=(
                    f"REST route {path!r} has no netctl, proxy, or test "
                    "reference — dead surface (or untested one)"
                ),
            ))
        return out

    def _reference_corpus(self, project: Project,
                          exclude: str) -> List[str]:
        corpus = [sf.text for sf in project.files.values()
                  if sf.path != exclude]
        for d in self.reference_dirs:
            if not os.path.isdir(d):
                continue
            for dirpath, dirnames, filenames in os.walk(d):
                dirnames[:] = [x for x in dirnames if x != "__pycache__"]
                for fn in filenames:
                    if fn.endswith(".py"):
                        try:
                            with open(os.path.join(dirpath, fn)) as fh:
                                corpus.append(fh.read())
                        except OSError:
                            continue
        return corpus
