"""jit-discipline — jax.jit construction and pre-warm registration.

Two invariants from the PR 4 governor work:

1. **No jit construction in hot code.**  ``jax.jit(...)`` inside a
   function body in ``ops/`` or ``datapath/`` builds a NEW jit wrapper
   (and its own cache entry) per call — a load spike then stalls on a
   fresh trace+compile exactly when latency matters.  Jit callables
   must be module-level (``pipeline_step_jit = jax.jit(...)``) or
   decorator-applied; anything else needs a waiver explaining its
   caching story.

2. **Dispatch-shaped jits register with the pre-warm ledger.**  Every
   ``pipeline_*_jit`` entry point the runner's dispatch references
   must also be referenced by ``DataplaneRunner._prewarm_one`` — the
   pow2-bucket pre-warm compiles every shape a load spike can select,
   and a dispatch path that can pick a jit the warmer never compiled
   reintroduces the mid-traffic compile stall the ledger exists to
   kill.

3. **No dead dispatch entry points** (ISSUE 11).  ``pipeline_*_jit``
   is the dispatch-entry-point namespace: every module-level jit of
   that shape in scope must be BOTH pre-warm-registered AND referenced
   from the dispatch discipline selection.  A jit no discipline can
   select is dead weight that silently drifts from the production
   semantics (the pre-packed ts0 entry points rotted exactly this way
   once the packed-harvest variants shipped); a selectable-but-unwarmed
   one is invariant 2's compile stall.  Helper jits that are not
   dispatch entry points must not squat on the ``pipeline_*_jit``
   naming.
"""

from __future__ import annotations

import ast
from typing import List, Sequence, Set

from .core import Checker, Finding, Project, register

DEFAULT_SCOPES = ("vpp_tpu.ops.", "vpp_tpu.datapath.")
DEFAULT_DISPATCH_FUNC = "DataplaneRunner._dispatch_locked"
DEFAULT_PREWARM_FUNC = "DataplaneRunner._prewarm_one"


def _jit_aliases(tree: ast.AST) -> tuple:
    """(jax module aliases, bare names bound to jax.jit)."""
    jax_aliases: Set[str] = set()
    jit_names: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name == "jax":
                    jax_aliases.add(a.asname or "jax")
        elif isinstance(node, ast.ImportFrom) and node.module == "jax" \
                and not node.level:
            for a in node.names:
                if a.name == "jit":
                    jit_names.add(a.asname or "jit")
    return jax_aliases, jit_names


def _is_jit_call(node: ast.Call, jax_aliases, jit_names) -> bool:
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit" and \
            isinstance(f.value, ast.Name) and f.value.id in jax_aliases:
        return True
    return isinstance(f, ast.Name) and f.id in jit_names


@register
class JitDisciplineChecker(Checker):
    rule = "jit-discipline"
    description = (
        "jax.jit callables in ops/ and datapath/ are module-level (no "
        "construction in functions), and dispatch-referenced "
        "pipeline_*_jit entry points are pre-warm-registered"
    )

    def __init__(self, scopes: Sequence[str] = DEFAULT_SCOPES,
                 dispatch_func: str = DEFAULT_DISPATCH_FUNC,
                 prewarm_func: str = DEFAULT_PREWARM_FUNC):
        self.scopes = scopes
        self.dispatch_func = dispatch_func
        self.prewarm_func = prewarm_func

    def _in_scope(self, module: str) -> bool:
        return any(module.startswith(s) or module == s.rstrip(".")
                   for s in self.scopes)

    def check(self, project: Project) -> List[Finding]:
        findings: List[Finding] = []
        # Module-level pipeline_*_jit assignments: name -> (file, line),
        # for the dead-entry-point check below.
        pipeline_jits: dict = {}
        for sf in project.files.values():
            if not self._in_scope(sf.module):
                continue
            jax_aliases, jit_names = _jit_aliases(sf.tree)
            if not jax_aliases and not jit_names:
                continue
            # Module-level jit assignments are the SANCTIONED form.
            for node in sf.tree.body:
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        _is_jit_call(node.value, jax_aliases, jit_names):
                    for t in node.targets:
                        if isinstance(t, ast.Name) and \
                                t.id.startswith("pipeline_") and \
                                t.id.endswith("_jit"):
                            pipeline_jits[t.id] = (sf, node.lineno)
            # jit construction inside ANY function body is flagged.
            for func in ast.walk(sf.tree):
                if not isinstance(func, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for node in ast.walk(func):
                    if isinstance(node, ast.Call) and \
                            _is_jit_call(node, jax_aliases, jit_names):
                        findings.append(Finding(
                            rule=self.rule, path=sf.path, line=node.lineno,
                            message=(
                                f"jax.jit constructed inside "
                                f"{func.name}() — builds a new wrapper "
                                "(and trace) per call; hoist to module "
                                "level or cache it"
                            ),
                        ))
        findings.extend(
            self._check_prewarm_registration(project, pipeline_jits))
        return findings

    # ------------------------------------------------- pre-warm registration

    def _find_func(self, project: Project, suffix: str):
        cls_name, _, fn_name = suffix.rpartition(".")
        for sf in project.files.values():
            for node in ast.walk(sf.tree):
                if isinstance(node, ast.ClassDef) and node.name == cls_name:
                    for item in node.body:
                        if isinstance(item, ast.FunctionDef) and \
                                item.name == fn_name:
                            return sf, item
                elif not cls_name and isinstance(node, ast.FunctionDef) \
                        and node.name == fn_name:
                    return sf, node
        return None, None

    @staticmethod
    def _names_in(node: ast.AST) -> Set[str]:
        return {n.id for n in ast.walk(node) if isinstance(n, ast.Name)}

    def _check_prewarm_registration(self, project: Project,
                                    pipeline_jits: dict) -> List[Finding]:
        disp_sf, disp = self._find_func(project, self.dispatch_func)
        warm_sf, warm = self._find_func(project, self.prewarm_func)
        if disp is None or warm is None:
            return []   # fixture projects without a runner: nothing to do
        dispatch_jits = {n for n in self._names_in(disp)
                         if n.startswith("pipeline_") and n.endswith("_jit")}
        warm_jits = self._names_in(warm)
        out = []
        for name in sorted(dispatch_jits - warm_jits):
            out.append(Finding(
                rule=self.rule, path=disp_sf.path, line=disp.lineno,
                message=(
                    f"dispatch-shaped jit `{name}` is used by "
                    f"{self.dispatch_func.split('.')[-1]}() but not "
                    f"registered with the pre-warm ledger "
                    f"({self.prewarm_func.split('.')[-1]}) — a load "
                    "spike selecting it stalls on a mid-traffic compile"
                ),
            ))
        # Dead/unreachable entry points (ISSUE 11): every module-level
        # pipeline_*_jit must be BOTH dispatch-selectable and warmed.
        # The selectable-but-unwarmed direction is the check above
        # (which also covers names imported from out-of-scope modules),
        # so this one fires only for dispatch-UNREACHABLE names — one
        # finding per dead jit, never two for the same defect.
        for name in sorted(pipeline_jits):
            if name in dispatch_jits:
                continue
            sf, line = pipeline_jits[name]
            missing = [f"the dispatch discipline selection "
                       f"({self.dispatch_func.split('.')[-1]})"]
            if name not in warm_jits:
                missing.append(
                    f"the pre-warm ledger "
                    f"({self.prewarm_func.split('.')[-1]})")
            out.append(Finding(
                rule=self.rule, path=sf.path, line=line,
                message=(
                    f"pipeline entry point `{name}` is not "
                    f"referenced from {' or '.join(missing)} — a "
                    "dead entry point drifts from the production "
                    "semantics (rename it out of the pipeline_*_jit "
                    "namespace if it is not a dispatch entry point)"
                ),
            ))
        return out
