"""hot-path-sync — no host↔device syncs on the dispatch-floor path.

NOTES_r05: the production dispatch is dispatch-floor-bound — device
compute is essentially free and each host↔device round trip is what
costs.  One accidental ``.item()`` / ``np.asarray`` / implicit
``bool()`` on a device value inside admit/dispatch/steering erases the
governor's 2.83× win and nothing functional breaks, so only a machine
check catches it.  This checker walks every function reachable (call
graph, method dispatch included) from the datapath roots and flags:

- ``.item()`` and ``.block_until_ready()`` calls;
- ``np.asarray(...)`` / ``jax.device_get(...)`` — device→host reads;
- ``time.time()`` — wall clock on the hot path (drifts under NTP; the
  timing fit must use ``perf_counter``/``monotonic``);
- ``int()/float()/bool()`` over expressions that mention a device
  value (``jnp.``-rooted expressions, pipeline ``result`` fields, the
  device ``sessions`` table).

Sanctioned sync points (the harvest materialisation, swap-time bypass
derivation, the all-shards-down host path, occupancy gauges) are
listed in ``SANCTIONED``: their own bodies are exempt and traversal
stops there.  Anything else syncs only with an inline waiver.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence

from .callgraph import CallGraph
from .core import Checker, Finding, Project, register

# Where the hot paths start (qualname suffixes; resolved against the
# project, so fixture modules can declare their own roots).
DEFAULT_ROOTS = (
    "DataplaneRunner._dispatch",
    "DataplaneRunner._admit",
    "DataplaneRunner._harvest",
    "ShardedDataplane._steer",
    "ShardedDataplane.poll",
)

# Sanctioned sync points: these functions' own bodies may sync (each
# one is a DESIGNED host block); traversal is pruned at them.
DEFAULT_SANCTIONED = (
    # The harvest is the one sanctioned materialisation point: the host
    # blocks on the OLDEST in-flight batch only, by design.
    "DataplaneRunner._harvest_native",
    "DataplaneRunner._harvest_python",
    # Host-stitched quarantine recovery: already on the failure path.
    "DataplaneRunner._quarantine_dispatch",
    # Swap-time bypass eligibility pays its occupancy reads once per
    # table swap, not per batch.
    "DataplaneRunner._refresh_bypass",
    "DataplaneRunner._bypass_static_ok",
    "DataplaneRunner._bypass_state_clear",
    "DataplaneRunner._bypass_once",
    # The all-shards-down degraded mode is an explicit host path.
    "ShardedDataplane._bypass_forward",
    # Occupancy gauges are host-side reads by contract (/metrics).
    "session_occupancy",
    "affinity_occupancy",
)

# Modules BELOW the device boundary: pure host-side marshalling whose
# numpy work never touches a device value (np.asarray on a host buffer
# is a view, not a sync).  Reached functions there are exempt.
DEFAULT_HOST_MODULES = (
    "vpp_tpu.shim.hostshim",
)

# Names whose appearance inside an int()/float()/bool() argument marks
# the cast as a device-value materialisation.
DEVICE_VALUE_NAMES = frozenset({"result", "res", "sessions"})

_CASTS = ("int", "float", "bool")


def _mentions_device_value(node: ast.AST, jnp_aliases: frozenset) -> bool:
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and sub.id in DEVICE_VALUE_NAMES:
            return True
        if isinstance(sub, ast.Attribute) and sub.attr in DEVICE_VALUE_NAMES:
            return True
        if isinstance(sub, ast.Name) and sub.id in jnp_aliases:
            return True
    return False


@register
class HotPathSyncChecker(Checker):
    rule = "hot-path-sync"
    description = (
        "no host-sync constructs (.item/np.asarray/device casts/"
        "block_until_ready/time.time) reachable from the datapath "
        "dispatch, admit, harvest, or steering roots"
    )

    def __init__(self, roots: Sequence[str] = DEFAULT_ROOTS,
                 sanctioned: Sequence[str] = DEFAULT_SANCTIONED,
                 host_modules: Sequence[str] = DEFAULT_HOST_MODULES):
        self.roots = roots
        self.sanctioned = sanctioned
        self.host_modules = host_modules

    def check(self, project: Project) -> List[Finding]:
        graph = CallGraph(project)
        # Sanctioned functions are BODY-exempt but still traversed
        # through: a helper they call is on the hot path unless it is
        # itself sanctioned.
        chains = graph.reachable(self.roots, prune=())
        findings: List[Finding] = []
        for qual, chain in sorted(chains.items()):
            if any(qual == p or qual.endswith("." + p)
                   for p in self.sanctioned):
                continue
            if graph.funcs[qual].module in self.host_modules:
                continue
            info = graph.funcs[qual]
            sf = project.files[info.path]
            findings.extend(self._check_func(sf, info, chain))
        return findings

    # ------------------------------------------------------------ per-func

    def _check_func(self, sf, info, chain) -> List[Finding]:
        imap = {}
        np_aliases = set()
        jax_aliases = set()
        time_aliases = set()
        jnp_aliases = set()
        # Alias maps come from the whole module (imports may be at the
        # top or function-local, e.g. `import time as _time`).
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    alias = a.asname or a.name.split(".")[0]
                    if a.name == "numpy":
                        np_aliases.add(alias)
                    elif a.name == "jax":
                        jax_aliases.add(alias)
                    elif a.name == "time":
                        time_aliases.add(alias)
                    elif a.name == "jax.numpy":
                        jnp_aliases.add(alias)
            elif isinstance(node, ast.ImportFrom):
                if node.module == "jax" and not node.level:
                    for a in node.names:
                        if a.name == "numpy":
                            jnp_aliases.add(a.asname or "numpy")
                        if a.name == "device_get":
                            imap[a.asname or "device_get"] = "jax.device_get"
                if node.module == "time" and not node.level:
                    for a in node.names:
                        if a.name == "time":
                            imap[a.asname or "time"] = "time.time"
        jnp_frozen = frozenset(jnp_aliases)
        hop = " → ".join(q.rsplit(".", 1)[-1] for q in chain)
        out: List[Finding] = []

        def flag(node: ast.AST, what: str) -> None:
            out.append(Finding(
                rule=self.rule, path=sf.path, line=node.lineno,
                message=f"{what} on the hot path (via {hop})",
            ))

        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute):
                base = func.value
                base_name = base.id if isinstance(base, ast.Name) else None
                if func.attr == "item" and not node.args:
                    flag(node, "`.item()` (device→host scalar sync)")
                elif func.attr == "block_until_ready":
                    flag(node, "`.block_until_ready()` (explicit device barrier)")
                elif func.attr == "asarray" and base_name in np_aliases:
                    flag(node, "`np.asarray(...)` (device→host materialisation)")
                elif func.attr == "device_get" and base_name in jax_aliases:
                    flag(node, "`jax.device_get(...)` (device→host transfer)")
                elif func.attr == "time" and base_name in time_aliases:
                    flag(node, "`time.time()` (wall clock; use "
                               "perf_counter/monotonic)")
            elif isinstance(func, ast.Name):
                target = imap.get(func.id)
                if target == "jax.device_get":
                    flag(node, "`device_get(...)` (device→host transfer)")
                elif target == "time.time":
                    flag(node, "`time()` (wall clock; use "
                               "perf_counter/monotonic)")
                elif func.id in _CASTS and node.args and \
                        _mentions_device_value(node.args[0], jnp_frozen):
                    flag(node, f"`{func.id}(...)` over a device value "
                               "(implicit host sync)")
        return out
