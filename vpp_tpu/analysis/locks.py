"""lock-discipline — annotated cross-thread state, enforced writes.

The PR 3/4 threading work (shard worker threads + supervisor, HA tick
loop + replication pool, REST handler threads) mutates shared state
from multiple thread entry points.  CPython has no race detector, so
the discipline is made machine-checkable via annotations:

- ``self.attr = ...  # guarded-by: <lock>`` — declared at the
  attribute's construction site: every OTHER write to ``attr`` in the
  scoped files must sit inside ``with <lock>:`` or inside a function
  annotated ``# holds: <lock>`` (for ``*_locked`` helpers and
  acquire/release patterns).
- ``# lock-free: <reason>`` — a deliberate single-word/atomic-ref
  publication (e.g. the table swap's reference assignment); reason
  required.
- ``# owner: <reason>`` — single-writer state owned by one thread
  (e.g. per-shard governor state touched only by that shard's worker);
  reason required.

Any attribute written from more than one thread entry point WITHOUT
one of the three annotations is flagged.  Thread entry points are
inferred per file: ``threading.Thread(target=X)`` / ``Timer(..., X)``
targets, executor ``submit``/``map`` callables, and everything
transitively reachable from them through the project call graph.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph
from .core import Checker, Finding, Project, register

DEFAULT_SCOPES = (
    "vpp_tpu.datapath.runner",
    "vpp_tpu.datapath.shards",
    "vpp_tpu.datapath.governor",
    "vpp_tpu.kvstore.ha",
)

_GUARDED_RE = re.compile(r"#\s*guarded-by:\s*(\S+)")
_LOCKFREE_RE = re.compile(r"#\s*lock-free:(.*)$")
_OWNER_RE = re.compile(r"#\s*owner:(.*)$")
_HOLDS_RE = re.compile(r"#\s*holds:\s*(\S+)")
_ATTR_ON_LINE_RE = re.compile(r"(?:self|sessions)\.(\w+)|^\s*(\w+)\s*[:=]")

_INIT_FUNCS = ("__init__", "__post_init__", "__new__")


def _lock_token(lockexpr: str) -> str:
    """The comparison token of a lock expression: its last dotted
    component (``self._state.lock`` → ``lock``)."""
    return lockexpr.rstrip(":").split(".")[-1]


class _WriteSite:
    def __init__(self, path: str, line: int, attr: str,
                 func_stack: Tuple[str, ...], with_locks: Tuple[str, ...]):
        self.path = path
        self.line = line
        self.attr = attr
        self.func_stack = func_stack        # outermost → innermost names
        self.with_locks = with_locks        # lock tokens of enclosing withs

    @property
    def func(self) -> str:
        return self.func_stack[-1] if self.func_stack else "<module>"


class _FileScan(ast.NodeVisitor):
    """Collect attribute write sites with their enclosing function and
    ``with`` context."""

    def __init__(self, sf):
        self.sf = sf
        self.writes: List[_WriteSite] = []
        self._funcs: List[str] = []
        self._withs: List[str] = []

    # --- context tracking

    def visit_FunctionDef(self, node):
        self._funcs.append(node.name)
        self.generic_visit(node)
        self._funcs.pop()

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_With(self, node):
        tokens = []
        for item in node.items:
            src = self.sf.src(item.context_expr)
            # `with lock:` / `with self._state.lock:` / `with a, b:`
            tokens.append(_lock_token(src.split("(")[0].strip()))
        self._withs.extend(tokens)
        self.generic_visit(node)
        del self._withs[len(self._withs) - len(tokens):]

    # --- write collection

    def _record(self, target: ast.AST, line: int) -> None:
        if isinstance(target, ast.Attribute):
            self._add(target.attr, line)
        elif isinstance(target, ast.Subscript) and \
                isinstance(target.value, ast.Attribute):
            self._add(target.value.attr, line)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._record(elt, line)

    def _add(self, attr: str, line: int) -> None:
        self.writes.append(_WriteSite(
            self.sf.path, line, attr,
            tuple(self._funcs), tuple(self._withs)))

    def visit_Assign(self, node):
        for t in node.targets:
            self._record(t, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node):
        self._record(node.target, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node):
        if node.value is not None:
            self._record(node.target, node.lineno)
        self.generic_visit(node)


@register
class LockDisciplineChecker(Checker):
    rule = "lock-discipline"
    description = (
        "cross-thread attributes are annotated (guarded-by / lock-free "
        "/ owner) and guarded writes happen inside their lock"
    )

    def __init__(self, scopes: Sequence[str] = DEFAULT_SCOPES):
        self.scopes = scopes

    def _scoped(self, project: Project):
        return [sf for sf in project.files.values()
                if sf.module in self.scopes
                or any(sf.module.startswith(s + ".") for s in self.scopes)]

    # ------------------------------------------------------------------ run

    def check(self, project: Project) -> List[Finding]:
        scoped = self._scoped(project)
        if not scoped:
            return []
        findings: List[Finding] = []
        guarded: Dict[str, str] = {}        # attr -> lock token
        annotated: Set[str] = set()         # attrs with ANY annotation
        holds: Dict[Tuple[str, str], str] = {}   # (path, func) -> lock token

        for sf in scoped:
            for i, line in enumerate(sf.lines, start=1):
                g = _GUARDED_RE.search(line)
                lf = _LOCKFREE_RE.search(line)
                ow = _OWNER_RE.search(line)
                # `class Foo:  # owner: …` annotates every field of the
                # class at once (counter dataclasses are single-owner
                # as a unit, not per field).
                cls_m = re.match(r"\s*class\s+(\w+)", line) \
                    if (g or lf or ow) else None
                if cls_m is not None:
                    for field in self._class_fields(sf, cls_m.group(1)):
                        annotated.add(field)
                        if g:
                            guarded[field] = _lock_token(g.group(1))
                attr = self._attr_on_line(sf, i)
                if g:
                    if attr is None:
                        findings.append(Finding(
                            rule=self.rule, path=sf.path, line=i,
                            message="guarded-by annotation on a line with "
                                    "no attribute assignment",
                        ))
                    else:
                        guarded[attr] = _lock_token(g.group(1))
                        annotated.add(attr)
                for m, kind in ((lf, "lock-free"), (ow, "owner")):
                    if m is None:
                        continue
                    if not m.group(1).strip():
                        findings.append(Finding(
                            rule=self.rule, path=sf.path, line=i,
                            message=f"{kind} annotation without a reason — "
                                    f"write '# {kind}: <why this is safe>'",
                        ))
                    if attr is not None:
                        annotated.add(attr)
                h = _HOLDS_RE.search(line)
                if h:
                    fn = self._def_at_or_below(sf, i)
                    if fn is not None:
                        holds[(sf.path, fn)] = _lock_token(h.group(1))

        scans = {}
        for sf in scoped:
            scan = _FileScan(sf)
            scan.visit(sf.tree)
            scans[sf.path] = scan

        findings.extend(self._check_guarded_writes(scans, guarded, holds))
        findings.extend(self._check_unannotated(
            project, scoped, scans, annotated))
        return findings

    # ----------------------------------------------------------- helpers

    @staticmethod
    def _class_fields(sf, cls_name: str) -> Set[str]:
        """Field names of one class: annotated class-level fields plus
        ``self.X = …`` targets in its ``__init__``."""
        fields: Set[str] = set()
        for node in ast.walk(sf.tree):
            if not (isinstance(node, ast.ClassDef) and node.name == cls_name):
                continue
            for item in node.body:
                if isinstance(item, ast.AnnAssign) and \
                        isinstance(item.target, ast.Name):
                    fields.add(item.target.id)
            for sub in ast.walk(node):
                if isinstance(sub, ast.FunctionDef) and \
                        sub.name in _INIT_FUNCS:
                    for a in ast.walk(sub):
                        if isinstance(a, ast.Attribute) and \
                                isinstance(a.ctx, ast.Store):
                            fields.add(a.attr)
        return fields

    @staticmethod
    def _attr_on_line(sf, lineno: int) -> Optional[str]:
        line = sf.lines[lineno - 1]
        code = line.split("#", 1)[0]
        m = _ATTR_ON_LINE_RE.search(code)
        if m:
            return m.group(1) or m.group(2)
        return None

    @staticmethod
    def _def_at_or_below(sf, lineno: int) -> Optional[str]:
        """The function a `# holds:` comment annotates: a def on the
        same line, the line below (comment above the def), or a couple
        of lines up (comment trailing a multi-line signature)."""
        for i in (lineno, lineno + 1, lineno - 1, lineno - 2):
            if 0 < i <= len(sf.lines):
                m = re.match(r"\s*(?:async\s+)?def\s+(\w+)", sf.lines[i - 1])
                if m:
                    return m.group(1)
        return None

    # ------------------------------------------------- guarded-write check

    def _check_guarded_writes(self, scans, guarded, holds) -> List[Finding]:
        out: List[Finding] = []
        for scan in scans.values():
            for w in scan.writes:
                token = guarded.get(w.attr)
                if token is None or w.func in _INIT_FUNCS:
                    continue
                if token in w.with_locks:
                    continue
                if any(holds.get((w.path, fn)) == token
                       for fn in w.func_stack):
                    continue
                out.append(Finding(
                    rule=self.rule, path=w.path, line=w.line,
                    message=(
                        f"write to guarded attribute `{w.attr}` outside "
                        f"`with {token}` (declare `# holds: {token}` on "
                        f"{w.func}() if every caller takes the lock)"
                    ),
                ))
        return out

    # --------------------------------------------- cross-thread inference

    def _thread_entries(self, sf) -> Set[str]:
        """Function names handed to Thread/Timer/submit/map in one file."""
        entries: Set[str] = set()

        def callable_name(node: ast.AST) -> Optional[str]:
            if isinstance(node, ast.Attribute):
                return node.attr
            if isinstance(node, ast.Name):
                return node.id
            return None

        for node in ast.walk(sf.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name) else "")
            if fname in ("Thread", "Timer"):
                for kw in node.keywords:
                    if kw.arg == "target":
                        n = callable_name(kw.value)
                        if n:
                            entries.add(n)
                if fname == "Timer" and len(node.args) >= 2:
                    n = callable_name(node.args[1])
                    if n:
                        entries.add(n)
            elif fname in ("submit", "map") and node.args:
                n = callable_name(node.args[0])
                if n:
                    entries.add(n)
        return entries

    def _check_unannotated(self, project, scoped, scans,
                           annotated) -> List[Finding]:
        graph = CallGraph(project)
        entry_names: Set[str] = set()
        for sf in scoped:
            entry_names.update(self._thread_entries(sf))
        scoped_paths = {sf.path for sf in scoped}
        # Per-entry reachability: a function reachable from TWO entry
        # points runs on two threads even if it is the only writer.
        entry_of: Dict[str, Set[str]] = {}
        for entry in sorted(entry_names):
            for q in graph.reachable([entry]):
                if graph.funcs[q].path in scoped_paths:
                    entry_of.setdefault(graph.funcs[q].name, set()).add(entry)
        threaded_names = set(entry_of)

        by_attr: Dict[str, List[_WriteSite]] = {}
        for scan in scans.values():
            for w in scan.writes:
                if w.func in _INIT_FUNCS or not w.func_stack:
                    continue
                by_attr.setdefault(w.attr, []).append(w)

        out: List[Finding] = []
        for attr, sites in sorted(by_attr.items()):
            if attr in annotated:
                continue
            writers = {(w.path, w.func) for w in sites}
            threaded_writers = {(p, f) for (p, f) in writers
                                if f in threaded_names}
            multi_entry = {
                f for _, f in threaded_writers if len(entry_of[f]) > 1}
            if not threaded_writers or (
                    len(writers) < 2 and not multi_entry):
                continue
            first = min(sites, key=lambda w: (w.path, w.line))
            funcs = ", ".join(sorted({f for _, f in writers}))
            detail = (
                f"from multiple thread entry points ({funcs})"
                if len(writers) > 1 else
                f"by {funcs}(), which runs on multiple threads "
                f"({', '.join(sorted(entry_of[first.func]))})"
            )
            out.append(Finding(
                rule=self.rule, path=first.path, line=first.line,
                message=(
                    f"attribute `{attr}` is written {detail} with no "
                    "guarded-by / lock-free / owner annotation"
                ),
            ))
        return out
