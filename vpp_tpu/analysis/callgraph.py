"""Call-graph builder — name/import-resolved, with method dispatch.

Gives the hot-path checker its reachability set: which functions can
run under ``runner._dispatch`` / admit / harvest / shard steering.

Resolution is deliberately CONSERVATIVE (an over-approximation — a
missed edge would silently exempt code from the hot-path invariant,
while a spurious edge costs at worst one explicit waiver):

- ``name(...)``       → the caller's module first, then the caller's
  ``from X import name`` bindings, then any project def of that name;
- ``alias.attr(...)`` where ``alias`` is an imported module → that
  module's ``attr`` exactly;
- ``self.m(...)``     → methods named ``m`` on the caller's class, its
  project bases and its project subclasses (method dispatch);
- ``obj.m(...)``      → every project def named ``m`` — except names in
  ``COMMON_METHODS`` (dict/list/deque/lock/executor vocabulary), which
  would wire the whole repo into every hot path.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .core import Project, SourceFile

# Attribute-call names too generic to resolve project-wide: stdlib
# container/concurrency vocabulary.  `self.<name>` calls still resolve
# class-locally, so a project method with one of these names keeps its
# same-class edges.
COMMON_METHODS = frozenset({
    "get", "put", "set", "add", "pop", "popleft", "append", "appendleft",
    "remove", "clear", "update", "copy", "keys", "values", "items",
    "join", "split", "strip", "startswith", "endswith", "format",
    "encode", "decode", "read", "write", "flush", "close", "open",
    "acquire", "release", "wait", "notify", "submit", "map", "shutdown",
    "result", "done", "cancel", "start", "stop", "sort", "sum", "any",
    "all", "index", "count", "extend", "setdefault", "is_set", "send",
    "__init__", "delete", "create", "commit", "poll", "apply", "status",
    "replace", "snapshot", "resync", "dump", "list",
})

# Callables handed to these become edges too: a thread/executor target
# IS called, just on another thread.
_DEFERRED_CALLERS = frozenset({"submit", "map", "Thread", "Timer",
                               "start_new_thread"})


@dataclasses.dataclass
class FuncInfo:
    qualname: str                 # module.Class.name | module.name
    module: str
    cls: Optional[str]            # enclosing class simple name
    name: str
    path: str
    node: ast.AST                 # FunctionDef / AsyncFunctionDef
    lineno: int


class _ImportMap:
    """alias → dotted target for one module."""

    def __init__(self, sf: SourceFile):
        self.modules: Dict[str, str] = {}   # alias -> module dotted path
        self.names: Dict[str, str] = {}     # alias -> module.attr
        pkg_parts = sf.module.split(".")
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.modules[a.asname or a.name.split(".")[0]] = a.name
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = pkg_parts[: len(pkg_parts) - node.level]
                    mod = ".".join(base + ([node.module] if node.module else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    self.names[a.asname or a.name] = f"{mod}.{a.name}"


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.funcs: Dict[str, FuncInfo] = {}
        self.by_name: Dict[str, List[FuncInfo]] = {}
        self.class_bases: Dict[str, List[str]] = {}   # module.Class -> base names
        self.imports: Dict[str, _ImportMap] = {}
        self._edges: Dict[str, Set[str]] = {}
        self._index()

    # ------------------------------------------------------------ indexing

    def _index(self) -> None:
        for sf in self.project.files.values():
            self.imports[sf.module] = _ImportMap(sf)
            for node in sf.tree.body:
                if isinstance(node, ast.ClassDef):
                    bases = [self._base_name(b) for b in node.bases]
                    self.class_bases[f"{sf.module}.{node.name}"] = \
                        [b for b in bases if b]
                    for item in node.body:
                        if isinstance(item, (ast.FunctionDef,
                                             ast.AsyncFunctionDef)):
                            self._add(sf, item, cls=node.name)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._add(sf, node, cls=None)

    @staticmethod
    def _base_name(node: ast.AST) -> str:
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        return ""

    def _add(self, sf: SourceFile, node, cls: Optional[str]) -> None:
        qual = f"{sf.module}.{cls}.{node.name}" if cls else \
            f"{sf.module}.{node.name}"
        info = FuncInfo(qualname=qual, module=sf.module, cls=cls,
                        name=node.name, path=sf.path, node=node,
                        lineno=node.lineno)
        self.funcs[qual] = info
        self.by_name.setdefault(node.name, []).append(info)

    # ---------------------------------------------------------- resolution

    def _related_classes(self, module: str, cls: str) -> Set[Tuple[str, str]]:
        """(module, class) pairs dispatch on ``self`` may land in: the
        class itself, project bases, and project subclasses."""
        out = {(module, cls)}
        # bases (one level is enough for this repo's hierarchies)
        for qual, bases in self.class_bases.items():
            mod, _, name = qual.rpartition(".")
            if name == cls and mod == module:
                for b in bases:
                    for q2 in self.class_bases:
                        m2, _, n2 = q2.rpartition(".")
                        if n2 == b:
                            out.add((m2, n2))
            # subclasses of cls anywhere in the project
            if cls in bases:
                out.add((mod, name))
        return out

    def callees(self, info: FuncInfo) -> List[FuncInfo]:
        cached = self._edges.get(info.qualname)
        if cached is not None:
            return [self.funcs[q] for q in cached if q in self.funcs]
        imap = self.imports.get(info.module)
        out: Set[str] = set()

        def resolve_ref(ref: ast.AST) -> None:
            """A callable REFERENCE (thread target, submit arg)."""
            if isinstance(ref, ast.Attribute):
                out.update(f.qualname for f in self._resolve_attr(
                    ref, info, imap, allow_common=True))
            elif isinstance(ref, ast.Name):
                out.update(f.qualname for f in self._resolve_name(
                    ref.id, info, imap))

        for node in ast.walk(info.node):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Name):
                out.update(f.qualname for f in self._resolve_name(
                    func.id, info, imap))
                name = func.id
            elif isinstance(func, ast.Attribute):
                out.update(f.qualname for f in self._resolve_attr(
                    func, info, imap))
                name = func.attr
            else:
                continue
            if name in _DEFERRED_CALLERS:
                # submit(fn, ...) / map(fn, it) / Thread(target=fn)
                if node.args:
                    resolve_ref(node.args[0])
                if name == "Timer" and len(node.args) >= 2:
                    resolve_ref(node.args[1])
                for kw in node.keywords:
                    if kw.arg == "target":
                        resolve_ref(kw.value)
        self._edges[info.qualname] = out
        return [self.funcs[q] for q in out if q in self.funcs]

    def _resolve_name(self, name: str, caller: FuncInfo,
                      imap: Optional[_ImportMap]) -> List[FuncInfo]:
        local = self.funcs.get(f"{caller.module}.{name}")
        if local is not None:
            return [local]
        if imap and name in imap.names:
            target = self.funcs.get(imap.names[name])
            if target is not None:
                return [target]
            # from X import Y where Y is a class: constructor edge
            mod, _, attr = imap.names[name].rpartition(".")
            init = self.funcs.get(f"{mod}.{attr}.__init__")
            if init is not None:
                return [init]
            return []
        # Class constructor in the same module.
        init = self.funcs.get(f"{caller.module}.{name}.__init__")
        if init is not None:
            return [init]
        return []

    def _resolve_attr(self, func: ast.Attribute, caller: FuncInfo,
                      imap: Optional[_ImportMap],
                      allow_common: bool = False) -> List[FuncInfo]:
        attr = func.attr
        value = func.value
        # super().m(...) → project base classes of the caller's class
        if isinstance(value, ast.Call) and \
                isinstance(value.func, ast.Name) and \
                value.func.id == "super" and caller.cls is not None:
            hits = []
            for base in self.class_bases.get(
                    f"{caller.module}.{caller.cls}", ()):
                for q, info in self.funcs.items():
                    if info.cls == base and info.name == attr:
                        hits.append(info)
            return hits
        # module alias: np.asarray, mod.func — exact or external (empty)
        if isinstance(value, ast.Name):
            if imap and value.id in imap.modules:
                target = self.funcs.get(f"{imap.modules[value.id]}.{attr}")
                return [target] if target else []
            if value.id == "self" and caller.cls is not None:
                hits = []
                for mod, cls in self._related_classes(caller.module,
                                                      caller.cls):
                    t = self.funcs.get(f"{mod}.{cls}.{attr}")
                    if t is not None:
                        hits.append(t)
                if hits:
                    return hits
                # fall through: self.<injected-component>.… not a method
        if attr in COMMON_METHODS and not allow_common:
            return []
        if allow_common and attr == "__init__":
            return []
        return list(self.by_name.get(attr, ()))

    # -------------------------------------------------------- reachability

    def reachable(
        self,
        roots: Iterable[str],
        prune: Sequence[str] = (),
    ) -> Dict[str, List[str]]:
        """BFS from root qualnames; returns {qualname: chain-from-root}.
        ``prune`` entries (qualname suffixes) are still REPORTED as
        reached but their bodies are not traversed — the sanctioned-
        sync-point semantics (their own code is exempt, their callees
        are only checked if reached some other way)."""
        chains: Dict[str, List[str]] = {}
        queue: List[str] = []
        for r in roots:
            matches = [q for q in self.funcs if q == r or q.endswith("." + r)]
            for q in matches:
                if q not in chains:
                    chains[q] = [q]
                    queue.append(q)
        def pruned(q: str) -> bool:
            return any(q == p or q.endswith("." + p) for p in prune)
        while queue:
            q = queue.pop(0)
            if pruned(q):
                continue
            for callee in self.callees(self.funcs[q]):
                if callee.qualname not in chains:
                    chains[callee.qualname] = chains[q] + [callee.qualname]
                    queue.append(callee.qualname)
        return chains
