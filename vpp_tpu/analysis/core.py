"""Static-analysis core: source index, waivers, registry, runner.

Design constraints that shaped this module:

- **stdlib only** (``ast`` + ``tokenize``): the checkers run in CI and
  in the container image, which bakes no linting toolchain.
- **project-native**: generic linters cannot know that ``np.asarray``
  in ``_harvest_native`` is the sanctioned materialisation point while
  the same call in ``_dispatch_locked`` erases the 2.83× governor win.
  Checkers here are parameterised with the repo's own roots/allowlists.
- **waivable with a written reason**: every rule can be silenced at a
  single site with ``# static: allow(<rule>) — <reason>``; a waiver
  without a reason is itself a finding (no silent waivers — the ISSUE 7
  policy, enforced here rather than by review).
"""

from __future__ import annotations

import ast
import dataclasses
import os
import re
from typing import Dict, Iterable, List, Optional, Sequence, Tuple, Type

# Waiver syntax:   # static: allow(<rule>) — <reason>
# The dash may be an em/en dash or one or more ASCII hyphens; the
# reason is REQUIRED (an empty reason is reported as a finding).
# A waiver trailing a line covers that line; a waiver alone on a line
# covers the NEXT source line (for statements too long to share one).
_WAIVER_RE = re.compile(
    r"#\s*static:\s*allow\(\s*([\w*-]+)\s*\)\s*(?:[—–-]+\s*(?P<reason>.*\S))?\s*$"
)


@dataclasses.dataclass
class Waiver:
    rule: str
    line: int          # the source line the waiver covers
    reason: str
    decl_line: int     # where the waiver comment itself sits
    used: bool = False


@dataclasses.dataclass
class Finding:
    """One rule violation at one site."""

    rule: str
    path: str
    line: int
    message: str
    waived: bool = False
    waiver_reason: str = ""

    def format(self) -> str:
        tag = " (waived: %s)" % self.waiver_reason if self.waived else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}{tag}"


class SourceFile:
    """One parsed python source file + its waiver table."""

    def __init__(self, path: str, text: str, module: str):
        self.path = path
        self.text = text
        self.module = module          # dotted module name, e.g. vpp_tpu.ops.nat
        self.lines = text.splitlines()
        self.tree = ast.parse(text, filename=path)
        self.waivers: List[Waiver] = []
        self._parse_waivers()

    def _parse_waivers(self) -> None:
        for i, raw in enumerate(self.lines, start=1):
            if "static:" not in raw:
                continue
            m = _WAIVER_RE.search(raw)
            if m is None:
                continue
            covers = i if raw[: m.start()].strip() else i + 1
            self.waivers.append(Waiver(
                rule=m.group(1),
                line=covers,
                reason=(m.group("reason") or "").strip(),
                decl_line=i,
            ))

    def waiver_for(self, rule: str, line: int) -> Optional[Waiver]:
        for w in self.waivers:
            if w.line == line and w.rule in (rule, "*"):
                return w
        return None

    def src(self, node: ast.AST) -> str:
        return ast.get_source_segment(self.text, node) or ""


class Project:
    """The file index every checker works over."""

    def __init__(self, files: Dict[str, SourceFile]):
        self.files = files

    @classmethod
    def load(cls, paths: Sequence[str], root: Optional[str] = None) -> "Project":
        """Index every ``*.py`` under ``paths``.  ``root`` anchors the
        dotted module names (defaults to the common parent so that
        ``vpp_tpu/ops/nat.py`` → ``vpp_tpu.ops.nat``)."""
        files: Dict[str, SourceFile] = {}
        for p in paths:
            p = os.path.abspath(p)
            base = os.path.abspath(root) if root else os.path.dirname(p)
            if os.path.isfile(p):
                cls._add(files, p, base)
                continue
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        cls._add(files, os.path.join(dirpath, fn), base)
        return cls(files)

    @classmethod
    def from_sources(cls, sources: Dict[str, str]) -> "Project":
        """Build from in-memory {relpath: source} — the fixture path the
        self-tests use."""
        files = {}
        for relpath, text in sources.items():
            module = relpath[:-3].replace("/", ".").replace("\\", ".")
            files[relpath] = SourceFile(relpath, text, module)
        return cls(files)

    @staticmethod
    def _add(files: Dict[str, SourceFile], path: str, base: str) -> None:
        rel = os.path.relpath(path, base)
        module = rel[:-3].replace(os.sep, ".")
        if module.endswith(".__init__"):
            module = module[: -len(".__init__")]
        with open(path) as fh:
            text = fh.read()
        files[rel] = SourceFile(path=rel, text=text, module=module)

    def by_module(self, module: str) -> Optional[SourceFile]:
        for f in self.files.values():
            if f.module == module:
                return f
        return None


class Checker:
    """Base checker: subclass, set ``rule``, implement ``check``."""

    rule: str = ""
    description: str = ""

    def check(self, project: Project) -> List[Finding]:  # pragma: no cover
        raise NotImplementedError


CHECKERS: Dict[str, Type[Checker]] = {}


def register(cls: Type[Checker]) -> Type[Checker]:
    if not cls.rule:
        raise ValueError(f"{cls.__name__} has no rule name")
    if cls.rule in CHECKERS:
        raise ValueError(f"duplicate checker rule {cls.rule!r}")
    CHECKERS[cls.rule] = cls
    return cls


def run_checks(
    project: Project,
    rules: Optional[Iterable[str]] = None,
    checkers: Optional[Sequence[Checker]] = None,
) -> Tuple[List[Finding], List[Finding]]:
    """Run the selected checkers; returns ``(unwaived, waived)``.

    Waivers are applied here (one implementation for every rule), and
    waiver hygiene is enforced: a waiver with no reason string is an
    unwaivable ``waiver-syntax`` finding.
    """
    if checkers is None:
        selected = rules if rules is not None else sorted(CHECKERS)
        checkers = [CHECKERS[r]() for r in selected]
    unwaived: List[Finding] = []
    waived: List[Finding] = []
    for checker in checkers:
        for finding in checker.check(project):
            sf = project.files.get(finding.path)
            w = sf.waiver_for(checker.rule, finding.line) if sf else None
            if w is not None and w.reason:
                w.used = True
                finding.waived = True
                finding.waiver_reason = w.reason
                waived.append(finding)
            else:
                unwaived.append(finding)
    # Waiver hygiene: reasons are mandatory, waivers must attach to a rule.
    for sf in project.files.values():
        for w in sf.waivers:
            if not w.reason:
                unwaived.append(Finding(
                    rule="waiver-syntax", path=sf.path, line=w.decl_line,
                    message=(
                        f"waiver for {w.rule!r} has no reason — write "
                        "'# static: allow(%s) — <why this site is safe>'"
                        % w.rule
                    ),
                ))
            elif w.rule != "*" and w.rule not in CHECKERS:
                unwaived.append(Finding(
                    rule="waiver-syntax", path=sf.path, line=w.decl_line,
                    message=f"waiver names unknown rule {w.rule!r} "
                            f"(have: {', '.join(sorted(CHECKERS))})",
                ))
    unwaived.sort(key=lambda f: (f.path, f.line, f.rule))
    waived.sort(key=lambda f: (f.path, f.line, f.rule))
    return unwaived, waived
