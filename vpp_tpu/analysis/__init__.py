"""Project-native static analysis — the invariant battery.

NOTES_r05 proved the datapath is dispatch-floor-bound: one accidental
host↔device sync in the admit/dispatch/harvest path silently erases
the governor's win, and nothing in `make lint` would catch it.  This
package encodes the repo's REAL invariants as ``ast``-based checkers:

- ``hot-path-sync``     — no host-sync constructs reachable from the
  dispatch/admit/harvest/steering hot paths (vpp_tpu/analysis/hotpath.py);
- ``jit-discipline``    — jax.jit callables in ops/ and datapath/ are
  module-level, and dispatch-shaped jits are pre-warm-registered
  (vpp_tpu/analysis/jit_discipline.py);
- ``lock-discipline``   — cross-thread attributes carry ``# guarded-by:``
  / ``# lock-free:`` / ``# owner:`` annotations and guarded writes stay
  inside their lock (vpp_tpu/analysis/locks.py);
- ``obs-parity``        — every counter is live and exported, inspect()
  matches the dashboard's expectations, every REST route has a netctl
  or test consumer (vpp_tpu/analysis/obs_parity.py).

Findings can be waived INLINE with a reason (core.py waiver syntax):

    something_suspect()  # static: allow(hot-path-sync) — why it's fine

The CLI gate is ``scripts/check_static.py`` (wired into ``make lint``
and ``make verify-static``); the checkers self-test on fixture
snippets in ``tests/test_static_analysis.py``.
"""

from .core import (  # noqa: F401
    CHECKERS,
    Checker,
    Finding,
    Project,
    register,
    run_checks,
)

# Importing the checker modules registers them.
from . import hotpath  # noqa: F401,E402
from . import jit_discipline  # noqa: F401,E402
from . import locks  # noqa: F401,E402
from . import obs_parity  # noqa: F401,E402
