"""NodeSync — cluster membership and node-ID allocation.

Analog of ``plugins/nodesync``: each agent atomically allocates the
first free positive integer as its node ID using the KV store's
create-if-absent primitive (nodesync.go allocateID :328,
putIfNotExists :392), publishes its data-plane IPs as a ``VppNode``
record (PublishNodeIPs :122), and tracks all other nodes from the
watched vppnode prefix (GetAllNodes :177) — zero direct agent-to-agent
communication (SURVEY.md §2.4).
"""

from __future__ import annotations

import logging
from typing import Dict, Optional, Tuple

from ..controller.api import EventHandler, KubeStateChange, UpdateEvent
from ..kvstore import KVStore
from ..models import VppNode, key_for
from ..models.registry import NODESYNC_PREFIX

log = logging.getLogger(__name__)

VPPNODE_PREFIX = NODESYNC_PREFIX + "vppnode/"


class NodeUpdate(UpdateEvent):
    """Another node joined / changed / left (nodesync_api NodeUpdate).

    Re-emitted by NodeSync when the watched vppnode state changes, so
    downstream handlers (ipv4net connectivity, service NodePorts) get a
    typed event instead of raw KV changes.
    """

    name = "Node Update"

    def __init__(self, node_name: str, prev: Optional[VppNode], new: Optional[VppNode]):
        super().__init__()
        self.node_name = node_name
        self.prev = prev
        self.new = new

    def __str__(self) -> str:
        op = "update"
        if self.prev is None:
            op = "join"
        elif self.new is None:
            op = "leave"
        return f"{self.name} [{op} {self.node_name}]"


class NodeSync(EventHandler):
    """Event handler + node registry."""

    name = "nodesync"

    def __init__(self, store: KVStore, node_name: str, event_loop=None):
        self.store = store
        self.node_name = node_name
        # When wired, vppnode KV changes are re-emitted as typed NodeUpdate
        # follow-up events for downstream handlers (ipv4net, service).
        self.event_loop = event_loop
        self.node_id: Optional[int] = None
        self._nodes: Dict[str, VppNode] = {}  # name -> record

    # ----------------------------------------------------------- allocation

    def allocate_id(self) -> int:
        """First-free-positive-integer allocation via atomic create.

        May block on allocation races; the reference likewise blocks the
        first resync on etcd (SURVEY §3.1).  If a record with our name
        already exists (agent restart), its ID is adopted.
        """
        if self.node_id is not None:
            return self.node_id
        while True:
            taken = {}
            for _, node in self.store.list(VPPNODE_PREFIX):
                taken[node.id] = node
                if node.name == self.node_name:
                    self.node_id = node.id
                    log.info("adopted existing node ID %d", node.id)
                    return node.id
            candidate = 1
            while candidate in taken:
                candidate += 1
            record = VppNode(id=candidate, name=self.node_name)
            if self.store.put_if_not_exists(key_for(record), record):
                self.node_id = candidate
                log.info("allocated node ID %d for %s", candidate, self.node_name)
                return candidate
            # Lost the race; retry with a fresh snapshot.

    def release_id(self) -> None:
        """Give the ID back on clean departure (release+reuse semantics)."""
        if self.node_id is None:
            return
        record = self._nodes.get(self.node_name)
        if record is not None:
            self.store.delete(key_for(record))
        else:
            self.store.delete(VPPNODE_PREFIX + str(self.node_id))
        self.node_id = None

    def publish_node_ips(
        self,
        ip_addresses: Tuple[str, ...],
        mgmt_ip_addresses: Tuple[str, ...] = (),
    ) -> VppNode:
        """Publish/refresh this node's VppNode record with its IPs."""
        if self.node_id is None:
            raise RuntimeError("node ID not allocated yet")
        record = VppNode(
            id=self.node_id,
            name=self.node_name,
            ip_addresses=tuple(ip_addresses),
            mgmt_ip_addresses=tuple(mgmt_ip_addresses),
        )
        self.store.put(key_for(record), record)
        self._nodes[self.node_name] = record
        return record

    # -------------------------------------------------------------- registry

    def get_all_nodes(self) -> Dict[str, VppNode]:
        return dict(self._nodes)

    def other_nodes(self) -> Dict[str, VppNode]:
        return {n: r for n, r in self._nodes.items() if n != self.node_name}

    # ------------------------------------------------------- event handling

    def handles_event(self, event) -> bool:
        if isinstance(event, KubeStateChange):
            return event.resource == "vppnode"
        return True

    def resync(self, event, kube_state, resync_count, txn) -> None:
        self.allocate_id()
        self._nodes = {}
        for node in kube_state.get("vppnode", {}).values():
            self._nodes[node.name] = node

    def update(self, event, txn) -> str:
        if not isinstance(event, KubeStateChange) or event.resource != "vppnode":
            return ""
        node = event.new_value if event.new_value is not None else event.prev_value
        if node is None:
            return ""
        if event.new_value is None:
            self._nodes.pop(node.name, None)
        else:
            self._nodes[node.name] = event.new_value
        if self.event_loop is not None and node.name != self.node_name:
            self.event_loop.push_event(
                NodeUpdate(node.name, event.prev_value, event.new_value)
            )
        return f"node {node.name} {'removed' if event.new_value is None else 'updated'}"
