from .nodesync import NodeSync, NodeUpdate

__all__ = ["NodeSync", "NodeUpdate"]
