"""Service stack — K8s Services -> NAT44 DNAT/LB maps.

Mirrors the reference's layering (plugins/service, SURVEY.md §2.1):

    ServicePlugin (plugin.py)        event-handler skeleton
      -> ServiceProcessor (processor.py) pairs Services with Endpoints,
                                     builds ContivService, tracks
                                     frontends/backends and node IPs
      -> renderers (renderer/)       DNAT mapping tensors for the TPU
                                     NAT kernel (ops/nat.py)
"""

from .renderer.api import (
    ContivService,
    ServiceBackend,
    ServicePortSpec,
    ServiceRendererAPI,
    TrafficPolicy,
)
from .processor import ServiceProcessor
from .plugin import ServicePlugin

__all__ = [
    "ContivService",
    "ServiceBackend",
    "ServicePortSpec",
    "ServiceRendererAPI",
    "TrafficPolicy",
    "ServiceProcessor",
    "ServicePlugin",
]
