from .api import (
    ContivService,
    ServiceBackend,
    ServicePortSpec,
    ServiceRendererAPI,
    TrafficPolicy,
)

__all__ = [
    "ContivService",
    "ServiceBackend",
    "ServicePortSpec",
    "ServiceRendererAPI",
    "TrafficPolicy",
]
