"""TPU service renderer — ContivService -> NAT mapping tensors.

Analog of ``plugins/service/renderer/nat44/nat44_renderer.go``: exports
one DNAT mapping per (service IP x port), with weighted backends and
twice-NAT flags (exportDNATMappings :421-513), and compiles the whole
mapping set into ``NatTables`` for the NAT kernel on every change.

Reference semantics kept:
- NodePort mappings are exported for every node IP in the cluster;
- remote backends are skipped when the traffic policy is node-local;
- local backends get ``local_weight`` (ServiceLocalEndpointWeight);
- external-IP mappings of cluster-wide services use twice-NAT ENABLED
  (client source always rewritten), everything else SELF (hairpin only);
- a mapping with no eligible backends is not installed.
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from ...models import ProtocolType, ServiceID
from ...ops.nat import (
    NatMapping,
    NatTables,
    TWICE_NAT_ENABLED,
    TWICE_NAT_SELF,
)
from .api import ContivService, ServiceRendererAPI, TrafficPolicy

log = logging.getLogger(__name__)


def export_service_mappings(
    svc: ContivService, node_ips: Sequence[str], local_weight: int
) -> List[NatMapping]:
    """exportDNATMappings for one service (nat44_renderer.go:421-513)."""
    out: List[NatMapping] = []

    def backends_for(port_name: str) -> List[Tuple[str, int, int]]:
        chosen: List[Tuple[str, int, int]] = []
        for b in svc.backends.get(port_name, []):
            if svc.traffic_policy is not TrafficPolicy.CLUSTER_WIDE and not b.local:
                continue  # do not LB to remote backends (node-local policy)
            weight = local_weight if b.local else 1
            chosen.append((b.ip, b.port, weight))
        if len(chosen) == 1:
            # Single backend: weight is irrelevant (reference sets
            # probability 0 = unconfigured).
            chosen = [(chosen[0][0], chosen[0][1], 1)]
        return chosen

    def add(ip: str, port: int, proto: ProtocolType, twice_nat: int, port_name: str):
        if port == 0:
            return
        backends = backends_for(port_name)
        if not backends:
            return
        out.append(
            NatMapping(
                external_ip=ip,
                external_port=port,
                protocol=int(proto),
                backends=backends,
                twice_nat=twice_nat,
                session_affinity_timeout=svc.session_affinity_timeout,
            )
        )

    for port_name, spec in svc.ports.items():
        # NodePort mappings on every node IP.
        if spec.node_port:
            for node_ip in node_ips:
                add(node_ip, spec.node_port, spec.protocol, TWICE_NAT_SELF, port_name)
        # Cluster IPs.
        for ip in svc.cluster_ips:
            add(ip, spec.port, spec.protocol, TWICE_NAT_SELF, port_name)
        # External IPs: cluster-wide services rewrite the client source
        # so replies return through this node (twice-NAT ENABLED).
        twice = (
            TWICE_NAT_ENABLED
            if svc.traffic_policy is TrafficPolicy.CLUSTER_WIDE
            else TWICE_NAT_SELF
        )
        for ip in svc.external_ips:
            add(ip, spec.port, spec.protocol, twice, port_name)
    return out


class TpuNatRenderer(ServiceRendererAPI):
    """Keeps rendered services; compiles NAT tensors on every change."""

    def __init__(
        self,
        nat_loopback: str = "0.0.0.0",
        snat_ip: str = "0.0.0.0",
        snat_enabled: bool = False,
        pod_subnet: str = "10.1.0.0/16",
        local_weight: int = 1,
        on_compiled: Optional[Callable[[NatTables], None]] = None,
    ):
        self.nat_loopback = nat_loopback
        self.snat_ip = snat_ip
        self.snat_enabled = snat_enabled
        self.pod_subnet = pod_subnet
        self.local_weight = max(1, local_weight)
        self._services: Dict[ServiceID, ContivService] = {}
        self._node_ips: List[str] = []
        self._frontends: Set[str] = set()
        self._backends: Set[str] = set()
        self._lock = threading.Lock()
        self._compiled: Optional[NatTables] = None
        self._on_compiled = on_compiled
        # Persistent incremental compiler: a service/endpoint change
        # patches its mapping rows and backend rings in place instead of
        # rebuilding (and re-uploading) the whole table (ops/nat_delta).
        from ...ops.nat_delta import NatTableBuilder

        self._builder = NatTableBuilder()
        # Exported-mapping cache per service: _recompile hands the
        # builder the SAME tuple objects for untouched services, so its
        # diff is an identity check, not a value compare of every
        # mapping — the host side stays O(changed) too.  Invalidated
        # per-service on CRUD, wholesale when node IPs change (NodePort
        # exports depend on them).
        self._export_cache: Dict[ServiceID, tuple] = {}
        self._recompile()

    # --------------------------------------------------------------- queries

    @property
    def tables(self) -> Optional[NatTables]:
        with self._lock:
            return self._compiled

    def mappings(self) -> List[NatMapping]:
        with self._lock:
            return self._export_all()

    # ------------------------------------------------------------- renderer

    def add_service(self, service: ContivService) -> None:
        with self._lock:
            self._services[service.id] = service
            self._export_cache.pop(service.id, None)
        self._recompile()

    def update_service(self, old: ContivService, new: ContivService) -> None:
        with self._lock:
            self._services[new.id] = new
            self._export_cache.pop(old.id, None)
            self._export_cache.pop(new.id, None)
        self._recompile()

    def delete_service(self, service: ContivService) -> None:
        with self._lock:
            self._services.pop(service.id, None)
            self._export_cache.pop(service.id, None)
        self._recompile()

    def update_node_port_services(self, node_ips, np_services) -> None:
        with self._lock:
            if list(node_ips) != self._node_ips:
                self._export_cache.clear()  # NodePort exports shift
            self._node_ips = list(node_ips)
            for svc in np_services:
                self._services[svc.id] = svc
                self._export_cache.pop(svc.id, None)
        self._recompile()

    def update_local_frontends(self, frontends: Set[str]) -> None:
        with self._lock:
            self._frontends = set(frontends)

    def update_local_backends(self, backends: Set[str]) -> None:
        with self._lock:
            self._backends = set(backends)

    def resync(self, services, node_ips, frontends, backends) -> None:
        with self._lock:
            self._services = {s.id: s for s in services}
            self._export_cache.clear()
            self._node_ips = list(node_ips)
            self._frontends = set(frontends)
            self._backends = set(backends)
        self._recompile()

    # ---------------------------------------------------------------- export

    def _export_service(self, svc: ContivService) -> List[NatMapping]:
        return export_service_mappings(svc, self._node_ips, self.local_weight)

    def _export_all(self) -> List[NatMapping]:
        mappings: List[NatMapping] = []
        for sid in sorted(self._services):
            mappings.extend(self._export_service(self._services[sid]))
        return mappings

    def _recompile(self) -> None:
        with self._lock:
            # Per-service mapping dict (sorted-service flatten order is
            # the builder's canonical order, matching build_nat_tables
            # over _export_all()).  Untouched services come from the
            # export cache — same tuple objects, so the builder's diff
            # short-circuits on identity.
            exported = {}
            for sid in self._services:
                cached = self._export_cache.get(sid)
                if cached is None:
                    cached = tuple(self._export_service(self._services[sid]))
                    self._export_cache[sid] = cached
                exported[sid] = cached
            compiled = self._builder.sync(
                exported,
                nat_loopback=self.nat_loopback,
                snat_ip=self.snat_ip,
                snat_enabled=self.snat_enabled,
                pod_subnet=self.pod_subnet,
            )
            self._compiled = compiled
        if self._on_compiled is not None:
            self._on_compiled(compiled)

    def stats(self) -> Dict[str, object]:
        with self._lock:
            compiled = self._compiled
            return {
                "services": len(self._services),
                "mappings": compiled.num_mappings if compiled else 0,
                "compile": self._builder.stats.as_dict(),
            }
