"""Service renderer boundary — ContivService.

Analog of ``plugins/service/renderer/api.go``: a less-abstract,
reference-free representation of one K8s Service with its endpoints
combined in, plus the renderer plug-in interface the processor drives
(AddService/UpdateService/DeleteService/UpdateNodePortServices/Resync
:78-111).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Dict, List, Sequence, Set, Tuple

from ...models import ProtocolType, ServiceID


class TrafficPolicy(enum.Enum):
    """Cluster-wide vs node-local load balancing (api.go TrafficPolicyType)."""

    CLUSTER_WIDE = "cluster-wide"
    NODE_LOCAL = "node-local"


@dataclass(frozen=True)
class ServicePortSpec:
    """One exposed port (api.go ServicePort)."""

    protocol: ProtocolType
    port: int          # exposed on cluster/external IPs (0 if none)
    node_port: int = 0  # exposed on node IPs (0 if none)


@dataclass(frozen=True)
class ServiceBackend:
    """One endpoint (api.go ServiceBackend)."""

    ip: str
    port: int
    local: bool = False         # deployed on this node
    host_network: bool = False  # IP outside the pod subnet


@dataclass
class ContivService:
    """One service, endpoints combined in (api.go ContivService :113)."""

    id: ServiceID
    traffic_policy: TrafficPolicy = TrafficPolicy.CLUSTER_WIDE
    session_affinity_timeout: int = 0
    cluster_ips: Tuple[str, ...] = ()
    external_ips: Tuple[str, ...] = ()
    # port name -> spec / backends.
    ports: Dict[str, ServicePortSpec] = field(default_factory=dict)
    backends: Dict[str, List[ServiceBackend]] = field(default_factory=dict)

    @property
    def has_node_port(self) -> bool:
        return any(p.node_port != 0 for p in self.ports.values())


class ServiceRendererAPI:
    """Renderer plug-in interface (api.go ServiceRendererAPI)."""

    def add_service(self, service: ContivService) -> None:
        raise NotImplementedError

    def update_service(self, old: ContivService, new: ContivService) -> None:
        raise NotImplementedError

    def delete_service(self, service: ContivService) -> None:
        raise NotImplementedError

    def update_node_port_services(
        self, node_ips: Sequence[str], np_services: Sequence[ContivService]
    ) -> None:
        """Called whenever the set of node IPs changes."""
        raise NotImplementedError

    def update_local_frontends(self, frontends: Set[str]) -> None:
        """Pod IPs acting as service clients on this node (the reference's
        interface-name sets become pod-IP sets in the TPU data plane)."""

    def update_local_backends(self, backends: Set[str]) -> None:
        """Pod IPs acting as service endpoints on this node."""

    def resync(
        self,
        services: Sequence[ContivService],
        node_ips: Sequence[str],
        frontends: Set[str],
        backends: Set[str],
    ) -> None:
        raise NotImplementedError
