"""Scheduler-routed TPU service renderer.

The txn-emitting counterpart of ``TpuNatRenderer`` (VERDICT round-1
item 4): instead of compiling NAT tensors inside its own methods, it
exports each service's DNAT mappings (export logic shared with the
direct renderer, nat44_renderer.go:421-513) and puts them — plus the
NAT global config — as plain KVs into the CURRENT EVENT TRANSACTION.
The ``TpuNatApplicator`` owns the compile + atomic device swap, with
scheduler retries and resync-diff semantics.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Set

from ...models import ServiceID
from ...scheduler.tpu_applicators import (
    NAT_GLOBAL_KEY,
    NAT_SERVICE_PREFIX,
    NatGlobalConfig,
    TpuNatApplicator,
)
from .api import ContivService, ServiceRendererAPI
from .tpu import export_service_mappings


def nat_service_key(sid: ServiceID) -> str:
    return f"{NAT_SERVICE_PREFIX}{sid.namespace}/{sid.name}"


class SchedNatRenderer(ServiceRendererAPI):
    """Emits tpu/nat/* KVs into the event txn; the applicator compiles."""

    def __init__(
        self,
        txn_provider: Callable[[], object],
        nat_loopback: str = "0.0.0.0",
        snat_ip: str = "0.0.0.0",
        snat_enabled: bool = False,
        pod_subnet: str = "10.1.0.0/16",
        local_weight: int = 1,
        applicator: Optional[TpuNatApplicator] = None,
    ):
        self._txn_provider = txn_provider
        self.global_config = NatGlobalConfig(
            nat_loopback=nat_loopback,
            snat_ip=snat_ip,
            snat_enabled=snat_enabled,
            pod_subnet=pod_subnet,
        )
        self.local_weight = max(1, local_weight)
        self.applicator = applicator
        # Control-plane state needed to re-export mappings (node IPs for
        # NodePorts); rendered services are tracked so NodePort changes
        # can re-emit and so delete_service knows what to remove.
        self._services: Dict[ServiceID, ContivService] = {}
        self._node_ips: List[str] = []

    # --------------------------------------------------------------- queries

    @property
    def tables(self):
        return self.applicator.tables if self.applicator else None

    def mappings(self):
        return self.applicator.mappings() if self.applicator else []

    # ------------------------------------------------------------------ txn

    def _txn(self):
        txn = self._txn_provider()
        if txn is None:
            raise RuntimeError("SchedNatRenderer used outside an event transaction")
        return txn

    def _emit_service(self, txn, svc: ContivService) -> None:
        mappings = tuple(
            export_service_mappings(svc, self._node_ips, self.local_weight)
        )
        key = nat_service_key(svc.id)
        if mappings:
            txn.put(key, mappings)
        elif not txn.is_resync:
            # No eligible backends: mapping must not be installed.
            txn.delete(key)

    def _emit_global(self, txn) -> None:
        txn.put(NAT_GLOBAL_KEY, self.global_config)

    # ------------------------------------------------------------- renderer

    def add_service(self, service: ContivService) -> None:
        self._services[service.id] = service
        txn = self._txn()
        self._emit_global(txn)
        self._emit_service(txn, service)

    def update_service(self, old: ContivService, new: ContivService) -> None:
        self._services[new.id] = new
        txn = self._txn()
        self._emit_global(txn)
        self._emit_service(txn, new)

    def delete_service(self, service: ContivService) -> None:
        self._services.pop(service.id, None)
        txn = self._txn()
        if not txn.is_resync:
            txn.delete(nat_service_key(service.id))

    def update_node_port_services(
        self, node_ips: Sequence[str], np_services: Sequence[ContivService]
    ) -> None:
        self._node_ips = list(node_ips)
        txn = self._txn()
        self._emit_global(txn)
        for svc in np_services:
            self._services[svc.id] = svc
            self._emit_service(txn, svc)

    def update_local_frontends(self, frontends: Set[str]) -> None:
        pass

    def update_local_backends(self, backends: Set[str]) -> None:
        pass

    def resync(
        self,
        services: Sequence[ContivService],
        node_ips: Sequence[str],
        frontends: Set[str],
        backends: Set[str],
    ) -> None:
        self._services = {s.id: s for s in services}
        self._node_ips = list(node_ips)
        txn = self._txn()
        self._emit_global(txn)
        for svc in self._services.values():
            self._emit_service(txn, svc)
