"""Service plugin — event-handler skeleton wiring the service layers.

Analog of ``plugins/service/plugin_impl_service.go`` (:41-129): routes
KubeStateChange events for services/endpoints/pods and NodeUpdate
events into the processor.
"""

from __future__ import annotations

import logging

from ..controller.api import EventHandler, KubeStateChange
from ..nodesync import NodeUpdate
from .processor import ServiceProcessor

log = logging.getLogger(__name__)


class ServicePlugin(EventHandler):
    name = "service"

    def __init__(self, node_name: str, ipam=None, nodesync=None):
        self.processor = ServiceProcessor(node_name, ipam=ipam, nodesync=nodesync)

    def register_renderer(self, renderer) -> None:
        self.processor.register_renderer(renderer)

    # -------------------------------------------------------- event handling

    def handles_event(self, event) -> bool:
        if isinstance(event, KubeStateChange):
            return event.resource in ("service", "endpoints", "pod")
        if isinstance(event, NodeUpdate):
            return True
        return event.method.is_resync

    def resync(self, event, kube_state, resync_count, txn) -> None:
        self.processor.resync(kube_state)

    def update(self, event, txn) -> str:
        if isinstance(event, NodeUpdate):
            self.processor.on_node_change()
            return "re-rendered NodePort mappings"
        if not isinstance(event, KubeStateChange):
            return ""
        if event.resource == "service":
            self.processor.on_service_change(event.prev_value, event.new_value)
            return "re-rendered service"
        if event.resource == "endpoints":
            self.processor.on_endpoints_change(event.prev_value, event.new_value)
            return "re-rendered endpoints"
        if event.resource == "pod":
            self.processor.on_pod_change(event.prev_value, event.new_value)
            return "refreshed frontends/backends"
        return ""
