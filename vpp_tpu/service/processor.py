"""Service processor — pairs Services with Endpoints and drives renderers.

Analog of ``plugins/service/processor/processor_impl.go``:

- pairs Service metadata with Endpoints by (namespace, name)
  (processNewEndpoints/-Service :205-266);
- builds ContivService per the reference's Refresh() semantics
  (processor/service.go :80-203): cluster/external/LB-ingress IPs,
  per-port backend lists, locality (endpoint node name vs this node),
  host-network detection (IP outside the pod subnet);
- tracks local frontends (all local pods) and local backends (local
  pods serving >=1 service);
- re-renders NodePort services whenever cluster node IPs change
  (renderNodePorts :366, getNodeIPs :391).
"""

from __future__ import annotations

import ipaddress
import logging
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..models import (
    Endpoints,
    Pod,
    PodID,
    ProtocolType,
    Service,
    ServiceID,
)
from .renderer.api import (
    ContivService,
    ServiceBackend,
    ServicePortSpec,
    ServiceRendererAPI,
    TrafficPolicy,
)

log = logging.getLogger(__name__)


class ServiceProcessor:
    def __init__(self, node_name: str, ipam=None, nodesync=None):
        self.node_name = node_name
        self.ipam = ipam          # pod-subnet membership for host_network
        self.nodesync = nodesync  # cluster node IPs for NodePorts
        self.renderers: List[ServiceRendererAPI] = []

        self._services: Dict[ServiceID, Service] = {}
        self._endpoints: Dict[ServiceID, Endpoints] = {}
        self._rendered: Dict[ServiceID, ContivService] = {}
        self._local_pods: Dict[PodID, str] = {}  # pod -> IP
        self._backend_pods: Set[str] = set()

    def register_renderer(self, renderer: ServiceRendererAPI) -> None:
        self.renderers.append(renderer)

    # ------------------------------------------------------------- building

    def _build_contiv_service(self, svc: Service, eps: Optional[Endpoints]) -> Optional[ContivService]:
        """Refresh() equivalent: combine metadata + endpoints."""
        if eps is None:
            return None
        out = ContivService(
            id=svc.id,
            traffic_policy=(
                TrafficPolicy.NODE_LOCAL
                if svc.external_traffic_policy == "Local"
                else TrafficPolicy.CLUSTER_WIDE
            ),
            session_affinity_timeout=(
                (svc.session_affinity_timeout or 10800)
                if svc.session_affinity == "ClientIP"
                else 0
            ),
        )
        cluster_ips = []
        if svc.cluster_ip and not svc.is_headless:
            cluster_ips.append(svc.cluster_ip)
        out.cluster_ips = tuple(cluster_ips)
        external = list(svc.external_ips)
        if svc.service_type == "LoadBalancer":
            external.extend(ip for ip in svc.lb_ingress_ips if ip)
        out.external_ips = tuple(dict.fromkeys(external))

        for port in svc.ports:
            out.ports[port.name] = ServicePortSpec(
                protocol=port.protocol, port=port.port, node_port=port.node_port
            )
            out.backends[port.name] = []

        pod_subnet = self.ipam.pod_subnet_all_nodes if self.ipam else None
        for subset in eps.subsets:
            for addr in subset.addresses:
                try:
                    ep_ip = ipaddress.ip_address(addr.ip)
                except ValueError:
                    log.warning("service %s: bad endpoint IP %r", svc.id, addr.ip)
                    continue
                local = addr.node_name == "" or addr.node_name == self.node_name
                host_network = pod_subnet is not None and ep_ip not in pod_subnet
                for ep_port in subset.ports:
                    if ep_port.name in out.ports:
                        out.backends[ep_port.name].append(
                            ServiceBackend(
                                ip=addr.ip,
                                port=ep_port.port,
                                local=local,
                                host_network=host_network,
                            )
                        )
        return out

    def _local_backend_ips(self) -> Set[str]:
        """IPs of local pods that serve at least one service."""
        out: Set[str] = set()
        local_ips = set(self._local_pods.values())
        for contiv in self._rendered.values():
            for backends in contiv.backends.values():
                for b in backends:
                    if b.local and b.ip in local_ips:
                        out.add(b.ip)
        return out

    def node_ips(self) -> List[str]:
        """All node IPs in the cluster, without duplicates (getNodeIPs)."""
        out: List[str] = []
        if self.nodesync is None:
            return out
        for node in self.nodesync.get_all_nodes().values():
            for ip in node.ip_addresses:
                plain = ip.split("/")[0]
                if plain not in out:
                    out.append(plain)
            for ip in node.mgmt_ip_addresses:
                if ip not in out:
                    out.append(ip)
        return out

    # ------------------------------------------------------------ rendering

    def _render(self, sid: ServiceID) -> None:
        svc = self._services.get(sid)
        eps = self._endpoints.get(sid)
        new = self._build_contiv_service(svc, eps) if svc is not None else None
        old = self._rendered.get(sid)
        if new is not None:
            self._rendered[sid] = new
            for r in self.renderers:
                if old is None:
                    r.add_service(new)
                else:
                    r.update_service(old, new)
        elif old is not None:
            self._rendered.pop(sid, None)
            for r in self.renderers:
                r.delete_service(old)
        self._refresh_backends()
        # NodePort mappings are re-exported by the renderer itself from its
        # stored node-IP set on every add/update/delete — a second
        # update_node_port_services() here would just recompile twice.
        # _render_node_ports() is reserved for node-membership changes.

    def _refresh_backends(self) -> None:
        backends = self._local_backend_ips()
        if backends != self._backend_pods:
            self._backend_pods = backends
            for r in self.renderers:
                r.update_local_backends(set(backends))

    def _render_node_ports(self) -> None:
        np_services = [s for s in self._rendered.values() if s.has_node_port]
        ips = self.node_ips()
        for r in self.renderers:
            r.update_node_port_services(ips, np_services)

    # --------------------------------------------------------------- events

    def resync(self, kube_state) -> None:
        self._services = {s.id: s for s in kube_state.get("service", {}).values()}
        self._endpoints = {
            ServiceID(e.name, e.namespace): e
            for e in kube_state.get("endpoints", {}).values()
        }
        self._local_pods = {}
        for pod in kube_state.get("pod", {}).values():
            if pod.ip_address and self._is_local_ip(pod.ip_address):
                self._local_pods[pod.id] = pod.ip_address
        self._rendered = {}
        for sid, svc in self._services.items():
            contiv = self._build_contiv_service(svc, self._endpoints.get(sid))
            if contiv is not None:
                self._rendered[sid] = contiv
        self._backend_pods = self._local_backend_ips()
        for r in self.renderers:
            r.resync(
                list(self._rendered.values()),
                self.node_ips(),
                set(self._local_pods.values()),
                set(self._backend_pods),
            )

    def _is_local_ip(self, ip: str) -> bool:
        """A pod is local iff its IP falls in this node's IPAM-dissected
        pod subnet — pure arithmetic, no extra state (the reference keys
        locality off podmanager's Docker-learned LocalPods instead)."""
        if self.ipam is None:
            return True
        try:
            return ipaddress.ip_address(ip) in self.ipam.pod_subnet_this_node
        except ValueError:
            return False

    def on_service_change(self, old: Optional[Service], new: Optional[Service]) -> None:
        svc = new if new is not None else old
        if svc is None:
            return
        if new is not None:
            self._services[new.id] = new
        else:
            self._services.pop(old.id, None)
        self._render(svc.id)

    def on_endpoints_change(self, old: Optional[Endpoints], new: Optional[Endpoints]) -> None:
        eps = new if new is not None else old
        if eps is None:
            return
        sid = ServiceID(eps.name, eps.namespace)
        if new is not None:
            self._endpoints[sid] = new
        else:
            self._endpoints.pop(sid, None)
        self._render(sid)

    def on_pod_change(self, old: Optional[Pod], new: Optional[Pod]) -> None:
        pod = new if new is not None else old
        if pod is None:
            return
        if new is not None and new.ip_address and self._is_local_ip(new.ip_address):
            self._local_pods[new.id] = new.ip_address
        else:
            self._local_pods.pop(pod.id, None)
        self._refresh_backends()
        for r in self.renderers:
            r.update_local_frontends(set(self._local_pods.values()))

    def on_node_change(self) -> None:
        """Node joined/left/changed IPs: refresh all NodePort mappings."""
        self._render_node_ports()
