"""Agent REST API."""

from .server import AgentRestServer

__all__ = ["AgentRestServer"]
