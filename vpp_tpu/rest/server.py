"""Per-agent REST API.

Analog of the reference's per-node REST surfaces (SURVEY.md §5.5):

- ``GET /controller/event-history`` + ``POST /controller/resync``
  (plugins/controller/rest.go :58-186);
- ``GET /contiv/v1/ipam`` (plugins/ipv4net/rest.go :23-69);
- ``GET /scheduler/dump`` (vendored kvscheduler REST dumps, consumed by
  CRD telemetry and netctl);
- ``GET /contiv/v1/nodes`` / ``/contiv/v1/pods`` (netctl's per-node
  data sources);
- ``GET /metrics`` — Prometheus text exposition (cn-infra prometheus
  plugin analog);
- ``GET /liveness`` — the statuscheck probe;
- ``GET /contiv/v1/store?prefix=`` + ``GET /contiv/v1/store/classes``
  — arbitrary keyspace dump of this agent's cluster-store view with
  key-class selection (the ``netctl vppdump`` data source, reference
  plugins/netctl/cmdimpl/vppdump.go);
- ``GET|POST /logging`` — runtime per-component log levels (the
  cn-infra logmanager analog, cmd/contiv-agent/main.go:71,231);
- ``GET /contiv/v1/health`` + ``POST /contiv/v1/health/recover`` —
  datapath fault-domain health (shard supervision states, quarantine /
  rollback counters) and operator-expedited shard recovery;
- ``GET /contiv/v1/spans`` — recent config-propagation spans (event →
  compile → swap → shard adoption stage timings) + the propagation
  latency histogram (ISSUE 8);
- ``GET /contiv/v1/flight`` — the per-shard flight recorder: the last
  N dispatch records (K, backlog, in-flight depth, table generation,
  verdict counts, round-trip µs) for live post-mortems;
- ``GET /contiv/v1/faults`` + ``POST /contiv/v1/faults/arm|disarm`` —
  the fault-injection harness (vpp_tpu/testing/faults.py), the REST
  arming surface chaos drills use.

Implemented on the stdlib threading HTTP server; components are
injected and every endpoint degrades to 404 when its component is
absent (agents can run partial stacks, e.g. in tests).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

log = logging.getLogger(__name__)


def _jsonable(obj: Any):
    import enum

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.name
    if is_dataclass(obj) and not isinstance(obj, type):
        return {k: _jsonable(v) for k, v in asdict(obj).items()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "__dict__"):
        return {k: _jsonable(v) for k, v in vars(obj).items() if not k.startswith("_")}
    return str(obj)


class AgentRestServer:
    """REST facade over the agent's components."""

    def __init__(
        self,
        node_name: str = "",
        controller=None,
        dbwatcher=None,
        ipam=None,
        nodesync=None,
        podmanager=None,
        scheduler=None,
        stats_registry=None,
        tracer=None,
        datapath=None,
        store=None,
        spans=None,
        drain=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.node_name = node_name
        self.controller = controller
        self.dbwatcher = dbwatcher
        self.ipam = ipam
        self.nodesync = nodesync
        self.podmanager = podmanager
        self.scheduler = scheduler
        self.stats_registry = stats_registry
        self.tracer = tracer
        # The live datapath (DataplaneRunner / ShardedDataplane), or a
        # zero-arg callable resolving to it (the agent's runner attaches
        # after REST construction when an uplink comes up).
        self.datapath = datapath
        # This agent's cluster-store handle (KVStore or RemoteKVStore):
        # the data source for the arbitrary-keyspace dump.
        self.store = store
        # Propagation spans: an explicit SpanTracker, or (default) the
        # controller's own — every Controller carries one.
        self.spans = spans
        # Graceful drain/rejoin coordinator (ISSUE 13) — `netctl
        # drain|undrain` land here.
        self.drain = drain
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ endpoints

    def get_liveness(self) -> dict:
        return {"alive": True, "node": self.node_name}

    def get_event_history(self) -> list:
        if self.controller is None:
            raise LookupError("no controller")
        return [_jsonable(rec) for rec in self.controller.event_history]

    def post_resync(self) -> dict:
        """On-demand full resync (controller/rest.go resync trigger)."""
        if self.dbwatcher is None:
            raise LookupError("no dbwatcher")
        self.dbwatcher.resync()
        return {"resync": "scheduled"}

    def get_ipam(self) -> dict:
        if self.ipam is None:
            raise LookupError("no ipam")
        ipam = self.ipam
        return {
            "nodeId": ipam.node_id,
            "nodeIP": str(ipam.node_ip()),
            "podSubnetAllNodes": str(ipam.pod_subnet_all_nodes),
            "podSubnetThisNode": str(ipam.pod_subnet_this_node),
            "podGatewayIP": str(ipam.pod_gateway_ip),
            "hostSubnetThisNode": str(ipam.host_subnet_this_node),
            "natLoopbackIP": str(ipam.nat_loopback_ip()),
            "serviceCIDR": str(ipam.service_network()),
            "allocatedPodIPs": {
                str(pod): str(ip) for pod, ip in sorted(ipam.assigned_pods().items())
            },
        }

    def get_nodes(self) -> list:
        if self.nodesync is None:
            raise LookupError("no nodesync")
        out = []
        for node in self.nodesync.get_all_nodes().values():
            out.append(_jsonable(node))
        return out

    def get_pods(self) -> list:
        if self.podmanager is None:
            raise LookupError("no podmanager")
        return [_jsonable(p) for p in self.podmanager.local_pods.values()]

    def get_scheduler_dump(self, prefix: str = "") -> list:
        if self.scheduler is None:
            raise LookupError("no scheduler")
        return [_jsonable(v) for v in self.scheduler.dump(prefix)]

    def get_trace(self) -> dict:
        """Sampled packet traces (scripts/vpptrace.sh `show trace` analog)."""
        if self.tracer is None:
            raise LookupError("no tracer")
        return {"status": self.tracer.status(), "entries": self.tracer.dump()}

    def post_trace(self, action: str, sample: int = 1) -> dict:
        if self.tracer is None:
            raise LookupError("no tracer")
        if action == "enable":
            self.tracer.enable(sample_every=sample)
        elif action == "disable":
            self.tracer.disable()
        elif action == "clear":
            self.tracer.clear()
        else:
            raise FileNotFoundError(f"trace action {action!r}")
        return {"trace": action, **self.tracer.status()}

    def get_inspect(self) -> dict:
        """Live datapath introspection (`netctl inspect`, the vppcli
        analog): classify/NAT table stats, session + affinity
        occupancy, ring depths, punt counters, dispatch config — plus
        the controller resilience snapshot when a control plane is
        wired (ISSUE 9 satellite)."""
        dp = self._resolve_datapath()
        out = {"node": self.node_name, **dp.inspect()}
        if self.controller is not None:
            out["controller"] = self.controller.status()
        return out

    def _resolve_datapath(self):
        dp = self.datapath() if callable(self.datapath) else self.datapath
        if dp is None:
            raise LookupError("no datapath")
        return dp

    def get_health(self) -> dict:
        """Agent health (`netctl health`): controller resilience
        counters (healing resyncs scheduled/completed/failed, event
        errors, last-resync age — ISSUE 9 "no silent healing loop"
        oracle) plus, when a datapath is attached, the fault-domain
        view — per-shard supervision state, ejection/rejoin/steer
        counters, poisoned-batch quarantine totals, swap rollbacks.
        Control-plane-only agents (no datapath) serve the controller
        section alone instead of 404ing."""
        out = {"node": self.node_name}
        if self.controller is not None:
            out["controller"] = self.controller.status()
        if self.drain is not None:
            out["drain"] = self.drain.status()
        dp = self.datapath() if callable(self.datapath) else self.datapath
        if dp is not None:
            out.update(dp.health())
        elif self.controller is None and self.drain is None:
            raise LookupError("no datapath")
        return out

    def post_drain(self, action: str) -> dict:
        """Graceful drain / rejoin (ISSUE 13; `netctl drain|undrain`):
        ``drain`` gates new CNI ADDs (retriable code-11 rejection),
        quiesces in-flight dispatch, flushes the flight/latency
        forensics and flips the heartbeat to a *drained* tombstone;
        ``undrain`` rejoins cleanly."""
        if self.drain is None:
            raise LookupError("no drain coordinator")
        if action == "drain":
            return self.drain.drain()
        if action == "undrain":
            return self.drain.undrain()
        raise FileNotFoundError(f"drain action {action!r}")

    def post_health_recover(self, query: dict) -> dict:
        """Expedite ejected shards into probation (skip the backoff);
        optional ``shard=`` restricts to one."""
        dp = self._resolve_datapath()
        recover = getattr(dp, "recover", None)
        if recover is None:
            raise LookupError("datapath has no shard supervisor")
        n = recover(int(query["shard"]) if "shard" in query else None)
        return {"recovering": n, **dp.health()}

    def get_faults(self) -> dict:
        """The fault-injection harness's armed plans (testing/chaos
        surface — see vpp_tpu/testing/faults.py)."""
        return self._resolve_datapath().faults.status()

    def get_spans(self, query: dict) -> dict:
        """Recent config-propagation spans + the end-to-end propagation
        histogram (`netctl spans`); ``limit=`` bounds the dump."""
        tracker = self.spans or getattr(self.controller, "spans", None)
        if tracker is None:
            raise LookupError("no span tracker")
        limit = int(query.get("limit", "0"))
        return {
            "node": self.node_name,
            "status": tracker.status(),
            "spans": tracker.dump(limit),
        }

    def get_flight(self, query: dict) -> dict:
        """Flight-recorder dump (`netctl flight`): per shard, the last
        N dispatch records; ``limit=`` bounds records per shard."""
        dp = self._resolve_datapath()
        limit = int(query.get("limit", "0"))
        return {"node": self.node_name, **dp.dump_flight(limit)}

    def post_fault(self, action: str, query: dict) -> dict:
        """Arm/disarm a named fault-injection site on the live
        datapath: ``POST /contiv/v1/faults/arm?site=dispatch-raise&``
        ``shard=1&count=4`` (optional ``mode=raise|hang``,
        ``seconds=``, and ``match_src_port=``-style 5-tuple fields for
        poison predicates); ``POST /contiv/v1/faults/disarm`` clears
        plans (optionally one ``site=`` / ``id=``)."""
        faults = self._resolve_datapath().faults
        if action == "disarm":
            removed = faults.disarm(
                site=query.get("site"),
                plan_id=int(query["id"]) if "id" in query else None,
            )
            return {"disarmed": removed, **faults.status()}
        if action != "arm":
            raise FileNotFoundError(f"fault action {action!r}")
        if "site" not in query:
            raise ValueError("need site= query parameter")
        from ..ops.packets import ip_to_u32

        match = {}
        for field_name in ("src_ip", "dst_ip", "protocol",
                           "src_port", "dst_port"):
            raw = query.get(f"match_{field_name}")
            if raw is None:
                continue
            match[field_name] = (
                ip_to_u32(raw) if field_name.endswith("_ip") and "." in raw
                else int(raw)
            )
        plan_id = faults.arm(
            query["site"],
            shard=int(query["shard"]) if "shard" in query else None,
            count=int(query["count"]) if "count" in query else None,
            mode=query.get("mode"),
            seconds=float(query.get("seconds", "30")),
            match=match or None,
        )
        return {"armed_plan": plan_id, **faults.status()}

    def get_metrics(self) -> str:
        from prometheus_client import generate_latest

        if self.stats_registry is None:
            raise LookupError("no stats registry")
        return generate_latest(self.stats_registry).decode()

    def get_store_dump(self, prefix: str = "") -> list:
        """Arbitrary keyspace dump of this agent's cluster-store view
        (the `netctl vppdump` analog): every (key, value) under the
        selected key class, through whatever handle the agent has —
        in-process store or leader-following remote client."""
        if self.store is None:
            raise LookupError("no store")
        return [{"key": k, "value": _jsonable(v)}
                for k, v in self.store.list(prefix)]

    def get_store_classes(self) -> list:
        """The key classes a dump can select on: every registered DB
        resource prefix plus the external-config space."""
        from ..controller.dbwatcher import EXTERNAL_CONFIG_PREFIX
        from ..models import registry

        classes = [
            {"keyword": r.keyword, "prefix": r.key_prefix}
            for r in registry.DB_RESOURCES
        ]
        classes.append({"keyword": "external-config",
                        "prefix": EXTERNAL_CONFIG_PREFIX})
        return classes

    def get_logging(self) -> dict:
        """Effective level of every vpp_tpu component logger (the
        cn-infra logmanager list surface).  Values are structured —
        ``{"level": "INFO", "inherited": true}`` — so programmatic
        consumers compare clean level names; display decoration is
        netctl's job."""
        root = logging.getLogger("vpp_tpu")
        out = {"vpp_tpu": {
            "level": logging.getLevelName(root.getEffectiveLevel()),
            "inherited": not root.level,
        }}
        for name in sorted(logging.root.manager.loggerDict):
            if not name.startswith("vpp_tpu."):
                continue
            logger = logging.getLogger(name)
            out[name] = {
                "level": logging.getLevelName(logger.getEffectiveLevel()),
                "inherited": not logger.level,
            }
        return out

    def post_logging(self, logger_name: str, level: str) -> dict:
        """Set one component logger's level at runtime."""
        if not (logger_name == "vpp_tpu" or logger_name.startswith("vpp_tpu.")):
            raise ValueError(f"not a vpp_tpu component logger: {logger_name!r}")
        numeric = logging.getLevelName(level.upper())
        if not isinstance(numeric, int):
            raise ValueError(f"unknown log level: {level!r}")
        logging.getLogger(logger_name).setLevel(numeric)
        return {"logger": logger_name, "level": level.upper()}

    def post_cni(self, action: str, body: bytes) -> dict:
        """CNI Add/Del over plain HTTP — the stdlib fallback transport
        for host shims whose system python has no grpcio (the gRPC
        service remains the primary, cni.proto-parity path)."""
        if self.podmanager is None:
            raise LookupError("no podmanager")
        from dataclasses import asdict

        from ..cni.messages import CNIRequest
        from ..cni.rpc import CNIServer

        request = CNIRequest(**json.loads(body.decode()))
        handlers = CNIServer(self.podmanager)  # reuse handlers, no server
        reply = handlers.add(request) if action == "add" else handlers.delete(request)
        return asdict(reply)

    # ------------------------------------------------------------ http glue

    def _route(self, method: str, path: str, query: dict, body: bytes = b""):
        routes = {
            ("GET", "/liveness"): self.get_liveness,
            ("GET", "/controller/event-history"): self.get_event_history,
            ("POST", "/controller/resync"): self.post_resync,
            ("GET", "/contiv/v1/ipam"): self.get_ipam,
            ("GET", "/contiv/v1/nodes"): self.get_nodes,
            ("GET", "/contiv/v1/pods"): self.get_pods,
            ("GET", "/contiv/v1/inspect"): self.get_inspect,
            ("GET", "/contiv/v1/health"): self.get_health,
            ("GET", "/contiv/v1/faults"): self.get_faults,
        }
        if (method, path) in routes:
            return routes[(method, path)]()
        if method == "POST" and path in ("/cni/add", "/cni/del"):
            return self.post_cni(path.rsplit("/", 1)[1], body)
        if method == "GET" and path == "/scheduler/dump":
            return self.get_scheduler_dump(query.get("prefix", ""))
        if method == "GET" and path == "/contiv/v1/store":
            return self.get_store_dump(query.get("prefix", ""))
        if method == "GET" and path == "/contiv/v1/store/classes":
            return self.get_store_classes()
        if method == "GET" and path == "/logging":
            return self.get_logging()
        if method == "POST" and path == "/logging":
            if "logger" not in query or "level" not in query:
                raise ValueError("need logger= and level= query parameters")
            return self.post_logging(query["logger"], query["level"])
        if method == "GET" and path == "/metrics":
            return self.get_metrics()
        if method == "GET" and path == "/contiv/v1/trace":
            return self.get_trace()
        if method == "GET" and path == "/contiv/v1/spans":
            return self.get_spans(query)
        if method == "GET" and path == "/contiv/v1/flight":
            return self.get_flight(query)
        if method == "POST" and path.startswith("/contiv/v1/trace/"):
            return self.post_trace(
                path.rsplit("/", 1)[1], int(query.get("sample", "1"))
            )
        if method == "POST" and path.startswith("/contiv/v1/faults/"):
            return self.post_fault(path.rsplit("/", 1)[1], query)
        if method == "POST" and path == "/contiv/v1/health/recover":
            return self.post_health_recover(query)
        if method == "POST" and path in ("/contiv/v1/drain",
                                         "/contiv/v1/undrain"):
            return self.post_drain(path.rsplit("/", 1)[1])
        raise FileNotFoundError(path)

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _handle(self, method: str):
                from urllib.parse import parse_qsl, urlparse

                parsed = urlparse(self.path)
                query = dict(parse_qsl(parsed.query))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    result = server._route(method, parsed.path, query, body)
                except FileNotFoundError:
                    self.send_error(404)
                    return
                except LookupError as err:
                    self.send_error(404, str(err))
                    return
                except ValueError as err:
                    # Malformed client input (e.g. a non-numeric query
                    # parameter) is the caller's fault, not a server fault.
                    self.send_error(400, str(err))
                    return
                except Exception as err:  # noqa: BLE001
                    self.send_error(500, str(err))
                    return
                if isinstance(result, str):
                    body = result.encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = json.dumps(result, indent=1).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def log_message(self, fmt, *args):
                log.debug("REST: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="agent-rest", daemon=True
        )
        self._thread.start()
        log.info("agent REST on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
