"""Per-agent REST API.

Analog of the reference's per-node REST surfaces (SURVEY.md §5.5):

- ``GET /controller/event-history`` + ``POST /controller/resync``
  (plugins/controller/rest.go :58-186);
- ``GET /contiv/v1/ipam`` (plugins/ipv4net/rest.go :23-69);
- ``GET /scheduler/dump`` (vendored kvscheduler REST dumps, consumed by
  CRD telemetry and netctl);
- ``GET /contiv/v1/nodes`` / ``/contiv/v1/pods`` (netctl's per-node
  data sources);
- ``GET /metrics`` — Prometheus text exposition (cn-infra prometheus
  plugin analog);
- ``GET /liveness`` — the statuscheck probe.

Implemented on the stdlib threading HTTP server; components are
injected and every endpoint degrades to 404 when its component is
absent (agents can run partial stacks, e.g. in tests).
"""

from __future__ import annotations

import json
import logging
import threading
from dataclasses import asdict, is_dataclass
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Optional

log = logging.getLogger(__name__)


def _jsonable(obj: Any):
    import enum

    if obj is None or isinstance(obj, (bool, int, float, str)):
        return obj
    if isinstance(obj, enum.Enum):
        return obj.name
    if is_dataclass(obj) and not isinstance(obj, type):
        return {k: _jsonable(v) for k, v in asdict(obj).items()}
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple, set)):
        return [_jsonable(v) for v in obj]
    if hasattr(obj, "__dict__"):
        return {k: _jsonable(v) for k, v in vars(obj).items() if not k.startswith("_")}
    return str(obj)


class AgentRestServer:
    """REST facade over the agent's components."""

    def __init__(
        self,
        node_name: str = "",
        controller=None,
        dbwatcher=None,
        ipam=None,
        nodesync=None,
        podmanager=None,
        scheduler=None,
        stats_registry=None,
        tracer=None,
        datapath=None,
        host: str = "127.0.0.1",
        port: int = 0,
    ):
        self.node_name = node_name
        self.controller = controller
        self.dbwatcher = dbwatcher
        self.ipam = ipam
        self.nodesync = nodesync
        self.podmanager = podmanager
        self.scheduler = scheduler
        self.stats_registry = stats_registry
        self.tracer = tracer
        # The live datapath (DataplaneRunner / ShardedDataplane), or a
        # zero-arg callable resolving to it (the agent's runner attaches
        # after REST construction when an uplink comes up).
        self.datapath = datapath
        self.host = host
        self.port = port
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------ endpoints

    def get_liveness(self) -> dict:
        return {"alive": True, "node": self.node_name}

    def get_event_history(self) -> list:
        if self.controller is None:
            raise LookupError("no controller")
        return [_jsonable(rec) for rec in self.controller.event_history]

    def post_resync(self) -> dict:
        """On-demand full resync (controller/rest.go resync trigger)."""
        if self.dbwatcher is None:
            raise LookupError("no dbwatcher")
        self.dbwatcher.resync()
        return {"resync": "scheduled"}

    def get_ipam(self) -> dict:
        if self.ipam is None:
            raise LookupError("no ipam")
        ipam = self.ipam
        return {
            "nodeId": ipam.node_id,
            "nodeIP": str(ipam.node_ip()),
            "podSubnetAllNodes": str(ipam.pod_subnet_all_nodes),
            "podSubnetThisNode": str(ipam.pod_subnet_this_node),
            "podGatewayIP": str(ipam.pod_gateway_ip),
            "hostSubnetThisNode": str(ipam.host_subnet_this_node),
            "natLoopbackIP": str(ipam.nat_loopback_ip()),
            "serviceCIDR": str(ipam.service_network()),
            "allocatedPodIPs": {
                str(pod): str(ip) for pod, ip in sorted(ipam.assigned_pods().items())
            },
        }

    def get_nodes(self) -> list:
        if self.nodesync is None:
            raise LookupError("no nodesync")
        out = []
        for node in self.nodesync.get_all_nodes().values():
            out.append(_jsonable(node))
        return out

    def get_pods(self) -> list:
        if self.podmanager is None:
            raise LookupError("no podmanager")
        return [_jsonable(p) for p in self.podmanager.local_pods.values()]

    def get_scheduler_dump(self, prefix: str = "") -> list:
        if self.scheduler is None:
            raise LookupError("no scheduler")
        return [_jsonable(v) for v in self.scheduler.dump(prefix)]

    def get_trace(self) -> dict:
        """Sampled packet traces (scripts/vpptrace.sh `show trace` analog)."""
        if self.tracer is None:
            raise LookupError("no tracer")
        return {"status": self.tracer.status(), "entries": self.tracer.dump()}

    def post_trace(self, action: str, sample: int = 1) -> dict:
        if self.tracer is None:
            raise LookupError("no tracer")
        if action == "enable":
            self.tracer.enable(sample_every=sample)
        elif action == "disable":
            self.tracer.disable()
        elif action == "clear":
            self.tracer.clear()
        else:
            raise FileNotFoundError(f"trace action {action!r}")
        return {"trace": action, **self.tracer.status()}

    def get_inspect(self) -> dict:
        """Live datapath introspection (`netctl inspect`, the vppcli
        analog): classify/NAT table stats, session + affinity
        occupancy, ring depths, punt counters, dispatch config."""
        dp = self.datapath() if callable(self.datapath) else self.datapath
        if dp is None:
            raise LookupError("no datapath")
        return {"node": self.node_name, **dp.inspect()}

    def get_metrics(self) -> str:
        from prometheus_client import generate_latest

        if self.stats_registry is None:
            raise LookupError("no stats registry")
        return generate_latest(self.stats_registry).decode()

    def post_cni(self, action: str, body: bytes) -> dict:
        """CNI Add/Del over plain HTTP — the stdlib fallback transport
        for host shims whose system python has no grpcio (the gRPC
        service remains the primary, cni.proto-parity path)."""
        if self.podmanager is None:
            raise LookupError("no podmanager")
        from dataclasses import asdict

        from ..cni.messages import CNIRequest
        from ..cni.rpc import CNIServer

        request = CNIRequest(**json.loads(body.decode()))
        handlers = CNIServer(self.podmanager)  # reuse handlers, no server
        reply = handlers.add(request) if action == "add" else handlers.delete(request)
        return asdict(reply)

    # ------------------------------------------------------------ http glue

    def _route(self, method: str, path: str, query: dict, body: bytes = b""):
        routes = {
            ("GET", "/liveness"): self.get_liveness,
            ("GET", "/controller/event-history"): self.get_event_history,
            ("POST", "/controller/resync"): self.post_resync,
            ("GET", "/contiv/v1/ipam"): self.get_ipam,
            ("GET", "/contiv/v1/nodes"): self.get_nodes,
            ("GET", "/contiv/v1/pods"): self.get_pods,
            ("GET", "/contiv/v1/inspect"): self.get_inspect,
        }
        if (method, path) in routes:
            return routes[(method, path)]()
        if method == "POST" and path in ("/cni/add", "/cni/del"):
            return self.post_cni(path.rsplit("/", 1)[1], body)
        if method == "GET" and path == "/scheduler/dump":
            return self.get_scheduler_dump(query.get("prefix", ""))
        if method == "GET" and path == "/metrics":
            return self.get_metrics()
        if method == "GET" and path == "/contiv/v1/trace":
            return self.get_trace()
        if method == "POST" and path.startswith("/contiv/v1/trace/"):
            return self.post_trace(
                path.rsplit("/", 1)[1], int(query.get("sample", "1"))
            )
        raise FileNotFoundError(path)

    def start(self) -> int:
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _handle(self, method: str):
                from urllib.parse import parse_qsl, urlparse

                parsed = urlparse(self.path)
                query = dict(parse_qsl(parsed.query))
                length = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(length) if length else b""
                try:
                    result = server._route(method, parsed.path, query, body)
                except FileNotFoundError:
                    self.send_error(404)
                    return
                except LookupError as err:
                    self.send_error(404, str(err))
                    return
                except ValueError as err:
                    # Malformed client input (e.g. a non-numeric query
                    # parameter) is the caller's fault, not a server fault.
                    self.send_error(400, str(err))
                    return
                except Exception as err:  # noqa: BLE001
                    self.send_error(500, str(err))
                    return
                if isinstance(result, str):
                    body = result.encode()
                    ctype = "text/plain; version=0.0.4"
                else:
                    body = json.dumps(result, indent=1).encode()
                    ctype = "application/json"
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                self._handle("GET")

            def do_POST(self):
                self._handle("POST")

            def log_message(self, fmt, *args):
                log.debug("REST: " + fmt, *args)

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_port
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="agent-rest", daemon=True
        )
        self._thread.start()
        log.info("agent REST on %s:%d", self.host, self.port)
        return self.port

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
