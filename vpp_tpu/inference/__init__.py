"""In-network inference plane (ISSUE 14).

The control-plane half of the in-datapath DNN scoring subsystem: the
model container (:mod:`model`), the event-handler plugin that turns
InferPolicy CRDs + pod state into rendered enrollments
(:mod:`plugin`), and the host-side reference oracle the parity tests
pin the device scorer against (:mod:`oracle`).  The device half lives
in ``ops/infer.py`` (the fused scoring stage) and ``ops/infer_delta.py``
(the incremental weight/table builder); the renderers that bridge the
two sit beside the policy renderers (``policy/renderer/infer.py``).
"""

from .model import InferModel, anomaly_port_model, default_model
from .oracle import InferOracle
from .plugin import InferencePlugin

__all__ = [
    "InferModel",
    "InferOracle",
    "InferencePlugin",
    "anomaly_port_model",
    "default_model",
]
