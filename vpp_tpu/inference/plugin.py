"""InferencePlugin — the event handler of the in-network inference plane.

The same position PolicyPlugin occupies for network policies: an event
handler on the controller loop that turns declarative intent
(InferPolicy CRDs, pushed as :class:`~vpp_tpu.crd.plugin.InferPolicyChange`
events by the CRD controller) plus live pod state (KubeStateChange /
resync) into RENDERED state — the active model and one
``(pod_ip, threshold, action)`` enrollment per pod of an enrolled
namespace — delivered to every registered renderer inside the current
event transaction.  The scheduler-routed renderer
(policy/renderer/infer.py) emits the state as ``tpu/infer/*`` KVs; the
TpuInferApplicator compiles them incrementally and swaps the device
table atomically, minting ``compile:infer`` / ``swap:infer`` span
stages.  A model update is therefore an ordinary control-plane
transaction with a propagation span — never a redeploy.

Policy composition: policies are merged in sorted-name order.  A pod
in namespaces claimed by several enabled policies gets the FIRST
policy's (threshold, action) — deterministic, and matching the
sorted-key table compile discipline everywhere else in the repo.  The
active model is the first enabled policy (sorted by name) that ships
weights; policies without weights enroll against it.

InferPolicy delivery has two paths, both handled here:

- **store-fanout (production)**: the CRD controller publishes
  validated policies into the cluster store under the registry's
  ``inferpolicy`` prefix; every agent's DBWatcher delivers them as
  ``KubeStateChange("inferpolicy", ...)`` events, and a DBResync's
  kube_state snapshot is AUTHORITATIVE (resync rebuilds the policy
  cache from it, exactly like the pod cache — a policy deleted during
  a store outage is swept on the reconnect resync);
- **co-located (harnesses / single-process)**: ``CRDPlugin.
  apply_infer_policy`` pushes an ``InferPolicyChange`` directly into
  the local event loop.  When both are wired the second delivery
  re-renders identical state and the scheduler diff no-ops it.
"""

from __future__ import annotations

import logging
from typing import Dict, List, Optional, Tuple

from ..controller.api import EventHandler, KubeStateChange
from ..crd.models import InferPolicy
from ..crd.plugin import InferPolicyChange
from ..models import PodID
from ..ops.infer import INFER_ACTION_CODES
from ..ops.packets import ip_to_u32
from .model import InferModel

log = logging.getLogger(__name__)


class InferencePlugin(EventHandler):
    """InferPolicy + pod state → rendered model/enrollments."""

    name = "inference"

    def __init__(self):
        self._policies: Dict[str, InferPolicy] = {}
        self._pods: Dict[PodID, str] = {}  # pod -> allocated IP
        self._renderers: List[object] = []
        # Parsed-weights cache keyed on the source policy INSTANCE
        # (frozen dataclasses are replaced, never mutated): without it
        # every pod event in the cluster would re-parse the full
        # nested-list weight matrix just to reach an identical model.
        self._model_cache: Tuple[Optional[InferPolicy],
                                 Optional[InferModel]] = (None, None)

    def register_renderer(self, renderer) -> None:
        """A renderer exposes ``render(model, bindings, resync)`` with
        ``bindings = {pod_ip_u32: (threshold_band, action_code)}`` —
        the production SchedInferRenderer and the test oracle both
        implement it."""
        self._renderers.append(renderer)

    # ------------------------------------------------------ event handling

    def handles_event(self, event) -> bool:
        if isinstance(event, InferPolicyChange):
            return True
        if isinstance(event, KubeStateChange):
            return event.resource in ("pod", "inferpolicy")
        return event.method.is_resync

    def resync(self, event, kube_state, resync_count, txn) -> None:
        self._pods = {}
        for pod in (kube_state.get("pod") or {}).values():
            if getattr(pod, "ip_address", ""):
                self._pods[pod.id] = pod.ip_address
        # The snapshot is authoritative for the policy cache too (the
        # store is where the CRD controller publishes): a policy
        # deleted while this agent was partitioned is swept here.
        self._policies = {
            policy.name: policy
            for policy in (kube_state.get("inferpolicy") or {}).values()
        }
        self._render(resync=True)

    def update(self, event, txn) -> str:
        if isinstance(event, InferPolicyChange):
            if event.new is None:
                self._policies.pop(event.policy_name, None)
            else:
                self._policies[event.policy_name] = event.new
            self._render(resync=False)
            return f"re-rendered inference state after {event}"
        if isinstance(event, KubeStateChange) and \
                event.resource == "inferpolicy":
            policy = event.new_value
            if policy is None:
                prev = event.prev_value
                if prev is not None:
                    self._policies.pop(prev.name, None)
            else:
                self._policies[policy.name] = policy
            self._render(resync=False)
            return "re-rendered inference state after store policy change"
        if isinstance(event, KubeStateChange) and event.resource == "pod":
            pod = event.new_value if event.new_value is not None \
                else event.prev_value
            if pod is None:
                return ""
            if event.new_value is not None and \
                    getattr(pod, "ip_address", ""):
                self._pods[pod.id] = pod.ip_address
            else:
                self._pods.pop(pod.id, None)
            enrolled_namespaces = {
                ns for policy in self._active() for ns in policy.namespaces
            }
            if pod.id.namespace not in enrolled_namespaces:
                # The pod cannot change the rendered state (no policy
                # claims its namespace) — skip the render entirely;
                # cluster-wide pod churn must not cost O(render) each.
                return ""
            self._render(resync=False)
            return "re-rendered inference enrollments after pod change"
        return ""

    # ------------------------------------------------------------ rendering

    def _active(self) -> List[InferPolicy]:
        return [self._policies[name] for name in sorted(self._policies)
                if self._policies[name].enabled]

    def _desired(self) -> Tuple[Optional[InferModel],
                                Dict[int, Tuple[int, int]]]:
        """(active model, {pod_ip_u32: (threshold, action_code)})."""
        active = self._active()
        model: Optional[InferModel] = None
        for policy in active:
            if policy.model is not None:
                src, cached = self._model_cache
                if src is not policy:
                    cached = InferModel.from_dict(dict(policy.model))
                    self._model_cache = (policy, cached)
                model = cached
                break
        bindings: Dict[int, Tuple[int, int]] = {}
        pod_binding: Dict[PodID, Tuple[int, int]] = {}
        for policy in active:
            namespaces = set(policy.namespaces)
            code = INFER_ACTION_CODES[policy.action]
            for pod_id in self._pods:
                if pod_id.namespace in namespaces and \
                        pod_id not in pod_binding:
                    pod_binding[pod_id] = (policy.threshold, code)
        for pod_id, binding in pod_binding.items():
            bindings[ip_to_u32(self._pods[pod_id])] = binding
        return model, bindings

    def _render(self, resync: bool) -> None:
        model, bindings = self._desired()
        for renderer in self._renderers:
            renderer.render(model, bindings, resync)

    # -------------------------------------------------------------- queries

    def status(self) -> Dict[str, object]:
        model, bindings = self._desired()
        return {
            "policies": len(self._policies),
            "active_policies": len(self._active()),
            "enrolled_pods": len(bindings),
            "has_model": model is not None,
        }
