"""Host-side inference oracle — the mock-engine ground truth.

The same role MockACLEngine plays for the classify kernel
(testing/aclengine.py): a renderer-shaped reference implementation
that consumes EXACTLY what the production renderer consumes (the
rendered model + per-pod enrollments) and evaluates flows host-side
with the shared reference scorer (:func:`ops.infer.score_host` — the
same f32 feature/MLP/band bodies the device stage compiles).  The
parity tests pin the pipeline's score-band and action verdicts against
this oracle at every governor-chosen K on both engines, including the
quarantine action path.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from ..ops.infer import INFER_ACT_NONE, INFER_ACTION_CODES, score_host
from ..ops.packets import ip_to_u32
from .model import InferModel


class InferOracle:
    """Reference scorer + enrollment evaluator.

    Register it with an InferencePlugin next to the production
    renderer (it implements the same ``render(model, bindings,
    resync)`` contract), or feed it directly with ``set_state``."""

    def __init__(self):
        self.model: Optional[InferModel] = None
        # pod_ip_u32 -> (threshold band, action code)
        self.bindings: Dict[int, Tuple[int, int]] = {}

    # ------------------------------------------------------------ renderer

    def render(self, model, bindings, resync: bool) -> None:
        """The InferencePlugin renderer hook: keep the latest rendered
        state (the oracle has no transactions — last render wins, which
        is exactly the post-commit state the datapath converges to)."""
        self.set_state(model, {ip: (thr, act)
                               for ip, (thr, act) in bindings.items()})

    def set_state(self, model, bindings: Dict[int, Tuple[int, int]]) -> None:
        if model is not None and not isinstance(model, InferModel):
            model = InferModel.from_dict(
                model.to_dict() if hasattr(model, "to_dict") else model)
        self.model = model
        self.bindings = dict(bindings)

    # ---------------------------------------------------------- evaluation

    @property
    def enabled(self) -> bool:
        return self.model is not None and bool(self.bindings)

    def evaluate(self, src_ip: str, dst_ip: str, protocol: int,
                 src_port: int, dst_port: int,
                 reply: bool = False, dnat: bool = False,
                 snat: bool = False) -> Tuple[bool, int, int]:
        """One flow through the reference scorer: (scored, band,
        action_fired) with the EXACT device semantics — binary-search
        enrollment on the (rewritten) source pod first, destination
        fallback; action fires when band >= the enrolled threshold."""
        if not self.enabled:
            return False, 0, INFER_ACT_NONE
        src = ip_to_u32(src_ip)
        dst = ip_to_u32(dst_ip)
        binding = self.bindings.get(src)
        if binding is None:
            binding = self.bindings.get(dst)
        if binding is None:
            return False, 0, INFER_ACT_NONE
        _, band = score_host(
            self.model.w1, self.model.b1, self.model.w2, self.model.b2,
            np.asarray([src], dtype=np.uint32),
            np.asarray([dst], dtype=np.uint32),
            np.asarray([protocol], dtype=np.int64),
            np.asarray([src_port], dtype=np.int64),
            np.asarray([dst_port], dtype=np.int64),
            np.asarray([reply]), np.asarray([dnat]), np.asarray([snat]),
        )
        band = int(np.asarray(band).reshape(-1)[0])
        threshold, action = binding
        fired = action if band >= threshold else INFER_ACT_NONE
        return True, band, fired

    def expected_quarantined(self, flows) -> int:
        """Convenience for parity tests: how many (src, dst, proto,
        sport, dport) tuples the oracle quarantines."""
        q = INFER_ACTION_CODES["quarantine"]
        return sum(
            1 for f in flows if self.evaluate(*f)[2] == q
        )
