"""Model container for the in-network inference plane.

An :class:`InferModel` is the host-side, JSON-shippable form of the
fused MLP the datapath scorer runs (ops/infer.py): f32 weights for

    h = relu(f @ w1 + b1);  score = sigmoid(h @ w2 + b2)

over the fixed 16-feature packet vector.  It rides an InferPolicy CRD
spec (nested lists), the cluster store, and the scheduler transaction
as a plain dict — the incremental builder (ops/infer_delta) diffs the
rows and ships only what changed.

Two constructors matter operationally:

- :func:`default_model` — deterministic pseudo-random weights, a
  stand-in for "whatever the training pipeline produced" in benches
  and soaks (scores spread across the low bands; nothing fires).
- :func:`anomaly_port_model` — a hand-crafted detector that saturates
  (band 7) on flows targeting unusually high destination ports, with a
  decisive margin on both sides.  It is the demo/drill model: a
  crafted anomalous flow provably crosses any threshold band while
  normal traffic provably stays at band 0.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List

import numpy as np

from ..ops.infer import INFER_FEATURES, INFER_HIDDEN


@dataclass(frozen=True)
class InferModel:
    """f32 MLP weights in wire shape (nested lists via to_dict)."""

    w1: np.ndarray   # [INFER_FEATURES, H]
    b1: np.ndarray   # [H]
    w2: np.ndarray   # [H]
    b2: float

    def __post_init__(self):
        object.__setattr__(self, "w1",
                           np.asarray(self.w1, dtype=np.float32))
        object.__setattr__(self, "b1",
                           np.asarray(self.b1, dtype=np.float32))
        object.__setattr__(self, "w2",
                           np.asarray(self.w2, dtype=np.float32))
        object.__setattr__(self, "b2", float(np.float32(self.b2)))
        if self.w1.shape[0] != INFER_FEATURES:
            raise ValueError(
                f"w1 has {self.w1.shape[0]} feature rows, expected "
                f"{INFER_FEATURES}")
        if not (self.w1.shape[1] == self.b1.shape[0] == self.w2.shape[0]):
            raise ValueError(
                f"inconsistent hidden width: w1 {self.w1.shape}, "
                f"b1 {self.b1.shape}, w2 {self.w2.shape}")

    @property
    def hidden(self) -> int:
        return int(self.w1.shape[1])

    def to_dict(self) -> Dict[str, object]:
        """The JSON/CRD/store wire shape (f32 values as floats)."""
        return {
            "w1": [[float(x) for x in row] for row in self.w1],
            "b1": [float(x) for x in self.b1],
            "w2": [float(x) for x in self.w2],
            "b2": float(self.b2),
        }

    @classmethod
    def from_dict(cls, data: Dict[str, object]) -> "InferModel":
        return cls(w1=np.asarray(data["w1"], dtype=np.float32),
                   b1=np.asarray(data["b1"], dtype=np.float32),
                   w2=np.asarray(data["w2"], dtype=np.float32),
                   b2=float(data["b2"]))


def default_model(seed: int = 7, hidden: int = INFER_HIDDEN) -> InferModel:
    """Deterministic pseudo-random weights (the bench/soak stand-in for
    a trained model): small magnitudes keep scores spread across the
    low bands, so enrolling traffic against it exercises the scoring
    stage without firing actions."""
    rng = np.random.RandomState(seed)
    return InferModel(
        w1=(rng.randn(INFER_FEATURES, hidden) * 0.3).astype(np.float32),
        b1=(rng.randn(hidden) * 0.1).astype(np.float32),
        w2=(rng.randn(hidden) * 0.3).astype(np.float32),
        b2=float(rng.randn() * 0.1),
    )


def anomaly_port_model(port_floor: int = 60000,
                       hidden: int = INFER_HIDDEN) -> InferModel:
    """The crafted high-port anomaly detector (demo / drill / parity
    model): one active hidden unit keyed on the normalised destination
    port (feature f9 = dst_port / 65535),

        h0 = relu(200 * (f9 - port_floor/65535));  z = 2*h0 - 6

    so a flow at or above ``port_floor`` saturates toward score 1.0
    (band 7) within a couple thousand ports of the floor, while a flow
    at a conventional service port scores sigmoid(-6) ≈ 0.0025
    (band 0).  Decisive margins on both sides make the device↔host
    band parity exact — no boundary rounding to argue about."""
    w1 = np.zeros((INFER_FEATURES, hidden), dtype=np.float32)
    b1 = np.zeros(hidden, dtype=np.float32)
    w2 = np.zeros(hidden, dtype=np.float32)
    w1[9, 0] = 200.0
    b1[0] = -200.0 * (port_floor / 65535.0)
    w2[0] = 2.0
    return InferModel(w1=w1, b1=b1, w2=w2, b2=-6.0)


def model_rows_changed(old: InferModel, new: InferModel) -> List[int]:
    """Which w1 feature rows differ — handy for tests asserting the
    delta builder ships O(changed) rows on a model update."""
    if old.w1.shape != new.w1.shape:
        return list(range(new.w1.shape[0]))
    return [int(i) for i in
            np.nonzero((old.w1 != new.w1).any(axis=1))[0]]
