"""NAT44 — DNAT/LB map compilation, session table, and rewrite kernel.

The TPU replacement for VPP's nat44 plugin (SURVEY.md §2.3): K8s
Services become static DNAT mappings with load-balanced backends
(nat44_renderer.go exportDNATMappings :421); the per-packet work is a
jit-compiled rewrite over header batches:

- **DNAT (out2in)**: match (dst ip, dst port, proto) against the
  mapping table, pick a backend by *flow hash* over a weighted bucket
  ring — deterministic and flow-sticky, the TPU-native analog of VPP's
  probability-based random pick (SURVEY §7.3: hash keeps flows sticky
  without per-packet RNG divergence).  Client-IP session affinity
  hashes only the source address.
- **self-twice-NAT hairpin**: when the chosen backend equals the
  client, the source is rewritten to the virtual NAT loopback so
  replies return through the data plane (nat44 TwiceNat=SELF);
  mappings with twice-NAT ENABLED always rewrite the source.
- **SNAT (in2out)**: pod traffic leaving the cluster is source-NATted
  to the node IP with a hash-allocated ephemeral port.
- **sessions**: a device-resident open-addressed hash table keyed by
  the *reply* flow 5-tuple with ``PROBE_WAYS``-way linear probing; the
  forward pass scatters new sessions in, the reply pass restores
  original addresses.  Insertion never evicts an established flow:
  a full bucket or an ambiguous reply key (two distinct flows whose
  translated reply tuples collide — the SNAT port-collision case)
  raises the per-packet ``punt`` flag and the flow is handed to the
  host slow path (:mod:`vpp_tpu.ops.slowpath`), mirroring how VPP
  punts NAT misses to the slow path.  The host sweeps stale entries
  by age (the reference's idle-session GC goroutine,
  nat44_renderer.go ~:691, becomes a host-side sweep of ``last_seen``).

All state lives in device arrays; updates are functional (the caller
threads ``NatSessions`` through) so the whole step stays inside one
XLA program.
"""

from __future__ import annotations

import ipaddress
import logging
from dataclasses import dataclass, replace as _dc_replace
from typing import Any, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .classify import _next_pow2
from .packets import PacketBatch, ip_to_u32

logger = logging.getLogger(__name__)

# Twice-NAT modes (nat44 DNat44_StaticMapping TwiceNat).
TWICE_NAT_NONE = 0
TWICE_NAT_SELF = 1
TWICE_NAT_ENABLED = 2

# Session-table probe width: each flow may live in any of the W
# linearly-probed slots after its hash slot (VPP's bihash has 2-entry
# buckets + overflow; W=4 keeps the gather cheap while making
# same-batch evictions impossible until a bucket truly fills).
PROBE_WAYS = 4

# DNAT mapping-index hash table probe width.  Unlike the session table
# the mapping set is compiled on the host, so the build can simply grow
# the table until every key lands within the probe window — the device
# lookup is always exactly W gathers.
MAP_PROBE_WAYS = 4

# TPU crossover for the lookup discipline, measured on v5e through the
# chained config-5 pipeline (64x256 scan dispatch, B=16384): the dense
# [B, M] compare FUSES into a VPU-friendly reduce and beats the 4-way
# gather probe up to at least M=8192 (hash 107us vs dense 97us p50 at
# M=1024; dead even at 8192), because random gathers are the TPU
# anti-pattern while regular compares are nearly free.  Past this the
# dense compare's O(B*M) work dominates and the hash takes over.  On
# CPU/GPU backends gathers are cheap and the hash wins at any size.
HMAP_MIN_MAPPINGS_TPU = 8192


@dataclass
class NatMapping:
    """One DNAT static mapping (host-side description)."""

    external_ip: str
    external_port: int
    protocol: int  # 6 / 17
    # (backend_ip, backend_port, weight) — weight models LocalIps
    # Probability (ServiceLocalEndpointWeight for local backends).
    backends: List[Tuple[str, int, int]]
    twice_nat: int = TWICE_NAT_SELF
    # ClientIP session affinity timeout (0 = disabled).
    session_affinity_timeout: int = 0


@dataclass
class NatTables:
    """Compiled NAT state (device arrays)."""

    # Mappings [M].
    map_ext_ip: jnp.ndarray     # uint32
    map_ext_port: jnp.ndarray   # int32
    map_proto: jnp.ndarray      # int32
    map_twice_nat: jnp.ndarray  # int32
    map_affinity: jnp.ndarray   # int32 (bool: hash client IP only)
    map_valid: jnp.ndarray      # bool

    # Weighted backend bucket ring [M, K].
    backend_ip: jnp.ndarray     # uint32
    backend_port: jnp.ndarray   # int32

    # Exact-match mapping index [H]: open-addressed hash over
    # (ext_ip, ext_port, proto) -> mapping row, -1 = empty.  Replaces
    # the dense [B, M] compare with MAP_PROBE_WAYS gathers per packet
    # (VPP's nat44 static-mapping lookup is likewise a hash probe, not
    # a linear scan over mappings).
    hmap_idx: jnp.ndarray       # int32

    # SNAT config (scalars).
    nat_loopback: jnp.ndarray   # uint32 []
    snat_ip: jnp.ndarray        # uint32 [] - node IP for egress SNAT
    snat_enabled: jnp.ndarray   # bool []
    # Pod/service subnets for routing decisions (base, mask).
    pod_subnet_base: jnp.ndarray  # uint32 []
    pod_subnet_mask: jnp.ndarray  # uint32 []
    # ClientIP affinity timeout per mapping, SECONDS (0 = disabled);
    # the host sweep converts to timestamp units at its measured rate.
    map_aff_timeout: jnp.ndarray = None  # int32 [M]

    num_mappings: int = 0
    bucket_size: int = 0
    # Static (trace-time) lookup discipline.  False in two cases:
    # (a) TPU backend with a padded mapping width at or below the
    #     measured crossover (HMAP_MIN_MAPPINGS_TPU) — the fused dense
    #     compare beats gather probes there; hmap_idx is still built so
    #     A/B tests and a ``dataclasses.replace`` re-enable keep working;
    # (b) the hash build hit its growth bound (> MAP_PROBE_WAYS mapping
    #     keys sharing one full 32-bit hash — constructible by an
    #     adversary since the hash is unseeded); only then is hmap_idx
    #     a 16-entry stub and the dense path the sole correct lookup.
    use_hmap: bool = True
    # Static gate: ANY mapping has ClientIP affinity (compiles the
    # affinity probe/commit into the program only when true).
    has_affinity: bool = False

    def tree_flatten(self):
        children = (
            self.map_ext_ip, self.map_ext_port, self.map_proto,
            self.map_twice_nat, self.map_affinity, self.map_valid,
            self.backend_ip, self.backend_port, self.hmap_idx,
            self.nat_loopback, self.snat_ip, self.snat_enabled,
            self.pod_subnet_base, self.pod_subnet_mask,
            self.map_aff_timeout,
        )
        return children, (
            self.num_mappings, self.bucket_size, self.use_hmap,
            self.has_affinity,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(
            *children, num_mappings=aux[0], bucket_size=aux[1],
            use_hmap=aux[2], has_affinity=aux[3],
        )


jax.tree_util.register_pytree_node(NatTables, NatTables.tree_flatten, NatTables.tree_unflatten)


# Column indices of the NatSessions key table (16-byte key rows).
_K_META = 0       # 0 = empty slot, else protocol

# Meta-column tag bit marking "written by the CURRENT dispatch".  Set
# by nat_commit_sessions_full(tag_writes=True) and cleared by the
# flat-safe finalize scatter before the dispatch returns, so it never
# survives in a materialised table.  Folding the mark into the meta
# word lets ONE key-row probe answer both "does this key match?" and
# "was it written this batch?" — the alternative (a separate written-
# mask table) costs a zeros+scatter+gather chain of its own, and the
# session stages are bound by the NUMBER of small random-access ops,
# not their bytes.
WRITE_TAG = 1 << 31
_META_MASK = WRITE_TAG ^ 0xFFFFFFFF

# Meta-column flag marking a CLIENT-IP AFFINITY entry.  Affinity state
# (K8s ``ClientIP`` service affinity with a timeout) shares the session
# table's slots: an entry pins (client, service) -> backend so the pick
# survives backend-ring changes until the affinity EXPIRES (the
# reference expires NAT affinity entries after session_affinity_timeout
# — nat44's affinity timeout semantic).  Protocols are <= 255, so the
# flag bit can never make an affinity row match a session probe (whose
# meta compare masks only WRITE_TAG), and vice versa.
AFFINITY_FLAG = 1 << 8

# Affinity value-row columns (reinterpreting the session value row).
_AV_BIP = 0       # pinned backend ip
_AV_BPORT = 1     # pinned backend port
_AV_MIDX = 2      # mapping row AT COMMIT TIME (debug only — table
                  # rebuilds reorder rows, so the sweep re-resolves the
                  # mapping from the key row, never from this cache)
_AV_SEEN = 3      # last_seen (same column as sessions' _V_SEEN)
_K_RSRC = 1       # reply key: src ip (backend / server)
_K_RDST = 2       # reply key: dst ip (client after twice-nat)
_K_RPORTS = 3     # reply key: src_port << 16 | dst_port
# Column indices of the NatSessions value table (16-byte value rows).
_V_OSRC = 0       # restore: original client ip
_V_ODST = 1       # restore: original dst (VIP / node IP)
_V_OPORTS = 2     # restore: orig src_port << 16 | dst_port
_V_SEEN = 3       # last_seen batch-counter timestamp (uint32 view)


@dataclass
class NatSessions:
    """Device-resident session hash table, keyed by reply-flow hash.

    HYBRID AoS layout — TWO ``[capacity, 4]`` uint32 matrices instead
    of an array per field: the session stages are gather/scatter bound
    on TPU, where one row gather moves a whole 16-byte slot row in one
    memory transaction but separate field arrays pay one gather each
    (VPP's bihash packs buckets into cache lines for the same reason).
    The split is byte-exact for the access pattern: probes touch ONLY
    ``key_tbl`` rows (meta, reply src/dst, packed ports) across all W
    ways, and ``val_tbl`` rows (restore values + last_seen) are
    gathered only at the single selected slot — a full-AoS 32-byte row
    would double the probe traffic for columns probes never read
    (measured: full AoS costs the 16k-packet flat-safe dispatch ~15%
    while winning at 64k; the split wins at both).  Ports pack into
    one word per direction; the protocol doubles as the validity flag
    (meta 0 = empty; protocol 0 is never recordable and probes of
    proto-0 packets are masked out explicitly).

    Field views (``valid``, ``r_src_ip``, ``last_seen``, ...) are
    computed properties for metrics, sweeps and tests; hot paths
    operate on gathered rows directly.
    """

    key_tbl: jnp.ndarray  # uint32 [capacity, 4]
    val_tbl: jnp.ndarray  # uint32 [capacity, 4]

    @property
    def valid(self) -> jnp.ndarray:
        """Live SESSION rows (affinity entries excluded)."""
        meta = self.key_tbl[:, _K_META]
        return (meta > 0) & ((meta & jnp.uint32(AFFINITY_FLAG)) == 0)

    @property
    def aff_valid(self) -> jnp.ndarray:
        """Live client-IP AFFINITY rows."""
        return (self.key_tbl[:, _K_META] & jnp.uint32(AFFINITY_FLAG)) != 0

    @property
    def r_meta(self) -> jnp.ndarray:
        return self.key_tbl[:, _K_META].astype(jnp.int32)

    @property
    def r_src_ip(self) -> jnp.ndarray:
        return self.key_tbl[:, _K_RSRC]

    @property
    def r_dst_ip(self) -> jnp.ndarray:
        return self.key_tbl[:, _K_RDST]

    @property
    def r_ports(self) -> jnp.ndarray:
        return self.key_tbl[:, _K_RPORTS]

    @property
    def orig_src_ip(self) -> jnp.ndarray:
        return self.val_tbl[:, _V_OSRC]

    @property
    def orig_dst_ip(self) -> jnp.ndarray:
        return self.val_tbl[:, _V_ODST]

    @property
    def orig_ports(self) -> jnp.ndarray:
        return self.val_tbl[:, _V_OPORTS]

    @property
    def last_seen(self) -> jnp.ndarray:
        return self.val_tbl[:, _V_SEEN].astype(jnp.int32)

    @property
    def capacity(self) -> int:
        return self.key_tbl.shape[0]

    def tree_flatten(self):
        return (self.key_tbl, self.val_tbl), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(NatSessions, NatSessions.tree_flatten, NatSessions.tree_unflatten)


def empty_sessions(capacity: int = 65536) -> NatSessions:
    """Fresh session table (capacity must be a power of two)."""
    assert capacity & (capacity - 1) == 0, "capacity must be a power of two"
    # Two DISTINCT buffers: jit donation of a NatSessions would alias
    # one donated buffer to both leaves otherwise.
    return NatSessions(
        key_tbl=jnp.zeros((capacity, 4), dtype=jnp.uint32),
        val_tbl=jnp.zeros((capacity, 4), dtype=jnp.uint32),
    )


def _pack_ports(src_port: jnp.ndarray, dst_port: jnp.ndarray) -> jnp.ndarray:
    """(sp & 0xFFFF) << 16 | (dp & 0xFFFF) as uint32 — one
    gather/scatter word per pair.  Both halves are masked: ports ride
    int32 batch columns and nothing clamps them on the Python/test
    ingestion path, so an out-of-range value must not bleed into the
    other half and alias two distinct tuples onto one packed key."""
    return (
        ((src_port.astype(jnp.uint32) & jnp.uint32(0xFFFF)) << jnp.uint32(16))
        | (dst_port.astype(jnp.uint32) & jnp.uint32(0xFFFF))
    )


def _mix_py(h: int) -> int:
    """Host mirror of :func:`_mix` (explicit 32-bit wraparound)."""
    h &= 0xFFFFFFFF
    h ^= h >> 16
    h = (h * 0x85EBCA6B) & 0xFFFFFFFF
    h ^= h >> 13
    h = (h * 0xC2B2AE35) & 0xFFFFFFFF
    h ^= h >> 16
    return h


def _map_key_hash_py(ext_ip: int, ext_port: int, proto: int) -> int:
    """Host mirror of :func:`_map_key_hash` — the two must stay in
    lockstep (tested in tests/test_tpu_nat.py)."""
    h = (ext_ip * 0x9E3779B1) & 0xFFFFFFFF
    return _mix_py(h ^ ((ext_port << 16) | proto))


def _map_key_hash(dst_ip: jnp.ndarray, dst_port: jnp.ndarray, proto: jnp.ndarray) -> jnp.ndarray:
    """Device hash of the DNAT exact-match key (uint32 [B])."""
    h = dst_ip.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
    return _mix(h ^ ((dst_port.astype(jnp.uint32) << jnp.uint32(16)) | proto.astype(jnp.uint32)))


def _build_map_hash(
    entries: Sequence[Tuple[int, Tuple[int, int, int]]], start_capacity: int = 16
) -> Optional[np.ndarray]:
    """Open-addressed (ext_ip, ext_port, proto) -> mapping-index table.

    Inserts every key within ``MAP_PROBE_WAYS`` linear-probe slots of
    its hash slot, doubling the table until that invariant holds — the
    device lookup then needs exactly W gathers, no overflow chains.
    Duplicate keys keep the FIRST mapping index (the dense first-match
    semantics, since later duplicates are unreachable there too).

    Returns ``None`` when growth hits its bound: more than W distinct
    keys with the SAME full 32-bit hash collide at every capacity, so
    doubling can never separate them.  The unseeded hash is invertible,
    so such key sets are craftable by whoever controls Service specs —
    the caller must fall back to the dense lookup, not hang the
    control plane.
    """
    capacity = max(16, start_capacity)
    assert capacity & (capacity - 1) == 0
    # The bound exists to stop UNBOUNDED growth on same-full-hash key
    # sets; it must never sit below the starting capacity (a caller
    # sizing from a mostly-invalid mapping list would otherwise get a
    # spurious None before the first insert attempt).
    limit = max(1 << 16, 16 * _next_pow2(max(len(entries), 1)), capacity)
    while capacity <= limit:
        table = np.full(capacity, -1, dtype=np.int32)
        seen: Dict[Tuple[int, int, int], int] = {}
        ok = True
        for idx, key in entries:
            if key in seen:
                continue  # first mapping wins, matching dense argmax
            base = _map_key_hash_py(*key) & (capacity - 1)
            for w in range(MAP_PROBE_WAYS):
                slot = (base + w) & (capacity - 1)
                if table[slot] < 0:
                    table[slot] = idx
                    seen[key] = idx
                    break
            else:
                ok = False
                break
        if ok:
            return table
        capacity *= 2
    return None


def effective_bucket_size(
    mappings: Sequence[NatMapping],
    bucket_size: int = 64,
    max_bucket_size: int = 4096,
    log_widen: bool = True,
) -> int:
    """Table-wide backend-ring width: auto-widened (pow2) to fit the
    largest weighted-expanded backend list, capped at ``max_bucket_size``
    slots — but never below the caller's width, and never below the
    largest raw backend COUNT (so every backend keeps at least one slot
    even when weights must be downscaled into the cap; a single mapping
    with more than ``max_bucket_size`` backends therefore still exceeds
    the cap via the one-slot-per-backend floor).

    The widening is table-wide — one high-weight mapping inflates the
    ``backend_ip``/``backend_port`` rows of EVERY mapping — so any
    widening beyond the caller's width is logged with the resulting
    footprint multiplier rather than growing silently (advisor r3).
    """
    need = 0
    n_max = 0
    for mp in mappings:
        if not mp.backends:
            continue
        need = max(need, sum(max(1, w) for _, _, w in mp.backends))
        n_max = max(n_max, len(mp.backends))
    k = bucket_size
    if need > k:
        k = max(k, _next_pow2(min(need, max_bucket_size)))
    if n_max > k:
        k = _next_pow2(n_max)
    if k > bucket_size and log_widen:
        logger.info(
            "NAT backend ring auto-widened %d -> %d slots "
            "(largest weighted expansion %d, largest backend count %d; "
            "table-wide footprint x%d)",
            bucket_size, k, need, n_max, max(1, k // max(1, bucket_size)),
        )
    return k


def bucket_ring(mapping: NatMapping, k_ring: int) -> List[Tuple[int, int]]:
    """One mapping's backend ring [k_ring] of (ip_u32, port): weighted
    round-robin, stride-sampled so every backend is represented in
    proportion.  When the weighted expansion exceeds the ring, weights
    are downscaled proportionally with a floor of one slot per backend
    (k_ring >= backend count is the caller's contract — see
    effective_bucket_size), so no backend is ever starved; weight
    granularity coarsens instead.  Shared by build_nat_tables and the
    MockNatEngine oracle so the two stay lockstep by construction."""
    expanded: List[Tuple[int, int]] = []
    for ip, port, weight in mapping.backends:
        expanded.extend([(ip_to_u32(ip), port)] * max(1, weight))
    if len(expanded) > k_ring:
        # Scale into a budget of (k_ring - n) so the +1-per-backend
        # floors can never overflow the ring.
        total = len(expanded)
        budget = k_ring - len(mapping.backends)
        expanded = []
        for ip, port, weight in mapping.backends:
            scaled = max(1, (max(1, weight) * budget) // total)
            expanded.extend([(ip_to_u32(ip), port)] * scaled)
        assert len(expanded) <= k_ring
    n = len(expanded)
    return [expanded[(k * n) // k_ring] for k in range(k_ring)]


def _pick_use_hmap(padded_width: int, target_backend: Optional[str]) -> bool:
    """Lookup-discipline crossover for a given target backend.  On TPU
    the dense [B, M] compare fuses on the VPU and beats gather probes
    up to the measured HMAP_MIN_MAPPINGS_TPU padded width; gathers are
    cheap everywhere else so the hash always wins there."""
    backend = target_backend or jax.default_backend()
    if backend == "tpu":
        return padded_width > HMAP_MIN_MAPPINGS_TPU
    return True


def retarget_tables(tables: NatTables, target_backend: str) -> NatTables:
    """Re-derive the trace-time lookup gate for the backend the dispatch
    actually targets.  Tables built in a CPU-default process and shipped
    to TPU workers (or vice versa) would otherwise keep the builder's
    crossover pick; use_hmap is pytree AUX data so this is free — no
    device arrays are touched, only retraces differ.  A dense-fallback
    table (hmap growth bound hit) is returned unchanged: its stub index
    must never be re-enabled.  ``None`` passes through: runners may be
    constructed before the renderer's first commit delivers tables (the
    table swap arrives via update_tables)."""
    if tables is None:
        return None
    if (
        not tables.use_hmap
        and tables.num_mappings > 0
        and not bool(jnp.any(tables.hmap_idx >= 0))
    ):
        return tables  # dense fallback — hmap_idx is a stub
    return _dc_replace(
        tables, use_hmap=_pick_use_hmap(tables.map_ext_ip.shape[0], target_backend)
    )


def build_nat_tables(
    mappings: Sequence[NatMapping],
    nat_loopback: str = "0.0.0.0",
    snat_ip: str = "0.0.0.0",
    snat_enabled: bool = False,
    pod_subnet: str = "10.1.0.0/16",
    bucket_size: int = 64,
    target_backend: Optional[str] = None,
) -> NatTables:
    """Compile DNAT mappings to tensors.

    The backend ring of each mapping is filled by weighted round-robin
    so that ``flow_hash %% K`` lands on backend b with probability
    weight_b / sum(weights) (up to rounding) — flow-sticky weighted LB.

    ``target_backend`` names the JAX backend the dispatch will RUN on
    ("tpu"/"cpu"/"gpu"); it gates the lookup-discipline crossover
    (``use_hmap``).  Default is this process's ``jax.default_backend()``
    — correct when tables are built in the device process; a builder
    shipping tables elsewhere must pass the target explicitly or call
    :func:`retarget_tables` at the dispatch site (advisor r3: the gate
    is perf-only — both lookups are bit-equal — but the wrong pick
    costs the measured crossover margin).
    """
    host = build_nat_host(
        mappings,
        nat_loopback=nat_loopback,
        snat_ip=snat_ip,
        snat_enabled=snat_enabled,
        pod_subnet=pod_subnet,
        bucket_size=bucket_size,
    )
    use_hmap = (
        _pick_use_hmap(host["map_ext_ip"].shape[0], target_backend)
        if host["hmap_ok"] else False
    )
    return NatTables(
        map_ext_ip=jnp.asarray(host["map_ext_ip"]),
        map_ext_port=jnp.asarray(host["map_ext_port"]),
        map_proto=jnp.asarray(host["map_proto"]),
        map_twice_nat=jnp.asarray(host["map_twice_nat"]),
        map_affinity=jnp.asarray(host["map_affinity"]),
        map_valid=jnp.asarray(host["map_valid"]),
        backend_ip=jnp.asarray(host["backend_ip"]),
        backend_port=jnp.asarray(host["backend_port"]),
        hmap_idx=jnp.asarray(host["hmap_idx"]),
        nat_loopback=jnp.asarray(host["nat_loopback"]),
        snat_ip=jnp.asarray(host["snat_ip"]),
        snat_enabled=jnp.asarray(host["snat_enabled"]),
        pod_subnet_base=jnp.asarray(host["pod_subnet_base"]),
        pod_subnet_mask=jnp.asarray(host["pod_subnet_mask"]),
        map_aff_timeout=jnp.asarray(host["map_aff_timeout"]),
        num_mappings=host["num_mappings"],
        bucket_size=host["bucket_size"],
        use_hmap=use_hmap,
        has_affinity=host["has_affinity"],
    )


def build_nat_host(
    mappings: Sequence[NatMapping],
    nat_loopback: str = "0.0.0.0",
    snat_ip: str = "0.0.0.0",
    snat_enabled: bool = False,
    pod_subnet: str = "10.1.0.0/16",
    bucket_size: int = 64,
) -> Dict[str, Any]:
    """The host-array core of :func:`build_nat_tables`: numpy columns +
    aux, no device transfers.  Shared with the incremental builder
    (:mod:`vpp_tpu.ops.nat_delta`) so full and delta compiles encode
    rows through ONE code path.  ``hmap_ok`` is False when the hash
    build hit its growth bound (dense fallback, stub index)."""
    m = len(mappings)
    padded = _next_pow2(max(m, 1))
    # Auto-widen the ring: a fixed width would silently drop backends
    # past it.  The reference's NAT44 caps a service at 256 backends
    # receiving traffic (CHANGELOG.md:13-14); here the ring grows with
    # demand (see effective_bucket_size for the cap/guarantees).
    bucket_size = effective_bucket_size(mappings, bucket_size)
    ext_ip = np.zeros(padded, dtype=np.uint32)
    ext_port = np.zeros(padded, dtype=np.int32)
    proto = np.zeros(padded, dtype=np.int32)
    twice = np.zeros(padded, dtype=np.int32)
    affinity = np.zeros(padded, dtype=np.int32)
    aff_timeout = np.zeros(padded, dtype=np.int32)
    valid = np.zeros(padded, dtype=bool)
    b_ip = np.zeros((padded, bucket_size), dtype=np.uint32)
    b_port = np.zeros((padded, bucket_size), dtype=np.int32)

    for i, mapping in enumerate(mappings):
        ext_ip[i] = ip_to_u32(mapping.external_ip)
        ext_port[i] = mapping.external_port
        proto[i] = mapping.protocol
        twice[i] = mapping.twice_nat
        affinity[i] = 1 if mapping.session_affinity_timeout > 0 else 0
        aff_timeout[i] = mapping.session_affinity_timeout
        valid[i] = True
        if not mapping.backends:
            valid[i] = False
            continue
        for k, (ip_u, port_u) in enumerate(bucket_ring(mapping, bucket_size)):
            b_ip[i, k] = ip_u
            b_port[i, k] = port_u

    net = ipaddress.ip_network(pod_subnet)
    mask = (0xFFFFFFFF << (32 - net.prefixlen)) & 0xFFFFFFFF if net.prefixlen else 0

    # Only valid mappings enter the exact-match index (invalid rows can
    # never hit the dense compare either); size for ~50% max load on
    # the VALID count so mostly-invalid mapping lists don't inflate it.
    n_valid = int(valid.sum())
    hmap = _build_map_hash(
        [
            (i, (int(ext_ip[i]), int(ext_port[i]), int(proto[i])))
            for i in range(m) if valid[i]
        ],
        start_capacity=_next_pow2(max(2 * n_valid, 8), minimum=16),
    )
    hmap_ok = hmap is not None
    if hmap is None:  # adversarial hash-collision set: dense fallback
        hmap = np.full(16, -1, dtype=np.int32)

    return {
        "map_ext_ip": ext_ip,
        "map_ext_port": ext_port,
        "map_proto": proto,
        "map_twice_nat": twice,
        "map_affinity": affinity,
        "map_valid": valid,
        "backend_ip": b_ip,
        "backend_port": b_port,
        "hmap_idx": hmap,
        "nat_loopback": np.asarray(ip_to_u32(nat_loopback), dtype=np.uint32),
        "snat_ip": np.asarray(ip_to_u32(snat_ip), dtype=np.uint32),
        "snat_enabled": np.asarray(snat_enabled),
        "pod_subnet_base": np.asarray(int(net.network_address), dtype=np.uint32),
        "pod_subnet_mask": np.asarray(mask, dtype=np.uint32),
        "map_aff_timeout": aff_timeout,
        "num_mappings": m,
        "bucket_size": bucket_size,
        "hmap_ok": hmap_ok,
        "has_affinity": bool(aff_timeout.any()),
    }


# ---------------------------------------------------------------------------
# Hashing
# ---------------------------------------------------------------------------


def _mix(h: jnp.ndarray) -> jnp.ndarray:
    """Final avalanche of a murmur3-style 32-bit mixer."""
    h = h ^ (h >> 16)
    h = h * jnp.uint32(0x85EBCA6B)
    h = h ^ (h >> 13)
    h = h * jnp.uint32(0xC2B2AE35)
    h = h ^ (h >> 16)
    return h


def flow_hash(
    src_ip: jnp.ndarray,
    dst_ip: jnp.ndarray,
    proto: jnp.ndarray,
    src_port: jnp.ndarray,
    dst_port: jnp.ndarray,
) -> jnp.ndarray:
    """Deterministic per-flow 32-bit hash (uint32 [B])."""
    h = src_ip.astype(jnp.uint32) * jnp.uint32(0x9E3779B1)
    h = _mix(h ^ dst_ip.astype(jnp.uint32))
    h = _mix(h ^ (proto.astype(jnp.uint32) << 16) ^ src_port.astype(jnp.uint32))
    h = _mix(h ^ dst_port.astype(jnp.uint32))
    return h


class NatResult(NamedTuple):
    batch: PacketBatch        # rewritten headers
    sessions: NatSessions     # updated session table
    dnat_hit: jnp.ndarray     # bool [B] forward DNAT applied
    reply_hit: jnp.ndarray    # bool [B] reply restoration applied
    snat_hit: jnp.ndarray     # bool [B] egress SNAT applied
    punt: jnp.ndarray         # bool [B] flow needs the host slow path


class NatRewrite(NamedTuple):
    """Output of the pure rewrite phase (no session writes yet)."""

    batch: PacketBatch
    dnat_hit: jnp.ndarray
    reply_hit: jnp.ndarray
    snat_hit: jnp.ndarray
    reply_slot: jnp.ndarray  # int32 [B] resolved session slot of reply hits
    midx: jnp.ndarray        # int32 [B] matched mapping row (dnat rows)
    aff_want: jnp.ndarray    # bool [B] dnat hit on an affinity mapping


def _probe_slots(base: jnp.ndarray, cap: int) -> jnp.ndarray:
    """[B, W] candidate slots: linear probe ring from the hash slot."""
    return (base[:, None] + jnp.arange(PROBE_WAYS, dtype=jnp.int32)[None, :]) & jnp.int32(cap - 1)


def _rows_key_match(key_rows: jnp.ndarray, batch: PacketBatch) -> jnp.ndarray:
    """[B, W] — do the gathered key rows hold each row's reply key?

    Operates on ``key_rows = sessions.key_tbl[cand]`` ([B, W, 4]) so
    the probe is ONE 16-byte row gather, not one per field.  The
    proto>0 guard keeps a protocol-0 packet from "matching" empty
    slots (meta 0).  The WRITE_TAG bit is masked out of the compare so
    a flat-safe probe matches this-dispatch writes too (the caller
    reads the tag from the same rows to tell the two classes apart)."""
    return (
        (batch.protocol[:, None] > 0)
        & ((key_rows[..., _K_META] & jnp.uint32(_META_MASK))
           == batch.protocol.astype(jnp.uint32)[:, None])
        & (key_rows[..., _K_RSRC] == batch.src_ip[:, None])
        & (key_rows[..., _K_RDST] == batch.dst_ip[:, None])
        & (key_rows[..., _K_RPORTS] == _pack_ports(batch.src_port, batch.dst_port)[:, None])
    )


class ReplyRestore(NamedTuple):
    """Output of the session-reading reply-restore phase."""

    batch: PacketBatch       # restored headers (rows without a hit keep
                             # their original values)
    reply_hit: jnp.ndarray   # bool [B]
    reply_slot: jnp.ndarray  # int32 [B] resolved session slot of hits


class StatelessRewrite(NamedTuple):
    """Output of the session-INDEPENDENT rewrite phase (DNAT LB + SNAT
    computed on the original headers).  Valid for every row that is not
    a reply hit; reply rows take the restored path instead.

    With ClientIP affinity compiled in (``tables.has_affinity``) the
    phase additionally reads the PRE-dispatch affinity pins — still
    hoistable flat (scan) because in-dispatch pin inserts always equal
    the deterministic client-IP hash pick a later vector would compute
    anyway.  ``midx``/``aff_want`` feed the post-commit affinity write.
    """

    batch: PacketBatch
    dnat_hit: jnp.ndarray
    snat_hit: jnp.ndarray
    midx: jnp.ndarray      # int32 [B] matched mapping row (dnat rows)
    aff_want: jnp.ndarray  # bool [B] dnat hit on an affinity mapping


def nat_reply_probe(
    sessions: NatSessions, batch: PacketBatch
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Reply probe: ``(key_match [B, W], cand [B, W], meta [B, W])`` —
    which probe slots hold each row's reply key (validity included),
    plus the raw meta words of the probed rows (the flat-safe
    discipline reads WRITE_TAG out of them to split matches into
    pre-dispatch sessions vs this-dispatch writes at zero extra memory
    traffic).  Probes touch only the 16-byte key rows; restore values
    live in ``val_tbl`` and are gathered by callers at the single
    selected slot."""
    cap = sessions.capacity
    slot_mask = jnp.uint32(cap - 1)
    rhash = flow_hash(batch.src_ip, batch.dst_ip, batch.protocol,
                      batch.src_port, batch.dst_port)
    base = (rhash & slot_mask).astype(jnp.int32)
    cand = _probe_slots(base, cap)                       # [B, W]
    key_rows = sessions.key_tbl[cand]                    # [B, W, 4]
    return _rows_key_match(key_rows, batch), cand, key_rows[..., _K_META]


def nat_reply_restore(sessions: NatSessions, batch: PacketBatch) -> ReplyRestore:
    """Probe the session table for reply keys and restore originals.

    This is the ONLY part of the NAT translation that reads session
    state — the scan dispatch keeps just this (plus the commit) inside
    ``lax.scan`` and hoists everything else flat across vectors.
    """
    key_match, cand, _ = nat_reply_probe(sessions, batch)
    reply_hit = jnp.any(key_match, axis=1)
    w = jnp.argmax(key_match, axis=1)
    slot = jnp.take_along_axis(cand, w[:, None], axis=1)[:, 0]
    vals = sessions.val_tbl[slot]  # [B, 4] one 16-byte row per packet
    # Restore: src <- original dst (VIP), dst <- original src (client).
    op = vals[:, _V_OPORTS]
    orig_src_port = (op >> jnp.uint32(16)).astype(jnp.int32)
    orig_dst_port = (op & jnp.uint32(0xFFFF)).astype(jnp.int32)
    restored = PacketBatch(
        src_ip=jnp.where(reply_hit, vals[:, _V_ODST], batch.src_ip),
        dst_ip=jnp.where(reply_hit, vals[:, _V_OSRC], batch.dst_ip),
        protocol=batch.protocol,
        src_port=jnp.where(reply_hit, orig_dst_port, batch.src_port),
        dst_port=jnp.where(reply_hit, orig_src_port, batch.dst_port),
    )
    return ReplyRestore(batch=restored, reply_hit=reply_hit, reply_slot=slot)


def _dnat_lookup_hash(tables: NatTables, batch: PacketBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(dnat_hit bool [B], mapping index int32 [B]) via the exact-match
    index: W gathers per packet instead of an O(M) compare.  Bit-equal
    to :func:`_dnat_lookup_dense` (A/B-tested)."""
    cap = tables.hmap_idx.shape[0]
    kh = _map_key_hash(batch.dst_ip, batch.dst_port, batch.protocol)
    base = (kh & jnp.uint32(cap - 1)).astype(jnp.int32)
    cand = (
        base[:, None] + jnp.arange(MAP_PROBE_WAYS, dtype=jnp.int32)[None, :]
    ) & jnp.int32(cap - 1)                      # [B, W]
    midx_c = tables.hmap_idx[cand]              # [B, W] (-1 = empty)
    safe = jnp.maximum(midx_c, 0)
    ok = (
        (midx_c >= 0)
        & (tables.map_ext_ip[safe] == batch.dst_ip[:, None])
        & (tables.map_ext_port[safe] == batch.dst_port[:, None])
        & (tables.map_proto[safe] == batch.protocol[:, None])
    )
    dnat_hit = jnp.any(ok, axis=1)
    w = jnp.argmax(ok, axis=1)
    midx = jnp.take_along_axis(safe, w[:, None], axis=1)[:, 0]
    # Miss rows must still index in-range (masked downstream); argmax
    # over all-False picks way 0 whose `safe` is already >= 0.
    return dnat_hit, jnp.where(dnat_hit, midx, jnp.int32(0))


def _dnat_lookup_dense(tables: NatTables, batch: PacketBatch) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Reference O(B·M) lookup, kept for A/B parity testing."""
    hit = (
        tables.map_valid[None, :]
        & (batch.dst_ip[:, None] == tables.map_ext_ip[None, :])
        & (batch.dst_port[:, None] == tables.map_ext_port[None, :])
        & (batch.protocol[:, None] == tables.map_proto[None, :])
    )  # [B, M]
    return jnp.any(hit, axis=1), jnp.argmax(hit, axis=1)


def nat_rewrite_stateless(
    tables: NatTables,
    batch: PacketBatch,
    sessions: Optional[NatSessions] = None,
) -> StatelessRewrite:
    """DNAT LB + twice-NAT + SNAT on the given headers — no session
    reads (so the scan dispatch computes this flat over all vectors at
    once; MXU/VPU-efficient wide shapes, Pallas-eligible batch sizes),
    EXCEPT when ClientIP affinity is compiled in: then the pre-dispatch
    affinity pins override the hash pick (see StatelessRewrite)."""
    # --------------------------------------------------------- 1. DNAT LB
    # use_hmap is pytree aux data, so this branch resolves at trace
    # time — the compiled program contains exactly one lookup.
    if tables.use_hmap:
        dnat_hit, midx = _dnat_lookup_hash(tables, batch)
    else:
        dnat_hit, midx = _dnat_lookup_dense(tables, batch)

    # Backend pick: affinity hashes the client IP only, else full 5-tuple.
    h_full = flow_hash(batch.src_ip, batch.dst_ip, batch.protocol,
                       batch.src_port, batch.dst_port)
    h_aff = _mix(batch.src_ip.astype(jnp.uint32) * jnp.uint32(0x9E3779B1))
    use_aff = tables.map_affinity[midx] == 1
    h_pick = jnp.where(use_aff, h_aff, h_full)
    k = (h_pick % jnp.uint32(tables.bucket_size)).astype(jnp.int32)
    new_dst_ip = tables.backend_ip[midx, k]
    new_dst_port = tables.backend_port[midx, k]
    # A mapping that lost all backends was compiled invalid -> no hit; a
    # zero backend entry inside a valid mapping cannot occur (ring filled).
    aff_want = dnat_hit & use_aff
    if tables.has_affinity and sessions is not None:
        # A live pin overrides the hash pick — the pin survives
        # backend-ring changes until it EXPIRES (sweep_affinity), the
        # ClientIP-affinity timeout semantic.
        aff_hit, pin_ip, pin_port = affinity_lookup(
            sessions, tables, batch, midx, aff_want
        )
        new_dst_ip = jnp.where(aff_hit, pin_ip, new_dst_ip)
        new_dst_port = jnp.where(aff_hit, pin_port, new_dst_port)

    dst_ip2 = jnp.where(dnat_hit, new_dst_ip, batch.dst_ip)
    dst_port2 = jnp.where(dnat_hit, new_dst_port, batch.dst_port)

    # Twice-NAT: SELF only when the backend is the client itself
    # (hairpin); ENABLED always.
    mode = tables.map_twice_nat[midx]
    hairpin = dnat_hit & (
        ((mode == TWICE_NAT_SELF) & (dst_ip2 == batch.src_ip))
        | (mode == TWICE_NAT_ENABLED)
    )
    src_ip2 = jnp.where(hairpin, jnp.broadcast_to(tables.nat_loopback, batch.src_ip.shape), batch.src_ip)

    # ------------------------------------------------------------ 2. SNAT
    in_cluster = (dst_ip2 & tables.pod_subnet_mask) == tables.pod_subnet_base
    from_pod = (src_ip2 & tables.pod_subnet_mask) == tables.pod_subnet_base
    snat_hit = (
        jnp.broadcast_to(tables.snat_enabled, dnat_hit.shape)
        & from_pod & ~in_cluster & ~dnat_hit
    )
    # Hash-allocated ephemeral port (32768..65535).
    snat_port = (h_full % jnp.uint32(32768)).astype(jnp.int32) + 32768
    src_ip3 = jnp.where(snat_hit, jnp.broadcast_to(tables.snat_ip, src_ip2.shape), src_ip2)
    src_port3 = jnp.where(snat_hit, snat_port, batch.src_port)

    out = PacketBatch(
        src_ip=src_ip3,
        dst_ip=dst_ip2,
        protocol=batch.protocol,
        src_port=src_port3,
        dst_port=dst_port2,
    )
    return StatelessRewrite(
        batch=out, dnat_hit=dnat_hit, snat_hit=snat_hit,
        midx=midx, aff_want=aff_want,
    )


def combine_rewrite(restore: ReplyRestore, stateless: StatelessRewrite) -> NatRewrite:
    """Merge the two phases into the full translation: reply rows take
    the restored headers and bypass DNAT/SNAT; everything else takes
    the stateless rewrite.  Bit-identical to the fused ``nat_rewrite``
    (the stateless phase sees original headers exactly when there is no
    reply hit, and its outputs are masked out exactly when there is)."""
    rh = restore.reply_hit

    def sel(a, b):
        return jnp.where(rh, a, b)

    out = PacketBatch(
        src_ip=sel(restore.batch.src_ip, stateless.batch.src_ip),
        dst_ip=sel(restore.batch.dst_ip, stateless.batch.dst_ip),
        protocol=restore.batch.protocol,
        src_port=sel(restore.batch.src_port, stateless.batch.src_port),
        dst_port=sel(restore.batch.dst_port, stateless.batch.dst_port),
    )
    return NatRewrite(
        batch=out,
        dnat_hit=stateless.dnat_hit & ~rh,
        reply_hit=rh,
        snat_hit=stateless.snat_hit & ~rh,
        reply_slot=restore.reply_slot,
        midx=stateless.midx,
        aff_want=stateless.aff_want & ~rh,
    )


def nat_rewrite(
    tables: NatTables,
    sessions: NatSessions,
    batch: PacketBatch,
) -> NatRewrite:
    """The pure NAT translation: reply restore -> DNAT LB -> SNAT.

    Reads the session table but does not modify it; call
    ``nat_commit_sessions`` afterwards with the flows that may record
    sessions (the pipeline gates this on its ACL verdict so denied flows
    can never seed a reflective bypass).
    """
    return combine_rewrite(
        nat_reply_restore(sessions, batch),
        nat_rewrite_stateless(tables, batch, sessions),
    )


class CommitResult(NamedTuple):
    """Full output of the session-commit phase (``nat_commit_sessions``
    returns the (sessions, punt) subset).  ``committed``/``ins_slot``
    let the flat-safe discipline undo a same-dispatch reply's bogus
    forward session: a committed row OWNS its slot's content (the
    post-write verify proved its scatter won), so invalidating that
    slot is race-free.  ``reused`` distinguishes a keep-alive refresh
    of a PRE-EXISTING slot (same key, same orig — clearing it would
    destroy a legit session) from a fresh insert (safe to undo)."""

    sessions: NatSessions
    punt: jnp.ndarray       # bool [B]
    committed: jnp.ndarray  # bool [B] row's session write won and verified
    ins_slot: jnp.ndarray   # int32 [B] slot written by committed rows
    reused: jnp.ndarray     # bool [B] committed into a pre-existing slot


def nat_commit_sessions_full(
    sessions: NatSessions,
    orig: PacketBatch,
    rewritten: PacketBatch,
    record: jnp.ndarray,
    reply_hit: jnp.ndarray,
    reply_slot: jnp.ndarray,
    timestamp: jnp.ndarray,
    tag_writes: bool = False,
) -> CommitResult:
    """Scatter new sessions in and refresh reply keep-alives.

    ``record`` (bool [B]) marks flows allowed to create a session —
    the pipeline's (translated ∧ ACL-permitted) mask.  Sessions are
    keyed by the hash of the expected *reply* tuple (src=server,
    dst=translated client) and inserted with W-way linear probing.

    Returns ``(sessions, punt)`` — ``punt`` (bool [B]) marks flows
    whose session could NOT be recorded and must go to the host slow
    path: (a) the probe bucket is full (no eviction of live flows),
    (b) another flow already owns the identical reply key (a SNAT
    port collision — replies would be indistinguishable), or (c) the
    flow lost an intra-batch scatter race for its slot.
    """
    cap = sessions.capacity
    slot_mask = jnp.uint32(cap - 1)
    # The reply key as a PacketBatch view (src/dst swapped).
    reply_view = PacketBatch(
        src_ip=rewritten.dst_ip, dst_ip=rewritten.src_ip,
        protocol=rewritten.protocol,
        src_port=rewritten.dst_port, dst_port=rewritten.src_port,
    )
    rkh = flow_hash(
        reply_view.src_ip, reply_view.dst_ip, reply_view.protocol,
        reply_view.src_port, reply_view.dst_port,
    )
    base = (rkh & slot_mask).astype(jnp.int32)
    cand = _probe_slots(base, cap)                     # [B, W]
    key_rows = sessions.key_tbl[cand]                  # [B, W, 4]
    same_key = _rows_key_match(key_rows, reply_view)   # [B, W]
    orig_ports = _pack_ports(orig.src_port, orig.dst_port)
    # Valid slots hold UNIQUE keys (inserts reuse a same-key slot or
    # punt on collision; intra-batch racers lose the scatter and punt),
    # so same_key has at most ONE true way — gather the 16-byte value
    # row at that single slot instead of all W ways (the session stages
    # are gather-bound on TPU; this quarters the commit's value
    # traffic).
    w_sk = jnp.argmax(same_key, axis=1)                          # [B]
    slot_sk = jnp.take_along_axis(cand, w_sk[:, None], axis=1)[:, 0]
    any_sk = jnp.any(same_key, axis=1)
    vals_sk = sessions.val_tbl[slot_sk]                # [B, 4]
    same_orig_row = (
        any_sk
        & (vals_sk[:, _V_OSRC] == orig.src_ip)
        & (vals_sk[:, _V_ODST] == orig.dst_ip)
        & (vals_sk[:, _V_OPORTS] == orig_ports)
    )
    # Another live flow already owns this reply key -> ambiguous replies.
    collision = any_sk & ~same_orig_row
    free = key_rows[..., _K_META] == 0
    has_same = same_orig_row
    has_free = jnp.any(free, axis=1)
    # Free-slot choice rotates per flow (hash bits above the slot mask):
    # concurrent same-bucket inserters in ONE batch cannot see each
    # other's scatter writes, so a shared "first free" would let only
    # one win per batch — rotated preferences spread them across the W
    # ways and up to W colliding flows insert in a single batch.
    pref = ((rkh >> jnp.uint32(16)) % jnp.uint32(PROBE_WAYS)).astype(jnp.int32)
    rank = (jnp.arange(PROBE_WAYS, dtype=jnp.int32)[None, :] - pref[:, None]) % PROBE_WAYS
    free_rank = jnp.where(free, rank, PROBE_WAYS)
    w_pick = jnp.where(has_same, w_sk, jnp.argmin(free_rank, axis=1))
    ins_slot = jnp.take_along_axis(cand, w_pick[:, None], axis=1)[:, 0]
    # A protocol-0 flow cannot be recorded (r_meta=0 means EMPTY — its
    # write would produce an invisible session that neither restores
    # nor punts).  Refusing the insert routes it to `punt` below, and
    # the host slow path — whose dict keys carry proto 0 fine — owns
    # the flow.
    can_insert = (
        record & (reply_view.protocol > 0) & (has_same | has_free) & ~collision
    )

    drop_sentinel = jnp.int32(cap)  # out-of-range -> scatter drops the write
    w = jnp.where(can_insert, ins_slot, drop_sentinel)
    reply_ports = _pack_ports(reply_view.src_port, reply_view.dst_port)
    ts_col = jnp.broadcast_to(timestamp.astype(jnp.uint32), reply_ports.shape)
    # tag_writes (static): mark this dispatch's writes in the meta word
    # so the flat-safe reconcile can split its probe matches without a
    # separate written-mask table; the caller MUST clear the tag before
    # returning the table (its finalize scatter).
    meta_col = reply_view.protocol.astype(jnp.uint32)
    if tag_writes:
        meta_col = meta_col | jnp.uint32(WRITE_TAG)
    new_keys = jnp.stack(
        [meta_col, reply_view.src_ip, reply_view.dst_ip, reply_ports],
        axis=1,
    )  # [B, 4]
    new_vals = jnp.stack(
        [orig.src_ip, orig.dst_ip, orig_ports, ts_col], axis=1
    )  # [B, 4]
    key1 = sessions.key_tbl.at[w].set(new_keys, mode="drop")
    val1 = sessions.val_tbl.at[w].set(new_vals, mode="drop")
    # Post-write verify: two distinct flows in one batch can pick the
    # same free slot; the scatter's last writer wins.  Re-read the slot
    # rows and flag losers (their written-back row differs) for the
    # slow path instead of silently losing their session.  last_seen
    # (val column 3) is excluded as before.
    wrote = (
        jnp.all(key1[ins_slot] == new_keys, axis=1)
        & jnp.all(val1[ins_slot][:, :_V_SEEN] == new_vals[:, :_V_SEEN], axis=1)
    )
    committed = can_insert & wrote
    punt = record & ~committed

    # Touch last_seen for reply hits too (keep-alive for the GC sweep).
    # ``max``, not ``set``: several rows of one batch may touch the SAME
    # slot with different per-row timestamps (flat-safe passes a ts
    # vector), and duplicate-index scatter-set resolution order is
    # undefined — max is monotone and order-independent.
    touch = jnp.where(reply_hit, reply_slot, drop_sentinel)
    val2 = val1.at[touch, _V_SEEN].max(timestamp.astype(jnp.uint32), mode="drop")
    return CommitResult(
        sessions=NatSessions(key_tbl=key1, val_tbl=val2),
        punt=punt,
        committed=committed,
        ins_slot=ins_slot,
        reused=committed & has_same,
    )


def nat_commit_sessions(
    sessions: NatSessions,
    orig: PacketBatch,
    rewritten: PacketBatch,
    record: jnp.ndarray,
    reply_hit: jnp.ndarray,
    reply_slot: jnp.ndarray,
    timestamp: jnp.ndarray,
) -> Tuple[NatSessions, jnp.ndarray]:
    """(sessions, punt) view of :func:`nat_commit_sessions_full`."""
    r = nat_commit_sessions_full(
        sessions, orig, rewritten, record, reply_hit, reply_slot, timestamp
    )
    return r.sessions, r.punt


def nat_step(
    tables: NatTables,
    sessions: NatSessions,
    batch: PacketBatch,
    timestamp: jnp.ndarray,
    permit: Optional[jnp.ndarray] = None,
) -> NatResult:
    """One NAT pass over a batch: rewrite + session commit.

    ``permit`` (bool [B]) gates session creation: sessions must only be
    recorded for flows the ACL stages permitted, otherwise a crafted
    "reply" to a denied flow would ride the reflective bypass.  The
    pipeline gates on its combined ACL verdict; standalone use defaults
    to all-permitted.
    """
    rw = nat_rewrite(tables, sessions, batch)
    record = rw.dnat_hit | rw.snat_hit
    if permit is not None:
        record = record & permit
    new_sessions, punt = nat_commit_sessions(
        sessions, batch, rw.batch, record, rw.reply_hit, rw.reply_slot, timestamp
    )
    if tables.has_affinity:  # static gate — compiled in only when used
        aff_record = rw.aff_want & rw.dnat_hit
        if permit is not None:
            aff_record = aff_record & permit
        new_sessions = affinity_commit(
            new_sessions, tables, batch, rw.midx, aff_record,
            rw.batch.dst_ip, rw.batch.dst_port, timestamp,
        )
    return NatResult(
        batch=rw.batch,
        sessions=new_sessions,
        dnat_hit=rw.dnat_hit,
        reply_hit=rw.reply_hit,
        snat_hit=rw.snat_hit,
        punt=punt,
    )


nat_step_jit = jax.jit(nat_step, donate_argnums=(1,))


def session_occupancy(sessions: NatSessions) -> int:
    """Live session count (for /metrics; host-side read)."""
    return int(jnp.sum(sessions.valid))


def sweep_sessions(sessions: NatSessions, now: int, max_age: int) -> NatSessions:
    """Host-side idle-session GC: invalidate entries not seen for
    ``max_age`` batches (the reference's cleanup goroutine analog).
    Affinity entries are excluded — they expire on their own
    per-mapping timeout (:func:`sweep_affinity`)."""
    stale = sessions.valid & ((now - sessions.last_seen) > max_age)
    meta = jnp.where(stale, jnp.uint32(0), sessions.key_tbl[:, _K_META])
    return NatSessions(
        key_tbl=sessions.key_tbl.at[:, _K_META].set(meta),
        val_tbl=sessions.val_tbl,
    )


# ---------------------------------------------------------------------------
# ClientIP affinity (session_affinity_timeout enforcement)
# ---------------------------------------------------------------------------
#
# K8s ``ClientIP`` service affinity pins a client to ONE backend until
# the affinity times out; the pin must survive backend-ring changes
# (that is its whole point — a pure client-IP hash would re-spread
# clients on every endpoint update).  Affinity entries share the
# session table's slots under AFFINITY_FLAG: key = (flag|proto,
# client_ip, ext_ip, ext_port), value = (backend_ip, backend_port,
# mapping_row, last_seen).  The DNAT stage probes them to override its
# hash pick; commits happen AFTER the session commit of the same
# dispatch (free slots are chosen against the post-commit table, so an
# affinity insert can never clobber a just-written session); the HOST
# sweeps expired entries at the per-mapping timeout (reference:
# nat44's affinity timeout, exportDNATMappings/affinity semantics).
# Affinity is deliberately best-effort under pressure: a full bucket
# or a lost intra-batch scatter race falls back to the (deterministic)
# client-IP hash pick — never a punt, never an eviction of a session.


def _affinity_probe(
    sessions: NatSessions, tables: NatTables, batch: PacketBatch,
    midx: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """(match [B, W], cand [B, W], key_rows [B, W, 4]) for the affinity
    key of each row's (client, mapping-external) pair."""
    cap = sessions.capacity
    aff_proto = batch.protocol + jnp.int32(AFFINITY_FLAG)
    ext_ip = tables.map_ext_ip[midx]
    ext_port = tables.map_ext_port[midx]
    h = flow_hash(batch.src_ip, ext_ip, aff_proto,
                  jnp.zeros_like(ext_port), ext_port)
    base = (h & jnp.uint32(cap - 1)).astype(jnp.int32)
    cand = _probe_slots(base, cap)                      # [B, W]
    key_rows = sessions.key_tbl[cand]                   # [B, W, 4]
    match = (
        (key_rows[..., _K_META] == aff_proto.astype(jnp.uint32)[:, None])
        & (key_rows[..., _K_RSRC] == batch.src_ip[:, None])
        & (key_rows[..., _K_RDST] == ext_ip[:, None])
        & (key_rows[..., _K_RPORTS] == _pack_ports(
            jnp.zeros_like(ext_port), ext_port)[:, None])
    )
    return match, cand, key_rows


def affinity_lookup(
    sessions: NatSessions, tables: NatTables, batch: PacketBatch,
    midx: jnp.ndarray, want: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Pinned backend of each row's (client, mapping): ``(aff_hit [B],
    backend_ip [B], backend_port [B])``.  ``want`` masks rows whose
    mapping has affinity enabled (others never probe-hit)."""
    match, cand, _rows = _affinity_probe(sessions, tables, batch, midx)
    match = match & want[:, None]
    hit = jnp.any(match, axis=1)
    w = jnp.argmax(match, axis=1)
    slot = jnp.take_along_axis(cand, w[:, None], axis=1)[:, 0]
    vals = sessions.val_tbl[slot]  # [B, 4]
    return hit, vals[:, _AV_BIP], vals[:, _AV_BPORT].astype(jnp.int32)


def affinity_commit(
    sessions: NatSessions, tables: NatTables, batch: PacketBatch,
    midx: jnp.ndarray, record: jnp.ndarray,
    backend_ip: jnp.ndarray, backend_port: jnp.ndarray,
    timestamp: jnp.ndarray,
) -> NatSessions:
    """Insert/refresh affinity pins for ``record`` rows (dnat-hit rows
    of affinity mappings), pinning the backend each row was ACTUALLY
    sent to this dispatch.  Probes the CURRENT (post-session-commit)
    table so fresh session writes are seen as occupied.  Intra-batch
    duplicate clients write identical content (the hash pick is
    deterministic per client); distinct clients racing for one free
    slot resolve last-writer-wins with the losers silently unpinned —
    they fall back to their deterministic hash pick next dispatch."""
    cap = sessions.capacity
    match, cand, key_rows = _affinity_probe(sessions, tables, batch, midx)
    has_own = jnp.any(match, axis=1)
    w_own = jnp.argmax(match, axis=1)
    free = key_rows[..., _K_META] == 0
    has_free = jnp.any(free, axis=1)
    w_free = jnp.argmax(free, axis=1)
    w_pick = jnp.where(has_own, w_own, w_free)
    slot = jnp.take_along_axis(cand, w_pick[:, None], axis=1)[:, 0]
    can_write = record & (has_own | has_free)
    drop = jnp.int32(cap)
    at = jnp.where(can_write, slot, drop)
    aff_proto = (batch.protocol + jnp.int32(AFFINITY_FLAG)).astype(jnp.uint32)
    ext_ip = tables.map_ext_ip[midx]
    ext_port = tables.map_ext_port[midx]
    new_keys = jnp.stack(
        [aff_proto, batch.src_ip, ext_ip,
         _pack_ports(jnp.zeros_like(ext_port), ext_port)],
        axis=1,
    )
    new_vals = jnp.stack(
        [backend_ip.astype(jnp.uint32),
         backend_port.astype(jnp.uint32),
         midx.astype(jnp.uint32),
         jnp.broadcast_to(timestamp.astype(jnp.uint32), backend_ip.shape)],
        axis=1,
    )
    return NatSessions(
        key_tbl=sessions.key_tbl.at[at].set(new_keys, mode="drop"),
        val_tbl=sessions.val_tbl.at[at].set(new_vals, mode="drop"),
    )


def sweep_affinity(
    sessions: NatSessions, tables: NatTables, now: int, ts_per_second: float
) -> NatSessions:
    """Host-side affinity expiry: clear affinity entries idle longer
    than their mapping's ``session_affinity_timeout`` (seconds),
    converted to timestamp units at the caller's measured rate.  After
    expiry the client re-picks from the CURRENT backend ring — the
    timeout semantic K8s ClientIP affinity requires for rebalancing.

    The pin's mapping is resolved from its KEY row (ext ip/port live in
    _K_RDST/_K_RPORTS, protocol in the meta low byte) against the
    CURRENT tables — never from the _AV_MIDX cached at commit time:
    service-table rebuilds reorder and shrink mapping rows, so a cached
    row index can silently point an idle pin at another mapping's
    timeout (possibly 0 → instant expiry, breaking the stickiness
    guarantee the pin exists to provide).  Pins whose external tuple no
    longer resolves to ANY affinity mapping are dropped outright —
    their service was deleted or lost affinity, so there is nothing
    left to pin (the reference likewise discards nat44 affinity with
    its mapping).  The match deliberately IGNORES ``map_valid``: a
    mapping whose backends transiently emptied (rolling restart)
    compiles valid=False, but its pins must ride out the gap — clients
    re-spreading on an endpoint flap is exactly what ClientIP affinity
    exists to prevent.  Padded rows can never match (their proto is 0;
    pinned protocols are 6/17), so a plain dense compare is safe, and
    at sweep cadence its O(capacity × M) cost is irrelevant."""
    if tables.map_aff_timeout is None:
        return sessions
    key_tbl = sessions.key_tbl
    ext_ip = key_tbl[:, _K_RDST]
    ext_port = (key_tbl[:, _K_RPORTS] & jnp.uint32(0xFFFF)).astype(jnp.int32)
    proto = (key_tbl[:, _K_META] & jnp.uint32(0xFF)).astype(jnp.int32)
    hit = (
        (ext_ip[:, None] == tables.map_ext_ip[None, :])
        & (ext_port[:, None] == tables.map_ext_port[None, :])
        & (proto[:, None] == tables.map_proto[None, :])
        & (tables.map_affinity[None, :] == 1)
    )  # [capacity, M]
    mapped = jnp.any(hit, axis=1)
    midx = jnp.argmax(hit, axis=1)
    timeout_ts = (
        tables.map_aff_timeout[midx].astype(jnp.float32) * ts_per_second
    ).astype(jnp.int32)
    age = now - sessions.val_tbl[:, _AV_SEEN].astype(jnp.int32)
    stale = sessions.aff_valid & (~mapped | (age > timeout_ts))
    meta = jnp.where(stale, jnp.uint32(0), key_tbl[:, _K_META])
    return NatSessions(
        key_tbl=key_tbl.at[:, _K_META].set(meta),
        val_tbl=sessions.val_tbl,
    )


def affinity_occupancy(sessions: NatSessions) -> int:
    """Live affinity-entry count (for /metrics; host-side read)."""
    return int(jnp.sum(sessions.aff_valid))
