"""Incremental InferTable compilation — ship only changed rows.

The PR 2 delta discipline applied to model weights: the builder keeps
host numpy mirrors of the weight tensors and the pod-enrollment slots
across transactions, diffs the new desired state against them, and
ships ONLY the dirty rows to the device through the shared jitted
scatter (:func:`ops.delta.apply_rows`).  A model update — typically a
few retrained ``w1`` rows or a threshold tweak — costs O(changed rows)
of host→device traffic instead of a full weight re-upload, and swaps
into the runner atomically with the ACL/NAT tables under the existing
last-good rollback.

Groups (one scatter program per group, pow2 index buckets):

- ``w1``   — [D, H] f32, row-granular (D = 16 feature rows)
- ``vec``  — b1 + w2 as two same-length [H] arrays, element-granular
- ``pods`` — sorted pod_ip + threshold + action slots, slot-granular

``b2`` is a scalar: re-shipped whole when changed (4 bytes, counted).
Bucket growth/shrink of the pod slots falls back to a full rebuild of
the pod group (counted in ``stats.grows``/``shrinks``), exactly like
the classify pod table.  The first sync is always a full build.

The scheduler's drift verify (tpu_applicators) falls back to the fused
device fingerprint for this table — the weight tensors are tiny (a few
KB), so the host-side wrap-sum bookkeeping the big ACL/NAT builders
maintain would buy nothing here.
"""

from __future__ import annotations

import time
from typing import Any, Dict, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from .classify import POD_PAD_IP, _next_pow2
from .delta import DeltaStats, apply_rows, group_nbytes
from .infer import (
    INFER_ACTION_CODES,
    INFER_FEATURES,
    POD_BUCKET_MIN,
    InferTable,
    build_infer_table,
)

# Scheduler keyspace (mirrors tpu_applicators ACL/NAT prefixes; also
# imported from there so the two never drift).
INFER_PREFIX = "tpu/infer/"
INFER_MODEL_KEY = "tpu/infer/model"
INFER_POD_PREFIX = "tpu/infer/pod/"


def _model_arrays(model: Any) -> Optional[Dict[str, np.ndarray]]:
    """Normalise a model value (dict of nested lists / numpy arrays, or
    an object with .to_dict()) into f32 numpy arrays."""
    if model is None:
        return None
    if hasattr(model, "to_dict"):
        model = model.to_dict()
    w1 = np.asarray(model["w1"], dtype=np.float32)
    b1 = np.asarray(model["b1"], dtype=np.float32)
    w2 = np.asarray(model["w2"], dtype=np.float32)
    if w1.shape[0] != INFER_FEATURES:
        raise ValueError(
            f"model w1 has {w1.shape[0]} feature rows, expected "
            f"{INFER_FEATURES}")
    if not (w1.shape[1] == b1.shape[0] == w2.shape[0]):
        raise ValueError(
            f"inconsistent hidden width: w1 {w1.shape}, b1 {b1.shape}, "
            f"w2 {w2.shape}")
    return {
        "w1": w1, "b1": b1, "w2": w2,
        "b2": np.float32(model["b2"]),
    }


class InferTableBuilder:
    """Persistent incremental compiler for the inference table.

    ``sync(state)`` takes the applicator's keyspace — the model under
    ``tpu/infer/model`` and one ``(pod_ip_u32, threshold, action)``
    tuple per ``tpu/infer/pod/<ns>/<name>`` key (action as a code or a
    name string) — and returns an InferTable whose arrays are patched
    copies of the previous device arrays wherever possible."""

    def __init__(self):
        self.stats = DeltaStats()
        self.last_tables: Optional[InferTable] = None
        # No host-side fingerprint maintenance (see module docstring):
        # the applicator's verify() pays the one fused device reduction.
        self.fingerprint = None
        self._model: Optional[Dict[str, np.ndarray]] = None
        self._pods: Optional[Dict[str, np.ndarray]] = None  # mirrors
        self._live = 0

    # ----------------------------------------------------------- desired

    @staticmethod
    def _desired_slots(state: Dict[str, Any]) -> Dict[int, Tuple[int, int]]:
        out: Dict[int, Tuple[int, int]] = {}
        for key, value in state.items():
            if not key.startswith(INFER_POD_PREFIX) or value is None:
                continue
            ip, thr, act = value
            if isinstance(act, str):
                act = INFER_ACTION_CODES[act]
            out[int(ip)] = (int(thr), int(act))
        return out

    # -------------------------------------------------------------- sync

    def sync(self, state: Dict[str, Any]) -> InferTable:
        t0 = time.perf_counter()
        self.stats.begin_build()
        model = _model_arrays(state.get(INFER_MODEL_KEY))
        bindings = self._desired_slots(state)
        try:
            tables = self._sync_inner(model, bindings)
        finally:
            dt = time.perf_counter() - t0
            self.stats.build_seconds += dt
            self.stats.last_build_seconds = dt
        self.last_tables = tables
        return tables

    def _sync_inner(self, model, bindings) -> InferTable:
        prev = self.last_tables
        if model is None:
            shape_ok = False
        else:
            shape_ok = (
                prev is not None and self._model is not None
                and self._model["w1"].shape == model["w1"].shape
            )
        bucket = _next_pow2(max(len(bindings), 1), POD_BUCKET_MIN)
        if not shape_ok or self._pods is None or \
                bucket != len(self._pods["pod_ip"]):
            return self._full_build(model, bindings, bucket)
        return self._delta_build(model, bindings)

    def _full_build(self, model, bindings, bucket) -> InferTable:
        prev_bucket = len(self._pods["pod_ip"]) if self._pods else 0
        if prev_bucket and bucket > prev_bucket:
            self.stats.grows += 1
        elif prev_bucket and bucket < prev_bucket:
            self.stats.shrinks += 1
        self.stats.full_builds += 1
        tables = build_infer_table(model, bindings)
        self._model = model
        self._pods = {
            "pod_ip": np.asarray(tables.pod_ip),
            "pod_threshold": np.asarray(tables.pod_threshold),
            "pod_action": np.asarray(tables.pod_action),
        }
        self._live = len(bindings)
        nbytes = sum(
            int(np.asarray(a).nbytes)
            for a in (tables.w1, tables.b1, tables.w2, tables.b2,
                      tables.pod_ip, tables.pod_threshold,
                      tables.pod_action)
        ) if model is not None else 0
        rows = (INFER_FEATURES + len(self._pods["pod_ip"])
                if model is not None else 0)
        self.stats.ship(rows, nbytes)
        return tables

    def _delta_build(self, model, bindings) -> InferTable:
        prev = self.last_tables
        self.stats.delta_builds += 1

        # ---- weight groups --------------------------------------------
        w1_dev, b1_dev, w2_dev, b2_dev = prev.w1, prev.b1, prev.w2, prev.b2
        dirty_w1 = np.nonzero(
            (self._model["w1"] != model["w1"]).any(axis=1))[0]
        if len(dirty_w1):
            idx = dirty_w1.astype(np.int32)
            rows = [model["w1"][idx]]
            (w1_dev,) = apply_rows([w1_dev], idx, rows)
            self.stats.ship(len(idx), group_nbytes(idx, rows))
        dirty_vec = np.nonzero(
            (self._model["b1"] != model["b1"])
            | (self._model["w2"] != model["w2"]))[0]
        if len(dirty_vec):
            idx = dirty_vec.astype(np.int32)
            rows = [model["b1"][idx], model["w2"][idx]]
            b1_dev, w2_dev = apply_rows([b1_dev, w2_dev], idx, rows)
            self.stats.ship(len(idx), group_nbytes(idx, rows))
        if self._model["b2"] != model["b2"]:
            b2_dev = jnp.asarray(model["b2"])
            self.stats.ship(1, 4)

        # ---- pod slots (canonical sorted layout, diffed per slot) -----
        bucket = len(self._pods["pod_ip"])
        pod_ip = np.full(bucket, POD_PAD_IP, dtype=np.uint32)
        pod_thr = np.zeros(bucket, dtype=np.int32)
        pod_act = np.zeros(bucket, dtype=np.int32)
        for i, ip in enumerate(sorted(bindings)):
            thr, act = bindings[ip]
            pod_ip[i] = ip
            pod_thr[i] = thr
            pod_act[i] = act
        ip_dev, thr_dev, act_dev = \
            prev.pod_ip, prev.pod_threshold, prev.pod_action
        dirty_p = np.nonzero(
            (self._pods["pod_ip"] != pod_ip)
            | (self._pods["pod_threshold"] != pod_thr)
            | (self._pods["pod_action"] != pod_act))[0]
        if len(dirty_p):
            idx = dirty_p.astype(np.int32)
            rows = [pod_ip[idx], pod_thr[idx], pod_act[idx]]
            ip_dev, thr_dev, act_dev = apply_rows(
                [ip_dev, thr_dev, act_dev], idx, rows)
            self.stats.ship(len(idx), group_nbytes(idx, rows))

        self._model = model
        self._pods = {
            "pod_ip": pod_ip, "pod_threshold": pod_thr,
            "pod_action": pod_act,
        }
        self._live = len(bindings)
        return InferTable(
            w1=w1_dev, b1=b1_dev, w2=w2_dev, b2=b2_dev,
            pod_ip=ip_dev, pod_threshold=thr_dev, pod_action=act_dev,
            num_pods=len(bindings),
            enabled=bool(bindings),
        )
