"""ACL classify — rule-table compilation and first-match evaluation.

The TPU replacement for VPP's ``acl-plugin-in/out-ip4-fa`` graph nodes
(SURVEY.md §2.3): ContivRule tables compile into padded
struct-of-arrays tensors, and a jit-compiled kernel evaluates a packet
batch against *all* rules at once — a [B, N] predicate matrix — then
reduces to the first matching rule per (packet, side-table) with an
argmax.  Linear-priority first-match becomes a data-parallel reduction
instead of VPP's per-packet loop.

Semantics are pinned to the oracle (vpp_tpu/testing/aclengine.py,
itself pinned to mock/aclengine/aclengine_mock.go): a packet must pass
the *ingress* table of its source pod (what the pod may send) and the
*egress* table of its destination pod (what may reach it); a pod
without tables (or non-pod traffic) passes by default; an empty table
allows everything (compiled as one synthetic permit-all rule); in a
non-empty table the first match decides and no-match denies.

Static-shape discipline: the rule tensor is padded to the next
power-of-two bucket.  Table-content changes swap device arrays without
recompiling; only a bucket-size change triggers a new XLA compile.
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import PodID
from ..policy.renderer.api import Action, ContivRule
from .packets import PacketBatch, ip_to_u32

# Action encoding in the tensor.
_DENY = 0
_PERMIT = 1
_PERMIT_REFLECT = 2

# Table-id sentinel: "no table attached" -> side passes by default.
NO_TABLE = -1


@dataclass
class RuleTables:
    """Compiled rule state for one node's data plane.

    ``rules_*`` hold every table's rules concatenated ([N], padded);
    ``rule_tid`` maps each rule row to its table; ``pod_*`` map pod IPs
    to their (ingress, egress) table ids.  All jnp arrays — ready to be
    donated to the classify kernel.
    """

    # Rules (concatenated over all tables, padded to a pow2 bucket).
    rule_valid: jnp.ndarray     # bool  [N]
    rule_tid: jnp.ndarray       # int32 [N]
    rule_src_base: jnp.ndarray  # uint32 [N]
    rule_src_mask: jnp.ndarray  # uint32 [N]
    rule_dst_base: jnp.ndarray  # uint32 [N]
    rule_dst_mask: jnp.ndarray  # uint32 [N]
    rule_proto: jnp.ndarray     # int32 [N] (0 = ANY)
    rule_src_port: jnp.ndarray  # int32 [N] (0 = any)
    rule_dst_port: jnp.ndarray  # int32 [N] (0 = any)
    rule_action: jnp.ndarray    # int32 [N]

    # Pod IP -> table ids ([P], padded with unmatchable IPs).
    pod_ip: jnp.ndarray          # uint32 [P]
    pod_ingress_tid: jnp.ndarray  # int32 [P]
    pod_egress_tid: jnp.ndarray   # int32 [P]

    num_rules: int = 0
    num_tables: int = 0
    num_pods: int = 0

    def tree_flatten(self):
        children = (
            self.rule_valid, self.rule_tid,
            self.rule_src_base, self.rule_src_mask,
            self.rule_dst_base, self.rule_dst_mask,
            self.rule_proto, self.rule_src_port, self.rule_dst_port,
            self.rule_action,
            self.pod_ip, self.pod_ingress_tid, self.pod_egress_tid,
        )
        aux = (self.num_rules, self.num_tables, self.num_pods)
        return children, aux

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, num_rules=aux[0], num_tables=aux[1], num_pods=aux[2])


jax.tree_util.register_pytree_node(
    RuleTables, RuleTables.tree_flatten, RuleTables.tree_unflatten
)


def _prefix_mask(net: Optional[ipaddress.IPv4Network]) -> Tuple[int, int]:
    """(base, mask) for a network; match-all -> (0, 0)."""
    if net is None:
        return 0, 0
    mask = (0xFFFFFFFF << (32 - net.prefixlen)) & 0xFFFFFFFF if net.prefixlen else 0
    return int(net.network_address) & mask, mask


_PERMIT_ALL = ContivRule(action=Action.PERMIT)

# Pod-slot padding IP (255.255.255.255 — never a pod IP; keeps the
# sorted binary search well-defined past the live slots).
POD_PAD_IP = 0xFFFFFFFF

_ACTION_CODE = {
    Action.DENY: _DENY,
    Action.PERMIT: _PERMIT,
    Action.PERMIT_REFLECT: _PERMIT_REFLECT,
}


def rule_fields(rule: ContivRule) -> Tuple[int, int, int, int, int, int, int, int]:
    """One rule's tensor row sans table id: (src_base, src_mask,
    dst_base, dst_mask, proto, src_port, dst_port, action).  Shared by
    the full build and the incremental builder (classify_delta) so the
    two encode bit-identically by construction."""
    src_base, src_mask = _prefix_mask(rule.src_network)
    dst_base, dst_mask = _prefix_mask(rule.dst_network)
    return (
        src_base, src_mask, dst_base, dst_mask,
        int(rule.protocol), rule.src_port, rule.dst_port,
        _ACTION_CODE[rule.action],
    )


def _next_pow2(n: int, minimum: int = 8) -> int:
    """Shared static-shape bucketing policy for ACL and NAT tables:
    pad to the next power of two so XLA compiles one program per bucket."""
    size = minimum
    while size < n:
        size *= 2
    return size


def build_rule_tables(
    tables: Sequence[Sequence[ContivRule]],
    pod_assignments: Dict[int, Tuple[int, int]],
    bucket_min: int = 8,
) -> RuleTables:
    """Compile rule tables + pod assignments to tensors.

    ``tables[t]`` is the ordered rule list of table id ``t`` (empty
    tables become one permit-all rule so that the uniform
    "no-match = deny" kernel rule preserves allow-by-default).
    ``pod_assignments`` maps pod IP (u32) -> (ingress_tid, egress_tid),
    either of which may be NO_TABLE.
    """
    rows: List[Tuple] = []
    for tid, table in enumerate(tables):
        rules = list(table) if table else [_PERMIT_ALL]
        for rule in rules:
            rows.append((tid,) + rule_fields(rule))

    n = len(rows)
    padded = _next_pow2(max(n, 1), bucket_min)
    arr = np.zeros((padded, 9), dtype=np.int64)
    if rows:
        arr[:n] = np.asarray(rows, dtype=np.int64)
    valid = np.zeros(padded, dtype=bool)
    valid[:n] = True

    pods = sorted(pod_assignments.items())
    p = len(pods)
    p_padded = _next_pow2(max(p, 1), bucket_min)
    # Sorted ascending with 255.255.255.255 padding (never a pod IP), so
    # the lookup is a binary search instead of a dense [B, P] compare.
    pod_ip = np.full(p_padded, POD_PAD_IP, dtype=np.uint32)
    pod_in = np.full(p_padded, NO_TABLE, dtype=np.int32)
    pod_eg = np.full(p_padded, NO_TABLE, dtype=np.int32)
    for i, (ip, (in_tid, eg_tid)) in enumerate(pods):
        pod_ip[i] = ip
        pod_in[i] = in_tid
        pod_eg[i] = eg_tid

    return RuleTables(
        rule_valid=jnp.asarray(valid),
        rule_tid=jnp.asarray(arr[:, 0].astype(np.int32)),
        rule_src_base=jnp.asarray(arr[:, 1].astype(np.uint32)),
        rule_src_mask=jnp.asarray(arr[:, 2].astype(np.uint32)),
        rule_dst_base=jnp.asarray(arr[:, 3].astype(np.uint32)),
        rule_dst_mask=jnp.asarray(arr[:, 4].astype(np.uint32)),
        rule_proto=jnp.asarray(arr[:, 5].astype(np.int32)),
        rule_src_port=jnp.asarray(arr[:, 6].astype(np.int32)),
        rule_dst_port=jnp.asarray(arr[:, 7].astype(np.int32)),
        rule_action=jnp.asarray(arr[:, 8].astype(np.int32)),
        pod_ip=jnp.asarray(pod_ip),
        pod_ingress_tid=jnp.asarray(pod_in),
        pod_egress_tid=jnp.asarray(pod_eg),
        num_rules=n,
        num_tables=len(tables),
        num_pods=p,
    )


class Verdicts(NamedTuple):
    """Classify output for a batch."""

    allowed: jnp.ndarray       # bool [B] - passed both sides
    src_action: jnp.ndarray    # int32 [B] - action on the source side
    dst_action: jnp.ndarray    # int32 [B] - action on the destination side


def _lookup_tid(ip: jnp.ndarray, pod_ip: jnp.ndarray, tid: jnp.ndarray) -> jnp.ndarray:
    """Per-packet pod-table lookup: binary search of the sorted pod-IP
    array — [B]·log2(P) instead of the dense [B, P] compare that
    dominated at thousands of pods; NO_TABLE when the IP is not a local
    pod."""
    idx = jnp.searchsorted(pod_ip, ip)
    idx = jnp.minimum(idx, pod_ip.shape[0] - 1)
    return jnp.where(pod_ip[idx] == ip, tid[idx], NO_TABLE)


def _first_match_action(
    match: jnp.ndarray, rule_tid: jnp.ndarray, rule_action: jnp.ndarray, side_tid: jnp.ndarray
) -> jnp.ndarray:
    """First matching rule's action within the packet's side table;
    DENY when nothing matches; PERMIT when the side has no table."""
    in_table = match & (rule_tid[None, :] == side_tid[:, None])   # [B, N]
    has = jnp.any(in_table, axis=1)
    first = jnp.argmax(in_table, axis=1)
    action = jnp.where(has, rule_action[first], _DENY)
    return jnp.where(side_tid == NO_TABLE, _PERMIT, action)


# Above this rule count the dense [B, N] matrix is replaced by the
# Pallas-tiled kernel (TPU only; shapes must align to its tiles).
PALLAS_MIN_RULES = 4096
# ...but only for wide dispatches: measured on v5e at 64k rules, the
# tiled kernel wins at B>=4096 flat batches (135 vs 86 Mpps/side) while
# the dense path wins inside 256-wide scan vectors (the per-step grid
# overhead dominates when the B tile dimension collapses to 1).
PALLAS_MIN_BATCH = 1024


def _pallas_eligible(tables: RuleTables, batch: PacketBatch) -> bool:
    import os

    from .classify_pallas import TILE_B, TILE_N

    n = tables.rule_valid.shape[0]
    b = batch.src_ip.shape[0]
    return (
        jax.default_backend() == "tpu"
        and not os.environ.get("VPP_TPU_FORCE_DENSE")  # bench A/B switch
        and n >= PALLAS_MIN_RULES
        and b >= PALLAS_MIN_BATCH
        and n % TILE_N == 0
        and b % TILE_B == 0
    )


def _side_action(tables: RuleTables, batch: PacketBatch, side_tid: jnp.ndarray) -> jnp.ndarray:
    """First-match action for one ACL side, choosing the dense-XLA or
    Pallas-tiled evaluation by table size and backend (a trace-time,
    static decision).  Both branches produce the raw first-match action;
    the NO_TABLE pass-by-default override applies once at the end."""
    if _pallas_eligible(tables, batch):
        from .classify_pallas import _NO_MATCH, first_match_index_pallas

        best = first_match_index_pallas(tables, batch, side_tid)
        found = best != _NO_MATCH
        action = jnp.where(
            found, tables.rule_action[jnp.where(found, best, 0)], _DENY
        )
    else:
        match = match_matrix(tables, batch)
        in_table = match & (tables.rule_tid[None, :] == side_tid[:, None])
        has = jnp.any(in_table, axis=1)
        first = jnp.argmax(in_table, axis=1)
        action = jnp.where(has, tables.rule_action[first], _DENY)
    return jnp.where(side_tid == NO_TABLE, _PERMIT, action)


def match_matrix(tables: RuleTables, batch: PacketBatch) -> jnp.ndarray:
    """The [B, N] all-rules predicate matrix."""
    src_ok = (batch.src_ip[:, None] & tables.rule_src_mask[None, :]) == tables.rule_src_base[None, :]
    dst_ok = (batch.dst_ip[:, None] & tables.rule_dst_mask[None, :]) == tables.rule_dst_base[None, :]
    proto_any = tables.rule_proto[None, :] == 0
    proto_ok = batch.protocol[:, None] == tables.rule_proto[None, :]
    sport_ok = (tables.rule_src_port[None, :] == 0) | (
        batch.src_port[:, None] == tables.rule_src_port[None, :]
    )
    dport_ok = (tables.rule_dst_port[None, :] == 0) | (
        batch.dst_port[:, None] == tables.rule_dst_port[None, :]
    )
    l4_ok = proto_any | (proto_ok & sport_ok & dport_ok)
    return tables.rule_valid[None, :] & src_ok & dst_ok & l4_ok


def classify_src(tables: RuleTables, batch: PacketBatch) -> jnp.ndarray:
    """Source-side (pod ingress table) action only — the pipeline's
    pre-NAT ACL stage; [B] int32 actions."""
    src_tid = _lookup_tid(batch.src_ip, tables.pod_ip, tables.pod_ingress_tid)
    return _side_action(tables, batch, src_tid)


def classify_dst(tables: RuleTables, batch: PacketBatch) -> jnp.ndarray:
    """Destination-side (pod egress table) action only — the pipeline's
    post-NAT ACL stage; [B] int32 actions."""
    dst_tid = _lookup_tid(batch.dst_ip, tables.pod_ip, tables.pod_egress_tid)
    return _side_action(tables, batch, dst_tid)


def classify(tables: RuleTables, batch: PacketBatch) -> Verdicts:
    """The ACL stage. jit-compatible; [B] batch vs [N] rules."""
    src_action = classify_src(tables, batch)
    dst_action = classify_dst(tables, batch)
    allowed = (src_action != _DENY) & (dst_action != _DENY)
    return Verdicts(allowed=allowed, src_action=src_action, dst_action=dst_action)


classify_jit = jax.jit(classify)
