"""The full data-plane step: ACL -> NAT -> routing, in VPP node order.

One jit-compiled program per batch-size/table-bucket combination,
implementing the reference's per-packet pipeline ordering
(docs/dev-guide/SERVICES.md:300-307):

    ingress ACL  ->  nat44 out2in (reply restore + DNAT)  ->
    ip4 routing  ->  nat44 in2out (SNAT)  ->  egress ACL

- The ingress ACL (source pod's table) sees the *original* headers;
  the egress ACL (destination pod's table) sees the *rewritten* ones —
  exactly how VPP orders `acl-plugin-in-ip4-fa` before nat44 and
  `acl-plugin-out-ip4-fa` after it.
- Routing is node-ID arithmetic (plugins/ipam dissection inverted):
  the post-NAT destination resolves to LOCAL (this node's pod subnet),
  REMOTE (another node's chunk of the cluster pod subnet, yielding the
  node ID for VXLAN encap by the host shim), or HOST/external.
- Reflective-ACL semantics ride the NAT session table: reply packets
  restored from a session skip the ACL stages.  Session creation is
  gated on the ACL verdict, so a session exists only when the forward
  direction was actually permitted — the analog of the reference's
  reflective ACL on permitted flows.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple

import jax
import jax.numpy as jnp

from .classify import RuleTables, _DENY, classify_dst, classify_src
from .nat import (
    _K_META,
    _V_ODST,
    _V_OPORTS,
    _V_OSRC,
    _V_SEEN,
    WRITE_TAG,
    NatSessions,
    NatTables,
    affinity_commit,
    combine_rewrite,
    nat_commit_sessions,
    nat_commit_sessions_full,
    nat_reply_probe,
    nat_reply_restore,
    nat_rewrite,
    nat_rewrite_stateless,
)
from .packets import PacketBatch

# Route tags.
ROUTE_DROP = 0
ROUTE_LOCAL = 1    # deliver to a pod on this node
ROUTE_REMOTE = 2   # VXLAN-encap to another node (see node_id)
ROUTE_HOST = 3     # hand to the host stack / external uplink


@dataclass
class RouteConfig:
    """Node-ID routing arithmetic (device scalars)."""

    pod_subnet_base: jnp.ndarray    # uint32 [] cluster pod subnet base
    pod_subnet_mask: jnp.ndarray    # uint32 []
    this_node_base: jnp.ndarray     # uint32 [] this node's pod subnet base
    this_node_mask: jnp.ndarray     # uint32 []
    host_bits: jnp.ndarray          # int32 [] bits of per-node subnet

    def tree_flatten(self):
        return (
            (
                self.pod_subnet_base, self.pod_subnet_mask,
                self.this_node_base, self.this_node_mask, self.host_bits,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    RouteConfig, RouteConfig.tree_flatten, RouteConfig.tree_unflatten
)


def make_route_config(ipam) -> RouteConfig:
    """Build routing scalars from an IPAM instance."""
    import ipaddress

    all_net = ipam.pod_subnet_all_nodes
    this_net = ipam.pod_subnet_this_node
    all_mask = (0xFFFFFFFF << (32 - all_net.prefixlen)) & 0xFFFFFFFF
    this_mask = (0xFFFFFFFF << (32 - this_net.prefixlen)) & 0xFFFFFFFF
    return RouteConfig(
        pod_subnet_base=jnp.asarray(int(all_net.network_address), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(all_mask, dtype=jnp.uint32),
        this_node_base=jnp.asarray(int(this_net.network_address), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(this_mask, dtype=jnp.uint32),
        host_bits=jnp.asarray(32 - this_net.prefixlen, dtype=jnp.int32),
    )


class PipelineResult(NamedTuple):
    batch: PacketBatch      # rewritten headers
    sessions: NatSessions   # updated NAT session table
    allowed: jnp.ndarray    # bool [B]
    route: jnp.ndarray      # int32 [B] ROUTE_* tag (DROP when denied)
    node_id: jnp.ndarray    # int32 [B] destination node for ROUTE_REMOTE
    dnat_hit: jnp.ndarray   # bool [B]
    snat_hit: jnp.ndarray   # bool [B]
    reply_hit: jnp.ndarray  # bool [B]
    punt: jnp.ndarray       # bool [B] flow needs the host slow path


def _route_tags(route: RouteConfig, dst: jnp.ndarray, allowed: jnp.ndarray):
    """Node-ID routing arithmetic on post-NAT destinations:
    (ROUTE_* tag [B], destination node id [B])."""
    in_cluster = (dst & route.pod_subnet_mask) == route.pod_subnet_base
    on_this_node = (dst & route.this_node_mask) == route.this_node_base
    tag = jnp.where(
        on_this_node,
        ROUTE_LOCAL,
        jnp.where(in_cluster, ROUTE_REMOTE, ROUTE_HOST),
    )
    tag = jnp.where(allowed, tag, ROUTE_DROP)
    node_id = jnp.where(
        in_cluster & ~on_this_node,
        ((dst - route.pod_subnet_base) >> route.host_bits.astype(jnp.uint32)).astype(jnp.int32),
        jnp.int32(0),
    )
    return tag, node_id


def _commit_and_route(
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batch: PacketBatch,
    rw,
    acl_ok: jnp.ndarray,
    timestamp: jnp.ndarray,
):
    """Shared tail of both disciplines: ACL/reply gating, session
    commit, affinity-pin commit, and node-ID routing.  Returns
    (new_sessions, result) with ``result.sessions`` left as a
    placeholder scalar — the caller decides whether it carries the
    table (flat) or the scan threads it.
    """
    rewritten = rw.batch
    # Session-restored replies skip ACLs (reflective semantics — valid
    # precisely because only permitted flows ever record sessions).
    allowed = acl_ok | rw.reply_hit

    # Commit sessions for translated AND permitted flows only: a denied
    # flow must never seed a session a crafted "reply" could ride.
    record = (rw.dnat_hit | rw.snat_hit) & allowed
    new_sessions, punt = nat_commit_sessions(
        sessions, batch, rewritten, record, rw.reply_hit, rw.reply_slot, timestamp
    )
    if nat.has_affinity:  # static gate — compiled in only when used
        new_sessions = affinity_commit(
            new_sessions, nat, batch, rw.midx,
            rw.aff_want & allowed, rewritten.dst_ip, rewritten.dst_port,
            timestamp,
        )

    # Routing on the post-NAT destination.
    tag, node_id = _route_tags(route, rewritten.dst_ip, allowed)

    result = PipelineResult(
        batch=rewritten,
        sessions=jnp.int32(0),
        allowed=allowed,
        route=tag,
        node_id=node_id,
        dnat_hit=rw.dnat_hit,
        snat_hit=rw.snat_hit,
        reply_hit=rw.reply_hit,
        punt=punt,
    )
    return new_sessions, result


def pipeline_step(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batch: PacketBatch,
    timestamp: jnp.ndarray,
) -> PipelineResult:
    """One batch through the whole data plane."""
    # 1. Ingress ACL on original headers (source pod's table).
    src_action = classify_src(acl, batch)

    # 2. NAT translation: reply restore -> DNAT LB -> SNAT (no session
    # writes yet — those are gated on the full ACL verdict below).
    rw = nat_rewrite(nat, sessions, batch)

    # 3. Egress ACL on rewritten headers (destination pod's table).
    dst_action = classify_dst(acl, rw.batch)
    acl_ok = (src_action != _DENY) & (dst_action != _DENY)

    new_sessions, result = _commit_and_route(
        nat, route, sessions, batch, rw, acl_ok, timestamp
    )
    return result._replace(sessions=new_sessions)


pipeline_step_jit = jax.jit(pipeline_step, donate_argnums=(3,))


# VPP's vector size: the dataplane's native unit of work.  The runner
# assembles frames into 256-packet vectors and dispatches K of them per
# device program (SURVEY §6: "VPP processes packets in up-to-256-packet
# vectors").
VECTOR_SIZE = 256


def pipeline_scan(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batches: PacketBatch,      # leaves shaped [K, V]
    timestamps: jnp.ndarray,   # int32 [K]
) -> PipelineResult:
    """K packet vectors through the pipeline in ONE device dispatch.

    Only the session-table stages are sequential: ``lax.scan`` threads
    the NAT table from vector to vector *on device* (a flow's session
    created in vector i is visible to its replies in vector i+1 —
    VPP's sequential-vector semantics).  Everything session-INDEPENDENT
    — both ACL classifies and the stateless DNAT/SNAT rewrite — is
    hoisted OUT of the scan and computed flat over all K·V packets at
    once, so the classify stage runs at wide-batch efficiency (MXU
    tiling, the Pallas first-match kernel's preferred shapes) instead
    of re-streaming the rule tables once per 256-packet vector.  At 64k
    rules that re-streaming made the scan dispatch 3x slower than a
    flat one (BENCHSCALE_r02); hoisting closes the gap while keeping
    the scan's session semantics bit-identical (reply rows bypass the
    ACL by the reflective rule, and their stateless rewrite is masked —
    see ``combine_rewrite``).

    Correctness note: the egress ACL is evaluated on the STATELESS
    rewrite of each packet.  That matches the fused per-vector step for
    every row because the only rows whose true rewrite differs (reply
    restores) never consult the ACL — ``allowed = acl_ok | reply_hit``.

    Returned leaves are stacked [K, V]; ``sessions`` is the final table.
    """
    k, v = batches.src_ip.shape

    def flatten(a):
        return a.reshape((k * v,) + a.shape[2:])

    def unflatten(a):
        return a.reshape((k, v) + a.shape[1:])

    flat = jax.tree_util.tree_map(flatten, batches)

    # ---- flat prepass: ingress ACL, stateless NAT, egress ACL --------
    src_action = classify_src(acl, flat)
    stateless = nat_rewrite_stateless(nat, flat, sessions)
    dst_action = classify_dst(acl, stateless.batch)
    acl_ok = (src_action != _DENY) & (dst_action != _DENY)

    per_vec = (
        batches,
        jax.tree_util.tree_map(unflatten, stateless),
        unflatten(acl_ok),
        timestamps,
    )

    # ---- sequential session stage ------------------------------------
    def body(sess, xs):
        batch, sless, ok, ts = xs
        rw = combine_rewrite(nat_reply_restore(sess, batch), sless)
        return _commit_and_route(nat, route, sess, batch, rw, ok, ts)

    final_sessions, stacked = jax.lax.scan(body, sessions, per_vec)
    return stacked._replace(sessions=final_sessions)


pipeline_scan_jit = jax.jit(pipeline_scan, donate_argnums=(3,))


def pipeline_flat_safe(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batches: PacketBatch,      # leaves shaped [K, V]
    timestamps: jnp.ndarray,   # int32 [K]
) -> PipelineResult:
    """All K·V packets through the pipeline in ONE flat pass — with the
    scan's same-dispatch reply semantics recovered by a post-commit
    re-probe instead of a sequential ``lax.scan``.

    The plain flat step (``pipeline_step``) mistranslates a reply that
    arrives in the same dispatch as its forward packet: the restore
    probe sees the PRE-dispatch table, misses, and the packet sails on
    as if it were a fresh flow.  The scan discipline fixes that by
    threading sessions vector-to-vector, paying a sequential stage that
    costs ~25-45% of the dispatch (BENCHSWEEP: 97 vs 72 Mpps at 16k
    packets, 428 vs 238 at 64k).  This discipline keeps every stage
    batch-parallel and instead reconciles in three bounded, data-
    independent passes:

    1. flat classify + stateless NAT + restore against the pre-table +
       gated session commit (exactly ``pipeline_step``);
    2. re-probe every row's ORIGINAL tuple against the committed
       table.  A row that now matches someone else's session — not the
       one it wrote itself — is a *straggler*: a reply whose forward
       flow sits earlier in this dispatch.  Stragglers that committed a
       session in pass 1 wrote a BOGUS forward session (they are
       replies, not new flows): invalidate exactly those slots — safe,
       because the post-write verify proved each committed row owns its
       slot's content;
    3. re-probe stragglers against the cleaned table: a hit restores
       the reply (headers, reflective-ACL bypass, keep-alive touch,
       dnat/snat flags cleared, route recomputed) precisely as the next
       dispatch would have; a miss means the row only ever matched
       another straggler's bogus entry (craftable aliasing, never
       organic traffic) — forward it per its pass-1 rewrite and PUNT so
       the host slow path records the authoritative session.

    Semantics vs the scan: a superset of restores (the scan restores a
    reply only when its forward ran in an EARLIER vector; this pass
    also restores same-vector and reply-before-forward orderings, both
    of which the scan would restore one dispatch later anyway), the
    same commit-race punts, and the same ACL gating.  A/B-tested
    against the scan and the sequential oracle in tests/test_pipeline.py.

    COMMIT-FIRST layout (r4): the session stages are gather-bound on
    TPU, so the discipline is arranged to touch the table as little as
    possible.  Two facts make a pre-commit restore probe unnecessary:
    (a) valid slots hold UNIQUE keys (inserts reuse a same-key slot or
    punt; intra-batch racers lose the scatter and punt), and (b) a
    fresh insert's key can never equal a pre-existing key (same key +
    same orig would have REUSED the slot; same key + different orig
    punts as a collision).  Therefore ONE probe of the post-commit
    table, split by a this-batch written mask, classifies every row in
    a single pass: a match on an unwritten slot is an organic reply to
    a pre-dispatch session; a match on a written slot is a straggler
    (its forward flow sits in this very dispatch) — the two are
    mutually exclusive.  Commit therefore runs FIRST, on the stateless
    rewrite (identical bytes for every row that can record — reply
    rows' stateless DNAT/SNAT hits are rare and their bogus sessions
    are undone, exactly like stragglers' always were).  vs the r3
    layout this deletes the full pre-table key+value restore probe
    ([B,W,4]+[B,4] random rows) — the session stage is now two key
    probes total (insert-side + restore-side), the same count as the
    UNSAFE flat step.
    """
    k, v = batches.src_ip.shape

    def flatten(a):
        return a.reshape((k * v,) + a.shape[2:])

    flat = jax.tree_util.tree_map(flatten, batches)
    ts_rows = jnp.repeat(timestamps, v)
    b = k * v
    cap = sessions.capacity
    cap_sentinel = jnp.int32(cap)

    # ---- pass 1: session-independent compute ------------------------
    src_action = classify_src(acl, flat)
    stateless = nat_rewrite_stateless(nat, flat, sessions)
    dst_action = classify_dst(acl, stateless.batch)
    acl_ok = (src_action != _DENY) & (dst_action != _DENY)

    # ---- pass 2: commit (insert-side probe) -------------------------
    # Keep-alive touches for restored replies are deferred to pass 4
    # (reply_hit=False here); scatter-max is order-independent.
    no_reply = jnp.zeros(b, dtype=bool)
    record0 = (stateless.dnat_hit | stateless.snat_hit) & acl_ok
    commit = nat_commit_sessions_full(
        sessions, flat, stateless.batch, record0, no_reply,
        jnp.zeros(b, dtype=jnp.int32), ts_rows, tag_writes=True,
    )

    # ---- pass 3: the ONE restore-side probe -------------------------
    # tag_writes marked this batch's writes in the meta word, so the
    # probe's own gathered rows split the matches — no separate
    # written-mask table (the session stages are bound by the COUNT of
    # small random-access ops, so every eliminated scatter/gather chain
    # is throughput).
    km2, cand2, meta2 = nat_reply_probe(commit.sessions, flat)
    wm = (meta2 & jnp.uint32(WRITE_TAG)) != 0           # [B, W]
    km_pre = km2 & ~wm        # matches against pre-dispatch sessions
    km_new = km2 & wm         # matches against this batch's writes
    # Valid slots hold unique keys, so km2 has at most ONE true way —
    # km_pre and km_new are mutually exclusive per row and the argmax
    # selections below are all over singleton sets.
    reply_pre = jnp.any(km_pre, axis=1)
    hit2 = jnp.any(km2, axis=1)
    w2 = jnp.argmax(km2, axis=1)
    slot2 = jnp.take_along_axis(cand2, w2[:, None], axis=1)[:, 0]
    own_write = commit.committed & (slot2 == commit.ins_slot)
    straggler = hit2 & ~reply_pre & ~own_write

    # Undo bogus forward sessions: any FRESH commit by a row that is
    # itself a reply (organic or straggler).  Reused slots are legit
    # pre-existing sessions being refreshed — clearing those would
    # destroy real state, so they are excluded (crafted corners only;
    # organic replies never DNAT/SNAT-hit and so never commit).
    # ONE finalize scatter serves undo AND tag clearing: every
    # committed row's slot gets its final meta (0 when undone, the
    # bare protocol otherwise).
    undo_rows = commit.committed & ~commit.reused & (reply_pre | straggler)
    fin_slot = jnp.where(commit.committed, commit.ins_slot, cap_sentinel)
    fin_meta = jnp.where(
        undo_rows, jnp.uint32(0), flat.protocol.astype(jnp.uint32)
    )
    sessions2 = NatSessions(
        key_tbl=commit.sessions.key_tbl.at[fin_slot, _K_META].set(
            fin_meta, mode="drop"
        ),
        val_tbl=commit.sessions.val_tbl,
    )

    # ---- pass 4: restores against the finalized table ---------------
    # A straggler's single matched slot may be another straggler's
    # undone bogus write — one scalar meta gather at the selected slot
    # re-checks validity (organic replies matched unwritten slots,
    # which the finalize scatter never clears).
    slot_pre = slot2  # singleton match: the km2 selection IS the slot
    rslot = jnp.where(reply_pre, slot_pre, slot2)
    meta_chk = sessions2.key_tbl[rslot, _K_META]        # [B]
    restored_strag = straggler & (meta_chk != 0)
    reply_final = reply_pre | restored_strag
    vals3 = sessions2.val_tbl[rslot]  # [B, 4] — one row per restore
    touch = jnp.where(reply_final, rslot, cap_sentinel)
    # max, not set: duplicate slots with differing per-row timestamps
    # (two restored replies to one session) scatter in undefined order.
    sessions3 = NatSessions(
        key_tbl=sessions2.key_tbl,
        val_tbl=sessions2.val_tbl.at[touch, _V_SEEN].max(
            ts_rows.astype(jnp.uint32), mode="drop"
        ),
    )
    if nat.has_affinity:  # static gate — compiled in only when used
        sessions3 = affinity_commit(
            sessions3, nat, flat, stateless.midx,
            stateless.aff_want & acl_ok & ~reply_final,
            stateless.batch.dst_ip, stateless.batch.dst_port, ts_rows,
        )

    def merge(a, b_):
        return jnp.where(reply_final, a, b_)

    # Restore mapping as in nat_reply_restore: src <- original dst
    # (VIP), dst <- original src (client), ports likewise (unpacked
    # from the packed-ports word of the selected value row).
    op3 = vals3[:, _V_OPORTS]
    final_batch = PacketBatch(
        src_ip=merge(vals3[:, _V_ODST], stateless.batch.src_ip),
        dst_ip=merge(vals3[:, _V_OSRC], stateless.batch.dst_ip),
        protocol=flat.protocol,
        src_port=merge((op3 & jnp.uint32(0xFFFF)).astype(jnp.int32),
                       stateless.batch.src_port),
        dst_port=merge((op3 >> jnp.uint32(16)).astype(jnp.int32),
                       stateless.batch.dst_port),
    )
    allowed_final = acl_ok | reply_final
    punt_final = (commit.punt & ~reply_final) | (straggler & ~restored_strag)
    tag, node_id = _route_tags(route, final_batch.dst_ip, allowed_final)

    def unflatten(a):
        return a.reshape((k, v) + a.shape[1:])

    return PipelineResult(
        batch=jax.tree_util.tree_map(unflatten, final_batch),
        sessions=sessions3,
        allowed=unflatten(allowed_final),
        route=unflatten(tag),
        node_id=unflatten(node_id),
        dnat_hit=unflatten(stateless.dnat_hit & ~reply_final),
        snat_hit=unflatten(stateless.snat_hit & ~reply_final),
        reply_hit=unflatten(reply_final),
        punt=unflatten(punt_final),
    )


pipeline_flat_safe_jit = jax.jit(pipeline_flat_safe, donate_argnums=(3,))


def _with_ts0(fn):
    """Wrap a [K, V] discipline to take a SCALAR base timestamp and
    derive the per-vector ts inside the program, returning [K·V]-flat
    leaves.  The host-side ``jnp.arange`` the raw signatures require is
    an extra tiny device-array creation per dispatch — on a remote-TPU
    tunnel that is one more round trip, measured at a 40-100% tax on
    the whole 16k-packet dispatch (r4: it was misattributed to the
    session stages for a full round).  Vector i gets ts0 + 1 + i."""

    def stepped(acl, nat, route, sessions, batches, ts0):
        k = batches.src_ip.shape[0]
        tss = ts0 + jnp.arange(1, k + 1, dtype=jnp.int32)
        return flatten_scan_result(fn(acl, nat, route, sessions, batches, tss))

    return stepped


# Production entry points: scalar base-ts in, flat leaves out (the
# runner consumes flat [K·V] arrays; flattening inside the program
# costs nothing and returns rank-1 buffers).
pipeline_scan_ts0_jit = jax.jit(_with_ts0(pipeline_scan), donate_argnums=(3,))
pipeline_flat_safe_ts0_jit = jax.jit(_with_ts0(pipeline_flat_safe), donate_argnums=(3,))


def flatten_scan_result(res: PipelineResult) -> PipelineResult:
    """Reshape a ``pipeline_scan`` result's [K, V] leaves to [K·V]."""

    def flat(a):
        return a.reshape((-1,) + a.shape[2:])

    return PipelineResult(
        batch=jax.tree_util.tree_map(flat, res.batch),
        sessions=res.sessions,
        allowed=flat(res.allowed),
        route=flat(res.route),
        node_id=flat(res.node_id),
        dnat_hit=flat(res.dnat_hit),
        snat_hit=flat(res.snat_hit),
        reply_hit=flat(res.reply_hit),
        punt=flat(res.punt),
    )
