"""The full data-plane step: ACL -> NAT -> routing, in VPP node order.

One jit-compiled program per batch-size/table-bucket combination,
implementing the reference's per-packet pipeline ordering
(docs/dev-guide/SERVICES.md:300-307):

    ingress ACL  ->  nat44 out2in (reply restore + DNAT)  ->
    ip4 routing  ->  nat44 in2out (SNAT)  ->  egress ACL

- The ingress ACL (source pod's table) sees the *original* headers;
  the egress ACL (destination pod's table) sees the *rewritten* ones —
  exactly how VPP orders `acl-plugin-in-ip4-fa` before nat44 and
  `acl-plugin-out-ip4-fa` after it.
- Routing is node-ID arithmetic (plugins/ipam dissection inverted):
  the post-NAT destination resolves to LOCAL (this node's pod subnet),
  REMOTE (another node's chunk of the cluster pod subnet, yielding the
  node ID for VXLAN encap by the host shim), or HOST/external.
- Reflective-ACL semantics ride the NAT session table: reply packets
  restored from a session skip the ACL stages.  Session creation is
  gated on the ACL verdict, so a session exists only when the forward
  direction was actually permitted — the analog of the reference's
  reflective ACL on permitted flows.

PACKED HARVEST (ISSUE 11): the production jit entry points end in a
packing tail that fuses the verdict bits (allowed/punt/reply/dnat/snat
+ straggler + route tag + node id) and the rewritten 5-tuple into ONE
contiguous ``uint32 [4, B]`` device array, so the harvest blocks on a
single device→host materialisation per batch (down from ~12 separate
``np.asarray`` transfers — each a round trip on a remote-TPU tunnel)
and unpacks host-side with cheap numpy views (:func:`unpack_verdicts`).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import NamedTuple, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .classify import RuleTables, _DENY, classify_dst, classify_src
from .nat import (
    _K_META,
    _V_ODST,
    _V_OPORTS,
    _V_OSRC,
    _V_SEEN,
    WRITE_TAG,
    CommitResult,
    NatSessions,
    NatTables,
    affinity_commit,
    combine_rewrite,
    nat_commit_sessions,
    nat_commit_sessions_full,
    nat_reply_probe,
    nat_reply_restore,
    nat_rewrite,
    nat_rewrite_stateless,
)
from .packets import PacketBatch

# Route tags.
ROUTE_DROP = 0
ROUTE_LOCAL = 1    # deliver to a pod on this node
ROUTE_REMOTE = 2   # VXLAN-encap to another node (see node_id)
ROUTE_HOST = 3     # hand to the host stack / external uplink


@dataclass
class RouteConfig:
    """Node-ID routing arithmetic (device scalars)."""

    pod_subnet_base: jnp.ndarray    # uint32 [] cluster pod subnet base
    pod_subnet_mask: jnp.ndarray    # uint32 []
    this_node_base: jnp.ndarray     # uint32 [] this node's pod subnet base
    this_node_mask: jnp.ndarray     # uint32 []
    host_bits: jnp.ndarray          # int32 [] bits of per-node subnet

    def tree_flatten(self):
        return (
            (
                self.pod_subnet_base, self.pod_subnet_mask,
                self.this_node_base, self.this_node_mask, self.host_bits,
            ),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


jax.tree_util.register_pytree_node(
    RouteConfig, RouteConfig.tree_flatten, RouteConfig.tree_unflatten
)


def make_route_config(ipam) -> RouteConfig:
    """Build routing scalars from an IPAM instance."""
    import ipaddress

    all_net = ipam.pod_subnet_all_nodes
    this_net = ipam.pod_subnet_this_node
    # The packed verdict word carries 16 bits of destination node id
    # (VERDICT_NODE_MASK; the upper byte was reclaimed for the ISSUE 14
    # inference verdict).  A layout that can mint a wider node id must
    # be refused HERE, loudly, at table-build time — packing would
    # silently truncate it and tunnel frames to the wrong node.
    node_bits = this_net.prefixlen - all_net.prefixlen
    if node_bits > 16:
        raise ValueError(
            f"pod subnet layout yields {node_bits}-bit node ids "
            f"({all_net} carved into /{this_net.prefixlen} chunks); the "
            "packed verdict word carries at most 16 bits of node id")
    all_mask = (0xFFFFFFFF << (32 - all_net.prefixlen)) & 0xFFFFFFFF
    this_mask = (0xFFFFFFFF << (32 - this_net.prefixlen)) & 0xFFFFFFFF
    return RouteConfig(
        pod_subnet_base=jnp.asarray(int(all_net.network_address), dtype=jnp.uint32),
        pod_subnet_mask=jnp.asarray(all_mask, dtype=jnp.uint32),
        this_node_base=jnp.asarray(int(this_net.network_address), dtype=jnp.uint32),
        this_node_mask=jnp.asarray(this_mask, dtype=jnp.uint32),
        host_bits=jnp.asarray(32 - this_net.prefixlen, dtype=jnp.int32),
    )


class PipelineResult(NamedTuple):
    batch: PacketBatch      # rewritten headers
    sessions: NatSessions   # updated NAT session table
    allowed: jnp.ndarray    # bool [B]
    route: jnp.ndarray      # int32 [B] ROUTE_* tag (DROP when denied)
    node_id: jnp.ndarray    # int32 [B] destination node for ROUTE_REMOTE
    dnat_hit: jnp.ndarray   # bool [B]
    snat_hit: jnp.ndarray   # bool [B]
    reply_hit: jnp.ndarray  # bool [B]
    punt: jnp.ndarray       # bool [B] flow needs the host slow path


def _route_tags(route: RouteConfig, dst: jnp.ndarray, allowed: jnp.ndarray):
    """Node-ID routing arithmetic on post-NAT destinations:
    (ROUTE_* tag [B], destination node id [B])."""
    in_cluster = (dst & route.pod_subnet_mask) == route.pod_subnet_base
    on_this_node = (dst & route.this_node_mask) == route.this_node_base
    tag = jnp.where(
        on_this_node,
        ROUTE_LOCAL,
        jnp.where(in_cluster, ROUTE_REMOTE, ROUTE_HOST),
    )
    tag = jnp.where(allowed, tag, ROUTE_DROP)
    node_id = jnp.where(
        in_cluster & ~on_this_node,
        ((dst - route.pod_subnet_base) >> route.host_bits.astype(jnp.uint32)).astype(jnp.int32),
        jnp.int32(0),
    )
    return tag, node_id


def _commit_and_route(
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batch: PacketBatch,
    rw,
    acl_ok: jnp.ndarray,
    timestamp: jnp.ndarray,
):
    """Shared tail of both disciplines: ACL/reply gating, session
    commit, affinity-pin commit, and node-ID routing.  Returns
    (new_sessions, result) with ``result.sessions`` left as a
    placeholder scalar — the caller decides whether it carries the
    table (flat) or the scan threads it.
    """
    rewritten = rw.batch
    # Session-restored replies skip ACLs (reflective semantics — valid
    # precisely because only permitted flows ever record sessions).
    allowed = acl_ok | rw.reply_hit

    # Commit sessions for translated AND permitted flows only: a denied
    # flow must never seed a session a crafted "reply" could ride.
    record = (rw.dnat_hit | rw.snat_hit) & allowed
    new_sessions, punt = nat_commit_sessions(
        sessions, batch, rewritten, record, rw.reply_hit, rw.reply_slot, timestamp
    )
    if nat.has_affinity:  # static gate — compiled in only when used
        new_sessions = affinity_commit(
            new_sessions, nat, batch, rw.midx,
            rw.aff_want & allowed, rewritten.dst_ip, rewritten.dst_port,
            timestamp,
        )

    # Routing on the post-NAT destination.
    tag, node_id = _route_tags(route, rewritten.dst_ip, allowed)

    result = PipelineResult(
        batch=rewritten,
        sessions=jnp.int32(0),
        allowed=allowed,
        route=tag,
        node_id=node_id,
        dnat_hit=rw.dnat_hit,
        snat_hit=rw.snat_hit,
        reply_hit=rw.reply_hit,
        punt=punt,
    )
    return new_sessions, result


def pipeline_step(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batch: PacketBatch,
    timestamp: jnp.ndarray,
) -> PipelineResult:
    """One batch through the whole data plane."""
    # 1. Ingress ACL on original headers (source pod's table).
    src_action = classify_src(acl, batch)

    # 2. NAT translation: reply restore -> DNAT LB -> SNAT (no session
    # writes yet — those are gated on the full ACL verdict below).
    rw = nat_rewrite(nat, sessions, batch)

    # 3. Egress ACL on rewritten headers (destination pod's table).
    dst_action = classify_dst(acl, rw.batch)
    acl_ok = (src_action != _DENY) & (dst_action != _DENY)

    new_sessions, result = _commit_and_route(
        nat, route, sessions, batch, rw, acl_ok, timestamp
    )
    return result._replace(sessions=new_sessions)


# VPP's vector size: the dataplane's native unit of work.  The runner
# assembles frames into 256-packet vectors and dispatches K of them per
# device program (SURVEY §6: "VPP processes packets in up-to-256-packet
# vectors").
VECTOR_SIZE = 256


def pipeline_scan(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batches: PacketBatch,      # leaves shaped [K, V]
    timestamps: jnp.ndarray,   # int32 [K]
) -> PipelineResult:
    """K packet vectors through the pipeline in ONE device dispatch.

    Only the session-table stages are sequential: ``lax.scan`` threads
    the NAT table from vector to vector *on device* (a flow's session
    created in vector i is visible to its replies in vector i+1 —
    VPP's sequential-vector semantics).  Everything session-INDEPENDENT
    — both ACL classifies and the stateless DNAT/SNAT rewrite — is
    hoisted OUT of the scan and computed flat over all K·V packets at
    once, so the classify stage runs at wide-batch efficiency (MXU
    tiling, the Pallas first-match kernel's preferred shapes) instead
    of re-streaming the rule tables once per 256-packet vector.  At 64k
    rules that re-streaming made the scan dispatch 3x slower than a
    flat one (BENCHSCALE_r02); hoisting closes the gap while keeping
    the scan's session semantics bit-identical (reply rows bypass the
    ACL by the reflective rule, and their stateless rewrite is masked —
    see ``combine_rewrite``).

    Correctness note: the egress ACL is evaluated on the STATELESS
    rewrite of each packet.  That matches the fused per-vector step for
    every row because the only rows whose true rewrite differs (reply
    restores) never consult the ACL — ``allowed = acl_ok | reply_hit``.

    Returned leaves are stacked [K, V]; ``sessions`` is the final table.
    """
    k, v = batches.src_ip.shape

    def flatten(a):
        return a.reshape((k * v,) + a.shape[2:])

    def unflatten(a):
        return a.reshape((k, v) + a.shape[1:])

    flat = jax.tree_util.tree_map(flatten, batches)

    # ---- flat prepass: ingress ACL, stateless NAT, egress ACL --------
    src_action = classify_src(acl, flat)
    stateless = nat_rewrite_stateless(nat, flat, sessions)
    dst_action = classify_dst(acl, stateless.batch)
    acl_ok = (src_action != _DENY) & (dst_action != _DENY)

    per_vec = (
        batches,
        jax.tree_util.tree_map(unflatten, stateless),
        unflatten(acl_ok),
        timestamps,
    )

    # ---- sequential session stage ------------------------------------
    def body(sess, xs):
        batch, sless, ok, ts = xs
        rw = combine_rewrite(nat_reply_restore(sess, batch), sless)
        return _commit_and_route(nat, route, sess, batch, rw, ok, ts)

    final_sessions, stacked = jax.lax.scan(body, sessions, per_vec)
    return stacked._replace(sessions=final_sessions)


class _FlatReconcile(NamedTuple):
    """Shared state of the flat-safe/flat-punt disciplines after the
    commit + ONE tagged post-commit probe: everything both tails need
    to finish their (different) restore policies."""

    flat: PacketBatch          # [B] original headers
    ts_rows: jnp.ndarray       # int32 [B]
    stateless: object          # StatelessRewrite over [B]
    acl_ok: jnp.ndarray        # bool [B]
    commit: CommitResult
    sessions2: NatSessions     # finalized keys (bogus undone, tags cleared)
    reply_pre: jnp.ndarray     # bool [B] organic reply to a pre-dispatch session
    straggler: jnp.ndarray     # bool [B] reply whose forward is in THIS dispatch
    slot2: jnp.ndarray         # int32 [B] the single matched slot per row
    cap_sentinel: jnp.ndarray  # int32 [] out-of-range scatter sentinel


def _flat_commit_and_probe(
    acl: RuleTables,
    nat: NatTables,
    sessions: NatSessions,
    batches: PacketBatch,      # leaves shaped [K, V]
    timestamps: jnp.ndarray,   # int32 [K]
) -> _FlatReconcile:
    """Passes 1-3 shared by ``pipeline_flat_safe`` and
    ``pipeline_flat_punt``: flat classify + stateless NAT, the
    commit-first session insert (write-tagged), the ONE restore-side
    probe whose tag split classifies every row (organic reply vs
    straggler), and the single finalize scatter that undoes bogus
    forward sessions and clears the write tags.  See
    ``pipeline_flat_safe`` for the full correctness argument."""
    k, v = batches.src_ip.shape

    def flatten(a):
        return a.reshape((k * v,) + a.shape[2:])

    flat = jax.tree_util.tree_map(flatten, batches)
    ts_rows = jnp.repeat(timestamps, v)
    b = k * v
    cap = sessions.capacity
    cap_sentinel = jnp.int32(cap)

    # ---- pass 1: session-independent compute ------------------------
    src_action = classify_src(acl, flat)
    stateless = nat_rewrite_stateless(nat, flat, sessions)
    dst_action = classify_dst(acl, stateless.batch)
    acl_ok = (src_action != _DENY) & (dst_action != _DENY)

    # ---- pass 2: commit (insert-side probe) -------------------------
    # Keep-alive touches for restored replies are deferred to the tail
    # (reply_hit=False here); scatter-max is order-independent.
    no_reply = jnp.zeros(b, dtype=bool)
    record0 = (stateless.dnat_hit | stateless.snat_hit) & acl_ok
    commit = nat_commit_sessions_full(
        sessions, flat, stateless.batch, record0, no_reply,
        jnp.zeros(b, dtype=jnp.int32), ts_rows, tag_writes=True,
    )

    # ---- pass 3: the ONE restore-side probe -------------------------
    # tag_writes marked this batch's writes in the meta word, so the
    # probe's own gathered rows split the matches — no separate
    # written-mask table (the session stages are bound by the COUNT of
    # small random-access ops, so every eliminated scatter/gather chain
    # is throughput).
    km2, cand2, meta2 = nat_reply_probe(commit.sessions, flat)
    wm = (meta2 & jnp.uint32(WRITE_TAG)) != 0           # [B, W]
    km_pre = km2 & ~wm        # matches against pre-dispatch sessions
    # Valid slots hold unique keys, so km2 has at most ONE true way —
    # km_pre is mutually exclusive with the written-slot matches per
    # row and the argmax selection below is over a singleton set.
    reply_pre = jnp.any(km_pre, axis=1)
    hit2 = jnp.any(km2, axis=1)
    w2 = jnp.argmax(km2, axis=1)
    slot2 = jnp.take_along_axis(cand2, w2[:, None], axis=1)[:, 0]
    own_write = commit.committed & (slot2 == commit.ins_slot)
    straggler = hit2 & ~reply_pre & ~own_write

    # Undo bogus forward sessions: any FRESH commit by a row that is
    # itself a reply (organic or straggler).  Reused slots are legit
    # pre-existing sessions being refreshed — clearing those would
    # destroy real state, so they are excluded (crafted corners only;
    # organic replies never DNAT/SNAT-hit and so never commit).
    # ONE finalize scatter serves undo AND tag clearing: every
    # committed row's slot gets its final meta (0 when undone, the
    # bare protocol otherwise).
    undo_rows = commit.committed & ~commit.reused & (reply_pre | straggler)
    fin_slot = jnp.where(commit.committed, commit.ins_slot, cap_sentinel)
    fin_meta = jnp.where(
        undo_rows, jnp.uint32(0), flat.protocol.astype(jnp.uint32)
    )
    sessions2 = NatSessions(
        key_tbl=commit.sessions.key_tbl.at[fin_slot, _K_META].set(
            fin_meta, mode="drop"
        ),
        val_tbl=commit.sessions.val_tbl,
    )
    return _FlatReconcile(
        flat=flat, ts_rows=ts_rows, stateless=stateless, acl_ok=acl_ok,
        commit=commit, sessions2=sessions2, reply_pre=reply_pre,
        straggler=straggler, slot2=slot2, cap_sentinel=cap_sentinel,
    )


def _restore_batch(rc: _FlatReconcile, reply_final: jnp.ndarray,
                   vals3: jnp.ndarray) -> PacketBatch:
    """Merge restored reply headers over the stateless rewrite.
    Restore mapping as in nat_reply_restore: src <- original dst
    (VIP), dst <- original src (client), ports likewise (unpacked
    from the packed-ports word of the selected value row)."""
    stateless = rc.stateless

    def merge(a, b_):
        return jnp.where(reply_final, a, b_)

    op3 = vals3[:, _V_OPORTS]
    return PacketBatch(
        src_ip=merge(vals3[:, _V_ODST], stateless.batch.src_ip),
        dst_ip=merge(vals3[:, _V_OSRC], stateless.batch.dst_ip),
        protocol=rc.flat.protocol,
        src_port=merge((op3 & jnp.uint32(0xFFFF)).astype(jnp.int32),
                       stateless.batch.src_port),
        dst_port=merge((op3 >> jnp.uint32(16)).astype(jnp.int32),
                       stateless.batch.dst_port),
    )


def pipeline_flat_safe(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batches: PacketBatch,      # leaves shaped [K, V]
    timestamps: jnp.ndarray,   # int32 [K]
) -> PipelineResult:
    """All K·V packets through the pipeline in ONE flat pass — with the
    scan's same-dispatch reply semantics recovered by a post-commit
    re-probe instead of a sequential ``lax.scan``.

    The plain flat step (``pipeline_step``) mistranslates a reply that
    arrives in the same dispatch as its forward packet: the restore
    probe sees the PRE-dispatch table, misses, and the packet sails on
    as if it were a fresh flow.  The scan discipline fixes that by
    threading sessions vector-to-vector, paying a sequential stage that
    costs ~25-45% of the dispatch (BENCHSWEEP: 97 vs 72 Mpps at 16k
    packets, 428 vs 238 at 64k).  This discipline keeps every stage
    batch-parallel and instead reconciles in three bounded, data-
    independent passes:

    1. flat classify + stateless NAT + restore against the pre-table +
       gated session commit (exactly ``pipeline_step``);
    2. re-probe every row's ORIGINAL tuple against the committed
       table.  A row that now matches someone else's session — not the
       one it wrote itself — is a *straggler*: a reply whose forward
       flow sits earlier in this dispatch.  Stragglers that committed a
       session in pass 1 wrote a BOGUS forward session (they are
       replies, not new flows): invalidate exactly those slots — safe,
       because the post-write verify proved each committed row owns its
       slot's content;
    3. re-probe stragglers against the cleaned table: a hit restores
       the reply (headers, reflective-ACL bypass, keep-alive touch,
       dnat/snat flags cleared, route recomputed) precisely as the next
       dispatch would have; a miss means the row only ever matched
       another straggler's bogus entry (craftable aliasing, never
       organic traffic) — forward it per its pass-1 rewrite and PUNT so
       the host slow path records the authoritative session.

    Semantics vs the scan: a superset of restores (the scan restores a
    reply only when its forward ran in an EARLIER vector; this pass
    also restores same-vector and reply-before-forward orderings, both
    of which the scan would restore one dispatch later anyway), the
    same commit-race punts, and the same ACL gating.  A/B-tested
    against the scan and the sequential oracle in tests/test_pipeline.py.

    COMMIT-FIRST layout (r4): the session stages are gather-bound on
    TPU, so the discipline is arranged to touch the table as little as
    possible.  Two facts make a pre-commit restore probe unnecessary:
    (a) valid slots hold UNIQUE keys (inserts reuse a same-key slot or
    punt; intra-batch racers lose the scatter and punt), and (b) a
    fresh insert's key can never equal a pre-existing key (same key +
    same orig would have REUSED the slot; same key + different orig
    punts as a collision).  Therefore ONE probe of the post-commit
    table, split by a this-batch written mask, classifies every row in
    a single pass: a match on an unwritten slot is an organic reply to
    a pre-dispatch session; a match on a written slot is a straggler
    (its forward flow sits in this very dispatch) — the two are
    mutually exclusive.  Commit therefore runs FIRST, on the stateless
    rewrite (identical bytes for every row that can record — reply
    rows' stateless DNAT/SNAT hits are rare and their bogus sessions
    are undone, exactly like stragglers' always were).  vs the r3
    layout this deletes the full pre-table key+value restore probe
    ([B,W,4]+[B,4] random rows) — the session stage is now two key
    probes total (insert-side + restore-side), the same count as the
    UNSAFE flat step.
    """
    k, v = batches.src_ip.shape
    rc = _flat_commit_and_probe(acl, nat, sessions, batches, timestamps)

    # ---- pass 4: restores against the finalized table ---------------
    # A straggler's single matched slot may be another straggler's
    # undone bogus write — one scalar meta gather at the selected slot
    # re-checks validity (organic replies matched unwritten slots,
    # which the finalize scatter never clears).  This gather is the
    # only read DEPENDENT on the finalize scatter — the round the
    # flat-punt discipline cuts by punting stragglers instead.
    rslot = rc.slot2  # singleton match: the km2 selection IS the slot
    meta_chk = rc.sessions2.key_tbl[rslot, _K_META]        # [B]
    restored_strag = rc.straggler & (meta_chk != 0)
    reply_final = rc.reply_pre | restored_strag
    vals3 = rc.sessions2.val_tbl[rslot]  # [B, 4] — one row per restore
    touch = jnp.where(reply_final, rslot, rc.cap_sentinel)
    # max, not set: duplicate slots with differing per-row timestamps
    # (two restored replies to one session) scatter in undefined order.
    sessions3 = NatSessions(
        key_tbl=rc.sessions2.key_tbl,
        val_tbl=rc.sessions2.val_tbl.at[touch, _V_SEEN].max(
            rc.ts_rows.astype(jnp.uint32), mode="drop"
        ),
    )
    stateless = rc.stateless
    if nat.has_affinity:  # static gate — compiled in only when used
        sessions3 = affinity_commit(
            sessions3, nat, rc.flat, stateless.midx,
            stateless.aff_want & rc.acl_ok & ~reply_final,
            stateless.batch.dst_ip, stateless.batch.dst_port, rc.ts_rows,
        )

    final_batch = _restore_batch(rc, reply_final, vals3)
    allowed_final = rc.acl_ok | reply_final
    punt_final = (rc.commit.punt & ~reply_final) | \
        (rc.straggler & ~restored_strag)
    tag, node_id = _route_tags(route, final_batch.dst_ip, allowed_final)

    def unflatten(a):
        return a.reshape((k, v) + a.shape[1:])

    return PipelineResult(
        batch=jax.tree_util.tree_map(unflatten, final_batch),
        sessions=sessions3,
        allowed=unflatten(allowed_final),
        route=unflatten(tag),
        node_id=unflatten(node_id),
        dnat_hit=unflatten(stateless.dnat_hit & ~reply_final),
        snat_hit=unflatten(stateless.snat_hit & ~reply_final),
        reply_hit=unflatten(reply_final),
        punt=unflatten(punt_final),
    )


def pipeline_flat_punt(
    acl: RuleTables,
    nat: NatTables,
    route: RouteConfig,
    sessions: NatSessions,
    batches: PacketBatch,      # leaves shaped [K, V]
    timestamps: jnp.ndarray,   # int32 [K]
) -> Tuple[PipelineResult, jnp.ndarray]:
    """The round-cut discipline (ISSUE 11 / MESHOVERHEAD_r05 finding):
    identical to ``pipeline_flat_safe`` through the commit + ONE
    tagged post-commit probe, but DETECTED same-dispatch reply
    stragglers are PUNTED to the host slow path instead of restored on
    device.  Returns ``(result, straggler)`` where ``straggler``
    (bool [K, V]) marks the punted same-dispatch replies — the harvest
    resolves them host-side against the SAME batch's committed forward
    rows (``ops.slowpath.resolve_stragglers``), so they still reach
    the oracle verdict; plain flat is NOT an option because it
    silently mistranslates them instead of punting.

    What this buys: flat-safe's straggler restore needs a meta re-check
    gather that DEPENDS on the finalize scatter (commit → probe →
    finalize → re-check → touch — the longest dependent chain of the
    discipline), and on a GSPMD mesh every dependent scatter/gather
    round over the session table is a collective.  Cutting the
    restore truncates the chain at the finalize: the organic-reply
    value gather and keep-alive touch hang off the PROBE, not the
    finalize, so the dependent session-table round count drops by one
    and the dispatch's critical path shortens — the ~4× sharding tax
    of MESHOVERHEAD_r05 is round-count-bound, not placement-bound.

    Straggler frequency is workload-bound (a reply must land in the
    very dispatch of its forward — the coalesce window, ≤1.6 ms at the
    production shape), so the host punt is rare by construction;
    flat-safe remains the right pick when same-dispatch replies are
    common (e.g. loopback-heavy east-west with deep coalesce).

    Other differences vs flat-safe, all on adversarial corners only:
    a detected straggler never commits an affinity pin (it is a reply;
    flat-safe likewise excludes the ones it restores), and the
    crafted-aliasing rows flat-safe forwards per their pass-1 rewrite
    arrive here as ordinary unresolved punts (same punt verdict, same
    slow-path ownership).
    """
    k, v = batches.src_ip.shape
    rc = _flat_commit_and_probe(acl, nat, sessions, batches, timestamps)

    # ---- tail: organic restores only; stragglers punt ---------------
    # Both the value gather and the keep-alive touch key off the probe
    # (pass 3) — nothing here reads the finalized key table, so the
    # finalize scatter is a chain LEAF, not a link.
    reply_final = rc.reply_pre
    vals3 = rc.sessions2.val_tbl[rc.slot2]  # [B, 4]
    touch = jnp.where(reply_final, rc.slot2, rc.cap_sentinel)
    sessions3 = NatSessions(
        key_tbl=rc.sessions2.key_tbl,
        val_tbl=rc.sessions2.val_tbl.at[touch, _V_SEEN].max(
            rc.ts_rows.astype(jnp.uint32), mode="drop"
        ),
    )
    stateless = rc.stateless
    if nat.has_affinity:  # static gate — compiled in only when used
        sessions3 = affinity_commit(
            sessions3, nat, rc.flat, stateless.midx,
            stateless.aff_want & rc.acl_ok & ~reply_final & ~rc.straggler,
            stateless.batch.dst_ip, stateless.batch.dst_port, rc.ts_rows,
        )

    final_batch = _restore_batch(rc, reply_final, vals3)
    allowed_final = rc.acl_ok | reply_final
    punt_final = (rc.commit.punt & ~reply_final) | rc.straggler
    tag, node_id = _route_tags(route, final_batch.dst_ip, allowed_final)

    def unflatten(a):
        return a.reshape((k, v) + a.shape[1:])

    result = PipelineResult(
        batch=jax.tree_util.tree_map(unflatten, final_batch),
        sessions=sessions3,
        allowed=unflatten(allowed_final),
        route=unflatten(tag),
        node_id=unflatten(node_id),
        dnat_hit=unflatten(stateless.dnat_hit & ~reply_final),
        snat_hit=unflatten(stateless.snat_hit & ~reply_final),
        reply_hit=unflatten(reply_final),
        punt=unflatten(punt_final),
    )
    return result, unflatten(rc.straggler)


def flatten_scan_result(res: PipelineResult) -> PipelineResult:
    """Reshape a ``pipeline_scan`` result's [K, V] leaves to [K·V]."""

    def flat(a):
        return a.reshape((-1,) + a.shape[2:])

    return PipelineResult(
        batch=jax.tree_util.tree_map(flat, res.batch),
        sessions=res.sessions,
        allowed=flat(res.allowed),
        route=flat(res.route),
        node_id=flat(res.node_id),
        dnat_hit=flat(res.dnat_hit),
        snat_hit=flat(res.snat_hit),
        reply_hit=flat(res.reply_hit),
        punt=flat(res.punt),
    )


# ---------------------------------------------------------------------------
# Packed single-transfer harvest (ISSUE 11 tentpole)
# ---------------------------------------------------------------------------

# Verdict-word layout (uint32 per packet, row 0 of the packed array).
# THIS BLOCK IS THE SINGLE SOURCE OF TRUTH for the bit layout: the
# three encoders (pack_result on device, pack_verdicts_host for the
# quarantine stitcher, unpack_verdicts on the harvest) all read these
# named masks and nothing else, and a bit-for-bit round-trip property
# test (tests/test_inference.py) holds them together.
#
#   bit  0      allowed            bit  7     straggler (flat-punt)
#   bit  1      punt               bits 8-23  destination node id
#   bit  2      reply restore      bits 24-26 inference score band
#   bit  3      dnat hit           bit  27    inference scored
#   bit  4      snat hit           bits 28-29 inference action fired
#   bits 5-6    ROUTE_* tag        bits 30-31 reserved
VERDICT_ALLOWED = 1 << 0
VERDICT_PUNT = 1 << 1
VERDICT_REPLY = 1 << 2
VERDICT_DNAT = 1 << 3
VERDICT_SNAT = 1 << 4
VERDICT_ROUTE_SHIFT = 5        # bits 5-6: ROUTE_* tag (0..3)
VERDICT_ROUTE_MASK = 0x3
VERDICT_STRAGGLER_SHIFT = 7    # flat-punt: same-dispatch reply, punted
VERDICT_STRAGGLER = 1 << VERDICT_STRAGGLER_SHIFT
VERDICT_NODE_SHIFT = 8         # bits 8-23: destination node id
VERDICT_NODE_MASK = 0xFFFF
# node_id fits 16 bits by construction at every deployable layout: it
# is pod-subnet arithmetic ((dst - base) >> host_bits), and a /8
# cluster subnet carved into /24 per-node chunks — far beyond the
# 100-node design point — is exactly 2^16 nodes.  The upper byte was
# reclaimed for the in-network inference verdict (ISSUE 14); layouts
# with more than 65536 nodes are not representable in the packed word.
INFER_BAND_SHIFT = 24          # bits 24-26: log2 score band (0..7)
INFER_BAND_MASK = 0x7
INFER_SCORED_SHIFT = 27        # bit 27: row was scored (pod enrolled)
INFER_SCORED = 1 << INFER_SCORED_SHIFT
INFER_ACTION_SHIFT = 28        # bits 28-29: INFER_ACT_* fired (0 = none)
INFER_ACTION_MASK = 0x3

# The packed rows (uint32 [4, B]; row-major so each leaf is ONE
# contiguous host-side view after the single materialisation).
PACKED_WORD = 0     # verdict bits | route << 5 | node_id << 8
PACKED_SRC = 1      # rewritten src_ip
PACKED_DST = 2      # rewritten dst_ip
PACKED_PORTS = 3    # rewritten src_port << 16 | dst_port
# (protocol is NOT packed: no pipeline stage rewrites it, so the
# harvest reads it from the host-side original headers for free.)


class PackedResult(NamedTuple):
    """What the production jit entry points return: the single packed
    verdict+rewrite array (ONE device→host transfer per harvest) plus
    the session table threaded to the next dispatch on device."""

    packed: jnp.ndarray     # uint32 [4, B]
    sessions: NatSessions


def pack_result(res: PipelineResult,
                straggler: Optional[jnp.ndarray] = None,
                scores: Optional[Tuple] = None) -> PackedResult:
    """In-program packing tail: fuse the 7 verdict leaves and the
    rewritten 5-tuple (12 separate host materialisations before ISSUE
    11) into one contiguous uint32 [4, B] device array.  ``res`` must
    carry flat [B] leaves.  ``scores`` is the inference stage's
    (scored, band, action) triple (ISSUE 14) folded into the reclaimed
    upper byte — None (scoring off) leaves those bits zero, so the
    score-off word is bit-identical to the pre-inference layout."""
    word = (
        res.allowed.astype(jnp.uint32)
        | (res.punt.astype(jnp.uint32) << 1)
        | (res.reply_hit.astype(jnp.uint32) << 2)
        | (res.dnat_hit.astype(jnp.uint32) << 3)
        | (res.snat_hit.astype(jnp.uint32) << 4)
        | (res.route.astype(jnp.uint32) << VERDICT_ROUTE_SHIFT)
        | ((res.node_id.astype(jnp.uint32) & jnp.uint32(VERDICT_NODE_MASK))
           << VERDICT_NODE_SHIFT)
    )
    if straggler is not None:
        word = word | (straggler.astype(jnp.uint32)
                       << VERDICT_STRAGGLER_SHIFT)
    if scores is not None:
        scored, band, action = scores
        word = word | (
            ((band & jnp.uint32(INFER_BAND_MASK)) << INFER_BAND_SHIFT)
            | (scored.astype(jnp.uint32) << INFER_SCORED_SHIFT)
            | ((action & jnp.uint32(INFER_ACTION_MASK))
               << INFER_ACTION_SHIFT)
        )
    ports = (
        (res.batch.src_port.astype(jnp.uint32) << 16)
        | res.batch.dst_port.astype(jnp.uint32)
    )
    packed = jnp.stack([word, res.batch.src_ip, res.batch.dst_ip, ports])
    return PackedResult(packed=packed, sessions=res.sessions)


class HostVerdicts(NamedTuple):
    """Host-side unpacked view of one packed result (numpy).  The flag
    and port leaves are fresh writable arrays (the slow path mutates
    them in place); ``src_ip``/``dst_ip`` are zero-copy views into the
    packed rows unless ``writable`` asked for copies."""

    allowed: np.ndarray     # bool [n]
    punt: np.ndarray        # bool [n]
    reply_hit: np.ndarray   # bool [n]
    dnat_hit: np.ndarray    # bool [n]
    snat_hit: np.ndarray    # bool [n]
    straggler: np.ndarray   # bool [n]
    route: np.ndarray       # int32 [n]
    node_id: np.ndarray     # int32 [n]
    src_ip: np.ndarray      # uint32 [n]
    dst_ip: np.ndarray      # uint32 [n]
    src_port: np.ndarray    # int32 [n]
    dst_port: np.ndarray    # int32 [n]
    # In-network inference verdict (ISSUE 14; all-zero when scoring is
    # off — appended so positional consumers of the 12 classic leaves
    # keep their indices).
    scored: np.ndarray      # bool [n] row was scored (pod enrolled)
    band: np.ndarray        # int32 [n] log2 score band (0..7)
    action: np.ndarray      # int32 [n] INFER_ACT_* fired (0 = none)


def unpack_verdicts(packed_rows: np.ndarray, n: Optional[int] = None,
                    writable: bool = False) -> HostVerdicts:
    """Split one materialised packed array (numpy uint32 [4, B]) into
    the 12 harvest leaves with cheap numpy ops: the derived flag/tag/
    port arrays are fresh allocations either way; the two rewritten-IP
    rows stay zero-copy row views unless ``writable`` (the slow path
    needs to patch restored headers in place, and a materialised
    device buffer may be read-only)."""
    n = packed_rows.shape[1] if n is None else n
    word = packed_rows[PACKED_WORD][:n]
    src = packed_rows[PACKED_SRC][:n]
    dst = packed_rows[PACKED_DST][:n]
    ports = packed_rows[PACKED_PORTS][:n]
    if writable:
        src = src.copy()
        dst = dst.copy()
    return HostVerdicts(
        allowed=(word & VERDICT_ALLOWED) != 0,
        punt=(word & VERDICT_PUNT) != 0,
        reply_hit=(word & VERDICT_REPLY) != 0,
        dnat_hit=(word & VERDICT_DNAT) != 0,
        snat_hit=(word & VERDICT_SNAT) != 0,
        straggler=(word & VERDICT_STRAGGLER) != 0,
        route=((word >> VERDICT_ROUTE_SHIFT)
               & VERDICT_ROUTE_MASK).astype(np.int32),
        node_id=((word >> VERDICT_NODE_SHIFT)
                 & VERDICT_NODE_MASK).astype(np.int32),
        src_ip=src,
        dst_ip=dst,
        src_port=(ports >> 16).astype(np.int32),
        dst_port=(ports & 0xFFFF).astype(np.int32),
        scored=(word & INFER_SCORED) != 0,
        band=((word >> INFER_BAND_SHIFT)
              & INFER_BAND_MASK).astype(np.int32),
        action=((word >> INFER_ACTION_SHIFT)
                & INFER_ACTION_MASK).astype(np.int32),
    )


def pack_verdicts_host(allowed, punt, reply_hit, dnat_hit, snat_hit,
                       route, node_id, src_ip, dst_ip, src_port, dst_port,
                       straggler=None, scored=None, band=None,
                       action=None) -> np.ndarray:
    """Numpy twin of :func:`pack_result`'s layout — used by the
    poisoned-batch quarantine to assemble a host-stitched packed
    result, and by the round-trip property tests (host pack ≡ device
    pack bit-for-bit).  Inputs must already be HOST numpy arrays: the
    quarantine path is hot-path-reachable and this function performs
    no device materialisation (``.astype`` on numpy is a host cast).
    The optional inference leaves (ISSUE 14) default to the all-zero
    score-off encoding."""
    word = (
        allowed.astype(np.uint32)
        | (punt.astype(np.uint32) << 1)
        | (reply_hit.astype(np.uint32) << 2)
        | (dnat_hit.astype(np.uint32) << 3)
        | (snat_hit.astype(np.uint32) << 4)
        | (route.astype(np.uint32) << VERDICT_ROUTE_SHIFT)
        | ((node_id.astype(np.uint32) & np.uint32(VERDICT_NODE_MASK))
           << VERDICT_NODE_SHIFT)
    )
    if straggler is not None:
        word = word | (straggler.astype(np.uint32)
                       << VERDICT_STRAGGLER_SHIFT)
    if scored is not None:
        word = word | (scored.astype(np.uint32) << INFER_SCORED_SHIFT)
    if band is not None:
        word = word | ((band.astype(np.uint32)
                        & np.uint32(INFER_BAND_MASK)) << INFER_BAND_SHIFT)
    if action is not None:
        word = word | ((action.astype(np.uint32)
                        & np.uint32(INFER_ACTION_MASK))
                       << INFER_ACTION_SHIFT)
    ports = (src_port.astype(np.uint32) << 16) | dst_port.astype(np.uint32)
    return np.stack([
        word, src_ip.astype(np.uint32), dst_ip.astype(np.uint32), ports,
    ])


# ---------------------------------------------------------------------------
# Production jit entry points
# ---------------------------------------------------------------------------

def _score_stage(infer, res: PipelineResult):
    """The in-network inference stage (ISSUE 14): score every packet
    of the settled flat result — between the classify/NAT verdict
    stages and the pack_result tail, for EVERY discipline.  ``infer``
    is an :class:`~vpp_tpu.ops.infer.InferTable` or None; None or a
    disabled table is a trace-time static, so the score-off program
    compiles to exactly the pre-inference pipeline (zero cost when no
    namespace is enrolled)."""
    if infer is None or not infer.enabled:
        return None
    from .infer import infer_scores

    return infer_scores(infer, res.batch, res.reply_hit,
                        res.dnat_hit, res.snat_hit)


def _packed_step(acl, nat, route, sessions, batch, timestamp, infer=None):
    """Flat single-vector step + packing tail (the K=1 scan-discipline
    dispatch shape)."""
    res = pipeline_step(acl, nat, route, sessions, batch, timestamp)
    return pack_result(res, scores=_score_stage(infer, res))


def _with_ts0(fn):
    """Wrap a [K, V] discipline to take a SCALAR base timestamp and
    derive the per-vector ts inside the program, returning the PACKED
    single-transfer result over [K·V]-flat rows.  The host-side
    ``jnp.arange`` the raw signatures require is an extra tiny
    device-array creation per dispatch — on a remote-TPU tunnel that
    is one more round trip, measured at a 40-100% tax on the whole
    16k-packet dispatch (r4: it was misattributed to the session
    stages for a full round).  Vector i gets ts0 + 1 + i."""

    def stepped(acl, nat, route, sessions, batches, ts0, infer=None):
        k = batches.src_ip.shape[0]
        tss = ts0 + jnp.arange(1, k + 1, dtype=jnp.int32)
        res = flatten_scan_result(
            fn(acl, nat, route, sessions, batches, tss))
        return pack_result(res, scores=_score_stage(infer, res))

    return stepped


def _flat_punt_ts0(acl, nat, route, sessions, batches, ts0, infer=None):
    """flat-punt's ts0 wrapper: same scalar-base-ts contract, plus the
    straggler mask folded into the packed verdict word (bit 7)."""
    k = batches.src_ip.shape[0]
    tss = ts0 + jnp.arange(1, k + 1, dtype=jnp.int32)
    res, straggler = pipeline_flat_punt(acl, nat, route, sessions,
                                        batches, tss)
    flat = flatten_scan_result(res)
    return pack_result(flat, straggler.reshape(-1),
                       scores=_score_stage(infer, flat))


# Production entry points: scalar base-ts in (the ts0 shapes), the
# packed single-transfer result out.  Every one of these is referenced
# by BOTH the runner's dispatch discipline selection and its pre-warm
# ledger — the jit-discipline checker enforces that pairing (a
# dispatch-reachable jit the warmer never compiled stalls a load
# spike; a warmed jit no dispatch can select is dead weight).
pipeline_step_jit = jax.jit(_packed_step, donate_argnums=(3,))
pipeline_scan_ts0_jit = jax.jit(_with_ts0(pipeline_scan), donate_argnums=(3,))
pipeline_flat_safe_ts0_jit = jax.jit(_with_ts0(pipeline_flat_safe), donate_argnums=(3,))
pipeline_flat_punt_ts0_jit = jax.jit(_flat_punt_ts0, donate_argnums=(3,))
