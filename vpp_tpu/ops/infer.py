"""In-network inference — per-vector DNN scoring on the datapath.

ROADMAP item 3 (FENIX arXiv:2507.14891, INSIGHT arXiv:2505.24269): run
a small anomaly/priority scorer *inside* the network element.  This
datapath already dispatches every packet through a jit-compiled device
program whose cost is floor-bound (NOTES_r05: extra per-vector compute
is ~free under the dispatch round-trip floor), so a fused scoring stage
costs near-zero marginal dispatch time — the whole subsystem is "one
more tensor op" between the classify/NAT verdict settlement and the
packed-harvest tail.

**Model shape.**  A deliberately small fused MLP over a fixed
16-feature vector per packet:

    h = relu(f @ w1 + b1)        # [B, D] @ [D, H] -> [B, H]
    score = sigmoid(h @ w2 + b2) # [B]

The feature vector is built from what the pipeline already holds on
device — the (rewritten) 5-tuple, session-table state bits
(reply-restored / DNAT / SNAT hits), and two feature-hash buckets of
the flow tuple (the INSIGHT-style hashed-feature trick: a learned
model can key on flow identity without a per-flow table).  Per-flow
byte/packet counters live host-side only in this architecture (the
device keeps no per-flow accumulators beyond the session table); the
honest consequence is documented in docs/ARCHITECTURE.md.

**Score bands.**  The device ships a 3-bit log2 score band in the
packed verdict word, not the f32 score: band k means

    score in [1 - 2^-k, 1 - 2^-(k+1)),   k = 0..7 (clamped)

i.e. bands are log2-spaced in (1 - score) — fine resolution exactly
where thresholds live (near 1.0).  A policy threshold t fires when
band >= t, equivalently score >= 1 - 2^-t.  The per-band counters the
runner keeps ARE the score log2-histogram surfaced through
``inspect()["inference"]``.

**Weights as a table.**  :class:`InferTable` is just another device
table: swapped atomically with ACL/NAT under the runner's last-good
rollback, shipped incrementally through the PR 2 delta scatter path
(ops/infer_delta.py), fingerprinted by the same scheduler drift check.
A model update is a control-plane transaction with a propagation span
— never a redeploy.

**Enrollment.**  Scoring is enabled per pod IP (the renderer maps
enrolled namespaces to pod IPs): a sorted pod-IP array with per-slot
(threshold band, action) — the same binary-search lookup discipline as
the classify pod tables.  A flow is scored when its (rewritten) source
OR destination is an enrolled pod; the source binding wins when both
are enrolled (the flow's originating namespace owns its policy).

``enabled`` is pytree aux (a trace-time static): a disabled table
compiles to *nothing* — the score-off program is bit-identical to one
built with no table at all, so un-enrolled clusters pay zero.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .classify import POD_PAD_IP, _next_pow2

# Fixed feature-vector width (f0..f15, see infer_features) and the
# default hidden width.  D is part of the wire contract (w1 rows ship
# as delta rows); H is free per model.
INFER_FEATURES = 16
INFER_HIDDEN = 8

# Score bands: 3 bits in the packed verdict word.
INFER_BANDS = 8

# Actions a threshold crossing can fire (2 bits in the packed word).
# NONE doubles as "scored but below threshold".
INFER_ACT_NONE = 0
INFER_ACT_LOG = 1
INFER_ACT_DEPRIORITIZE = 2
INFER_ACT_QUARANTINE = 3

INFER_ACTION_NAMES = {
    INFER_ACT_NONE: "none",
    INFER_ACT_LOG: "log",
    INFER_ACT_DEPRIORITIZE: "deprioritize",
    INFER_ACT_QUARANTINE: "quarantine",
}
INFER_ACTION_CODES = {v: k for k, v in INFER_ACTION_NAMES.items()}

# Smallest pod-slot bucket (same pow2 discipline as the classify pod
# table: content changes swap arrays, only bucket changes recompile).
POD_BUCKET_MIN = 16

# Feature-hash multipliers (Knuth/xxhash-style odd constants; the same
# numbers on device and host — the two scorers must agree bit-for-bit
# on the hash features).
_HASH_A = 0x9E3779B1
_HASH_B = 0x85EBCA77
_HASH_C = 0xC2B2AE3D


@dataclass
class InferTable:
    """Model weights + per-pod enrollment as one device table."""

    w1: jnp.ndarray             # f32 [D, H]
    b1: jnp.ndarray             # f32 [H]
    w2: jnp.ndarray             # f32 [H]
    b2: jnp.ndarray             # f32 []
    pod_ip: jnp.ndarray         # uint32 [P] sorted, POD_PAD_IP padding
    pod_threshold: jnp.ndarray  # int32 [P] band threshold (0..7)
    pod_action: jnp.ndarray     # int32 [P] INFER_ACT_* fired at threshold
    num_pods: int = 0           # aux
    enabled: bool = False       # aux — static gate; False compiles to nothing

    def tree_flatten(self):
        children = (
            self.w1, self.b1, self.w2, self.b2,
            self.pod_ip, self.pod_threshold, self.pod_action,
        )
        return children, (self.num_pods, self.enabled)

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children, num_pods=aux[0], enabled=aux[1])


jax.tree_util.register_pytree_node(
    InferTable, InferTable.tree_flatten, InferTable.tree_unflatten
)


# ---------------------------------------------------------------------------
# Feature extraction + scoring (device)
# ---------------------------------------------------------------------------


def _flow_hash_u32(src, dst, proto, sport, dport, xp):
    """Symmetric-free 32-bit flow mix shared by device and host (both
    sides compute in uint32 wraparound, so the hash features agree
    exactly).  ``xp`` is jnp or np."""
    u32 = xp.uint32
    h = src.astype(u32) * u32(_HASH_A) ^ dst.astype(u32) * u32(_HASH_B)
    ports = (sport.astype(u32) << u32(16)) | dport.astype(u32)
    h = h ^ ports * u32(_HASH_C)
    h = h ^ proto.astype(u32)
    h = (h ^ (h >> u32(15))) * u32(_HASH_A)
    return h ^ (h >> u32(13))


def _features(src_ip, dst_ip, protocol, src_port, dst_port,
              reply_hit, dnat_hit, snat_hit, xp):
    """The fixed 16-feature vector, [B, 16] f32 — ONE implementation
    shared by the device stage (xp=jnp) and the host reference scorer
    (xp=np); any drift between the two is a parity-test failure, not a
    silent mis-scoring.

    f0-f3   src IP octets / 255
    f4-f7   dst IP octets / 255
    f8, f9  src/dst port / 65535
    f10,f11 protocol one-hots (TCP, UDP)
    f12     session reply restore hit
    f13     DNAT or SNAT translation hit
    f14,f15 two 16-bit feature-hash buckets of the flow tuple / 65535
    """
    f32 = xp.float32
    u32 = xp.uint32
    src = src_ip.astype(u32)
    dst = dst_ip.astype(u32)
    h = _flow_hash_u32(src, dst, protocol, src_port, dst_port, xp)

    def octet(ip, shift):
        return ((ip >> u32(shift)) & u32(0xFF)).astype(f32) * f32(1.0 / 255.0)

    feats = [
        octet(src, 24), octet(src, 16), octet(src, 8), octet(src, 0),
        octet(dst, 24), octet(dst, 16), octet(dst, 8), octet(dst, 0),
        src_port.astype(f32) * f32(1.0 / 65535.0),
        dst_port.astype(f32) * f32(1.0 / 65535.0),
        (protocol == 6).astype(f32),
        (protocol == 17).astype(f32),
        reply_hit.astype(f32),
        (dnat_hit | snat_hit).astype(f32),
        (h & u32(0xFFFF)).astype(f32) * f32(1.0 / 65535.0),
        ((h >> u32(16)) & u32(0xFFFF)).astype(f32) * f32(1.0 / 65535.0),
    ]
    return xp.stack(feats, axis=-1)


def _mlp_score(feats, w1, b1, w2, b2, xp):
    """relu MLP + sigmoid, f32 throughout (shared device/host body).
    Every scalar is wrapped f32: a bare python float would promote the
    numpy side to f64 and break device/host band parity."""
    one = xp.float32(1.0)
    hidden = xp.maximum(feats @ w1 + b1, xp.float32(0.0))
    z = hidden @ w2 + b2
    return one / (one + xp.exp(-z))


def _score_band(score, xp):
    """log2 band of a score: floor(-log2(1 - score)) clamped to 0..7.
    Band k <=> score >= 1 - 2^-k, so a threshold comparison is a pure
    integer >=.  The 2^-31 clamp keeps a saturated f32 score (==1.0)
    finite; it lands in band 7 like everything past 1 - 2^-7."""
    rem = xp.maximum(xp.float32(1.0) - score, xp.float32(2.0 ** -31))
    band = xp.floor(-xp.log2(rem))
    return xp.clip(band, 0, INFER_BANDS - 1).astype(xp.uint32)


def _lookup_slot(ip: jnp.ndarray, pod_ip: jnp.ndarray):
    """(enrolled bool [B], slot int32 [B]) — the classify pod-table
    binary-search discipline over the sorted enrollment array.  The
    padding IP itself must never match: a broadcast packet
    (255.255.255.255) would otherwise "enroll" against the pad slots
    and pollute the scored counters/band histogram."""
    idx = jnp.searchsorted(pod_ip, ip)
    idx = jnp.minimum(idx, pod_ip.shape[0] - 1)
    hit = (pod_ip[idx] == ip) & (ip != jnp.uint32(POD_PAD_IP))
    return hit, idx


def infer_scores(
    infer: InferTable,
    batch,                    # PacketBatch, flat [B] (rewritten headers)
    reply_hit: jnp.ndarray,   # bool [B]
    dnat_hit: jnp.ndarray,    # bool [B]
    snat_hit: jnp.ndarray,    # bool [B]
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """The scoring stage: (scored bool [B], band uint32 [B], action
    uint32 [B]).  ``action`` is nonzero only where the band crossed the
    enrolled pod's threshold (INFER_ACT_NONE otherwise); ``band`` is 0
    on un-scored rows.  Runs INSIDE the jit entry points, between the
    pipeline verdict settlement and the pack_result tail — all
    batch-parallel tensor ops, no host round trips."""
    feats = _features(
        batch.src_ip, batch.dst_ip, batch.protocol,
        batch.src_port, batch.dst_port,
        reply_hit, dnat_hit, snat_hit, jnp,
    )
    score = _mlp_score(feats, infer.w1, infer.b1, infer.w2, infer.b2, jnp)
    band = _score_band(score, jnp)

    src_hit, src_slot = _lookup_slot(batch.src_ip, infer.pod_ip)
    dst_hit, dst_slot = _lookup_slot(batch.dst_ip, infer.pod_ip)
    scored = src_hit | dst_hit
    slot = jnp.where(src_hit, src_slot, dst_slot)
    threshold = infer.pod_threshold[slot]
    bound_action = infer.pod_action[slot]

    band = jnp.where(scored, band, jnp.uint32(0))
    fired = scored & (band >= threshold.astype(jnp.uint32))
    action = jnp.where(fired, bound_action.astype(jnp.uint32),
                       jnp.uint32(INFER_ACT_NONE))
    return scored, band, action


# ---------------------------------------------------------------------------
# Host reference scorer (the oracle side)
# ---------------------------------------------------------------------------


def score_host(w1: np.ndarray, b1: np.ndarray, w2: np.ndarray, b2,
               src_ip, dst_ip, protocol, src_port, dst_port,
               reply_hit=None, dnat_hit=None, snat_hit=None):
    """Numpy twin of the device scorer: (score f32 [B], band uint32
    [B]).  Shares the exact feature/MLP/band bodies with the device
    stage (same f32 ops, same hash constants), so it is the ground
    truth the mock-engine parity tests pin the pipeline against."""
    src_ip = np.asarray(src_ip, dtype=np.uint32)
    b = src_ip.shape if src_ip.shape else (1,)
    zeros = np.zeros(b, dtype=bool)
    feats = _features(
        src_ip,
        np.asarray(dst_ip, dtype=np.uint32),
        np.asarray(protocol, dtype=np.int64),
        np.asarray(src_port, dtype=np.int64),
        np.asarray(dst_port, dtype=np.int64),
        zeros if reply_hit is None else np.asarray(reply_hit, dtype=bool),
        zeros if dnat_hit is None else np.asarray(dnat_hit, dtype=bool),
        zeros if snat_hit is None else np.asarray(snat_hit, dtype=bool),
        np,
    ).astype(np.float32)
    score = _mlp_score(
        feats, np.asarray(w1, dtype=np.float32),
        np.asarray(b1, dtype=np.float32),
        np.asarray(w2, dtype=np.float32), np.float32(b2), np,
    ).astype(np.float32)
    return score, _score_band(score, np)


# ---------------------------------------------------------------------------
# Direct (non-incremental) table build
# ---------------------------------------------------------------------------


def build_infer_table(
    model: Optional[Dict[str, object]],
    bindings: Optional[Dict[int, Tuple[int, int]]] = None,
) -> InferTable:
    """Compile a model dict ({"w1","b1","w2","b2"} nested lists or
    arrays) + {pod_ip_u32: (threshold_band, action_code)} bindings into
    an InferTable — the from-scratch twin of the incremental builder
    (ops/infer_delta), used by tests and the builder's full-build path.
    ``model=None`` or empty bindings produce a DISABLED table (the
    static gate compiles the scoring stage away)."""
    bindings = bindings or {}
    if model is not None:
        w1 = np.asarray(model["w1"], dtype=np.float32)
        b1 = np.asarray(model["b1"], dtype=np.float32)
        w2 = np.asarray(model["w2"], dtype=np.float32)
        b2 = np.float32(model["b2"])
        if w1.shape[0] != INFER_FEATURES:
            raise ValueError(
                f"model w1 has {w1.shape[0]} feature rows, the datapath "
                f"feature vector is {INFER_FEATURES}-wide")
    else:
        w1 = np.zeros((INFER_FEATURES, INFER_HIDDEN), dtype=np.float32)
        b1 = np.zeros(INFER_HIDDEN, dtype=np.float32)
        w2 = np.zeros(INFER_HIDDEN, dtype=np.float32)
        b2 = np.float32(0.0)

    p = _next_pow2(max(len(bindings), 1), POD_BUCKET_MIN)
    pod_ip = np.full(p, POD_PAD_IP, dtype=np.uint32)
    pod_thr = np.zeros(p, dtype=np.int32)
    pod_act = np.zeros(p, dtype=np.int32)
    for i, ip in enumerate(sorted(bindings)):
        thr, act = bindings[ip]
        pod_ip[i] = ip
        pod_thr[i] = thr
        pod_act[i] = act
    return InferTable(
        w1=jnp.asarray(w1), b1=jnp.asarray(b1),
        w2=jnp.asarray(w2), b2=jnp.asarray(b2),
        pod_ip=jnp.asarray(pod_ip),
        pod_threshold=jnp.asarray(pod_thr),
        pod_action=jnp.asarray(pod_act),
        num_pods=len(bindings),
        enabled=bool(bindings) and model is not None,
    )
