"""Packet-header batches — the data-plane unit of work.

The analog of VPP's up-to-256-packet vectors (SURVEY.md §3.5): the host
shim parses headers off the wire and ships them as a struct-of-arrays
batch; the TPU pipeline classifies/rewrites the batch and the shim
applies the verdicts to the buffered payloads.  Only the 5-tuple +
bookkeeping fields travel to the device — payloads never do.

All arrays share one leading batch dimension.  uint32 IPs, int32
ports/protocols (TPU-native lane types).
"""

from __future__ import annotations

import ipaddress
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np


# The data-plane vector size — the VPP 256-packet vector analog
# (SURVEY.md §3.5); batches are padded to multiples of this.
VECTOR_SIZE = 256


def ip_to_u32(ip: Union[str, ipaddress.IPv4Address, int]) -> int:
    if isinstance(ip, int):
        return ip
    return int(ipaddress.ip_address(ip))


def u32_to_ip(value: int) -> str:
    return str(ipaddress.ip_address(int(value) & 0xFFFFFFFF))


@dataclass
class PacketBatch:
    """One batch of packet headers (device or host arrays).

    Registered as a JAX pytree so it can flow through jit directly.
    """

    src_ip: jnp.ndarray    # uint32 [B]
    dst_ip: jnp.ndarray    # uint32 [B]
    protocol: jnp.ndarray  # int32  [B] (IANA numbers; 6 TCP / 17 UDP)
    src_port: jnp.ndarray  # int32  [B]
    dst_port: jnp.ndarray  # int32  [B]

    @property
    def size(self) -> int:
        return self.src_ip.shape[-1]

    def tree_flatten(self):
        return (
            (self.src_ip, self.dst_ip, self.protocol, self.src_port, self.dst_port),
            None,
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        return cls(*children)


import jax.tree_util  # noqa: E402

jax.tree_util.register_pytree_node(
    PacketBatch, PacketBatch.tree_flatten, PacketBatch.tree_unflatten
)


def make_batch(
    flows: Sequence[Tuple],
    pad_to: Optional[int] = None,
) -> PacketBatch:
    """Build a batch from (src_ip, dst_ip, protocol, src_port, dst_port)
    tuples; pads by repeating the last flow to reach ``pad_to``."""
    if not flows:
        raise ValueError("empty batch")
    rows = list(flows)
    if pad_to is not None:
        if len(rows) > pad_to:
            raise ValueError(f"{len(rows)} flows exceed pad_to={pad_to}")
        rows = rows + [rows[-1]] * (pad_to - len(rows))
    src, dst, proto, sport, dport = zip(*rows)
    return PacketBatch(
        src_ip=jnp.asarray([ip_to_u32(s) for s in src], dtype=jnp.uint32),
        dst_ip=jnp.asarray([ip_to_u32(d) for d in dst], dtype=jnp.uint32),
        protocol=jnp.asarray([int(p) for p in proto], dtype=jnp.int32),
        src_port=jnp.asarray([int(p) for p in sport], dtype=jnp.int32),
        dst_port=jnp.asarray([int(p) for p in dport], dtype=jnp.int32),
    )


def random_batch(
    rng: np.random.Generator,
    size: int = 256,
    subnets: Sequence[str] = ("10.1.0.0/16",),
) -> PacketBatch:
    """Random traffic for benchmarks/fuzzing, sourced from given subnets."""
    nets = [ipaddress.ip_network(s) for s in subnets]
    bases = np.array([int(n.network_address) for n in nets], dtype=np.uint64)
    sizes = np.array([n.num_addresses for n in nets], dtype=np.uint64)
    pick_src = rng.integers(0, len(nets), size)
    pick_dst = rng.integers(0, len(nets), size)
    src = bases[pick_src] + (rng.integers(0, 1 << 62, size) % sizes[pick_src])
    dst = bases[pick_dst] + (rng.integers(0, 1 << 62, size) % sizes[pick_dst])
    proto = np.where(rng.random(size) < 0.7, 6, 17).astype(np.int32)
    return PacketBatch(
        src_ip=jnp.asarray(src.astype(np.uint32)),
        dst_ip=jnp.asarray(dst.astype(np.uint32)),
        protocol=jnp.asarray(proto),
        src_port=jnp.asarray(rng.integers(1, 65536, size).astype(np.int32)),
        dst_port=jnp.asarray(rng.integers(1, 65536, size).astype(np.int32)),
    )
